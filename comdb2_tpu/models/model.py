"""Consistency models.

A model is an immutable, hashable value with a single operation:
``step(model, f, value) -> model' | None`` — apply one operation to the
datatype's abstract state, returning the new state, or ``None`` if the
operation is illegal there (the reference's absorbing ``Inconsistent``
state, ``knossos/model.clj:10-38``).

Models mirror the reference's catalog:

- :func:`register` — ``knossos/model.clj:48-65``
- :func:`cas_register` — ``knossos/model.clj:95-116``
- :func:`cas_register_comdb2` — tuple-valued variant used by the comdb2
  register test (``knossos/model.clj:67-93``; values are ``[id v]``
  pairs produced by ``independent/tuple``)
- :func:`mutex` — ``knossos/model.clj:118-135``
- :func:`multi_register` — ``knossos/model.clj:137-161``
- :func:`set_model`, :func:`unordered_queue`, :func:`fifo_queue` —
  ``jepsen/model.clj:58-105``

Hashability matters: the memoizer (:mod:`comdb2_tpu.models.memo`) interns
model states by value to number the reachable state space.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple


@dataclass(frozen=True)
class Model:
    """Base class; subclasses are frozen dataclasses (hence hashable)."""

    def step(self, f: Any, value: Any) -> Optional["Model"]:
        raise NotImplementedError

    def describe(self) -> str:
        return repr(self)


def step(model: Optional[Model], f: Any, value: Any) -> Optional[Model]:
    """Step a model; ``None`` (inconsistent) is absorbing
    (``knossos/model.clj:22-38``)."""
    if model is None:
        return None
    return model.step(f, value)


# --- registers -------------------------------------------------------------

@dataclass(frozen=True)
class Register(Model):
    """A single read/write register. A read of ``None`` (unknown value)
    matches any state, mirroring the reference's nil-read allowance."""

    value: Any = None

    def step(self, f, value):
        if f == "write":
            return Register(value)
        if f == "read":
            if value is None or value == self.value:
                return self
            return None
        return None


@dataclass(frozen=True)
class CASRegister(Model):
    """Read/write/compare-and-set register (``knossos/model.clj:95-116``).
    ``cas`` takes a ``(expected, new)`` pair."""

    value: Any = None

    def step(self, f, value):
        if f == "write":
            return CASRegister(value)
        if f == "cas":
            if value is None:
                # indeterminate cas with unknown arguments can't be modeled
                return None
            expected, new = value
            return CASRegister(new) if self.value == expected else None
        if f == "read":
            if value is None or value == self.value:
                return self
            return None
        return None


@dataclass(frozen=True)
class CASRegisterComdb2(Model):
    """CAS register whose op values are ``(key, v)`` tuples as produced by
    ``independent/tuple`` (``knossos/model.clj:67-93``): the key is
    ignored, the payload is the second element. Only tagged
    :class:`~comdb2_tpu.ops.kv.KVTuple` values (or plain 2-sequences
    from EDN histories whose second element carries the payload) are
    unwrapped — a bare ``(expected, new)`` cas pair must NOT be."""

    value: Any = None

    def _unwrap(self, value):
        from ..ops.kv import KVTuple

        # only explicitly-tagged keyed values unwrap: a bare 2-tuple is
        # a cas (expected, new) pair, not a key wrapper — EDN histories
        # with [k v] vectors opt in via independent.wrap_keyed_history
        if isinstance(value, KVTuple):
            return value.value
        return value

    def step(self, f, value):
        v = self._unwrap(value)
        if f == "write":
            return CASRegisterComdb2(v)
        if f == "cas":
            if v is None:
                return None
            expected, new = v
            return CASRegisterComdb2(new) if self.value == expected else None
        if f == "read":
            if v is None or v == self.value:
                return self
            return None
        return None


# --- mutex -----------------------------------------------------------------

@dataclass(frozen=True)
class Mutex(Model):
    """acquire/release lock (``knossos/model.clj:118-135``)."""

    locked: bool = False

    def step(self, f, value):
        if f == "acquire":
            return Mutex(True) if not self.locked else None
        if f == "release":
            return Mutex(False) if self.locked else None
        return None


# --- multi-register (transactional) ---------------------------------------

@dataclass(frozen=True)
class MultiRegister(Model):
    """A map of registers stepped by transactions: the op value is a
    sequence of ``[f k v]`` micro-ops applied atomically
    (``knossos/model.clj:137-161``). State is a sorted tuple of (k, v)."""

    entries: Tuple[Tuple[Any, Any], ...] = ()

    def _get(self, k):
        for kk, vv in self.entries:
            if kk == k:
                return vv
        return None

    def _set(self, k, v):
        items = dict(self.entries)
        items[k] = v
        return tuple(sorted(items.items(), key=repr))

    def step(self, f, value):
        if f not in ("txn", "read", "write"):
            return None
        if value is None:
            return self
        cur = self
        for micro in value:
            mf, k, v = micro
            if mf == "read":
                if v is not None and cur._get(k) != v:
                    return None
            elif mf == "write":
                cur = MultiRegister(cur._set(k, v))
            else:
                return None
        return cur


# --- set -------------------------------------------------------------------

@dataclass(frozen=True)
class GSet(Model):
    """A grow-only set: ``add v``; ``read`` returns the full set
    (``jepsen/model.clj:58-75``). State is a frozenset."""

    elements: frozenset = frozenset()

    def step(self, f, value):
        if f == "add":
            return GSet(self.elements | {value})
        if f == "read":
            if value is None:
                return self
            want = frozenset(value) if not isinstance(value, frozenset) \
                else value
            return self if want == self.elements else None
        return None


# --- queues ----------------------------------------------------------------

@dataclass(frozen=True)
class UnorderedQueue(Model):
    """enqueue/dequeue where dequeue may return any enqueued element
    (``jepsen/model.clj:77-91``). State is a sorted tuple (multiset)."""

    elements: Tuple = ()

    def step(self, f, value):
        if f == "enqueue":
            return UnorderedQueue(tuple(sorted(
                self.elements + (value,), key=repr)))
        if f == "dequeue":
            if value in self.elements:
                items = list(self.elements)
                items.remove(value)
                return UnorderedQueue(tuple(items))
            return None
        return None


@dataclass(frozen=True)
class FIFOQueue(Model):
    """Strict FIFO queue (``jepsen/model.clj:93-105``)."""

    elements: Tuple = ()

    def step(self, f, value):
        if f == "enqueue":
            return FIFOQueue(self.elements + (value,))
        if f == "dequeue":
            if self.elements and self.elements[0] == value:
                return FIFOQueue(self.elements[1:])
            return None
        return None


# --- constructors (reference-parity names) ---------------------------------

def register(value=None) -> Register:
    return Register(value)


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


def cas_register_comdb2(value=None) -> CASRegisterComdb2:
    return CASRegisterComdb2(value)


def mutex() -> Mutex:
    return Mutex(False)


def multi_register(entries=None) -> MultiRegister:
    if entries:
        return MultiRegister(tuple(sorted(entries.items(), key=repr)))
    return MultiRegister()


def set_model() -> GSet:
    return GSet()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


MODELS = {
    "register": register,
    "cas-register": cas_register,
    "cas-register-comdb2": cas_register_comdb2,
    "mutex": mutex,
    "multi-register": multi_register,
    "set": set_model,
    "unordered-queue": unordered_queue,
    "fifo-queue": fifo_queue,
}
