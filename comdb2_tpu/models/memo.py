"""State-space memoization — the key to lowering model stepping onto TPU.

Mirrors the semantics of the reference's ``knossos/model/memo.clj``:
enumerate the *entire reachable state space* of a model under a history's
distinct transitions by fixed-point closure (``memo.clj:93-97``), number
states and transitions, and replace ``step`` with a table lookup:
``succ[state_id, transition_id] -> state_id' | -1`` (inconsistent).

On device, one model step is then a single gather — which is what makes
frontier expansion vmappable (``memo.clj:99-126`` does the same with two
java arrays).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import numpy as np

from .model import Model, step
from ..ops.packed import PackedHistory


class MemoOverflow(Exception):
    """Reachable state space exceeded the cap; callers should fall back to
    un-memoized host checking or report :unknown."""


@dataclass
class MemoizedModel:
    """A model compiled to integer tables.

    ``succ[s, t]`` is the state reached by applying transition ``t`` in
    state ``s``, or -1 if inconsistent. ``states[i]`` is the original
    model object for state id ``i`` (id 0 = initial). ``transitions[t]``
    is the ``(f, value)`` pair for transition id ``t``.
    """

    states: List[Model]
    transitions: List[Tuple[Any, Any]]
    succ: np.ndarray  # int32[S, T]

    @property
    def n_states(self) -> int:
        return len(self.states)

    @property
    def n_transitions(self) -> int:
        return len(self.transitions)

    def step_id(self, state_id: int, trans_id: int) -> int:
        return int(self.succ[state_id, trans_id])


def transitions_of(packed: PackedHistory) -> List[Tuple[Any, Any]]:
    """Distinct (f, value) transitions of a packed history, in transition-id
    order (``memo.clj:66-73``)."""
    out = []
    for f_id, v_id in packed.transition_table:
        out.append((packed.f_table[f_id], packed.value_table[v_id]))
    return out


def memoize_model(model: Model,
                  transitions: List[Tuple[Any, Any]],
                  max_states: int = 1 << 20,
                  max_depth: Optional[int] = None) -> MemoizedModel:
    """Fixed-point closure of ``model`` under ``transitions``.

    BFS from the initial model; every reachable state gets an id; the
    successor table is materialized densely (``memo.clj:156-170`` builds
    the same graph as linked wrapper objects).

    ``max_depth`` bounds the BFS depth. With ``max_depth`` = the number
    of invocations in the history this is *exact*, not an approximation:
    a checking run linearizes each invocation at most once, so states
    whose shortest path from the initial state exceeds the invocation
    count can never be stepped into. (States *at* the depth bound get
    all-inconsistent successor rows; reaching one consumes every
    invocation, so such a config has no pending calls left to step.)
    This keeps unbounded-growth models — queues, sets — finite where the
    reference's unbounded closure (``memo.clj:93-97``) would diverge.
    """
    ids = {model: 0}
    states: List[Model] = [model]
    rows: List[List[int]] = []
    frontier = [model]
    T = len(transitions)
    depth = 0
    while frontier:
        if max_depth is not None and depth >= max_depth:
            # terminal depth: never stepped (see docstring); -1 rows
            rows.extend([[-1] * T] * len(frontier))
            break
        next_frontier = []
        for m in frontier:
            row = []
            for (f, value) in transitions:
                m2 = step(m, f, value)
                if m2 is None:
                    row.append(-1)
                    continue
                sid = ids.get(m2)
                if sid is None:
                    sid = len(states)
                    if sid >= max_states:
                        raise MemoOverflow(
                            f"reachable state space exceeds {max_states}")
                    ids[m2] = sid
                    states.append(m2)
                    next_frontier.append(m2)
                row.append(sid)
            rows.append(row)
        frontier = next_frontier
        depth += 1
    succ = np.asarray(rows, np.int32).reshape(len(states), T)
    return MemoizedModel(states=states, transitions=transitions, succ=succ)


def memo(model: Model, packed: PackedHistory,
         max_states: int = 1 << 20) -> MemoizedModel:
    """Memoize ``model`` over the distinct transitions of ``packed``
    (the reference's entry point, ``memo.clj:182-196``), with the BFS
    depth bounded by the history's invocation count."""
    from ..ops.op import INVOKE

    n_invokes = int(((packed.type == INVOKE) & ~packed.fails).sum())
    return memoize_model(model, transitions_of(packed), max_states,
                         max_depth=n_invokes)
