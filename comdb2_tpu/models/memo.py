"""State-space memoization — the key to lowering model stepping onto TPU.

Mirrors the semantics of the reference's ``knossos/model/memo.clj``:
enumerate the *entire reachable state space* of a model under a history's
distinct transitions by fixed-point closure (``memo.clj:93-97``), number
states and transitions, and replace ``step`` with a table lookup:
``succ[state_id, transition_id] -> state_id' | -1`` (inconsistent).

On device, one model step is then a single gather — which is what makes
frontier expansion vmappable (``memo.clj:99-126`` does the same with two
java arrays).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

import numpy as np

from .model import Model, step
from ..ops.packed import PackedHistory


class MemoOverflow(Exception):
    """Reachable state space exceeded the cap; callers should fall back to
    un-memoized host checking or report :unknown."""


@dataclass
class MemoizedModel:
    """A model compiled to integer tables.

    ``succ[s, t]`` is the state reached by applying transition ``t`` in
    state ``s``, or -1 if inconsistent. ``states[i]`` is the original
    model object for state id ``i`` (id 0 = initial). ``transitions[t]``
    is the ``(f, value)`` pair for transition id ``t``.
    """

    states: List[Model]
    transitions: List[Tuple[Any, Any]]
    succ: np.ndarray  # int32[S, T]

    @property
    def n_states(self) -> int:
        return len(self.states)

    @property
    def n_transitions(self) -> int:
        return len(self.transitions)

    def step_id(self, state_id: int, trans_id: int) -> int:
        return int(self.succ[state_id, trans_id])


def transitions_of(packed: PackedHistory) -> List[Tuple[Any, Any]]:
    """Distinct (f, value) transitions of a packed history, in transition-id
    order (``memo.clj:66-73``)."""
    out = []
    for f_id, v_id in packed.transition_table:
        out.append((packed.f_table[f_id], packed.value_table[v_id]))
    return out


def memoize_model(model: Model,
                  transitions: List[Tuple[Any, Any]],
                  max_states: int = 1 << 20,
                  max_depth: Optional[int] = None) -> MemoizedModel:
    """Fixed-point closure of ``model`` under ``transitions``.

    BFS from the initial model; every reachable state gets an id; the
    successor table is materialized densely (``memo.clj:156-170`` builds
    the same graph as linked wrapper objects).

    ``max_depth`` bounds the BFS depth. With ``max_depth`` = the number
    of invocations in the history this is *exact*, not an approximation:
    a checking run linearizes each invocation at most once, so states
    whose shortest path from the initial state exceeds the invocation
    count can never be stepped into. (States *at* the depth bound get
    all-inconsistent successor rows; reaching one consumes every
    invocation, so such a config has no pending calls left to step.)
    This keeps unbounded-growth models — queues, sets — finite where the
    reference's unbounded closure (``memo.clj:93-97``) would diverge.
    """
    ids = {model: 0}
    states: List[Model] = [model]
    rows: List[List[int]] = []
    frontier = [model]
    T = len(transitions)
    depth = 0
    while frontier:
        if max_depth is not None and depth >= max_depth:
            # terminal depth: never stepped (see docstring); -1 rows
            rows.extend([[-1] * T] * len(frontier))
            break
        next_frontier = []
        for m in frontier:
            row = []
            for (f, value) in transitions:
                m2 = step(m, f, value)
                if m2 is None:
                    row.append(-1)
                    continue
                sid = ids.get(m2)
                if sid is None:
                    sid = len(states)
                    if sid >= max_states:
                        raise MemoOverflow(
                            f"reachable state space exceeds {max_states}")
                    ids[m2] = sid
                    states.append(m2)
                    next_frontier.append(m2)
                row.append(sid)
            rows.append(row)
        frontier = next_frontier
        depth += 1
    succ = np.asarray(rows, np.int32).reshape(len(states), T)
    return MemoizedModel(states=states, transitions=transitions, succ=succ)


class IncrementalMemo:
    """Grow-only memoization for streaming sessions — state ids are
    STABLE across extensions, which is what lets a device-resident
    frontier carry survive ``append``s that introduce new transitions
    (:mod:`comdb2_tpu.stream`): the carry stores state ids, so a
    re-numbering would invalidate every config on device.

    Semantics match :func:`memoize_model` run over the final
    (transitions, max_depth) pair: states are discovered at their
    MINIMAL distance from the initial state (a late-arriving
    transition that shortcuts an existing state relaxes its depth and
    re-expands it — without relaxation a state could stay terminal
    below the bound and wrongly reject a linearization), and states at
    depth >= ``max_depth`` keep all-inconsistent rows (the same
    exactness argument: reaching one consumes every invocation seen so
    far, so no config there has pending calls left to step). Only the
    state NUMBERING differs from a one-shot memoization (BFS discovery
    order vs extension order) — verdicts, fail indices and decoded
    counterexamples are id-independent.
    """

    def __init__(self, model: Model, max_states: int = 1 << 20):
        self.max_states = max_states
        self.states: List[Model] = [model]
        self.transitions: List[Tuple[Any, Any]] = []
        self._ids = {model: 0}
        self._depths = [0]
        #: per-state successor row (list of ids, len == len(transitions)
        #: when expanded) or None — unexpanded (terminal at the current
        #: depth bound, re-expandable when the bound grows)
        self._rows: List[Optional[List[int]]] = [None]
        self.max_depth = 0
        self._succ: Optional[np.ndarray] = None
        #: bumped whenever the table content changes — device-side
        #: copies (stream sessions) key their upload cache on it
        self.version = 0
        #: the extend-call log, replayed verbatim by checkpoint
        #: restore: state NUMBERING is extension-order-dependent and
        #: the device carries store state ids, so a restored memo must
        #: re-run the SAME extension sequence (a one-shot re-memoization
        #: would renumber and silently corrupt every resident config).
        #: O(distinct transitions), never O(history).
        self._log: List[Tuple[Tuple[Tuple[Any, Any], ...], int]] = []

    @property
    def n_states(self) -> int:
        return len(self.states)

    @property
    def n_transitions(self) -> int:
        return len(self.transitions)

    @property
    def succ(self) -> np.ndarray:
        """The dense successor table (unexpanded states: all -1).
        Cached until the next :meth:`extend`."""
        if self._succ is None:
            T = len(self.transitions)
            out = np.full((len(self.states), max(T, 1)), -1, np.int32)
            for i, row in enumerate(self._rows):
                if row is not None:
                    out[i, :len(row)] = row
            self._succ = out
        return self._succ

    def as_memoized(self) -> MemoizedModel:
        """A :class:`MemoizedModel` view (counterexample decode)."""
        return MemoizedModel(states=self.states,
                             transitions=self.transitions,
                             succ=self.succ)

    def _intern(self, m2: Model, depth: int, work) -> int:
        sid = self._ids.get(m2)
        if sid is None:
            sid = len(self.states)
            if sid >= self.max_states:
                raise MemoOverflow(
                    f"reachable state space exceeds {self.max_states}")
            self._ids[m2] = sid
            self.states.append(m2)
            self._depths.append(depth)
            self._rows.append(None)
            work.append(sid)
        elif depth < self._depths[sid]:
            # relaxation: a new shortcut lowered the state's minimal
            # distance. An unexpanded state may now sit below the
            # bound (expandable); an EXPANDED one must propagate the
            # lower depth through its successors — without the
            # cascade a state could stay terminal at the bound while
            # its true minimal distance is below it, and a
            # linearization stepping through it would be wrongly
            # rejected.
            self._depths[sid] = depth
            work.append(sid)
        return sid

    def checkpoint(self) -> dict:
        """Everything :meth:`restore` needs to rebuild this memo with
        IDENTICAL state numbering: the extend-call log (plus the cap).
        The states themselves are re-derived by replay — host data
        only, O(distinct transitions), never O(history)."""
        return {"max_states": self.max_states,
                "log": [(tuple(tr), d) for tr, d in self._log]}

    @classmethod
    def restore(cls, model: Model, ck: dict) -> "IncrementalMemo":
        """Replay the extend log onto a fresh memo — deterministic, so
        state ids (and therefore every id a device carry stores) come
        back bit-identical."""
        memo = cls(model, max_states=int(ck["max_states"]))
        for tr, d in ck["log"]:
            memo.extend([tuple(t) for t in tr], int(d))
        return memo

    def extend(self, transitions: List[Tuple[Any, Any]],
               max_depth: int) -> None:
        """Append ``transitions`` (ids continue the existing table) and
        raise the depth bound to ``max_depth``; close the reachable set
        under both. No-op when nothing changed."""
        from collections import deque

        T_old = len(self.transitions)
        if transitions:
            self.transitions = self.transitions + list(transitions)
        grew_depth = max_depth > self.max_depth
        self.max_depth = max(self.max_depth, max_depth)
        if not transitions and not grew_depth:
            return
        self._succ = None
        self.version += 1
        work: deque = deque()
        # new columns for every already-expanded state
        if transitions:
            for sid in range(len(self._rows)):
                row = self._rows[sid]
                if row is None:
                    continue
                m = self.states[sid]
                d = self._depths[sid]
                for (f, value) in self.transitions[T_old:]:
                    m2 = step(m, f, value)
                    row.append(-1 if m2 is None
                               else self._intern(m2, d + 1, work))
        # unexpanded states below the (possibly raised) bound
        for sid, row in enumerate(self._rows):
            if row is None and self._depths[sid] < self.max_depth:
                work.append(sid)
        while work:
            sid = work.popleft()
            d = self._depths[sid]
            row = self._rows[sid]
            if row is not None:
                # relaxation cascade: re-offer the (already computed)
                # successors at the lowered depth; terminates because
                # depths only decrease and are bounded by 0
                for s2 in row:
                    if s2 >= 0 and self._depths[s2] > d + 1:
                        self._depths[s2] = d + 1
                        work.append(s2)
                continue
            if d >= self.max_depth:
                continue
            m = self.states[sid]
            row = []
            for (f, value) in self.transitions:
                m2 = step(m, f, value)
                row.append(-1 if m2 is None
                           else self._intern(m2, d + 1, work))
            self._rows[sid] = row
        # log AFTER the closure succeeds: an extend that raises
        # MemoOverflow latches the session terminal-UNKNOWN but the
        # session stays checkpointable — a log entry for the failed
        # call would make every restore of that checkpoint replay the
        # overflow and raise, turning the latched verdict into a
        # spurious error (and losing a released migration outright)
        self._log.append((tuple(transitions), self.max_depth))


def memo(model: Model, packed: PackedHistory,
         max_states: int = 1 << 20) -> MemoizedModel:
    """Memoize ``model`` over the distinct transitions of ``packed``
    (the reference's entry point, ``memo.clj:182-196``), with the BFS
    depth bounded by the history's invocation count."""
    from ..ops.op import INVOKE

    n_invokes = int(((packed.type == INVOKE) & ~packed.fails).sum())
    return memoize_model(model, transitions_of(packed), max_states,
                         max_depth=n_invokes)
