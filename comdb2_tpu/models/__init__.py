"""Consistency models and their memoized (tensor-ready) form."""

from .model import (
    Model, Register, CASRegister, CASRegisterComdb2, Mutex, MultiRegister,
    GSet, UnorderedQueue, FIFOQueue, step,
    register, cas_register, cas_register_comdb2, mutex, multi_register,
    set_model, unordered_queue, fifo_queue, MODELS,
)
from .memo import MemoizedModel, MemoOverflow, memo, memoize_model, \
    transitions_of

__all__ = [
    "Model", "Register", "CASRegister", "CASRegisterComdb2", "Mutex",
    "MultiRegister", "GSet", "UnorderedQueue", "FIFOQueue", "step",
    "register", "cas_register", "cas_register_comdb2", "mutex",
    "multi_register", "set_model", "unordered_queue", "fifo_queue",
    "MODELS", "MemoizedModel", "MemoOverflow", "memo", "memoize_model",
    "transitions_of",
]
