"""Client for the verifier daemon — retry/backoff over the newline-
JSON protocol.

``check`` is pure verification (no side effects on the daemon beyond
metrics), so a lost connection retries the SAME request safely — the
cdb2api HA-retry shape without needing replay nonces. Only an
exhausted retry budget surfaces to the caller.
"""

from __future__ import annotations

import socket
import time
from typing import List, Optional, Union

from . import protocol
from .daemon import PMUX_SERVICE


class ServiceError(Exception):
    """The daemon answered ``ok: false`` (``.code`` holds the error
    code, e.g. ``"overload"``)."""

    def __init__(self, code: str, message: str = ""):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code


class ServiceClient:
    """One connection to the daemon, redialed on failure."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5107,
                 timeout_s: float = 120.0, retries: int = 3,
                 backoff_s: float = 0.05):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_s = backoff_s
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._seq = 0

    @classmethod
    def discover(cls, pmux_port: int = 5105,
                 service: str = PMUX_SERVICE, host: str = "127.0.0.1",
                 **kw) -> "ServiceClient":
        """Resolve the daemon's port through pmux (the port-less
        discovery path the native SUT clients use)."""
        from ..control.pmux import PmuxClient

        with PmuxClient(host, pmux_port) as c:
            port = c.get(service)
        if port is None:
            raise OSError(f"pmux does not know {service!r}")
        return cls(host, port, **kw)

    # -- plumbing ------------------------------------------------------

    def _conn(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._file = self._sock.makefile("rb")
        return self._sock, self._file

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._file = None

    def _request(self, obj: dict) -> dict:
        """Send one request, await its reply; redial + retry with
        backoff on connection failure (checks are idempotent)."""
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                sock, f = self._conn()
                sock.sendall(protocol.encode(obj))
                line = f.readline()
                if not line.endswith(b"\n"):
                    # truncated = daemon died mid-reply; same contract
                    # as the SUT client's partial-reply rejection
                    raise OSError("truncated reply")
                return protocol.decode(line)
            except (OSError, ValueError) as e:
                last = e
                self.close()
        raise OSError(f"verifier at {self.host}:{self.port} "
                      f"unreachable after {self.retries + 1} "
                      f"attempts: {last}")

    # -- API -----------------------------------------------------------

    def check(self, history: Union[str, List, None] = None, *,
              model: Optional[str] = None, keyed: bool = False,
              deadline_ms: Optional[int] = None,
              txn: bool = False, realtime: bool = False,
              raise_on_error: bool = True) -> dict:
        """Verify one history. ``history`` is EDN text or a list of
        ``Op``s (serialized via ``history_to_edn``). ``txn=True``
        submits the serializability kind (list-append txn ops; the
        reply carries ``anomaly_class``/``cycle`` on violations).
        Returns the reply dict (``valid`` is the tri-state);
        daemon-side errors raise :class:`ServiceError` unless
        ``raise_on_error=False``."""
        if not isinstance(history, str):
            from ..ops.history import history_to_edn

            history = history_to_edn(list(history or []))
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq,
                     "history": history}
        if txn:
            req["kind"] = "txn"
            if realtime:
                req["realtime"] = True
        if model is not None:
            req["model"] = model
        if keyed:
            req["keyed"] = True
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        reply = self._request(req)
        if raise_on_error and not reply.get("ok"):
            raise ServiceError(reply.get("error", "unknown-error"),
                               reply.get("message", ""))
        return reply

    def shrink(self, history: Union[str, List, None] = None, *,
               model: Optional[str] = None, keyed: bool = False,
               txn: bool = False, realtime: bool = False,
               deadline_ms: Optional[int] = None,
               raise_on_error: bool = True) -> dict:
        """Minimize one INVALID history (``kind: "shrink"``). The
        reply carries ``minimal_history`` (EDN text of the 1-minimal
        sub-history), ``minimal_ops``/``seed_ops``, round/dispatch
        counts and the ``one_minimal``/``partial`` flags; a deadline
        returns best-so-far flagged ``partial``. A VALID/UNKNOWN seed
        answers ``bad-request`` (shrinking it is an error, not a
        loop)."""
        if not isinstance(history, str):
            from ..ops.history import history_to_edn

            history = history_to_edn(list(history or []))
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq, "kind": "shrink",
                     "history": history}
        if txn:
            req["txn"] = True
            if realtime:
                req["realtime"] = True
        if model is not None:
            req["model"] = model
        if keyed:
            req["keyed"] = True
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        reply = self._request(req)
        if raise_on_error and not reply.get("ok"):
            raise ServiceError(reply.get("error", "unknown-error"),
                               reply.get("message", ""))
        return reply

    def status(self) -> dict:
        return self._request({"op": "status"})

    def metrics(self) -> dict:
        """Scrape the metrics plane (``kind:"metrics"``): one reply
        carrying both the JSON snapshot (``metrics``) and the
        Prometheus text form (``prometheus``)."""
        self._seq += 1
        return self._request({"op": "check", "kind": "metrics",
                              "id": self._seq})

    def ping(self) -> bool:
        try:
            return bool(self._request({"op": "ping"}).get("pong"))
        except OSError:
            return False

    def shutdown(self) -> bool:
        try:
            return bool(self._request({"op": "shutdown"}).get("bye"))
        except OSError:
            return False

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = ["ServiceClient", "ServiceError"]
