"""Clients for the verifier daemon — retry/backoff over the newline-
JSON protocol, and consistent-hash routing over a pmux-discovered
daemon fleet.

``check`` is pure verification (no side effects on the daemon beyond
metrics), so a lost connection retries the SAME request safely — the
cdb2api HA-retry shape without needing replay nonces. Only an
exhausted retry budget surfaces to the caller. Overload replies carry
the daemon's ``retry_after_ms`` hint (queue depth / drain rate);
:class:`ServiceClient` honors it with JITTERED backoff — fixed-
interval retries from N clients re-arrive as one synchronized wave
and shed again.

:class:`RoutedClient` is the horizontal-scale surface: daemons
register under ``sut/verifier/<shard>`` (``--pmux-shard``), discovery
reads every registration from ``ct_pmux``, and requests route by
consistent hash of the history payload — the same history lands on
the same daemon (warm programs, warm carry pool) and adding a daemon
remaps only ~1/N of the keyspace. A dead daemon fails over to the
next on the ring.
"""

from __future__ import annotations

import hashlib
import random
import socket
import time
from bisect import bisect_right
from typing import Dict, List, Optional, Union

from ..obs.trace import monotonic as _monotonic
from . import protocol
from .daemon import PMUX_SERVICE


class ServiceError(Exception):
    """The daemon answered ``ok: false`` (``.code`` holds the error
    code, e.g. ``"overload"``; ``.retry_after_ms`` the backoff hint
    when the reply carried one — the routed failover honors it
    per node)."""

    def __init__(self, code: str, message: str = "",
                 retry_after_ms: Optional[float] = None):
        super().__init__(f"{code}: {message}" if message else code)
        self.code = code
        self.retry_after_ms = retry_after_ms

    @classmethod
    def from_reply(cls, reply: dict) -> "ServiceError":
        return cls(reply.get("error", "unknown-error"),
                   reply.get("message", ""),
                   reply.get("retry_after_ms"))


def _checked(reply: dict, raise_on_error: bool) -> dict:
    if raise_on_error and not reply.get("ok"):
        raise ServiceError.from_reply(reply)
    return reply


class ServiceClient:
    """One connection to the daemon, redialed on failure."""

    def __init__(self, host: str = "127.0.0.1", port: int = 5107,
                 timeout_s: float = 120.0, retries: int = 3,
                 backoff_s: float = 0.05, overload_retries: int = 2):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.retries = retries
        self.backoff_s = backoff_s
        #: extra attempts on an explicit overload reply, each after a
        #: jittered sleep around the daemon's retry_after_ms hint
        #: (0 = surface overload immediately)
        self.overload_retries = overload_retries
        self._rng = random.Random()
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._seq = 0

    @classmethod
    def discover(cls, pmux_port: int = 5105,
                 service: str = PMUX_SERVICE, host: str = "127.0.0.1",
                 **kw) -> "ServiceClient":
        """Resolve the daemon's port through pmux (the port-less
        discovery path the native SUT clients use)."""
        from ..control.pmux import PmuxClient

        with PmuxClient(host, pmux_port) as c:
            port = c.get(service)
        if port is None:
            raise OSError(f"pmux does not know {service!r}")
        return cls(host, port, **kw)

    # -- plumbing ------------------------------------------------------

    def _conn(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._file = self._sock.makefile("rb")
        return self._sock, self._file

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self._file = None

    def _request(self, obj: dict) -> dict:
        """Send one request, await its reply; redial + retry with
        backoff on connection failure (checks are idempotent)."""
        last: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                time.sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                sock, f = self._conn()
                sock.sendall(protocol.encode(obj))
                line = f.readline()
                if not line.endswith(b"\n"):
                    # truncated = daemon died mid-reply; same contract
                    # as the SUT client's partial-reply rejection
                    raise OSError("truncated reply")
                return protocol.decode(line)
            except (OSError, ValueError) as e:
                last = e
                self.close()
        raise OSError(f"verifier at {self.host}:{self.port} "
                      f"unreachable after {self.retries + 1} "
                      f"attempts: {last}")

    def _request_shedding(self, req: dict) -> dict:
        """One request with overload backoff: an ``overload`` reply
        sleeps around the daemon's ``retry_after_ms`` hint with
        +/-50% jitter (N clients backing off the same hint must not
        re-arrive as one synchronized wave) and retries up to
        ``overload_retries`` times before surfacing the reply. The
        request's own ``deadline_ms`` caps the cumulative backoff: a
        sleep that would blow the caller's budget surfaces the
        overload instead of silently turning a 100 ms check into a
        multi-second blocking call."""
        budget_ms = req.get("deadline_ms")
        t0 = _monotonic()
        for attempt in range(self.overload_retries + 1):
            reply = self._request(req)
            if (reply.get("ok")
                    or reply.get("error") != protocol.OVERLOAD
                    or attempt == self.overload_retries):
                return reply
            hint_ms = reply.get("retry_after_ms")
            if not isinstance(hint_ms, (int, float)) or hint_ms <= 0:
                hint_ms = 100.0
            sleep_s = hint_ms / 1e3 * self._rng.uniform(0.5, 1.5)
            if budget_ms is not None and \
                    (_monotonic() - t0 + sleep_s) * 1e3 \
                    > float(budget_ms):
                return reply
            time.sleep(sleep_s)
        return reply

    # -- API -----------------------------------------------------------

    def check(self, history: Union[str, List, None] = None, *,
              model: Optional[str] = None, keyed: bool = False,
              deadline_ms: Optional[int] = None,
              txn: bool = False, realtime: bool = False,
              raise_on_error: bool = True) -> dict:
        """Verify one history. ``history`` is EDN text or a list of
        ``Op``s (serialized via ``history_to_edn``). ``txn=True``
        submits the serializability kind (list-append txn ops; the
        reply carries ``anomaly_class``/``cycle`` on violations).
        Returns the reply dict (``valid`` is the tri-state);
        daemon-side errors raise :class:`ServiceError` unless
        ``raise_on_error=False``."""
        history = _as_edn(history)
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq,
                     "history": history}
        if txn:
            req["kind"] = "txn"
            if realtime:
                req["realtime"] = True
        if model is not None:
            req["model"] = model
        if keyed:
            req["keyed"] = True
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        reply = self._request_shedding(req)
        return _checked(reply, raise_on_error)

    def check_wl(self, history: Union[str, List, None], family: str,
                 *, wl: Optional[dict] = None,
                 deadline_ms: Optional[int] = None,
                 raise_on_error: bool = True) -> dict:
        """Check one workload-family history (``kind:"wl"``,
        docs/workloads.md): ``family`` is ``"bank"``/``"sets"``/
        ``"dirty"``; bank takes ``wl={"n":..,"total":..}``. The reply
        carries the host oracle's verdict fields (``bad-reads`` /
        ``lost`` / ``dirty-reads`` ...) plus ``engine``/``bucket``
        attribution — bit-identical to the in-process
        ``check_wl_batch``."""
        history = _as_edn(history)
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq, "kind": "wl",
                     "family": family, "history": history}
        if wl is not None:
            req["wl"] = wl
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        reply = self._request_shedding(req)
        return _checked(reply, raise_on_error)

    def shrink(self, history: Union[str, List, None] = None, *,
               model: Optional[str] = None, keyed: bool = False,
               txn: bool = False, realtime: bool = False,
               deadline_ms: Optional[int] = None,
               raise_on_error: bool = True) -> dict:
        """Minimize one INVALID history (``kind: "shrink"``). The
        reply carries ``minimal_history`` (EDN text of the 1-minimal
        sub-history), ``minimal_ops``/``seed_ops``, round/dispatch
        counts and the ``one_minimal``/``partial`` flags; a deadline
        returns best-so-far flagged ``partial``. A VALID/UNKNOWN seed
        answers ``bad-request`` (shrinking it is an error, not a
        loop)."""
        history = _as_edn(history)
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq, "kind": "shrink",
                     "history": history}
        if txn:
            req["txn"] = True
            if realtime:
                req["realtime"] = True
        if model is not None:
            req["model"] = model
        if keyed:
            req["keyed"] = True
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        reply = self._request_shedding(req)
        return _checked(reply, raise_on_error)

    # -- streaming sessions (kind:"stream", docs/streaming.md) ---------

    def stream_open(self, *, model: Optional[str] = None,
                    keyed: bool = False, rung: Optional[str] = None,
                    checkpoint: Optional[dict] = None,
                    wl: Optional[dict] = None,
                    raise_on_error: bool = True) -> dict:
        """Open a streaming session; the reply carries ``session``
        (the id every later verb names). An ``overload`` reply means
        the daemon's session table is at cap — back off on its
        ``retry_after_ms`` like any other overload. ``checkpoint``
        (a wire checkpoint from :meth:`stream_checkpoint`) opens BY
        RESTORE — the migration handoff's receiving half; model/rung
        ride inside the checkpoint and are ignored. ``wl`` carries
        the workload-family params for the ``wl-bank``/``wl-sets``
        session models (docs/workloads.md)."""
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq,
                     "kind": "stream", "verb": "open"}
        if checkpoint is not None:
            req["checkpoint"] = checkpoint
        if model is not None:
            req["model"] = model
        if keyed:
            req["keyed"] = True
        if rung is not None:
            req["rung"] = rung
        if wl is not None:
            req["wl"] = wl
        reply = self._request_shedding(req)
        return _checked(reply, raise_on_error)

    def stream_checkpoint(self, session: str, *,
                          release: bool = False,
                          raise_on_error: bool = True) -> dict:
        """Fetch a session's host-numpy checkpoint (wire form, in
        ``checkpoint``; ``checkpoint_bytes`` its size).
        ``release=True`` is the migration form: the daemon REMOVES
        the session (a handoff moves, never copies — two daemons
        serving one session would double-serve its appends)."""
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq,
                     "kind": "stream", "verb": "checkpoint",
                     "session": session}
        if release:
            req["release"] = True
        return _checked(self._request(req), raise_on_error)

    def drain(self, raise_on_error: bool = True) -> dict:
        """``kind:"drain"``: ask the daemon to leave gracefully —
        deregister, re-route queued work, finalize staged dispatches,
        serve session-checkpoint handoffs through its grace window,
        exit. The reply reports what was flushed/resident."""
        self._seq += 1
        return _checked(self._request({"op": "check", "kind": "drain",
                                       "id": self._seq}),
                        raise_on_error)

    def stream_append(self, session: str,
                      history: Union[str, List, None], *,
                      deadline_ms: Optional[int] = None,
                      raise_on_error: bool = True) -> dict:
        """Append one op delta; the reply is the verdict-so-far
        (``valid`` tri-state, ``checked_through``, per-append
        ``stages``). Once a session latches INVALID/UNKNOWN, appends
        answer immediately with ``latched: true``."""
        history = _as_edn(history)
        self._seq += 1
        req: dict = {"op": "check", "id": self._seq,
                     "kind": "stream", "verb": "append",
                     "session": session, "history": history}
        if deadline_ms is not None:
            req["deadline_ms"] = deadline_ms
        reply = self._request_shedding(req)
        return _checked(reply, raise_on_error)

    def stream_poll(self, session: str,
                    raise_on_error: bool = True) -> dict:
        self._seq += 1
        reply = self._request({"op": "check", "id": self._seq,
                               "kind": "stream", "verb": "poll",
                               "session": session})
        return _checked(reply, raise_on_error)

    def stream_close(self, session: str,
                     raise_on_error: bool = True) -> dict:
        """Close: the tail settles (final verdict — bit-identical to
        a one-shot check of everything appended) and the carry frees."""
        self._seq += 1
        reply = self._request({"op": "check", "id": self._seq,
                               "kind": "stream", "verb": "close",
                               "session": session})
        return _checked(reply, raise_on_error)

    def status(self) -> dict:
        return self._request({"op": "status"})

    def metrics(self) -> dict:
        """Scrape the metrics plane (``kind:"metrics"``): one reply
        carrying both the JSON snapshot (``metrics``) and the
        Prometheus text form (``prometheus``)."""
        self._seq += 1
        return self._request({"op": "check", "kind": "metrics",
                              "id": self._seq})

    def ping(self) -> bool:
        try:
            return bool(self._request({"op": "ping"}).get("pong"))
        except OSError:
            return False

    def shutdown(self) -> bool:
        try:
            return bool(self._request({"op": "shutdown"}).get("bye"))
        except OSError:
            return False

    def __enter__(self) -> "ServiceClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _hash64(data: bytes) -> int:
    """Stable 64-bit ring position (md5 prefix — NOT Python's
    ``hash``, which is salted per process and would re-shuffle the
    ring every restart)."""
    return int.from_bytes(hashlib.md5(data).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring with virtual nodes: ``nodes_for(key)``
    yields every distinct node in ring order starting at the key's
    position — element 0 is the owner, the rest the failover chain.
    Pure data structure (unit-tested without sockets)."""

    def __init__(self, nodes, replicas: int = 64):
        if not nodes:
            raise ValueError("consistent-hash ring needs >= 1 node")
        self.nodes = sorted(set(nodes))
        self.replicas = replicas
        points = []
        for name in self.nodes:
            for v in range(replicas):
                points.append((_hash64(f"{name}#{v}".encode()), name))
        points.sort()
        self._points = points
        self._keys = [h for h, _ in points]

    def nodes_for(self, key: Union[str, bytes]) -> List[str]:
        if isinstance(key, str):
            key = key.encode()
        i = bisect_right(self._keys, _hash64(key)) % len(self._points)
        out, seen = [], set()
        for _, name in self._points[i:] + self._points[:i]:
            if name not in seen:
                seen.add(name)
                out.append(name)
        return out


class RoutedClient:
    """Consistent-hash routing over a fleet of verifier daemons.

    ``endpoints`` maps node name (the pmux service name, e.g.
    ``sut/verifier/0``) to an open :class:`ServiceClient`. Requests
    route by their SHAPE CLASS by default (kind | model | pow2 of the
    history size — everything the client can see of the daemon's
    shape bucket): same-class traffic coalesces on one daemon, so
    batch amortization survives routing, and the fleet PARTITIONS the
    compiled-program space + donated-carry pools instead of every
    daemon compiling every bucket (``route="payload"`` pins identical
    histories instead). Adding a daemon remaps only ~1/N of the
    classes. A node that fails (connect/IO after the client's own
    retry budget) fails over to the next distinct node on the ring;
    ``served`` counts per-node routed requests for placement
    audits."""

    def __init__(self, endpoints: Dict[str, ServiceClient],
                 replicas: int = 64, blacklist_ttl_s: float = 3.0,
                 epoch_poll_s: float = 1.0):
        if not endpoints:
            raise ValueError("RoutedClient needs >= 1 endpoint")
        self.clients = dict(endpoints)
        self.replicas = replicas
        self.ring = HashRing(list(endpoints), replicas=replicas)
        self.served: Dict[str, int] = {n: 0 for n in endpoints}
        self.failovers = 0
        self.refreshes = 0
        self.migrations = 0
        #: dead-node blacklist TTL: a node that failed a connect/IO
        #: is skipped on ring walks until the TTL expires, instead of
        #: paying a connect timeout on EVERY request that hashes near
        #: it; overload/drain replies park the node until the
        #: daemon's own retry_after_ms hint
        self.blacklist_ttl_s = float(blacklist_ttl_s)
        #: how often (at most) a routed call polls the single pmux
        #: epoch entry; failures force a poll immediately
        self.epoch_poll_s = float(epoch_poll_s)
        self._avoid: Dict[str, float] = {}   # node -> not-before
        self.epoch: Optional[int] = None     # ring version last seen
        self._epoch_checked = float("-inf")
        self._disco: Optional[tuple] = None  # (host, port, prefix, kw)
        #: a draining daemon deregisters FIRST and then serves session
        #: checkpoint handoffs on its ALREADY-OPEN connections only
        #: (the listener is closed) — so when a refresh drops a node
        #: that still has streams pinned to it, the warm client parks
        #: here instead of closing, or the O(carry) migration window
        #: would be destroyed by any unrelated routed request
        self._parting: Dict[str, ServiceClient] = {}
        self._pins: Dict[str, int] = {}      # node -> open streams

    @classmethod
    def discover(cls, pmux_port: int = 5105,
                 prefix: str = PMUX_SERVICE, host: str = "127.0.0.1",
                 **kw) -> "RoutedClient":
        """Build the fleet from ct_pmux: every registration named
        ``<prefix>`` or ``<prefix>/<shard>`` joins the ring (the
        ``--pmux-shard`` daemons). Raises when none is registered —
        an empty fleet is an operations failure, not an empty ring.
        The discovery parameters are retained: the client later
        REFRESHES the ring whenever the fleet's ring-version epoch
        bumps (a daemon joined or left), remapping ~1/N of the shape
        classes instead of ever serving from a stale membership."""
        from ..control.pmux import PmuxClient
        from .daemon import epoch_service_for

        # overload handling belongs to the ROUTED layer here: a node
        # answering overload is parked for its own retry_after_ms and
        # the walk moves on — the per-node client must not sleep-and-
        # re-dial the same overloaded daemon first (callers can still
        # opt back in explicitly)
        kw.setdefault("overload_retries", 0)
        with PmuxClient(host, pmux_port) as c:
            used = c.used()
        endpoints = {
            svc: ServiceClient(host, port, **kw)
            for svc, port in used.items()
            if svc == prefix or svc.startswith(prefix + "/")}
        if not endpoints:
            raise OSError(
                f"pmux at {host}:{pmux_port} knows no {prefix!r} "
                "daemons")
        rc = cls(endpoints)
        rc._disco = (host, pmux_port, prefix, dict(kw))
        rc.epoch = used.get(epoch_service_for(prefix))
        rc._epoch_checked = _monotonic()
        return rc

    # -- live membership (epochs) --------------------------------------

    def refresh(self) -> tuple:
        """Re-read the registry and rebuild the ring: new daemons
        join (their ~1/N of the classes remap onto them), departed
        ones leave (their classes remap onto survivors), surviving
        names keep their ServiceClient (warm connection). Returns
        ``(added, removed)`` name lists; a no-op without discovery
        parameters (statically-built clients)."""
        if self._disco is None:
            return [], []
        host, pmux_port, prefix, kw = self._disco
        from ..control.pmux import PmuxClient
        from .daemon import epoch_service_for

        with PmuxClient(host, pmux_port) as c:
            used = c.used()
        self.epoch = used.get(epoch_service_for(prefix))
        names = {svc: port for svc, port in used.items()
                 if svc == prefix or svc.startswith(prefix + "/")}
        if not names:
            # an empty registry mid-flight: keep serving on the
            # current ring — a stale ring beats no ring, and the
            # blacklist already shields dead nodes
            return [], []
        added = sorted(n for n in names if n not in self.clients)
        removed = sorted(n for n in self.clients if n not in names)
        for n in added:
            self.clients[n] = ServiceClient(host, names[n], **kw)
            self.served.setdefault(n, 0)
            self._avoid.pop(n, None)
        for n in removed:
            c = self.clients.pop(n)
            if self._pins.get(n):
                self._parting[n] = c     # pinned: see __init__ note
            else:
                c.close()
        repaired = 0
        for n, port in names.items():
            c = self.clients[n]
            if c.port != port:           # same name, restarted daemon
                c.close()
                c.port = port
                self._avoid.pop(n, None)
                repaired += 1
        self.ring = HashRing(list(self.clients),
                             replicas=self.replicas)
        if added or removed or repaired:
            self.refreshes += 1
        return added, removed

    def maybe_refresh(self, force: bool = False) -> bool:
        """Cheap membership check: ONE pmux ``get`` of the epoch
        entry, rate-limited to ``epoch_poll_s`` (every request pays a
        dict lookup, not a registry listing); a changed epoch
        triggers a full :meth:`refresh`. ``force`` skips the rate
        limit — failure paths call it so a dead/drained node is
        replaced on the spot."""
        if self._disco is None:
            return False
        now = _monotonic()
        if not force and now - self._epoch_checked < self.epoch_poll_s:
            return False
        self._epoch_checked = now
        host, pmux_port, prefix, _kw = self._disco
        from ..control.pmux import PmuxClient
        from .daemon import epoch_service_for

        try:
            with PmuxClient(host, pmux_port) as c:
                e = c.get(epoch_service_for(prefix))
        except OSError:
            return False
        if e is None or e == self.epoch:
            return False
        try:
            self.refresh()
        except OSError:
            return False
        # any epoch movement counts as "changed" for the caller's
        # retry decision: a refresh may have repaired a restarted
        # daemon's PORT without touching the name set, and the retry
        # must run against the repaired client either way
        return True

    # -- stream pins ---------------------------------------------------

    def _pin(self, name: str) -> None:
        self._pins[name] = self._pins.get(name, 0) + 1

    def _unpin(self, name: str) -> None:
        n = self._pins.get(name, 0) - 1
        if n > 0:
            self._pins[name] = n
            return
        self._pins.pop(name, None)
        c = self._parting.pop(name, None)
        if c is not None:
            c.close()

    # -- the ring walk -------------------------------------------------

    def _route(self, key: Union[str, bytes], fn, _retry: bool = True):
        """Walk the ring from the key's owner: blacklisted nodes
        (dead within TTL, overloaded within their own retry_after_ms
        hint, draining) are skipped — never re-dialed hot; a node
        that fails here is parked and the walk continues. When the
        whole walk fails, one forced membership refresh retries the
        walk once (the fleet may have changed under us)."""
        self.maybe_refresh()
        now = _monotonic()
        chain = self.ring.nodes_for(key)
        live = [n for n in chain if self._avoid.get(n, 0.0) <= now]
        last: Optional[Exception] = None
        for name in (live or chain):
            # all-parked falls through to the raw chain: trying a
            # blacklisted node beats refusing the request outright
            c = self.clients.get(name)
            if c is None:
                continue
            try:
                out = fn(c)
            except OSError as e:
                last = e
                self.failovers += 1
                # timestamp AFTER the failure: a hung connect burns
                # its timeout before raising, and a TTL anchored at
                # walk start would already be expired when written
                self._avoid[name] = _monotonic() + self.blacklist_ttl_s
                continue
            except ServiceError as e:
                if e.code == protocol.OVERLOAD:
                    # honor the node's own backpressure hint during
                    # failover: park it for retry_after_ms and try
                    # the next ring node (only the happy path backed
                    # off before)
                    ra = e.retry_after_ms
                    if not isinstance(ra, (int, float)) or ra <= 0:
                        ra = 100.0
                    self._avoid[name] = _monotonic() + float(ra) / 1e3
                    self.failovers += 1
                    last = e
                    continue
                if e.code == protocol.SHUTDOWN:
                    # draining daemon: it already deregistered AND
                    # bumped the epoch before this reply — park it
                    # and force the membership check now, restarting
                    # the walk on the refreshed ring instead of
                    # burning a hop on it per walk until the poll
                    self._avoid[name] = (_monotonic()
                                         + self.blacklist_ttl_s)
                    self.failovers += 1
                    last = e
                    if _retry and self.maybe_refresh(force=True):
                        return self._route(key, fn, _retry=False)
                    continue
                raise
            self.served[name] += 1
            return out
        if _retry and self.maybe_refresh(force=True):
            return self._route(key, fn, _retry=False)
        if isinstance(last, ServiceError):
            raise last
        raise OSError(f"every daemon on the ring failed: {last}")

    @staticmethod
    def route_key(history: str, kind: str = "check",
                  model: Optional[str] = None,
                  route: str = "shape") -> str:
        """The ring key for one request. ``"shape"`` (default) is the
        client-visible shape class — kind, model, and the pow2 size
        class of the EDN payload — so a daemon owns whole bucket
        classes; ``"payload"`` hashes the full history (identical
        histories pin, every bucket scatters across the fleet)."""
        if route == "payload":
            return f"{kind}|{model or ''}|{history}"
        size = max(len(history), 1)
        return f"{kind}|{model or ''}|{1 << (size - 1).bit_length()}"

    def check(self, history: Union[str, List, None] = None, *,
              route: str = "shape", **kw) -> dict:
        history = _as_edn(history)
        key = self.route_key(history, "txn" if kw.get("txn")
                             else "check", kw.get("model"), route)
        return self._route(key, lambda c: c.check(history, **kw))

    def shrink(self, history: Union[str, List, None] = None, *,
               route: str = "shape", **kw) -> dict:
        history = _as_edn(history)
        key = self.route_key(history, "shrink", kw.get("model"),
                             route)
        return self._route(key, lambda c: c.shrink(history, **kw))

    def check_wl(self, history: Union[str, List, None], family: str,
                 *, route: str = "shape", **kw) -> dict:
        """Route one workload-family check: the family IS the
        client-visible shape class root, so one daemon owns each
        family's bucket ladder and batch amortization survives
        routing (docs/workloads.md)."""
        history = _as_edn(history)
        key = self.route_key(history, "wl", family, route)
        return self._route(key,
                           lambda c: c.check_wl(history, family, **kw))

    def stream_open(self, *, model: Optional[str] = None,
                    keyed: bool = False, rung: Optional[str] = None,
                    wl: Optional[dict] = None) -> "RoutedStream":
        """Open a session with AFFINITY: the session id pins every
        later verb to the daemon holding the carry (routing an append
        elsewhere would find no session — a carry is not portable
        over the wire). Failover is replay: when the pinned daemon
        dies (or evicted the session), the handle re-opens on the
        next ring node and replays its retained deltas, then resumes
        — the client-side mirror of the daemon's retained columnar
        tables (docs/streaming.md "Failover")."""
        return RoutedStream(self, model=model, keyed=keyed, rung=rung,
                            wl=wl)

    def statuses(self) -> Dict[str, dict]:
        """Per-daemon status (skipping unreachable nodes)."""
        out = {}
        for name, c in self.clients.items():
            try:
                out[name] = c.status()["status"]
            except OSError:
                pass
        return out

    def ping_all(self) -> Dict[str, bool]:
        return {name: c.ping() for name, c in self.clients.items()}

    def close(self) -> None:
        for c in self.clients.values():
            c.close()
        for c in self._parting.values():
            c.close()
        self._parting.clear()

    def __enter__(self) -> "RoutedClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RoutedStream:
    """One streaming session pinned to its daemon (see
    :meth:`RoutedClient.stream_open`). Retains every appended delta's
    EDN so a node failure (or idle eviction) re-opens on the next
    ring node and REPLAYS — the final verdict is unchanged because a
    session's verdict is a pure function of the concatenated ops."""

    def __init__(self, routed: RoutedClient,
                 model: Optional[str] = None, keyed: bool = False,
                 rung: Optional[str] = None,
                 wl: Optional[dict] = None):
        self.routed = routed
        self.model = model
        self.keyed = keyed
        self.rung = rung
        self.wl = wl
        self._deltas: List[str] = []
        self.failovers = 0
        self.migrations = 0
        self.node: Optional[str] = None
        self.session: Optional[str] = None
        self._closed = False
        self._open_somewhere(
            routed.ring.nodes_for(f"stream|{model or ''}|"
                                  f"{id(self):x}"))

    def _client(self) -> ServiceClient:
        # prefer the PARTING table: a draining daemon is reachable
        # only over this retained warm connection (its listener is
        # closed), and if the same shard name has re-registered, the
        # fresh client in ``clients`` is a NEW process that does not
        # hold this session's carry
        c = (self.routed._parting.get(self.node)
             or self.routed.clients.get(self.node))
        if c is None:
            # the pinned daemon left the ring under a refresh
            raise OSError(f"session node {self.node!r} left the ring")
        return c

    def _open_somewhere(self, chain,
                        checkpoint: Optional[dict] = None) -> None:
        last: Optional[Exception] = None
        for name in chain:
            c = self.routed.clients.get(name)
            if c is None:
                continue
            try:
                r = c.stream_open(model=self.model, keyed=self.keyed,
                                  rung=self.rung, wl=self.wl,
                                  checkpoint=checkpoint)
                if self.node is not None:
                    self.routed._unpin(self.node)
                self.routed._pin(name)
                self.node = name
                self.session = r["session"]
                self.routed.served[name] = \
                    self.routed.served.get(name, 0) + 1
                return
            except (OSError, ServiceError) as e:
                last = e
        raise OSError(f"no daemon would open a stream session: {last}")

    def _migrate(self) -> bool:
        """The drain/leave handoff (docs/streaming.md "Checkpoint /
        migration"): fetch-AND-RELEASE the session's checkpoint from
        the departing daemon, re-open from it on the next ring node —
        O(carry) over the wire, zero device replay, dispatch count
        stays O(delta) afterward. Returns False when the old daemon
        can't serve the handoff (already dead) — the caller then
        falls back to retained-delta replay."""
        old = self.node
        try:
            r = self._client().stream_checkpoint(
                self.session, release=True, raise_on_error=False)
        except OSError:
            return False
        ck = r.get("checkpoint") if r.get("ok") else None
        if ck is None:
            return False
        self.routed.maybe_refresh(force=True)
        chain = [n for n in self.routed.ring.nodes_for(
            f"stream|{self.model or ''}|{id(self):x}")
            if n != old] or [n for n in self.routed.clients
                             if n != old]
        try:
            self._open_somewhere(chain, checkpoint=ck)
        except OSError:
            return False
        self.migrations += 1
        self.routed.migrations += 1
        return True

    def _failover(self) -> None:
        self.failovers += 1
        self.routed.failovers += 1
        self.routed.maybe_refresh(force=True)
        chain = [n for n in self.routed.ring.nodes_for(
            f"stream|{self.model or ''}|{id(self):x}")
            if n != self.node] or list(self.routed.clients)
        self._open_somewhere(chain)
        # replay the retained deltas ONE BY ONE in order: each delta
        # is a self-contained EDN document (vector-of-maps deltas
        # would mis-parse if concatenated into one text), and each
        # replay append is O(delta) anyway. A replay failure must
        # surface — continuing would silently verify a history with
        # the retained prefix missing.
        for d in self._deltas:
            r = self.routed.clients[self.node].stream_append(
                self.session, d, raise_on_error=False)
            if not r.get("ok"):
                raise OSError(
                    f"failover replay failed on {self.node}: {r}")

    def _pinned(self, fn, retried: bool = False):
        try:
            return fn(self._client())
        except OSError:
            if retried:
                raise
            self._failover()
            return self._pinned(fn, retried=True)

    def append(self, history: Union[str, List], **kw) -> dict:
        text = _as_edn(history)
        out = self._pinned(
            lambda c: c.stream_append(self.session, text,
                                      raise_on_error=False, **kw))
        if (not out.get("ok")
                and out.get("error") == protocol.SHUTDOWN):
            # the pinned daemon is draining: hand the session off by
            # checkpoint (O(carry)); only a daemon too dead to serve
            # the handoff costs the full retained-delta replay
            if not self._migrate():
                self._failover()
            out = self._pinned(
                lambda c: c.stream_append(self.session, text,
                                          raise_on_error=False, **kw))
        if (not out.get("ok")
                and out.get("error") == protocol.BAD_REQUEST
                and "unknown session" in out.get("message", "")):
            # aged fully out (checkpoint bound) on a live daemon:
            # same replay path as a dead node
            self._failover()
            out = self._pinned(
                lambda c: c.stream_append(self.session, text,
                                          raise_on_error=False, **kw))
        if out.get("ok") and out.get("cause") != "deadline":
            # a deadline expiry answers ok with cause="deadline" and
            # the delta was NEVER ingested (core._expire_one) — it
            # must not join the replay record as applied; the caller
            # sees the cause and may re-append the same delta
            self._deltas.append(text)
        return out

    def poll(self) -> dict:
        return self._pinned(
            lambda c: c.stream_poll(self.session,
                                    raise_on_error=False))

    def close(self) -> dict:
        try:
            out = self._pinned(
                lambda c: c.stream_close(self.session,
                                         raise_on_error=False))
        finally:
            # unpin even when the close request itself fails (dead
            # daemon, failover exhausted) — a leaked pin would park
            # the node's client in _parting forever on the next
            # refresh, with no remaining path that closes it
            self._deltas = []
            if not self._closed and self.node is not None:
                self.routed._unpin(self.node)
                self._closed = True
        return out


def _as_edn(history) -> str:
    if isinstance(history, str):
        return history
    from ..ops.history import history_to_edn

    return history_to_edn(list(history or []))


__all__ = ["HashRing", "RoutedClient", "RoutedStream",
           "ServiceClient", "ServiceError"]
