"""Wire protocol of the verifier service — newline-delimited JSON.

One request per line, one reply per line, same line-framing idiom as
the rest of the control plane (``control/pmux.py``'s pmux
conversation, ``workloads/tcp.py``'s SUT protocol): a reply that does
not end in ``\\n`` is truncated and must be treated as lost, never
parsed. JSON (not EDN) frames the envelope because every field is a
scalar; the history payload itself rides INSIDE the envelope as EDN
text — the exact format ``filetest`` reads and the native drivers
emit, so any persisted ``history.edn`` can be submitted unmodified.

Requests::

    {"op": "check", "id": 7, "history": "<EDN ops>",
     "model": "cas-register", "keyed": false, "deadline_ms": 5000}
    {"op": "status"}        {"op": "ping"}        {"op": "shutdown"}

Replies (``id`` echoed when given)::

    {"ok": true, "valid": true|false|"unknown", "op_index": -1,
     "engine": "keys", "bucket": "n64-s32-k2-p4", "batched": 17, ...}
    {"ok": false, "error": "overload" | "bad-request" | ...}

``valid`` is the checker tri-state: ``"unknown"`` carries a ``cause``
(``"deadline"``, ``"frontier overflow"``, ``"malformed"`` …) — the
reference's low-memory-abort contract, never a hang.
"""

from __future__ import annotations

import json
from typing import Optional, Union

# error codes (replies with {"ok": false, "error": <code>})
OVERLOAD = "overload"          # admission queue full — retry later
BAD_REQUEST = "bad-request"    # unparseable envelope or history
SHUTDOWN = "shutting-down"     # daemon is draining

#: ``valid`` values by engine status code (checker.linear_jax order)
STATUS_VALID = (True, False, "unknown")


def verdict(status: int) -> Union[bool, str]:
    """Engine status code -> the ``valid`` tri-state."""
    return STATUS_VALID[int(status)]


def encode(obj: dict) -> bytes:
    """One framed message. Compact separators: replies ride next to
    latency-sensitive traffic."""
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode()


def decode(line: Union[str, bytes]) -> dict:
    """Parse one request line; raises ``ValueError`` on garbage (the
    daemon answers ``bad-request`` instead of dying)."""
    if isinstance(line, bytes):
        line = line.decode("utf-8", errors="replace")
    try:
        obj = json.loads(line)
    except json.JSONDecodeError as e:
        raise ValueError(f"not JSON: {e}") from None
    if not isinstance(obj, dict):
        raise ValueError("request must be a JSON object")
    return obj


def error_reply(code: str, message: str = "",
                rid: Optional[object] = None) -> dict:
    out: dict = {"ok": False, "error": code}
    if message:
        out["message"] = message
    if rid is not None:
        out["id"] = rid
    return out


__all__ = ["OVERLOAD", "BAD_REQUEST", "SHUTDOWN", "STATUS_VALID",
           "verdict", "encode", "decode", "error_reply"]
