"""Shape bucketing for the verifier service.

XLA compiles one program per distinct input shape, and on this
hardware a cold compile costs seconds-to-minutes while a cached
dispatch costs microseconds — so the daemon must see a SMALL, CLOSED
set of shapes no matter what traffic arrives. Every admitted history
is quantized onto a bucket whose axes mirror what actually reaches
the jit boundaries in :func:`comdb2_tpu.checker.batch.check_batch`:

- ``n_pad``  — the op-stream pad (pow2, floor 16): the vmap engine's
  scan length.
- ``S``      — padded segment count (pow2, floor 8): the keys/flat
  engines' scan length and the streamed kernel's chunk budget.
- ``K``      — padded invokes-per-segment (pow2, floor 2).
- ``P``      — the slot-tensor width the engines compile for. This is
  the pow2 of the PROCESS-table size (what ``check_batch`` derives
  its ``P`` from), not the renamed-slot count — two histories with
  equal concurrency but different process counts would otherwise
  compile two programs.

The dispatcher additionally floors the memoized table sizes
(``n_states``/``n_transitions``) to pow2 per batch, so the packed key
field widths — the last shape-like input — are bucketed too.

Histories the bucket table can't serve cheaply (too long, too many
segments, invoke bursts past the kernel's K cap, concurrency past the
slot budget) are routed to the HOST engine instead of poisoning a
batch: one slow request degrades alone, exactly like the reference
wrapping per-key checker blowups in ``check-safe``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional

from ..ops.packed import PackedHistory
from ..utils import next_pow2 as _next_pow2


@dataclass(frozen=True)
class ServiceLimits:
    """Admission limits — anything beyond them degrades to the host
    engine (bounded there by ``max_host_configs``, so a pathological
    history answers ``unknown`` rather than wedging the tick loop)."""

    max_ops: int = 8192          # raw history rows
    max_segments: int = 4096     # ok-op segments (chunked-engine line)
    max_invokes_per_seg: int = 8  # the fused kernel's K cap
    max_slots: int = 16          # effective concurrency (P_eff)
    max_processes: int = 32      # raw process-table width
    max_txns: int = 4096         # txn-kind graph nodes (closure N)


class Bucket(NamedTuple):
    """One compiled-shape class; ``key`` names it in metrics/replies.
    ``P`` pins the XLA engines' slot width (process-table pow2);
    ``P_eff`` pins the fused stream kernel's renamed-slot spec — both
    must be in the bucket or the respective path recompiles per
    batch."""

    n_pad: int
    S: int
    K: int
    P: int
    P_eff: int

    @property
    def key(self) -> str:
        return (f"n{self.n_pad}-s{self.S}-k{self.K}-p{self.P}"
                f"-e{self.P_eff}")


def bucket_for(packed: PackedHistory,
               limits: ServiceLimits) -> Optional[Bucket]:
    """The bucket a packed history lands in, or None when it exceeds
    the limits (host-engine route). Raises ``ValueError`` on malformed
    histories (double-pending process — ``make_segments``' contract);
    the admission path answers those ``unknown``.

    The exact segment stream computed here is cached on ``packed``
    (``_segments_exact``) — the dispatch path's segment builders pad
    it to the bucket floors instead of re-running the O(total-ops)
    host pass (this container has ONE CPU; the pass would otherwise
    run twice per request)."""
    from ..checker import linear_jax as LJ

    segs = LJ.make_segments(packed)
    renamed, p_eff = LJ.remap_slots(segs)
    try:
        packed._segments_exact = segs
        # the slot renaming is determined by (inv_proc, ok_proc) alone
        # — identical whether it runs before or after transition-id
        # union remapping — so the dispatch path reuses these proc
        # arrays instead of re-running the O(ops) pass per request
        packed._remap_cache = (renamed.inv_proc, renamed.ok_proc,
                               p_eff)
    except AttributeError:
        pass                     # slotted/frozen variants: recompute
    S = segs.ok_proc.shape[0]
    K = segs.inv_proc.shape[1]
    n_procs = len(packed.process_table)
    if (len(packed) > limits.max_ops or S > limits.max_segments
            or K > limits.max_invokes_per_seg
            or p_eff > limits.max_slots
            or n_procs > limits.max_processes):
        return None
    # effective slots: even-bucket while that stays in the kernel's
    # (8,128) tier; past it use the exact count — a pad slot there can
    # cost a whole extra key word (same rule as the driver's P_k in
    # checker/linear.py)
    pe = max(p_eff + (p_eff & 1), 2)
    if pe > 7:
        pe = max(p_eff, 2)
    return Bucket(n_pad=_next_pow2(len(packed), 16),
                  S=_next_pow2(S, 8),
                  K=_next_pow2(K, 2),
                  P=_next_pow2(max(n_procs, 2), 2),
                  P_eff=pe)


class StreamBucket(NamedTuple):
    """One stream-session compiled-shape class: the slot key
    ``kind:"stream"`` appends coalesce under. ``cls`` is the
    session's :attr:`~comdb2_tpu.stream.session.StreamSession.
    shape_class` — rung, slot width, K bucket, table buckets — so
    same-shape sessions form batches together and share the
    ``stream-delta`` programs (PROGRAMS.md)."""

    cls: str

    @property
    def key(self) -> str:
        return self.cls


class WlBucket(NamedTuple):
    """One workload-family compiled-shape class (``kind:"wl"``,
    docs/workloads.md). ``sig`` holds the padded per-history axes as
    ``(letter, rung)`` pairs — exactly what reaches the family jit —
    and names the bucket in metrics. ``model_key`` pins the bank
    model CONTENT (frozen ``{"n","total","init"}``): one dispatch
    encodes the whole chunk against ONE model, so different-model
    requests must land in different slots — but the model is data,
    not shape, so it stays out of ``key`` (same program, same
    metrics row)."""

    family: str
    sig: tuple = ()
    model_key: tuple = ()

    @property
    def key(self) -> str:
        return "wl-" + self.family + "".join(
            f"-{a}{v}" for a, v in self.sig)


#: sig-letter -> the encode kwarg it pins (per family; letters are
#: unique within each family's dim set)
_WL_DIM_KEYS = {"r": "r_pad", "a": "a_pad", "t": "t_pad",
                "e": "e_pad", "n": "n_pad", "v": "v_pad"}


def wl_dims_of(bucket: "WlBucket") -> dict:
    """The encode kwargs a WlBucket pins (inverse of the sig)."""
    return {_WL_DIM_KEYS[a]: v for a, v in bucket.sig}


def wl_bucket_for(family: str, ops,
                  model: Optional[dict] = None) -> Optional["WlBucket"]:
    """The wl bucket one history lands in, or None when an axis
    exceeds its family's top rung (host-oracle route — one big
    history degrades alone, it never poisons a batch)."""
    from ..checker.wl.batch import wl_dims

    dims = wl_dims([ops], family, model)
    if dims is None:
        return None
    sig = tuple((k[0], v) for k, v in dims.items())
    mk = ()
    if family == "bank":
        from ..checker.workloads import freeze_value

        mk = freeze_value({k: model[k] for k in
                           ("n", "total", "init") if k in model})
    return WlBucket(family=family, sig=sig, model_key=mk)


class TxnBucket(NamedTuple):
    """One compiled-shape class of the txn closure engine: the only
    jit-visible axis is the padded txn count N (pow2, floor
    ``txn.edges.TXN_N_FLOOR``); the batch axis is pow2-padded at
    dispatch like the check kind's."""

    N: int

    @property
    def key(self) -> str:
        return f"txn-n{self.N}"


def txn_bucket_for(n_txns: int,
                   limits: ServiceLimits) -> Optional[TxnBucket]:
    """The closure bucket for an ``n_txns``-node dependency graph, or
    None past the limit (host-SCC route — one slow request degrades
    alone)."""
    from ..txn.edges import TXN_N_FLOOR

    if n_txns > limits.max_txns:
        return None
    return TxnBucket(N=_next_pow2(max(n_txns, 1), TXN_N_FLOOR))


__all__ = ["Bucket", "ServiceLimits", "StreamBucket", "TxnBucket",
           "WlBucket", "bucket_for", "txn_bucket_for",
           "wl_bucket_for", "wl_dims_of"]
