"""Fleet supervisor — spawn/retire verifier daemons from scrape data.

``python -m comdb2_tpu.service.supervisor`` keeps an elastic fleet of
pmux-registered daemons alive and right-sized (docs/service.md
"Elastic fleet"):

- **spawn**: each daemon registers as ``sut/verifier/<shard>`` and
  bumps the fleet's ring epoch — ``RoutedClient``s refresh and ~1/N
  of the shape classes remap onto the newcomer.
- **retire**: the supervisor sends ``kind:"drain"`` (the daemon
  deregisters first, re-routes queued work, finalizes staged
  dispatches, serves session-checkpoint handoffs through its grace
  window), escalates to SIGTERM (the same drain path), and only then
  SIGKILL — and always ``wait()``s the child: this container has no
  init reaper, so an unreaped daemon lingers as a zombie (CLAUDE.md).
- **autoscale**: the sizing signal is the scrape — fleet queue depth
  and completion (drain) rate as EWMAs, plus resident streaming
  sessions. :func:`desired_count` is the pure policy (unit-tested
  without sockets): scale up when the backlog's drain time exceeds
  ``up_backlog_s`` or the session tables near their cap, down when
  it undershoots ``down_backlog_s`` with session headroom.
- **crash cleanup**: a daemon that dies without draining (SIGKILL,
  OOM) left its pmux registration behind — clients would keep
  routing to it until a connect error. The supervisor deletes the
  stale entry, bumps the epoch, and respawns per policy.

Everything runs on one thread (one CPU — CLAUDE.md); the beat is a
poll loop, not a subprocess-per-metric scraper.
"""

from __future__ import annotations

import json
import logging
import os
import select
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..obs.trace import monotonic as _monotonic
from .daemon import PMUX_SERVICE, bump_ring_epoch

logger = logging.getLogger(__name__)


def desired_count(n: int, depth_ewma: float, drain_rate_ewma: float,
                  sessions: int, *, min_daemons: int = 1,
                  max_daemons: int = 4, up_backlog_s: float = 2.0,
                  down_backlog_s: float = 0.2,
                  session_headroom: float = 0.75,
                  max_sessions: int = 64) -> int:
    """The sizing policy, pure and unit-testable. ``depth_ewma`` is
    the fleet-wide admission queue depth, ``drain_rate_ewma`` the
    fleet completion rate (req/s), ``sessions`` the resident
    streaming sessions. Backlog seconds = depth / rate — the time the
    current queue needs to drain at the observed rate (the same
    quantity behind the daemon's ``retry_after_ms`` hint). One step
    at a time: the beat re-evaluates, so ramps converge without
    flapping."""
    rate = max(drain_rate_ewma, 1e-6)
    backlog_s = depth_ewma / rate if depth_ewma > 0 else 0.0
    cap = max(int(session_headroom * max_sessions * n), 1)
    if n < max_daemons and (backlog_s > up_backlog_s
                            or sessions >= cap):
        return n + 1
    if n > min_daemons and backlog_s < down_backlog_s \
            and sessions < int(session_headroom * max_sessions
                               * (n - 1)):
        return n - 1
    return n


def _client(port: int, timeout_s: float = 5.0):
    """One-shot daemon client (retries=0: the beat handles dead
    children itself — reuse the ONE wire implementation instead of a
    third hand-rolled socket path)."""
    from .client import ServiceClient

    return ServiceClient("127.0.0.1", port, timeout_s=timeout_s,
                         retries=0)


@dataclass
class Child:
    shard: int
    proc: subprocess.Popen
    port: int
    service: str
    t_spawn: float
    last_completed: int = 0
    stats: dict = field(default_factory=dict)


class Supervisor:
    """See module docstring. Drive :meth:`beat` yourself (tests) or
    :meth:`run` for the CLI loop."""

    def __init__(self, pmux_port: Optional[int] = None,
                 min_daemons: int = 1, max_daemons: int = 4,
                 daemon_args: Sequence[str] = (),
                 poll_s: float = 1.0,
                 drain_grace_s: float = 10.0,
                 scale_cooldown_s: float = 5.0,
                 up_backlog_s: float = 2.0,
                 down_backlog_s: float = 0.2,
                 max_sessions: int = 64,
                 ewma_alpha: float = 0.3,
                 spawn_timeout_s: float = 180.0,
                 prefix: str = PMUX_SERVICE):
        self.pmux_port = pmux_port
        self.min_daemons = int(min_daemons)
        self.max_daemons = int(max_daemons)
        self.daemon_args = list(daemon_args)
        self.poll_s = float(poll_s)
        self.drain_grace_s = float(drain_grace_s)
        self.scale_cooldown_s = float(scale_cooldown_s)
        self.up_backlog_s = float(up_backlog_s)
        self.down_backlog_s = float(down_backlog_s)
        self.max_sessions = int(max_sessions)
        self.ewma_alpha = float(ewma_alpha)
        #: cap on the wait for a child's ready line (generous: boot
        #: primes the compile cache, and cold compiles take minutes —
        #: CLAUDE.md); without it one wedged child blocks the whole
        #: single-threaded beat, so nothing gets reaped or refilled
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.prefix = prefix
        self.children: Dict[int, Child] = {}
        self._next_shard = 0
        self._stop = False
        self._t_scaled = float("-inf")
        self._t_last_beat: Optional[float] = None
        self.depth_ewma = 0.0
        self.drain_rate_ewma = 0.0
        # counters for status/tests
        self.spawned = 0
        self.retired = 0
        self.deaths = 0
        self.stale_cleanups = 0

    # -- lifecycle -----------------------------------------------------

    def spawn(self) -> Child:
        """Start one daemon on the next shard index and wait for its
        ready line (ready means pmux-registered — the epoch already
        bumped, clients already see it)."""
        shard = self._next_shard
        self._next_shard += 1
        service = f"{self.prefix}/{shard}"
        cmd = [sys.executable, "-m", "comdb2_tpu.service",
               "--port", "0", "--drain-s", str(self.drain_grace_s),
               *self.daemon_args]
        if self.pmux_port is not None:
            cmd += ["--pmux", str(self.pmux_port),
                    "--pmux-shard", str(shard)]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=dict(os.environ))
        ready_fds, _, _ = select.select([proc.stdout], [], [],
                                        self.spawn_timeout_s)
        if not ready_fds:
            proc.kill()
            proc.wait(timeout=30)
            raise OSError(f"daemon {shard} produced no ready line "
                          f"within {self.spawn_timeout_s:.0f}s")
        line = proc.stdout.readline()
        try:
            ready = json.loads(line)
        except json.JSONDecodeError:
            proc.kill()
            proc.wait(timeout=30)
            raise OSError(f"daemon {shard} never became ready: "
                          f"{line!r}")
        if not ready.get("ready"):
            proc.kill()
            proc.wait(timeout=30)
            raise OSError(f"daemon {shard} not ready: {ready}")
        child = Child(shard=shard, proc=proc, port=ready["port"],
                      service=service, t_spawn=_monotonic())
        self.children[shard] = child
        self.spawned += 1
        logger.info("spawned %s on port %d (pid %d)", service,
                    child.port, proc.pid)
        return child

    def retire(self, shard: int) -> None:
        """Drain-then-stop one daemon, and ALWAYS reap it: drain verb
        (graceful leave — deregistration, re-routes, checkpoint
        handoffs), SIGTERM escalation (same drain path in-process),
        SIGKILL as the last resort. ``wait()`` runs in every branch —
        a retired child must never outlive this call as a zombie."""
        child = self.children.pop(shard, None)
        if child is None:
            return
        try:
            with _client(child.port) as c:
                c.drain(raise_on_error=False)
        except (OSError, ValueError):
            pass
        try:
            child.proc.wait(timeout=self.drain_grace_s + 5.0)
        except subprocess.TimeoutExpired:
            child.proc.terminate()          # SIGTERM: the drain path
            try:
                child.proc.wait(timeout=self.drain_grace_s + 5.0)
            except subprocess.TimeoutExpired:
                child.proc.kill()
                child.proc.wait(timeout=30)
        self.retired += 1
        if child.proc.returncode not in (0, -signal.SIGKILL):
            logger.warning("%s exited %s", child.service,
                           child.proc.returncode)

    def shutdown(self) -> None:
        """Retire everything (largest shard first) and reap."""
        for shard in sorted(self.children, reverse=True):
            self.retire(shard)

    # -- the beat ------------------------------------------------------

    def _reap_and_respawn(self) -> None:
        """A child that died on its own (crash, SIGKILL nemesis) is
        reaped here (``poll()`` collects the zombie), its stale pmux
        registration deleted (+ epoch bump — clients must stop
        routing to a corpse), and the fleet refilled to the floor."""
        for shard, child in list(self.children.items()):
            if child.proc.poll() is None:
                continue
            self.children.pop(shard)
            self.deaths += 1
            logger.warning("%s died (exit %s)", child.service,
                           child.proc.returncode)
            self._cleanup_stale(child.service)
        while len(self.children) < self.min_daemons and not self._stop:
            try:
                self.spawn()
            except OSError as e:
                # a failed respawn must not escape the beat: run()'s
                # finally would retire the HEALTHY daemons too,
                # turning one wedged child into a fleet outage. Leave
                # the floor short; the next beat retries.
                logger.warning("respawn failed: %s (retry next beat)",
                               e)
                break

    def _cleanup_stale(self, service: str) -> None:
        if self.pmux_port is None:
            return
        from ..control.pmux import PmuxClient

        try:
            with PmuxClient(port=self.pmux_port) as c:
                if c.delete(service):
                    self.stale_cleanups += 1
                bump_ring_epoch(c, service)
        except OSError as e:
            logger.warning("stale-entry cleanup failed: %s", e)

    def scrape(self) -> List[dict]:
        """Per-child status (skipping the unreachable — their reaping
        is :meth:`_reap_and_respawn`'s job)."""
        out = []
        for child in self.children.values():
            try:
                with _client(child.port) as c:
                    st = c.status()["status"]
            except (OSError, ValueError, KeyError):
                continue
            child.stats = st
            out.append(st)
        return out

    def beat(self, now: Optional[float] = None) -> dict:
        """One supervision round: reap/respawn, scrape, update EWMAs,
        apply the policy (cooldown-limited). Returns a summary for
        logs/tests."""
        now = _monotonic() if now is None else now
        self._reap_and_respawn()
        stats = self.scrape()
        depth = float(sum(s.get("queue_depth", 0) for s in stats))
        sessions = sum(s.get("stream", {}).get("sessions", 0)
                       for s in stats)
        rate = 0.0
        dt = (now - self._t_last_beat) if self._t_last_beat else None
        if dt and dt > 0:
            done = 0
            for child in self.children.values():
                cur = child.stats.get("completed", 0)
                done += max(cur - child.last_completed, 0)
                child.last_completed = cur
            rate = done / dt
        else:
            for child in self.children.values():
                child.last_completed = child.stats.get("completed", 0)
        self._t_last_beat = now
        a = self.ewma_alpha
        self.depth_ewma = (1 - a) * self.depth_ewma + a * depth
        if dt:
            self.drain_rate_ewma = ((1 - a) * self.drain_rate_ewma
                                    + a * rate)
        want = desired_count(
            len(self.children), self.depth_ewma,
            self.drain_rate_ewma, sessions,
            min_daemons=self.min_daemons,
            max_daemons=self.max_daemons,
            up_backlog_s=self.up_backlog_s,
            down_backlog_s=self.down_backlog_s,
            max_sessions=self.max_sessions)
        acted = None
        if want != len(self.children) \
                and now - self._t_scaled >= self.scale_cooldown_s:
            self._t_scaled = now
            if want > len(self.children):
                try:
                    self.spawn()
                    acted = "spawn"
                except OSError as e:
                    # cooldown already stamped — no hot retry loop
                    logger.warning("scale-up spawn failed: %s", e)
            else:
                # retire the newest shard with the fewest resident
                # sessions — the cheapest handoff
                shard = min(
                    self.children,
                    key=lambda i: (self.children[i].stats
                                   .get("stream", {})
                                   .get("sessions", 0), -i))
                self.retire(shard)
                acted = "retire"
        return {"daemons": len(self.children),
                "depth_ewma": round(self.depth_ewma, 3),
                "drain_rate_ewma": round(self.drain_rate_ewma, 3),
                "sessions": sessions, "action": acted,
                "deaths": self.deaths, "spawned": self.spawned,
                "retired": self.retired}

    def run(self, initial: Optional[int] = None) -> int:
        """The CLI loop: boot ``initial`` daemons (default
        ``min_daemons``), beat until signalled, drain the fleet on
        the way out."""
        for _ in range(initial if initial is not None
                       else self.min_daemons):
            self.spawn()
        print(json.dumps({
            "ready": True, "supervisor": True,
            "pmux_port": self.pmux_port,
            "daemons": {c.shard: c.port
                        for c in self.children.values()}}),
            flush=True)
        try:
            while not self._stop:
                summary = self.beat()
                if summary["action"]:
                    logger.info("beat: %s", summary)
                time.sleep(self.poll_s)
        finally:
            self.shutdown()
        return 0

    def stop(self, *_args) -> None:
        self._stop = True


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m comdb2_tpu.service.supervisor",
        description="elastic verifier-fleet supervisor "
                    "(docs/service.md \"Elastic fleet\"); arguments "
                    "after -- pass through to every daemon")
    p.add_argument("--pmux", type=int, nargs="?", const=5105,
                   default=None, metavar="PORT",
                   help="ct_pmux port the fleet registers under "
                        "(default 5105 when given bare); without it "
                        "daemons run unregistered (no routing)")
    p.add_argument("--n", type=int, default=None,
                   help="initial fleet size (default: --min)")
    p.add_argument("--min", type=int, default=1, dest="min_daemons")
    p.add_argument("--max", type=int, default=4, dest="max_daemons")
    p.add_argument("--poll-s", type=float, default=1.0)
    p.add_argument("--drain-s", type=float, default=10.0)
    p.add_argument("--up-backlog-s", type=float, default=2.0,
                   help="scale up when queue-drain time exceeds this")
    p.add_argument("--down-backlog-s", type=float, default=0.2)
    p.add_argument("--max-sessions", type=int, default=64,
                   help="per-daemon session cap (the session-pressure "
                        "term of the policy; pass the same value to "
                        "the daemons after --)")
    argv = list(sys.argv[1:] if argv is None else argv)
    daemon_args: List[str] = []
    if "--" in argv:
        i = argv.index("--")
        argv, daemon_args = argv[:i], argv[i + 1:]
    args = p.parse_args(argv)
    sup = Supervisor(pmux_port=args.pmux,
                     min_daemons=args.min_daemons,
                     max_daemons=args.max_daemons,
                     daemon_args=daemon_args,
                     poll_s=args.poll_s,
                     drain_grace_s=args.drain_s,
                     up_backlog_s=args.up_backlog_s,
                     down_backlog_s=args.down_backlog_s,
                     max_sessions=args.max_sessions)
    signal.signal(signal.SIGTERM, sup.stop)
    signal.signal(signal.SIGINT, sup.stop)
    return sup.run(initial=args.n)


if __name__ == "__main__":
    sys.exit(main())


__all__ = ["Child", "Supervisor", "desired_count", "main"]
