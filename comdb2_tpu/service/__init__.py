"""``comdb2_tpu.service`` — the verification serving layer.

The checker's hot path amortizes only when many histories ride one
device dispatch (~100 ms tunnel round-trip per dispatch; 1.5k ops/s
per-item vs 93k streamed — CLAUDE.md), but every caller used to drive
it one history at a time. This package is the layer that exploits the
batch entry points (:mod:`comdb2_tpu.checker.batch`) as a persistent
daemon:

- :mod:`.protocol`   — newline-JSON framing over TCP.
- :mod:`.bucketing`  — shape quantization: a small closed set of
  compiled programs no matter what traffic arrives.
- :mod:`.core`       — continuous-batching admission (slot-filling
  launches, the bounded in-flight ring, donated carries),
  backpressure/deadlines, host-engine degradation, metrics.
- :mod:`.daemon`     — the selector/pump loop; ``python -m
  comdb2_tpu.service`` runs it (pmux discovery, store artifacts).
- :mod:`.client`     — retrying client with overload backoff, plus
  the consistent-hash :class:`~.client.RoutedClient` over a
  pmux-discovered fleet; ``filetest --service`` uses the former.
- :mod:`.sharding`   — device meshes + sharded batch checking (the
  former ``comdb2_tpu.parallel``).
"""

from .bucketing import Bucket, ServiceLimits, bucket_for     # noqa: F401
from .core import DEFAULT_PRIME, VerifierCore                # noqa: F401

__all__ = ["Bucket", "DEFAULT_PRIME", "ServiceLimits",
           "VerifierCore", "bucket_for"]
