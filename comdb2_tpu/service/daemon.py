"""The verifier daemon — a single-threaded selector loop over
:class:`~.core.VerifierCore`.

Continuous-batching loop (round 9): every selector round pumps the
core — requests slot into their buckets as the bytes arrive, a full
batch launches inside ``submit`` itself, and the pump launches
whatever bucket's launch budget came due. A quiet round (no bytes)
pumps with ``idle=True``: every forming batch launches and the
in-flight ring drains, so a lone serial caller is answered
immediately instead of paying the fill window. Device dispatches are
STAGED on this thread and finalize through the core's bounded ring —
the overlap is host-pack vs async device compute (one CPU; never
multiprocessing).

Discovery: with ``--pmux``, the daemon publishes its port under
``sut/verifier`` (or ``sut/verifier/<shard>`` for a horizontally
scaled fleet — ``--pmux-shard``) through the same ``ct_pmux`` path
the native SUT uses (``control/pmux.py``); clients then resolve the
service by name, and :class:`~.client.RoutedClient` consistent-hash
routes over every registered daemon.

Observability: ``{"op": "status"}`` returns the status JSON on the
same socket and ``{"op": "metrics"}`` (or ``kind:"metrics"`` on the
check op) scrapes the metrics plane (Prometheus text + JSON forms —
docs/observability.md); with ``--store`` the status snapshot is
persisted through :func:`comdb2_tpu.harness.store.
save_service_status` on every artifact interval and at shutdown,
alongside ``timeline.svg`` (the per-run latency/rate timeline) and —
with ``--trace`` — ``trace.json`` (Chrome/Perfetto span export),
where the store web browser serves them next to test runs.
"""

from __future__ import annotations

import logging
import os
import selectors
import socket
from typing import Dict, Optional

from ..obs import trace as obs
from . import protocol
from .core import VerifierCore

logger = logging.getLogger(__name__)

PMUX_SERVICE = "sut/verifier"


def epoch_service_for(service: str) -> str:
    """The fleet's ring-version entry in pmux, derived from a daemon's
    service name: ``sut/verifier`` and every ``sut/verifier/<shard>``
    share ``sut/verifier.epoch``. A ``.``-suffixed sibling on purpose —
    ``RoutedClient.discover`` matches ``<prefix>`` or ``<prefix>/...``,
    so the epoch entry never masquerades as a daemon endpoint."""
    base, sep, tail = service.rpartition("/")
    if sep and tail.isdigit():
        service = base
    return service + ".epoch"


def bump_ring_epoch(pmux, service: str) -> int:
    """Read-increment-publish the ring version (every membership
    change — join, leave, drain, crash cleanup — bumps it; clients
    poll the single entry instead of re-listing the registry). The
    RMW is unlocked: concurrent bumps may collapse into one, which is
    fine — clients only need the value to CHANGE, and a refresh reads
    the full registry anyway."""
    svc = epoch_service_for(service)
    cur = int(pmux.get(svc) or 0)
    # pmux rejects a value already published as another service's
    # PORT (epoch rides the port slot of its entry) — skip over
    # collisions; any strictly larger value is a valid bump
    for nxt in range(cur + 1, cur + 17):
        try:
            pmux.use(svc, nxt)
            return nxt
        except OSError:
            continue
    raise OSError(f"could not bump {svc} past {cur}")


class _Conn:
    __slots__ = ("sock", "addr", "rbuf")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.rbuf = b""


class VerifierDaemon:
    """One listening socket, N client connections, one tick loop."""

    def __init__(self, core: VerifierCore, host: str = "127.0.0.1",
                 port: int = 0, coalesce_s: Optional[float] = None,
                 pmux_port: Optional[int] = None,
                 pmux_service: str = PMUX_SERVICE,
                 store_root: Optional[str] = None,
                 artifact_interval_s: float = 30.0,
                 drain_grace_s: float = 10.0):
        self.core = core
        if coalesce_s is not None:
            # legacy knob: the coalesce window is now the core's
            # per-bucket fill window (a cap on batch formation, not a
            # tick round)
            core.fill_window_s = max(float(coalesce_s), 0.0)
        self.pmux_port = pmux_port
        self.pmux_service = pmux_service
        self.store_root = store_root
        self.artifact_interval_s = artifact_interval_s
        #: after drain entry, how long to keep serving session
        #: handoffs (checkpoint fetches) before closing anyway
        self.drain_grace_s = float(drain_grace_s)
        self._stop = False
        self._draining = False
        self._drain_req = False
        self._drain_deadline = 0.0
        self._published = False
        self._dropped_replies = 0
        self._sel = selectors.DefaultSelector()
        self._conns: Dict[int, _Conn] = {}
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind((host, port))
        lsock.listen(128)
        lsock.setblocking(False)
        self._lsock = lsock
        self.host, self.port = lsock.getsockname()
        self._sel.register(lsock, selectors.EVENT_READ, None)

    # -- lifecycle -----------------------------------------------------

    @property
    def published(self) -> bool:
        """Whether the pmux registration actually happened (the ready
        line reports ``pmux_service`` null when it did not)."""
        return self._published

    def stop(self, *_args) -> None:
        self._stop = True

    def drain(self, *_args) -> None:
        """Request a graceful leave (SIGTERM lands here): deregister
        from pmux and bump the ring epoch BEFORE anything closes,
        re-route queued work, finalize staged dispatches, keep serving
        session-checkpoint handoffs for ``drain_grace_s``, then exit.
        Signal-safe — only sets a flag; the run loop does the work."""
        self._drain_req = True

    def run(self) -> None:
        self._pmux_publish()
        last_artifact = obs.monotonic()
        try:
            while not self._stop:
                timeout = self._select_timeout()
                got_bytes = self._pump(timeout)
                now = obs.monotonic()
                if (self._drain_req or self.core.draining) \
                        and not self._draining:
                    self._begin_drain(now)
                # the scheduler beat: launch due buckets; on a quiet
                # round (no new bytes) launch everything forming and
                # drain the in-flight ring — serial callers never
                # wait out the fill window
                for p, reply in self.core.pump(now,
                                               idle=not got_bytes):
                    self._send(p.ctx, reply)
                if self._draining and self.core.drained() and \
                        ((len(self.core.sessions) == 0
                          and self.core.sessions.checkpoint_count()
                          == 0)
                         or now >= self._drain_deadline):
                    # idle-EVICTED sessions hold the daemon through
                    # the grace too: their host checkpoints are what
                    # the handoff serves — exiting on resident==0
                    # alone would discard them and cost the client a
                    # full retained-delta replay
                    self._stop = True
                if self.store_root is not None and \
                        now - last_artifact >= self.artifact_interval_s:
                    self._save_artifact()
                    last_artifact = now
        finally:
            self._shutdown()

    def _begin_drain(self, now: float) -> None:
        """Drain entry ordering is the whole contract (the stale-
        registration bug): DEREGISTER (+ epoch bump) first — so no
        client routes new work here — then stop accepting connections,
        then re-route the queued work. The listener closes while
        existing connections stay open: clients must be able to fetch
        their sessions' checkpoints through the grace window."""
        self._draining = True
        self._drain_deadline = now + self.drain_grace_s
        self._pmux_withdraw()
        try:
            self._sel.unregister(self._lsock)
        except (KeyError, ValueError):
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        self.core.begin_drain(now)
        logger.info("draining: grace %.1fs, %d session(s) resident",
                    self.drain_grace_s, len(self.core.sessions))

    #: with work queued (forming batches, host/shrink work, staged
    #: dispatches), select() sleeps at most this long — the pump then
    #: sees either new bytes (keep filling) or a quiet round (launch +
    #: drain)
    IDLE_PROBE_S = 0.001

    def _select_timeout(self) -> Optional[float]:
        if self.core.queue_depth() or self.core.inflight():
            nxt = self.core.next_event_at()
            if nxt is not None:
                return min(max(nxt - obs.monotonic(), 0.0),
                           self.IDLE_PROBE_S)
            return self.IDLE_PROBE_S
        return 0.5

    # -- socket plumbing -----------------------------------------------

    def _pump(self, timeout: Optional[float]) -> bool:
        """One select round; returns whether any payload arrived."""
        got = False
        for key, _ in self._sel.select(timeout):
            if key.data is None:
                self._accept()
                continue
            got |= self._read(key.data)
        return got

    def _accept(self) -> None:
        try:
            sock, addr = self._lsock.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = _Conn(sock, addr)
        self._conns[sock.fileno()] = conn
        self._sel.register(sock, selectors.EVENT_READ, conn)

    def _read(self, conn: _Conn) -> bool:
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return False
        except OSError:
            self._close(conn)
            return False
        if not data:
            self._close(conn)
            return False
        conn.rbuf += data
        while b"\n" in conn.rbuf:
            line, conn.rbuf = conn.rbuf.split(b"\n", 1)
            if line.strip():
                self._handle(conn, line)
        return True

    def _close(self, conn: _Conn) -> None:
        """A client vanished — mid-request is fine: its pending reply
        is dropped at send time, the batch runs regardless."""
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.sock.close()
        except OSError:
            pass

    #: per-reply send bound: client sockets are non-blocking for the
    #: selector reads, and sendall() on a non-blocking socket raises
    #: BlockingIOError the moment the kernel buffer fills (a pipelined
    #: client slow to read) — a live client's replies would be dropped
    #: mid-stream. A temporary timeout makes the send blocking-with-
    #: bound instead; a client that can't drain a small reply within
    #: it is treated as gone.
    SEND_TIMEOUT_S = 5.0

    def _send(self, conn: Optional[_Conn], obj: dict) -> None:
        if conn is None or conn.sock.fileno() < 0:
            self._dropped_replies += 1
            return
        try:
            conn.sock.settimeout(self.SEND_TIMEOUT_S)
            try:
                conn.sock.sendall(protocol.encode(obj))
            finally:
                conn.sock.settimeout(0)     # back to non-blocking
        except OSError:
            self._dropped_replies += 1
            self._close(conn)

    # -- requests ------------------------------------------------------

    def _handle(self, conn: _Conn, line: bytes) -> None:
        try:
            req = protocol.decode(line)
        except ValueError as e:
            self._send(conn, protocol.error_reply(
                protocol.BAD_REQUEST, str(e)))
            return
        op = req.get("op")
        now = obs.monotonic()
        if op == "check":
            try:
                pending, reply = self.core.submit(req, now, ctx=conn)
            except Exception as e:          # noqa: BLE001 — client data
                # belt-and-braces: a request that slips past submit's
                # validation must never tear down the shared daemon
                reply = protocol.error_reply(
                    protocol.BAD_REQUEST,
                    f"{type(e).__name__}: {e}", req.get("id"))
            if reply is not None:
                self._send(conn, reply)
            return
        rid = req.get("id")
        if op == "status":
            st = self.core.status(now)
            st["dropped_replies"] = self._dropped_replies
            st["connections"] = len(self._conns)
            out = {"ok": True, "status": st}
            if rid is not None:
                out["id"] = rid
            self._send(conn, out)
        elif op == "metrics":
            # alias of kind:"metrics" — same reply, scrape-friendly
            self._send(conn, self.core.metrics_reply(rid))
        elif op == "ping":
            self._send(conn, {"ok": True, "pong": True,
                              **({"id": rid} if rid is not None
                                 else {})})
        elif op == "shutdown":
            self._send(conn, {"ok": True, "bye": True,
                              **({"id": rid} if rid is not None
                                 else {})})
            self._stop = True
        else:
            self._send(conn, protocol.error_reply(
                protocol.BAD_REQUEST, f"unknown op {op!r}", rid))

    # -- discovery / artifacts -----------------------------------------

    def _pmux_publish(self) -> None:
        """Idempotent: ``__main__`` publishes BEFORE printing the
        ready line (ready must mean discoverable — a fleet booter
        races discovery against it), ``run()`` keeps the call for
        embedders driving the daemon directly."""
        if self.pmux_port is None or self._published:
            return
        from ..control.pmux import PmuxClient

        try:
            with PmuxClient(port=self.pmux_port) as c:
                c.use(self.pmux_service, self.port)
                # the registration is LIVE from here: mark published
                # BEFORE the epoch bump, or a bump failure would
                # leave _published False and _pmux_withdraw would
                # never delete the live entry — a permanently stale
                # registration, the exact bug drain ordering fixes
                self._published = True
                # a join is a membership change: bump the ring
                # version so RoutedClients refresh (~1/N of the
                # shape classes remap onto this daemon)
                self.core.ring_epoch = bump_ring_epoch(
                    c, self.pmux_service)
            logger.info("published %s -> %d via pmux:%d (epoch %d)",
                        self.pmux_service, self.port, self.pmux_port,
                        self.core.ring_epoch)
        except OSError as e:
            # discovery is additive; a dead pmux must not stop serving
            logger.warning("pmux %s failed: %s",
                           "epoch bump" if self._published
                           else "registration", e)

    def _pmux_withdraw(self) -> None:
        """Deregister + bump the ring epoch — the leave-side
        membership change. Idempotent (drain runs it early; shutdown
        runs it again)."""
        if self.pmux_port is None or not self._published:
            return
        self._published = False
        from ..control.pmux import PmuxClient

        try:
            with PmuxClient(port=self.pmux_port) as c:
                c.delete(self.pmux_service)
                self.core.ring_epoch = bump_ring_epoch(
                    c, self.pmux_service)
        except OSError:
            pass

    def _save_artifact(self) -> None:
        from ..harness.store import save_service_status

        try:
            save_service_status(self.core.status(),
                                store_root=self.store_root)
        except OSError as e:
            logger.warning("service artifact write failed: %s", e)
        self._save_obs()

    def _save_obs(self) -> None:
        """The observability artifacts next to the status snapshot:
        ``trace.json`` (Chrome/Perfetto trace-event export — only
        when tracing is enabled) and ``timeline.svg`` (the per-run
        latency/rate timeline), both under ``<store>/service/`` where
        the store web index links them."""
        d = os.path.join(self.store_root, "service")
        try:
            os.makedirs(d, exist_ok=True)
            if obs.enabled():
                obs.export_chrome(os.path.join(d, "trace.json"))
            records, events = self.core.timeline_records()
            if records:
                from ..report.service_svg import \
                    render_service_timeline

                render_service_timeline(
                    records, events,
                    path=os.path.join(d, "timeline.svg"))
        except OSError as e:
            logger.warning("obs artifact write failed: %s", e)

    def _shutdown(self) -> None:
        """Answer nothing new, flush queued requests as unknown, close
        every socket — a clean exit, never a hang with clients blocked
        on reads."""
        # withdraw FIRST: clients re-route on the epoch bump, so the
        # ring must stop advertising this node before its listener
        # starts refusing connects (rule deregister-before-close)
        self._pmux_withdraw()
        for p, reply in self.core.tick(obs.monotonic()):
            self._send(p.ctx, reply)
        for conn in list(self._conns.values()):
            self._close(conn)
        try:
            self._sel.unregister(self._lsock)
        except (KeyError, ValueError):
            pass
        self._lsock.close()
        self._sel.close()
        if self.store_root is not None:
            self._save_artifact()


__all__ = ["PMUX_SERVICE", "VerifierDaemon", "bump_ring_epoch",
           "epoch_service_for"]
