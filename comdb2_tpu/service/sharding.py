"""Device meshes and the shard-placement axis of the serving layer.

One daemon feeds N chips: the tick loop's bucketed batches gain a
shard axis — every dispatch fills ``D`` shard slots per bucket
(``VerifierCore(shards=D)`` pads the batch axis to a pow2 multiple of
D), ``check_batch``/``closure_diag_batch`` shard_map the batch axis
over the mesh (the fused Pallas kernel / closure matmul as the
per-shard body, zero cross-shard collectives), and the metrics report
per-shard occupancy. Histories are packed on host and shipped to
device once per dispatch; independent keys/histories shard across ICI
as pure data parallelism (each shard checks whole (sub)histories);
multi-host DCN only shards more histories. ``shards=1`` (the default)
is the single-device path, bit-identical and mesh-free.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

#: declared ceiling of the shard-placement axis — the compile-surface
#: inventory's mesh_D ladder tops out here (a pod slice is 256 chips;
#: one daemon feeding more than 64 is a new deployment shape, widen
#: deliberately)
MAX_SHARDS = 64


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch"):
    """A 1-D device mesh over the first n devices (all by default).
    Asking for more devices than the platform exposes is an error,
    not a silently smaller mesh."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} shards but only {len(devs)} "
                "device(s) are visible")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def shard_fill(n_live: int, b_prog: int, D: int) -> List[float]:
    """Per-shard occupancy of one dispatch: live (non-padding)
    histories land contiguously (shard d owns rows
    ``[d*g, (d+1)*g)``, g = b_prog/D — the ``plan_shard_slices``
    layout), so shard d's fill is the clamped overlap with the first
    ``n_live`` rows. Pure host arithmetic for the metrics; sums to
    ``n_live / g``."""
    g = max(b_prog // max(D, 1), 1)
    return [min(max(n_live - d * g, 0), g) / g for d in range(D)]


def check_histories_sharded(histories, model, mesh=None, F: int = 256,
                            axis: str = "batch"):
    """Check many independent histories with the batch axis sharded
    over a mesh; returns (status, fail_at, n_final) NumPy arrays.
    Builds the mesh over all local devices when none is given.
    ``check_batch`` pads the batch axis to a pow2 multiple of the mesh
    size with SENTINEL histories (excluded from verdicts — no real
    history is checked twice)."""
    from ..checker.batch import check_batch, pack_batch

    histories = list(histories)
    n = len(histories)
    if n == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int64),
                np.zeros(0, np.int32))
    mesh = mesh if mesh is not None else make_mesh(axis=axis)
    batch = pack_batch(histories, model)
    return check_batch(batch, F=F, mesh=mesh, batch_axis=axis)


__all__ = ["MAX_SHARDS", "check_histories_sharded", "make_mesh",
           "shard_fill"]
