"""Device meshes and sharded checking — the distributed execution
surface of the service layer (moved here from the former
``comdb2_tpu.parallel`` stub when the serving subsystem grew around
it; that name remains as a deprecation shim).

Histories are packed on host and shipped to device once per analysis;
independent keys/histories shard across ICI as pure data parallelism
(each device checks whole (sub)histories — no intra-search
communication); multi-host DCN only shards more histories. The
verifier daemon (:mod:`.daemon`) can hand a mesh-backed
``check_batch`` the same bucketed batches it builds for one chip.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch"):
    """A 1-D device mesh over the first n devices (all by default)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def check_histories_sharded(histories, model, mesh=None, F: int = 256,
                            axis: str = "batch"):
    """Check many independent histories with the batch axis sharded
    over a mesh; returns (status, fail_at, n_final) NumPy arrays.
    Builds the mesh over all local devices when none is given."""
    from ..checker.batch import check_batch, pack_batch

    histories = list(histories)
    n = len(histories)
    if n == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int64),
                np.zeros(0, np.int32))
    mesh = mesh if mesh is not None else make_mesh(axis=axis)
    # the batch axis must divide evenly across mesh devices; pad with
    # copies of the first history and slice the results back
    n_dev = mesh.devices.size
    pad = (-n) % n_dev
    batch = pack_batch(histories + [histories[0]] * pad, model)
    status, fail_at, n_final = check_batch(batch, F=F, mesh=mesh,
                                           batch_axis=axis)
    return status[:n], fail_at[:n], n_final[:n]


__all__ = ["make_mesh", "check_histories_sharded"]
