"""Verifier-service core — continuous-batching admission and fan-out.

Transport-independent on purpose: :class:`VerifierCore` owns the
admission slots, the in-flight dispatch ring and the metrics; the
TCP daemon (:mod:`.daemon`) is a thin selector loop over it and the
unit tests drive it directly. Everything runs on ONE thread — this
container exposes a single CPU; the overlap the ring buys is
host-compute vs device-compute (JAX dispatch is async), never
multiprocessing.

Admission is inference-server-style continuous batching (the round-9
rework; the tick-round coalescer it replaced queued a 64-request
burst behind per-tick drains and measured a 4.8 s queue-wait p99
against a 7.6 ms p50):

1. ``submit`` — backpressure first (queue at cap answers ``overload``
   with a ``retry_after_ms`` hint before any parsing work), then EDN
   parse + pack + bucket assignment. Trivial histories and malformed
   ones answer immediately; everything else is slotted into its
   bucket's forming batch. A batch that reaches the cap launches
   RIGHT THERE (``launch_full``) — no waiting for a tick round.
2. ``pump`` — the scheduler beat the daemon runs every selector
   round: expire deadline-passed requests, launch every bucket whose
   oldest request's deadline-derived launch budget expired
   (``launch_deadline``) or — on an idle round — that has any
   requests at all (``launch_idle``, so a lone serial caller never
   waits out the fill window). Launched dispatches are STAGED into a
   bounded in-flight ring (N >= 3 buckets staged/running/finalizing
   concurrently — the PR-4 stage/finalize seam generalized past the
   two-bucket double buffer); the ring finalizes oldest-first on
   overflow and drains on idle.
3. Requests whose shape exceeds the bucket table degrade to the HOST
   engine one by one; shrink jobs advance one candidate-capped ddmin
   round per pump and re-queue.

``tick`` survives as the flush form of ``pump`` (idle semantics:
launch everything, drain the ring) — priming, shutdown and the unit
tests drive it.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..obs import trace as obs
from ..obs.metrics import Registry
from ..utils import next_pow2 as _next_pow2
from . import protocol
from .bucketing import (Bucket, ServiceLimits, StreamBucket,
                        TxnBucket, WlBucket, bucket_for,
                        txn_bucket_for, wl_bucket_for, wl_dims_of)

#: the per-request stage names (docs/observability.md): they TILE the
#: measured wall per request — queue_wait (admission -> dispatch
#: begin), host_pack (columnar pack/segment/remap + stage), device
#: (dispatch -> readback complete, including the async overlap window
#: and any injected tunnel latency), finalize (readback -> reply) —
#: so scripts/bench_service.py can assert the sum against latency_ms.
#: EVERY completed request observes all four (absent stages count as
#: 0 — deadline expiries are pure queue wait), so the four histograms
#: and the latency histogram share one count.
STAGES = ("queue_wait_ms", "host_pack_ms", "device_ms",
          "finalize_ms")

#: (n_events, batch copies) pairs primed at boot — one small and one
#: mid bucket, each at the serial (B=1) and coalesced (B=cap) program
DEFAULT_PRIME: Tuple[Tuple[int, int], ...] = ((24, 1), (24, 8))

#: slots a non-full batch waits for mates when no deadline tightens
#: the budget (seconds) — a CAP on batch formation, not a coalescing
#: round: a full batch launches immediately and an idle wire launches
#: everything
DEFAULT_FILL_WINDOW_S = 0.005

#: staged dispatches in flight at once (staged / running /
#: finalizing); 3 is the measured knee on one CPU — the host packs
#: bucket i+2 while the device runs i+1 and i's readback completes
DEFAULT_RING_DEPTH = 3

#: of a request's deadline headroom, the fraction admission may spend
#: waiting for batch-mates — the rest is reserved for the dispatch
#: itself (launch budget = t_in + min(fill_window, headroom * this))
LAUNCH_HEADROOM_FRACTION = 0.5


@dataclass
class PendingRequest:
    """One queued check; ``ctx`` is the transport's opaque handle (the
    daemon stores the connection there). ``kind`` is ``"check"``
    (linearizability — ``packed`` holds the PackedHistory) or
    ``"txn"`` (serializability — ``packed`` holds the inferred
    TxnGraph); both kinds share the slots, the deadline expiry, and
    the launch policy."""

    rid: object
    model: str
    packed: object                       # PackedHistory | TxnGraph
    bucket: object                       # Bucket | TxnBucket | None
    t_in: float
    t_dead: Optional[float] = None
    ctx: object = None
    kind: str = "check"
    realtime: bool = False
    #: this request's launch budget: the latest instant its bucket
    #: may keep holding the batch open for it (deadline-derived;
    #: fill-window-capped) — the slot launches at the min over items
    t_budget: float = 0.0
    #: shrink only: when the job last re-queued (inter-round waits
    #: accumulate into queue_wait so stages keep tiling the wall)
    t_requeue: Optional[float] = None
    #: per-request stage attribution (STAGES keys, milliseconds) —
    #: filled along the dispatch path, echoed in the reply and fed to
    #: the stage histograms
    stages: dict = field(default_factory=dict)


@dataclass
class _Slot:
    """One bucket's forming batch (the continuous-batching admission
    unit): requests append as they arrive; ``t_launch`` is the min of
    their launch budgets."""

    items: List[PendingRequest] = field(default_factory=list)
    t_launch: float = float("inf")


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
    return sorted_vals[i]


@dataclass
class _BucketStats:
    requests: int = 0
    dispatches: int = 0
    batched: int = 0          # live (non-padding) requests dispatched
    compiles: int = 0         # first sighting of a program key
    occupancy_sum: float = 0.0  # live/B_prog per dispatch
    shard_fill_sum: float = 0.0  # occupied shard slots / D per dispatch
    device_s: float = 0.0
    programs: set = field(default_factory=set)


class VerifierCore:
    """See module docstring. All times are monotonic-clock floats
    (``obs.trace.monotonic`` — the pipeline's one sanctioned clock,
    rule ``raw-clock-in-pipeline``) passed in by the caller — the
    daemon owns the clock so tests can drive launch budgets and
    deadlines deterministically."""

    def __init__(self, model: str = "cas-register",
                 engine: str = "auto", F: int = 1024,
                 batch_cap: int = 64, max_queue: int = 256,
                 limits: Optional[ServiceLimits] = None,
                 max_host_configs: int = 1 << 20,
                 inject_dispatch_latency_s: float = 0.0,
                 shards: int = 1,
                 fill_window_s: float = DEFAULT_FILL_WINDOW_S,
                 ring_depth: int = DEFAULT_RING_DEPTH,
                 max_sessions: int = 64,
                 session_idle_s: float = 300.0):
        from ..models.model import MODELS

        if model not in MODELS:
            raise ValueError(f"unknown model {model!r}")
        self.model = model
        self.engine = engine
        self.F = F
        self.batch_cap = batch_cap
        self.max_queue = max_queue
        self.limits = limits or ServiceLimits()
        self.max_host_configs = max_host_configs
        self.fill_window_s = max(float(fill_window_s), 0.0)
        if ring_depth < 1:
            raise ValueError(f"ring_depth={ring_depth} must be >= 1")
        self.ring_depth = int(ring_depth)
        # shard-placement axis: every bucket dispatch fills D shard
        # slots (batch axis padded to a pow2 multiple of D) and rides
        # the shard_map engines over a device mesh. D=1 is the plain
        # single-device path — no mesh is ever built.
        from .sharding import MAX_SHARDS, make_mesh

        self.shards = max(int(shards), 1)
        if self.shards > MAX_SHARDS:
            raise ValueError(
                f"shards={shards} exceeds the declared shard-axis "
                f"ceiling MAX_SHARDS={MAX_SHARDS}")
        if self.shards & (self.shards - 1):
            # fail at STARTUP: the engines reject non-pow2 meshes per
            # dispatch, which the pump's blanket except would turn
            # into 100% unknown replies on a daemon that looked ready
            raise ValueError(
                f"shards={shards} must be a power of two — per-shard "
                "shapes are bucket/D and must stay pow2 (PROGRAMS.md "
                "mesh_D ladder)")
        self.mesh = make_mesh(self.shards) if self.shards > 1 else None
        # benchmarking/testing knob: model the tunneled TPU's ~100 ms
        # dispatch+readback round-trip when the daemon runs on CPU.
        # The link is ASYNC — readback completes ``inject`` seconds
        # after DISPATCH, not after the host starts waiting — so
        # finalize sleeps only the REMAINING latency; staging other
        # buckets meanwhile absorbs the round-trip exactly like the
        # real link does. Always reported in status() so benched
        # numbers can't masquerade as raw.
        self.inject_dispatch_latency_s = inject_dispatch_latency_s
        # streaming sessions (kind:"stream", docs/streaming.md): one
        # device-resident carry per monitored live history; the table
        # is capped (a carry is real device memory) and idle sessions
        # evict on the pump beat
        from ..stream.manager import SessionManager

        self.sessions = SessionManager(max_sessions=max_sessions,
                                       idle_s=session_idle_s)
        #: drain mode (round 12, docs/service.md "Elastic fleet"): a
        #: draining core re-routes forming batches (queued requests
        #: answer ``shutting-down``), finalizes staged dispatches
        #: normally, sheds NEW work, and keeps serving the session
        #: handoff verbs (checkpoint/poll/close) + metrics/status
        self.draining = False
        #: the fleet ring version this daemon last registered under
        #: (``sut/verifier.epoch`` in pmux; the daemon sets it) —
        #: scraped as the ``ring_epoch`` gauge so a fleet-wide scrape
        #: shows membership convergence
        self.ring_epoch = 0
        self.t_boot = obs.monotonic()
        # continuous-batching admission state
        self._slots: Dict[tuple, _Slot] = {}
        self._hosts: deque = deque()     # out-of-bucket degradations
        self._jobs: deque = deque()      # shrink jobs (step per pump)
        self._ring: deque = deque()      # staged finish() callables
        self._done: List[Tuple[PendingRequest, dict]] = []
        self._programs: set = set()
        self._latencies: deque = deque(maxlen=2048)
        self._buckets: Dict[str, _BucketStats] = {}
        #: completion timestamps for the drain-rate estimate behind
        #: the overload retry_after_ms hint
        self._drain_win: deque = deque(maxlen=256)
        # the metrics plane (docs/observability.md): per-core registry
        # — histograms are fixed-bucket (quantiles without samples),
        # always on (a handful of integer adds per dispatch); span
        # TRACING is the separately-gated layer (obs.trace.enable)
        self.metrics = Registry()
        self._stage_h = {
            s: self.metrics.histogram(
                "service_" + s.replace("_ms", "") + "_ms")
            for s in STAGES}
        self._h_latency = self.metrics.histogram("service_latency_ms")
        self._g_queue = self.metrics.gauge("service_queue_depth")
        self._g_ring = self.metrics.gauge(
            "service_inflight_ring",
            help="staged dispatches in the in-flight ring "
                 "(staged/running/finalizing)")
        self._c_h2d = self.metrics.counter(
            "service_transfer_h2d_bytes_total",
            help="host->device bytes shipped per dispatch (the ~25 "
                 "MB/s tunnel is a dominant cost)")
        self._c_d2h = self.metrics.counter(
            "service_transfer_d2h_bytes_total")
        # per-request rows + overload/deadline/degrade event marks for
        # the timeline SVG (report/service_svg.py); bounded deques —
        # rendering wants the recent window, not unbounded history
        self._timeline: deque = deque(maxlen=4096)
        self._events: deque = deque(maxlen=1024)
        self._priming = False
        self.m: Dict[str, int] = {
            "accepted": 0, "completed": 0, "overloads": 0,
            "bad_requests": 0, "malformed": 0, "deadline_expired": 0,
            "host_degraded": 0, "engine_errors": 0, "dispatches": 0,
            "compiles": 0, "program_hits": 0, "primed": 0,
            "shrink_requests": 0, "shrink_rounds": 0,
            # launch-reason counters: why each batch left its slot —
            # full (hit the cap at submit), deadline (oldest
            # request's launch budget expired), idle (wire went
            # quiet — the serial-caller path)
            "launch_full": 0, "launch_deadline": 0, "launch_idle": 0,
            # streaming sessions: opens/appends/closes + idle
            # evictions (docs/streaming.md)
            "stream_opens": 0, "stream_appends": 0,
            "stream_closes": 0, "stream_evicted": 0,
            # elastic fleet (round 12): checkpoint handoffs out
            # (verb:"checkpoint"), migrated sessions admitted in
            # (open-with-checkpoint), drain entries
            "stream_checkpoints": 0, "stream_migrations": 0,
            "drains": 0,
            # megabatched advances (round 13): fused programs that
            # carried >= 2 session lanes in one dispatch
            "stream_megabatches": 0,
            # workload-family checks admitted (kind:"wl",
            # docs/workloads.md) — they share accepted/completed/
            # dispatches with every other kind
            "wl_checks": 0,
        }
        self._g_sessions = self.metrics.gauge(
            "stream_sessions_active",
            help="streaming sessions holding a device-resident carry")
        self._g_carry_bytes = self.metrics.gauge(
            "stream_carry_resident_bytes",
            help="device bytes held by resident session carries")
        # megabatch amortization plane (docs/streaming.md
        # "Megabatched advance"): how many session lanes each
        # launched stream program advanced (solo dispatches observe
        # 1), and the latest beat's fused lane count
        self._h_mb_lanes = self.metrics.histogram(
            "sessions_per_dispatch",
            help="session lanes advanced per launched stream "
                 "program",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0))
        self._g_mb_lanes = self.metrics.gauge(
            "stream_megabatch_lanes",
            help="session lanes riding fused megabatch programs in "
                 "the most recent stream batch")
        # elastic-fleet plane (docs/service.md "Elastic fleet"):
        # membership + migration visibility in every scrape
        self._g_epoch = self.metrics.gauge(
            "ring_epoch",
            help="fleet ring version this daemon last registered "
                 "under (bumped by every pmux join/leave)")
        self._c_migrations = self.metrics.counter(
            "stream_migrations",
            help="sessions admitted from a checkpoint handoff "
                 "(open-with-checkpoint)")
        self._c_ck_bytes = self.metrics.counter(
            "checkpoint_bytes",
            help="cumulative wire bytes of session checkpoints "
                 "handed off or admitted")

    # -- admission queue views -----------------------------------------

    def queue_depth(self) -> int:
        """Requests admitted but not yet dispatched (slot batches +
        host-route + shrink jobs) — the backpressure quantity."""
        return (sum(len(s.items) for s in self._slots.values())
                + len(self._hosts) + len(self._jobs))

    def inflight(self) -> int:
        """Staged dispatches in the in-flight ring."""
        return len(self._ring)

    @property
    def queue(self) -> List[PendingRequest]:
        """All queued requests in arrival order (tests/status; the
        hot path uses :meth:`queue_depth` / :meth:`_pending`)."""
        return sorted(self._pending(), key=lambda p: p.t_in)

    def next_event_at(self) -> Optional[float]:
        """Earliest instant scheduled work comes due (a slot's launch
        budget or a queued request's deadline) — the daemon sizes its
        select timeout with it. Runs every selector round: min over
        the raw collections, never the sorted ``queue`` view."""
        nxt = None
        for s in self._slots.values():
            if s.items and (nxt is None or s.t_launch < nxt):
                nxt = s.t_launch
        for p in self._pending():
            if p.t_dead is not None and (nxt is None
                                         or p.t_dead < nxt):
                nxt = p.t_dead
        return nxt

    def _pending(self):
        """Every queued request, unordered (the hot-path iterator
        behind :meth:`next_event_at`; ``queue`` is the sorted view)."""
        for s in self._slots.values():
            yield from s.items
        yield from self._hosts
        yield from self._jobs

    # -- admission -----------------------------------------------------

    def submit(self, req: dict, now: float, ctx: object = None):
        """Admit one ``check`` request. Returns ``(pending, reply)``:
        exactly one is non-None — an immediate ``reply`` (overload,
        bad-request, trivial, malformed, metrics) or a slotted
        ``pending``. A slot that reaches the batch cap launches its
        dispatch inside this call (continuous batching — replies
        surface at the next ``pump``)."""
        rid = req.get("id")
        if req.get("kind") == "metrics":
            # the scrape answers AHEAD of backpressure: the metrics
            # plane must work exactly when the queue is full — it
            # never queues, never dispatches
            return None, self.metrics_reply(rid)
        if req.get("kind") == "drain":
            return None, self._drain_verb(rid, now)
        if self.draining and not self._drain_serves(req):
            # a draining daemon re-routes instead of queueing: the
            # client's ring walk treats shutting-down like a dead
            # node and fails over — "forming batches re-route"
            out = protocol.error_reply(
                protocol.SHUTDOWN,
                "daemon is draining — re-route to the fleet", rid)
            out["draining"] = True
            return None, out
        if self.queue_depth() >= self.max_queue:
            # backpressure BEFORE parse: shedding load must stay O(1)
            # — and before the kind split, so txn requests answer
            # overload exactly like check requests. The reply carries
            # a retry_after_ms hint derived from queue depth and the
            # recent drain rate so clients back off proportionally.
            self.m["overloads"] += 1
            self._event("overload", now)
            ra = self._retry_after_ms(now)
            out = protocol.error_reply(
                protocol.OVERLOAD,
                f"admission queue at cap ({self.max_queue}); retry "
                f"in ~{ra} ms", rid)
            out["retry_after_ms"] = ra
            return None, out
        with obs.span("admission", rid=rid,
                      kind=req.get("kind", "check")):
            return self._admit(req, now, ctx, rid)

    #: completions older than this leave the drain-rate window — a
    #: rate spanning an idle gap would hint the 5 s clamp at the
    #: first overload after every quiet spell
    DRAIN_WINDOW_S = 10.0

    def _retry_after_ms(self, now: float) -> int:
        """Overload hint: the time the current backlog needs to drain
        at the RECENTLY observed completion rate (stale completions
        aged out), clamped to [25 ms, 5 s]. With no recent drain
        history, a few fill windows."""
        depth = self.queue_depth()
        win = self._drain_win
        cutoff = now - self.DRAIN_WINDOW_S
        while win and win[0] < cutoff:
            win.popleft()
        if len(win) >= 2 and now > win[0]:
            rate = (len(win) - 1) / (now - win[0])
            ms = depth / rate * 1e3 if rate > 0 else 5e3
        else:
            ms = max(4 * self.fill_window_s * 1e3, 100.0)
        return int(min(max(ms, 25.0), 5000.0))

    def _launch_budget(self, p: PendingRequest, now: float) -> float:
        """How long this request's slot may keep filling: the fill
        window, tightened by the deadline (half the headroom stays
        reserved for the dispatch itself — a request with 10 ms to
        live must not spend all 10 queued)."""
        if p.t_dead is None:
            return p.t_in + self.fill_window_s
        headroom = max(p.t_dead - now, 0.0)
        return p.t_in + min(self.fill_window_s,
                            headroom * LAUNCH_HEADROOM_FRACTION)

    def _slot_add(self, p: PendingRequest, now: float) -> None:
        key = ((p.kind, p.model, p.bucket) if p.kind == "check"
               else (p.kind, None, p.bucket))
        slot = self._slots.get(key)
        if slot is None:
            slot = self._slots[key] = _Slot()
        p.t_budget = self._launch_budget(p, now)
        slot.items.append(p)
        slot.t_launch = min(slot.t_launch, p.t_budget)
        if len(slot.items) >= self.batch_cap:
            # slot-filling dispatch: the batch is full NOW — launch
            # without waiting for the scheduler beat
            self._launch(key, "full")

    def _admit(self, req: dict, now: float, ctx: object, rid):
        """Parse/pack/bucket under the admission span (see submit)."""
        kind = req.get("kind", "check")
        if kind == "txn":
            return self._submit_txn(req, now, ctx, rid)
        if kind == "shrink":
            return self._submit_shrink(req, now, ctx, rid)
        if kind == "stream":
            return self._submit_stream(req, now, ctx, rid)
        if kind == "wl":
            return self._submit_wl(req, now, ctx, rid)
        if kind != "check":
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unknown kind {kind!r}", rid)
        model = req.get("model") or self.model
        from ..models.model import MODELS

        if model not in MODELS:
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unknown model {model!r}", rid)
        text = req.get("history")
        if not isinstance(text, str) or not text.strip():
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, "missing history (EDN text)", rid)
        try:
            ops = self._parse(text, model,
                              keyed=bool(req.get("keyed")))
        except Exception as e:              # noqa: BLE001 — client data
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unparseable history: {e}", rid)
        try:
            packed = self._pack(ops)
        except (ValueError, RuntimeError) as e:
            # parsed but inconsistent (a double-pending process raises
            # RuntimeError in history.complete, bad op sequences raise
            # ValueError): the checker tri-state's honest answer
            self.m["malformed"] += 1
            return None, self._reply(rid, "unknown",
                                     cause=f"malformed: {e}")
        self.m["accepted"] += 1
        if packed is None:
            # no ok-completions: nothing ever constrains the frontier
            self.m["completed"] += 1
            return None, self._reply(rid, True, engine="trivial")
        try:
            bucket = bucket_for(packed, self.limits)
        except ValueError as e:
            self.m["malformed"] += 1
            return None, self._reply(rid, "unknown",
                                     cause=f"malformed: {e}")
        dl = req.get("deadline_ms")
        if dl is not None and not isinstance(dl, (int, float)):
            # one malformed field must stay THIS request's problem —
            # an exception here would tear down the shared daemon
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"deadline_ms must be a number, got {type(dl).__name__}",
                rid)
        pending = PendingRequest(
            rid=rid, model=model, packed=packed, bucket=bucket,
            t_in=now, ctx=ctx,
            t_dead=(now + float(dl) / 1e3) if dl is not None else None)
        if bucket is not None:
            self._bstats(bucket.key).requests += 1
            self._slot_add(pending, now)
        else:
            self._hosts.append(pending)
        return pending, None

    def _parse(self, text: str, model: str, keyed: bool):
        """EDN text -> Op list (parse failures are the CLIENT's bug —
        bad-request, never an unknown verdict)."""
        from ..ops.native_loader import parse_history_fast

        ops = parse_history_fast(text)
        if keyed or model == "cas-register-comdb2":
            from ..checker.independent import wrap_keyed_history

            ops = wrap_keyed_history(ops)
        return ops

    def _pack(self, ops):
        """Op list -> PackedHistory (None for trivially-valid)."""
        from ..ops.packed import pack_history

        if not ops or not any(op.type == "ok" for op in ops):
            return None
        return pack_history(list(ops))

    # -- txn-kind admission --------------------------------------------

    def _submit_txn(self, req: dict, now: float, ctx: object, rid):
        """Admit one serializability check. Same contract as the
        check kind: immediate reply for trivial/malformed, slotted
        PendingRequest otherwise — from here on the txn request rides
        the SAME launch policy, deadline expiry, and in-flight ring."""
        text = req.get("history")
        if not isinstance(text, str) or not text.strip():
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, "missing history (EDN text)", rid)
        try:
            # NEVER keyed-wrapped: txn values are micro-op vectors
            from ..ops.native_loader import parse_history_fast

            ops = parse_history_fast(text)
        except Exception as e:              # noqa: BLE001 — client data
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unparseable history: {e}", rid)
        realtime = bool(req.get("realtime"))
        try:
            from ..txn import infer_edges

            graph = infer_edges(ops, realtime=realtime)
        except ValueError as e:
            self.m["malformed"] += 1
            return None, self._reply(rid, "unknown",
                                     cause=f"malformed: {e}")
        self.m["accepted"] += 1
        if not graph.adj.any():
            # edge-free graphs never cycle — but direct anomalies
            # (G1a, duplicates) still decide the verdict. Answered
            # BEFORE deadline_ms validation, matching the check
            # kind's trivial path (reply-parity contract)
            from ..txn import check_txn

            result = check_txn((), graph=graph, realtime=realtime)
            self.m["completed"] += 1
            return None, self._txn_reply(rid, result, engine="trivial")
        dl = req.get("deadline_ms")
        if dl is not None and not isinstance(dl, (int, float)):
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"deadline_ms must be a number, got {type(dl).__name__}",
                rid)
        bucket = txn_bucket_for(graph.n, self.limits)
        pending = PendingRequest(
            rid=rid, model="txn", packed=graph, bucket=bucket,
            t_in=now, ctx=ctx, kind="txn", realtime=realtime,
            t_dead=(now + float(dl) / 1e3) if dl is not None else None)
        if bucket is not None:
            self._bstats(bucket.key).requests += 1
            self._slot_add(pending, now)
        else:
            self._hosts.append(pending)
        return pending, None

    # -- wl-kind admission ---------------------------------------------

    def _submit_wl(self, req: dict, now: float, ctx: object, rid):
        """Admit one workload-family check (docs/workloads.md):
        bank / sets / dirty-reads need no frontier search, so a
        history is a handful of column planes and a whole bucket's
        batch is ONE jit. From here the request rides the SAME
        continuous-batching machinery as every kind — bucket slot,
        launch policy, deadline expiry, in-flight ring; over-rung
        histories degrade to the host oracle one at a time."""
        from ..checker.wl import FAMILIES

        family = req.get("family")
        if family not in FAMILIES:
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"unknown wl family {family!r} (one of "
                f"{'/'.join(FAMILIES)})", rid)
        wlmodel = req.get("wl")
        if family == "bank" and (
                not isinstance(wlmodel, dict) or "n" not in wlmodel
                or "total" not in wlmodel):
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                "bank needs wl: {'n':..,'total':..}", rid)
        text = req.get("history")
        if not isinstance(text, str) or not text.strip():
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, "missing history (EDN text)", rid)
        try:
            # never keyed-wrapped: wl values are balances/sets, and a
            # bare [k v] read would mis-parse as a cas pair
            from ..ops.native_loader import parse_history_fast

            ops = parse_history_fast(text)
        except Exception as e:              # noqa: BLE001 — client data
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unparseable history: {e}", rid)
        dl = req.get("deadline_ms")
        if dl is not None and not isinstance(dl, (int, float)):
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"deadline_ms must be a number, got {type(dl).__name__}",
                rid)
        try:
            bucket = wl_bucket_for(family, ops, wlmodel)
        except (ValueError, TypeError) as e:
            self.m["malformed"] += 1
            return None, self._reply(rid, "unknown", kind="wl",
                                     family=family,
                                     cause=f"malformed: {e}")
        self.m["accepted"] += 1
        self.m["wl_checks"] += 1
        pending = PendingRequest(
            rid=rid, model=f"wl-{family}",
            packed=(family, wlmodel, ops), bucket=bucket,
            t_in=now, ctx=ctx, kind="wl",
            t_dead=(now + float(dl) / 1e3) if dl is not None else None)
        if bucket is not None:
            self._bstats(bucket.key).requests += 1
            self._slot_add(pending, now)
        else:
            self._hosts.append(pending)
        return pending, None

    def _wl_reply(self, rid, verdict: dict, family: str,
                  **extra) -> dict:
        """Compress one oracle-shaped wl verdict dict into a wire
        reply (the family fields ride along verbatim — golden parity
        means they are exactly the host checker's)."""
        out = self._reply(rid, verdict.get("valid?"), kind="wl",
                          family=family, **extra)
        for k, v in verdict.items():
            if k != "valid?":
                out.setdefault(k, v)
        return out

    # -- shrink-kind admission -----------------------------------------

    def _submit_shrink(self, req: dict, now: float, ctx: object, rid):
        """Admit one counterexample-minimization request. The job
        (a step-driven :class:`~comdb2_tpu.shrink.core.DdminEngine`)
        rides the SAME overload backpressure and deadline expiry as
        every other kind; each pump advances it one ddmin round —
        shrink rounds are just more pow2-bucketed batch traffic — and
        a deadline returns best-so-far flagged ``partial``."""
        txn = bool(req.get("txn"))
        text = req.get("history")
        if not isinstance(text, str) or not text.strip():
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, "missing history (EDN text)", rid)
        model = req.get("model") or self.model
        realtime = bool(req.get("realtime"))
        from ..models.model import MODELS

        if not txn and model not in MODELS:
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unknown model {model!r}", rid)
        dl = req.get("deadline_ms")
        if dl is not None and not isinstance(dl, (int, float)):
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"deadline_ms must be a number, got {type(dl).__name__}",
                rid)
        try:
            if txn:
                ops = self._parse(text, "txn", keyed=False)
            else:
                ops = self._parse(text, model,
                                  keyed=bool(req.get("keyed")))
        except Exception as e:              # noqa: BLE001 — client data
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unparseable history: {e}", rid)
        # one ddmin round runs synchronously inside a pump: cap its
        # candidate budget so a pathological seed costs a bounded
        # number of dispatches per round instead of wedging every
        # other request past its deadline
        round_cap = max(2 * self.batch_cap, 8)
        try:
            if txn:
                from ..shrink import TxnShrinker

                job = TxnShrinker(ops, realtime=realtime,
                                  round_cap=round_cap,
                                  mesh=self.mesh)
            else:
                from ..shrink import Shrinker

                if not ops or not any(op.type == "ok" for op in ops):
                    # trivially VALID: nothing constrains the frontier
                    # — a shrink of it is a client error, answered
                    # without burning a round (seed-rejection contract)
                    self.m["bad_requests"] += 1
                    return None, protocol.error_reply(
                        protocol.BAD_REQUEST,
                        "seed verdict is True — only INVALID "
                        "histories shrink", rid)
                job = Shrinker(ops, MODELS[model](), F=self.F,
                               engine=self.engine,
                               max_batch=self.batch_cap,
                               round_cap=round_cap,
                               mesh=self.mesh)
        except (ValueError, RuntimeError) as e:
            # includes MemoOverflow and malformed histories: the
            # tri-state's honest answer, same as the check kind
            self.m["malformed"] += 1
            return None, self._reply(rid, "unknown", kind="shrink",
                                     cause=f"malformed: {e}")
        self.m["accepted"] += 1
        self.m["shrink_requests"] += 1
        pending = PendingRequest(
            rid=rid, model=model, packed=job, bucket=None,
            t_in=now, ctx=ctx, kind="shrink", realtime=realtime,
            t_dead=(now + float(dl) / 1e3) if dl is not None else None)
        self._jobs.append(pending)
        return pending, None

    def _shrink_reply(self, p: PendingRequest, job,
                      partial: bool = False, **extra) -> dict:
        """Wire reply for a finished (or deadline-cut) shrink job."""
        if job.error is not None:
            # seed was VALID/UNKNOWN: an error, not a loop — the
            # client gets the observed verdict in the message
            self.m["bad_requests"] += 1
            return protocol.error_reply(protocol.BAD_REQUEST,
                                        str(job.error), p.rid)
        r = job.result(partial=partial)
        from ..ops.history import history_to_edn

        out = self._reply(
            p.rid, r.valid, kind="shrink",
            seed_ops=r.seed_ops, minimal_ops=r.n_ops,
            rounds=r.rounds, candidates=r.candidates,
            dispatches=r.dispatches, one_minimal=r.one_minimal,
            partial=r.partial, **r.extra, **extra)
        if r.n_ops <= 2048:
            out["minimal_history"] = history_to_edn(r.ops)
        else:
            # a deadline-cut 100k-event best-so-far must not blow up
            # the reply framing; the caller re-submits with more time
            out["minimal_history_omitted"] = True
        return out

    def _txn_reply(self, rid, result: dict, **extra) -> dict:
        """Compress a check_txn result map into a wire reply."""
        cex = result.get("counterexample")
        out = self._reply(
            rid, result["valid?"], kind="txn",
            txn_count=result.get("txn-count", 0),
            anomalies=[a["name"] for a in result.get("anomalies", ())],
            **extra)
        if result.get("malformed-ops"):
            # the unknown tri-state always carries a cause
            out["malformed_ops"] = result["malformed-ops"]
            out.setdefault(
                "cause", f"malformed: {result['malformed-ops']} "
                         "unparseable txn op(s)")
        if cex:
            out["anomaly_class"] = cex["class"]
            # full decode capped: replies ride next to latency-
            # sensitive traffic, and a pathological cycle can span
            # the whole graph
            out["cycle"] = cex["cycle"][:16]
            out["cycle_len"] = len(cex["cycle"])
        return out

    # -- stream-kind admission -----------------------------------------

    def _submit_stream(self, req: dict, now: float, ctx: object, rid):
        """Admit one streaming-session verb (docs/streaming.md).
        ``open``/``poll``/``close`` answer immediately (no device
        dispatch is staged for them — close's final tail flush is the
        one bounded exception); ``append`` slots into the session's
        SHAPE-CLASS batch and rides the same launch policy, deadline
        expiry, and in-flight ring as every other kind."""
        from ..stream.manager import SessionLimit

        verb = req.get("verb", "append")
        if verb == "open" and req.get("checkpoint") is not None:
            # open-with-checkpoint: the migration handoff's second
            # half (docs/streaming.md "Checkpoint / migration") — a
            # session drained off another daemon resumes HERE with
            # its carry bits intact, zero replay
            return self._stream_open_restored(req["checkpoint"], now,
                                              rid)
        if verb == "open":
            model = req.get("model") or self.model
            from ..models.model import MODELS
            from ..stream.wl import WL_MODELS

            is_wl = model in WL_MODELS
            if not is_wl and model not in MODELS:
                self.m["bad_requests"] += 1
                return None, protocol.error_reply(
                    protocol.BAD_REQUEST, f"unknown model {model!r}",
                    rid)
            try:
                if is_wl:
                    # workload-family session (stream/wl.py): ``wl``
                    # carries the family params (bank n/total); same
                    # table, cap, eviction and checkpoint machinery
                    sid, s = self.sessions.open(
                        now, model=model, wl=req.get("wl"))
                else:
                    sid, s = self.sessions.open(
                        now, model=model,
                        engine=req.get("rung", "auto"),
                        max_states=self.max_host_configs)
            except SessionLimit as e:
                # a carry is device memory: the cap sheds exactly like
                # the admission queue, hint included
                self.m["overloads"] += 1
                self._event("overload", now)
                ra = self._retry_after_ms(now)
                out = protocol.error_reply(
                    protocol.OVERLOAD, f"{e}; retry in ~{ra} ms", rid)
                out["retry_after_ms"] = ra
                return None, out
            except (ValueError, TypeError) as e:
                # bad wl params (bank without n/total): the client's
                # bug, answered before any session exists
                self.m["bad_requests"] += 1
                return None, protocol.error_reply(
                    protocol.BAD_REQUEST, f"bad wl params: {e}", rid)
            if not is_wl:
                # wl deltas are never keyed-wrapped (a bare [k v]
                # read would mis-parse as a cas pair)
                s.keyed = (bool(req.get("keyed"))
                           or model == "cas-register-comdb2")
            self.m["stream_opens"] += 1
            return None, self._reply(rid, True, kind="stream",
                                     session=sid, model=model)
        sid = req.get("session")
        if verb == "checkpoint":
            # resolved BEFORE the transparent-restore get(): an
            # idle-evicted session's held host snapshot is the
            # requested artifact — restoring it just to re-snapshot
            # would replay the memo extend log on the drain path
            return None, self._stream_checkpoint(sid, req, rid)
        s = self.sessions.get(sid, now)
        if s is None:
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"unknown session {sid!r} (expired or never opened — "
                "re-open and replay)", rid)
        if verb == "poll":
            return None, self._stream_reply(rid, sid, s.poll())
        if verb == "close":
            out = self.sessions.close(sid)
            self.m["stream_closes"] += 1
            return None, self._stream_reply(rid, sid, out)
        if verb != "append":
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unknown stream verb {verb!r}",
                rid)
        text = req.get("history")
        if not isinstance(text, str) or not text.strip():
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, "missing history (EDN delta)",
                rid)
        try:
            ops = self._parse(text, s.model_name,
                              keyed=getattr(s, "keyed", False))
        except Exception as e:              # noqa: BLE001 — client data
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unparseable delta: {e}", rid)
        self.m["accepted"] += 1
        self.m["stream_appends"] += 1
        if s.valid is not True:
            # the latch: answer without queueing a dispatch
            self.m["completed"] += 1
            out = self._stream_reply(rid, sid, s.poll())
            out["latched"] = True
            return None, out
        dl = req.get("deadline_ms")
        if dl is not None and not isinstance(dl, (int, float)):
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST,
                f"deadline_ms must be a number, got {type(dl).__name__}",
                rid)
        pending = PendingRequest(
            rid=rid, model=s.model_name, packed=(sid, s, ops),
            bucket=StreamBucket(s.shape_class), t_in=now, ctx=ctx,
            kind="stream",
            t_dead=(now + float(dl) / 1e3) if dl is not None else None)
        self._bstats(pending.bucket.key).requests += 1
        self._slot_add(pending, now)
        return pending, None

    def _stream_open_restored(self, ck_wire, now: float, rid):
        """Admit one migrated session from its wire checkpoint."""
        from ..stream import checkpoint as CKPT
        from ..stream.manager import SessionLimit

        try:
            ck = CKPT.from_wire(ck_wire)
            nbytes = CKPT.wire_nbytes(ck_wire)
        except Exception as e:              # noqa: BLE001 — client data
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"undecodable checkpoint: {e}",
                rid)
        try:
            sid, s = self.sessions.open_restored(now, ck)
        except SessionLimit as e:
            self.m["overloads"] += 1
            self._event("overload", now)
            ra = self._retry_after_ms(now)
            out = protocol.error_reply(
                protocol.OVERLOAD, f"{e}; retry in ~{ra} ms", rid)
            out["retry_after_ms"] = ra
            return None, out
        except (ValueError, KeyError, TypeError) as e:
            self.m["bad_requests"] += 1
            return None, protocol.error_reply(
                protocol.BAD_REQUEST, f"unrestorable checkpoint: {e}",
                rid)
        self.m["stream_opens"] += 1
        self.m["stream_migrations"] += 1
        self._c_migrations.inc()
        self._c_ck_bytes.inc(nbytes)
        out = self._stream_reply(rid, sid, s.poll())
        out["migrated"] = True
        out["checkpoint_bytes"] = nbytes
        return None, out

    def _stream_checkpoint(self, sid, req: dict, rid) -> dict:
        """``verb:"checkpoint"``: snapshot a session for handoff.
        ``release:true`` (the migration form) removes it — a handoff
        MOVES the session; both daemons serving it would double-serve
        its appends."""
        from ..stream import checkpoint as CKPT

        release = bool(req.get("release"))
        ck = self.sessions.checkpoint(sid)
        if ck is None:
            self.m["bad_requests"] += 1
            return protocol.error_reply(
                protocol.BAD_REQUEST, f"unknown session {sid!r}", rid)
        try:
            wire = CKPT.to_wire(ck)
            nbytes = CKPT.wire_nbytes(wire)
        except Exception as e:              # noqa: BLE001
            # encode failed: the session MUST survive — releasing
            # first would complete the MOVE's destructive half with
            # the checkpoint never delivered
            self.m["engine_errors"] += 1
            return protocol.error_reply(
                protocol.BAD_REQUEST,
                f"checkpoint not wire-encodable: "
                f"{type(e).__name__}: {e}", rid)
        if release:
            # the snapshot is encoded and about to ship: complete the
            # move (both daemons serving one session would
            # double-serve its appends)
            self.sessions.drop(sid)
        self.m["stream_checkpoints"] += 1
        self._c_ck_bytes.inc(nbytes)
        out = self._reply(rid, ck["valid"], kind="stream",
                          session=sid, checkpoint=wire,
                          checkpoint_bytes=nbytes, released=release)
        return out

    def _stream_reply(self, rid, sid, verdict: dict) -> dict:
        out = self._reply(rid, verdict.get("valid"), kind="stream",
                          session=sid)
        for k, v in verdict.items():
            out.setdefault(k, v)
        return out

    def _dispatch_stream_begin(self, bucket: StreamBucket,
                               items: List[PendingRequest]):
        """Stage one shape-class batch of session appends: each
        session ingests its delta and parks its new segments in the
        beat's forming MEGABATCH (``stream.engine.MegaBatch``) — one
        flush advances every lane in ONE fused device dispatch per
        shape class (docs/streaming.md "Megabatched advance");
        ``finish`` reads the verdicts back oldest-first. Same ring
        contract as :meth:`_dispatch_begin`. Deltas the fused entries
        can't lane (oversized, mid-batch growth replays) dispatch
        solo inside the same beat and count as 1-lane programs."""
        from ..stream import engine as _SE

        t0 = obs.monotonic()
        rids = [p.rid for p in items]
        for p in items:
            p.stages["queue_wait_ms"] = (t0 - p.t_in) * 1e3
        fins = []
        d0 = _SE.DISPATCHES
        coll = _SE.MegaBatch()
        with obs.span("stage", kind="stream", bucket=bucket.key,
                      b=len(items), rids=rids):
            for p in items:
                sid, s, ops = p.packed
                try:
                    fins.append(s.append_stage(ops, collector=coll))
                except Exception as e:          # noqa: BLE001
                    cause = f"engine: {type(e).__name__}: {e}"
                    fins.append(("err", cause))
            coll.flush()
        for c in coll.lane_counts:
            self._h_mb_lanes.observe(float(c))
        self._g_mb_lanes.set(float(coll.fused_lanes))
        self.m["stream_megabatches"] += coll.fused_launches
        t_staged = obs.monotonic()
        pack_ms = (t_staged - t0) * 1e3
        for p in items:
            p.stages["host_pack_ms"] = pack_ms

        def finish(done: list) -> None:
            t_fin = obs.monotonic()
            n_disp = _SE.DISPATCHES - d0
            if self.inject_dispatch_latency_s > 0.0 and n_disp:
                # the injected tunnel model, per dispatch like every
                # other kind — remaining-only against stage time
                remaining = (t_staged
                             + self.inject_dispatch_latency_s * n_disp
                             - obs.monotonic())
                if remaining > 0.0:
                    time.sleep(remaining)
            t_done = obs.monotonic()
            bs = self._bstats(bucket.key)
            bs.dispatches += n_disp
            bs.batched += len(items)
            bs.device_s += (t_staged - t0) + (t_done - t_fin)
            self.m["dispatches"] += n_disp
            obs.record("device", t_staged, t_done, bucket=bucket.key,
                       engine="stream-session", rids=rids)
            with obs.span("finalize", kind="stream",
                          bucket=bucket.key, rids=rids):
                for p, fin in zip(items, fins):
                    sid, s, _ops = p.packed
                    if isinstance(fin, tuple):
                        self.m["engine_errors"] += 1
                        self._event("engine_error", obs.monotonic())
                        reply = self._reply(p.rid, "unknown",
                                            kind="stream",
                                            session=sid, cause=fin[1])
                    else:
                        try:
                            verdict = fin()
                        except Exception as e:  # noqa: BLE001
                            self.m["engine_errors"] += 1
                            verdict = {
                                "valid": "unknown",
                                "cause": f"engine: "
                                         f"{type(e).__name__}: {e}"}
                        reply = self._stream_reply(p.rid, sid,
                                                   verdict)
                        reply["batched"] = len(items)
                    p.stages["device_ms"] = (t_done - t_staged) * 1e3
                    p.stages["finalize_ms"] = \
                        (obs.monotonic() - t_done) * 1e3
                    self._finish(p, reply, done)

        return finish

    # -- drain (elastic fleet, docs/service.md) ------------------------

    @staticmethod
    def _drain_serves(req: dict) -> bool:
        """What a draining core still answers: the session-handoff
        verbs (a departing daemon's whole point is letting clients
        pull their sessions out), plus poll/close. Everything else —
        new checks, txn, shrink, stream open/append — re-routes."""
        if req.get("kind") != "stream":
            return False
        return req.get("verb") in ("checkpoint", "poll", "close")

    def _drain_verb(self, rid, now: float) -> dict:
        """``kind:"drain"``: enter drain mode and report what's left.
        Idempotent — supervisors and SIGTERM both land here."""
        flushed = self.begin_drain(now)
        return {"ok": True, "kind": "drain", "draining": True,
                "flushed": flushed, "inflight": len(self._ring),
                "sessions": len(self.sessions),
                **({"id": rid} if rid is not None else {})}

    def begin_drain(self, now: float) -> int:
        """Enter drain: every QUEUED (not yet staged) request answers
        ``shutting-down`` so its client re-routes to the fleet —
        re-queueing them here would race the socket close. Staged
        dispatches in the in-flight ring are NOT touched: they
        finalize and reply normally (the pump drains the ring fully
        while draining). Sessions stay resident for checkpoint
        handoff. Returns the number of re-routed requests."""
        if not self.draining:
            self.draining = True
            self.m["drains"] += 1
            self._event("drain", now)
        flushed = 0
        for p in list(self._pending()):
            out = protocol.error_reply(
                protocol.SHUTDOWN,
                "daemon is draining — re-route to the fleet", p.rid)
            out["draining"] = True
            if p.kind == "stream":
                # the delta was never ingested; the session (and its
                # retained deltas client-side) are unchanged
                out["session"] = p.packed[0]
            self._finish(p, out, self._done)
            flushed += 1
        for slot in self._slots.values():
            slot.items = []
            slot.t_launch = float("inf")
        self._hosts.clear()
        self._jobs.clear()
        return flushed

    def drained(self) -> bool:
        """Nothing queued, nothing staged — the daemon may close once
        sessions are handed off (or its drain grace expires)."""
        return (self.draining and self.queue_depth() == 0
                and not self._ring)

    # -- the scheduler beat --------------------------------------------

    def pump(self, now: Optional[float] = None, idle: bool = False):
        """One scheduler beat: expire, launch due slots, run host
        degradations, advance shrink jobs, and return the completed
        ``[(pending, reply), ...]`` for the transport to fan out.
        ``idle=True`` means the wire went quiet — every non-empty slot
        launches (a lone serial caller never waits out the fill
        window) and the in-flight ring drains fully."""
        now = obs.monotonic() if now is None else now
        self._expire(now)
        # idle-session eviction on the scheduler beat: a carry nobody
        # appends to is device memory doing nothing — release it; the
        # client re-opens by replaying its retained deltas
        for _sid in self.sessions.evict_idle(now):
            self.m["stream_evicted"] += 1
            self._event("stream_evict", now)
        self._g_queue.set(self.queue_depth())
        for key in list(self._slots):
            slot = self._slots[key]
            if not slot.items:
                continue
            if len(slot.items) >= self.batch_cap:
                self._launch(key, "full")
            elif now >= slot.t_launch:
                self._launch(key, "deadline")
            elif idle:
                self._launch(key, "idle")
        while self._hosts:
            p = self._hosts.popleft()
            if p.kind == "txn":
                self._host_check_txn(p, self._done)
            elif p.kind == "wl":
                self._host_check_wl(p, self._done)
            else:
                self._host_check(p, self._done)
        self._step_shrinks()
        if idle or self.draining:
            self._ring_drain()
        elif self._ring and not any(s.items
                                    for s in self._slots.values()):
            # nothing is forming, so there is no batch left to
            # overlap against — finalize ONE staged dispatch per busy
            # beat. Non-queuing traffic (status/ping/metrics polls)
            # keeps got_bytes true forever, so idle rounds alone must
            # not be the only drain trigger; popping one entry bounds
            # a launched request's reply deferral without stalling
            # admission reads behind a full ring drain
            self._ring_pop()
        done, self._done = self._done, []
        return done

    def tick(self, now: Optional[float] = None):
        """The flush form of :meth:`pump` (idle semantics): launch
        everything queued, drain the ring, return the replies —
        priming, daemon shutdown and the unit tests drive it."""
        return self.pump(now, idle=True)

    def _step_shrinks(self) -> None:
        """Advance every queued shrink job ONE candidate-capped ddmin
        round (bounded dispatches per round via ``round_cap`` — long
        minimizations interleave with serving traffic instead of
        wedging the single-threaded loop) and re-queue the unfinished
        ones."""
        jobs, self._jobs = list(self._jobs), deque()
        for p in jobs:
            job = p.packed
            d0 = job.counters["dispatches"]
            t_s0 = obs.monotonic()
            # first round pins the queue wait; later rounds charge the
            # inter-round re-queue wait to queue_wait (so stages keep
            # tiling the wall) and pure engine time to the device stage
            if "queue_wait_ms" not in p.stages:
                p.stages["queue_wait_ms"] = (t_s0 - p.t_in) * 1e3
            elif p.t_requeue is not None:
                p.stages["queue_wait_ms"] += \
                    (t_s0 - p.t_requeue) * 1e3
            try:
                with obs.span("shrink.round", rid=p.rid):
                    finished = job.step()
            except Exception as e:              # noqa: BLE001
                self.m["engine_errors"] += 1
                self._event("engine_error", obs.monotonic())
                self._finish(p, self._reply(
                    p.rid, "unknown", kind="shrink",
                    cause=f"engine: {type(e).__name__}: {e}"),
                    self._done)
                continue
            self.m["shrink_rounds"] += 1
            if self.inject_dispatch_latency_s > 0.0:
                # per DISPATCH, like the check/txn kinds — the knob
                # models the tunnel round-trip each dispatch pays
                time.sleep(self.inject_dispatch_latency_s
                           * (job.counters["dispatches"] - d0))
            p.stages["device_ms"] = (
                p.stages.get("device_ms", 0.0)
                + (obs.monotonic() - t_s0) * 1e3)
            if finished:
                self._finish(p, self._shrink_reply(p, job), self._done)
            else:
                p.t_requeue = obs.monotonic()
                self._jobs.append(p)

    def _expire(self, now: float) -> None:
        """Answer every deadline-passed queued request ``unknown``
        (shrink: best-so-far ``partial``). An expired check/txn
        request never reached a dispatch: its whole wait IS queue
        wait — exactly the tail the latency histogram must explain
        (the remaining stages observe as 0, keeping the histogram
        counts tiled). A re-queued shrink job already pinned its real
        queue wait on the first round."""
        if self.queue_depth() == 0:
            return

        def expired(p):
            return p.t_dead is not None and now >= p.t_dead

        for slot in self._slots.values():
            if not any(expired(p) for p in slot.items):
                continue
            live = []
            for p in slot.items:
                if expired(p):
                    self._expire_one(p, now)
                else:
                    live.append(p)
            slot.items = live
            slot.t_launch = min((p.t_budget for p in live),
                                default=float("inf"))
        for q in (self._hosts, self._jobs):
            if not any(expired(p) for p in q):
                continue
            live = deque()
            for p in q:
                if expired(p):
                    self._expire_one(p, now)
                else:
                    live.append(p)
            q.clear()
            q.extend(live)

    def _expire_one(self, p: PendingRequest, now: float) -> None:
        self.m["deadline_expired"] += 1
        self._event("deadline", now)
        if "queue_wait_ms" not in p.stages:
            p.stages["queue_wait_ms"] = (now - p.t_in) * 1e3
        elif p.t_requeue is not None:
            # a shrink job expiring BETWEEN rounds: its final
            # re-queue wait is queue wait too, or sum(stages) stops
            # tiling the partial reply's latency
            p.stages["queue_wait_ms"] += (now - p.t_requeue) * 1e3
            p.t_requeue = None
        if p.kind == "shrink":
            # deadline returns BEST-SO-FAR, flagged partial — a
            # half-finished minimization is still a smaller repro
            # than the seed (seed-rejection errors keep their error
            # reply)
            self._finish(p, self._shrink_reply(
                p, p.packed, partial=True, cause="deadline"),
                self._done)
            return
        extra = {"kind": "txn"} if p.kind == "txn" else {}
        if p.kind == "wl":
            extra = {"kind": "wl", "family": p.packed[0]}
        if p.kind == "stream":
            # the delta was never ingested: the session is unchanged
            # and the client may retry the same append
            extra = {"kind": "stream", "session": p.packed[0]}
        self._finish(p, self._reply(p.rid, "unknown",
                                    cause="deadline", **extra),
                     self._done)

    # -- launch + the in-flight ring -----------------------------------

    def _launch(self, key: tuple, reason: str) -> None:
        """Move one slot's batch into the in-flight ring: stage the
        device dispatch(es) now, finalize when the ring overflows or
        drains — between the two, the device runs while the host packs
        the next batch (the PR-4 seam, ring-deep)."""
        slot = self._slots[key]
        items, slot.items = slot.items, []
        slot.t_launch = float("inf")
        if not items:
            return
        self.m["launch_" + reason] += 1
        kind, model, bucket = key
        for i in range(0, len(items), self.batch_cap):
            chunk = items[i:i + self.batch_cap]
            if kind == "txn":
                fin = self._dispatch_txn_begin(bucket, chunk)
            elif kind == "stream":
                fin = self._dispatch_stream_begin(bucket, chunk)
            elif kind == "wl":
                fin = self._dispatch_wl_begin(bucket, chunk)
            else:
                fin = self._dispatch_begin(model, bucket, chunk)
            self._ring_push(fin)

    def _ring_push(self, fin) -> None:
        while len(self._ring) >= self.ring_depth:
            self._ring_pop()
        self._ring.append(fin)
        self._g_ring.set(len(self._ring))

    def _ring_pop(self) -> None:
        fin = self._ring.popleft()
        self._g_ring.set(len(self._ring))
        fin(self._done)

    def _ring_drain(self) -> None:
        while self._ring:
            self._ring_pop()

    def _dispatch(self, model_name: str, bucket: Bucket,
                  items: List[PendingRequest], done: list) -> None:
        """Stage + finalize in one step (priming and direct callers;
        serving traffic rides the ring via :meth:`_launch`)."""
        self._dispatch_begin(model_name, bucket, items)(done)

    def _dispatch_begin(self, model_name: str, bucket: Bucket,
                        items: List[PendingRequest]):
        """Stage ONE device dispatch for a bucket's chunk and return a
        ``finish(done)`` callable: every shape that reaches a jit
        boundary is floored to the bucket, and the batch axis is
        pow2-padded with copies of the first history, so all chunks of
        this (bucket, B, sizes) class share one compiled program. The
        device runs between stage and finish — the ring stages other
        buckets' host packing in that window, and the stream carries
        are donated so a hot bucket reuses device memory across
        dispatches (checker/pallas_seg carry pool)."""
        from ..checker.batch import check_batch_async, pack_batch
        from ..models.memo import MemoOverflow
        from ..models.model import MODELS

        t0 = obs.monotonic()
        rids = [p.rid for p in items]
        for p in items:
            p.stages["queue_wait_ms"] = (t0 - p.t_in) * 1e3
        packeds = [p.packed for p in items]
        # the batch axis fills D shard slots per dispatch: pow2 AND a
        # multiple of the shard count, so every shard compiles the
        # same per-shard program (b_prog/D) and no dispatch leaves a
        # shard slot shapeless
        b_prog = max(_next_pow2(len(packeds)), self.shards)
        packeds = packeds + [packeds[0]] * (b_prog - len(packeds))
        info: dict = {}
        try:
            with obs.span("stage", kind="check", bucket=bucket.key,
                          b=len(items), b_prog=b_prog, rids=rids):
                batch = pack_batch(packeds, MODELS[model_name](),
                                   n_pad=bucket.n_pad)
                ns = _next_pow2(batch.memo.n_states)
                nt = _next_pow2(batch.memo.n_transitions)
                fin = check_batch_async(
                    batch, F=self.F, engine=self.engine, info=info,
                    mesh=self.mesh,
                    s_pad=bucket.S, k_pad=bucket.K,
                    n_states_pad=ns, n_transitions_pad=nt,
                    p_eff_pad=bucket.P_eff)
        except MemoOverflow as e:
            cause = f"memo overflow: {e}"
            return lambda done: self._fail_batch(items, bucket, cause,
                                                 done)
        except Exception as e:                  # noqa: BLE001
            # an engine blowup degrades THIS chunk to unknown; the
            # daemon must keep serving other buckets
            cause = f"{type(e).__name__}: {e}"
            return lambda done: self._fail_batch(items, bucket, cause,
                                                 done)

        t_staged = obs.monotonic()
        pack_ms = (t_staged - t0) * 1e3
        for p in items:
            p.stages["host_pack_ms"] = pack_ms

        def finish(done: list) -> None:
            t_fin = obs.monotonic()
            try:
                status, fail_at, n_final = fin()
            except Exception as e:              # noqa: BLE001
                self._fail_batch(items, bucket,
                                 f"{type(e).__name__}: {e}", done)
                return
            self._sleep_remaining_tunnel(t_staged)
            t_done = obs.monotonic()
            eng = info.get("engine", self.engine)
            xfer = info.get("transfer_bytes") or {}
            self._account_dispatch(bucket.key, t_staged, t_done,
                                   eng, xfer, rids)
            pk = (model_name, bucket.key, b_prog, ns, nt, self.F, eng)
            bs = self._bstats(bucket.key)
            bs.dispatches += 1
            bs.batched += len(items)
            bs.occupancy_sum += len(items) / b_prog
            if self.shards > 1:
                from .sharding import shard_fill

                fills = shard_fill(len(items), b_prog, self.shards)
                bs.shard_fill_sum += (
                    sum(1 for f in fills if f > 0) / self.shards)
            # stage duration + finalize wait for THIS dispatch only:
            # under the ring, wall time between stage and finish
            # belongs to OTHER buckets' host packs and must not
            # inflate this bucket's device seconds
            bs.device_s += (t_staged - t0) + (t_done - t_fin)
            if pk in self._programs:
                self.m["program_hits"] += 1
            else:
                self._programs.add(pk)
                bs.compiles += 1
                self.m["compiles"] += 1
            bs.programs.add(pk)
            self.m["dispatches"] += 1
            with obs.span("finalize", bucket=bucket.key, rids=rids):
                for i, p in enumerate(items):
                    p.stages["device_ms"] = (t_done - t_staged) * 1e3
                    p.stages["finalize_ms"] = \
                        (obs.monotonic() - t_done) * 1e3
                    self._finish(p, self._reply(
                        p.rid, protocol.verdict(status[i]),
                        op_index=int(fail_at[i]),
                        final_count=int(n_final[i]),
                        engine=eng, bucket=bucket.key,
                        batched=len(items)), done)

        return finish

    def _sleep_remaining_tunnel(self, t_staged: float) -> None:
        """The injected-latency model of the ASYNC tunnel: readback
        completes ``inject`` seconds after DISPATCH, so finalize pays
        only the part of the round-trip that has not already elapsed
        while the ring staged other buckets — exactly the overlap the
        real link gives the double-buffered path."""
        if self.inject_dispatch_latency_s <= 0.0:
            return
        remaining = (t_staged + self.inject_dispatch_latency_s
                     - obs.monotonic())
        if remaining > 0.0:
            time.sleep(remaining)

    def _account_dispatch(self, bucket_key: str, t_staged: float,
                          t_done: float, engine: str, xfer: dict,
                          rids: list) -> None:
        """Per-dispatch device window: the span (retroactive — the
        device ran asynchronously since stage time) and the
        host<->device transfer-byte counters. The device stage is
        dispatch->readback-complete: it includes the async overlap
        window the ring creates plus any injected tunnel latency,
        which is exactly what a request WAITS on (the per-dispatch
        compute-only seconds stay in the bucket's ``device_s``; the
        per-REQUEST stage histograms observe at reply time in
        ``_finish``)."""
        h2d, d2h = int(xfer.get("h2d", 0)), int(xfer.get("d2h", 0))
        obs.record("device", t_staged, t_done, bucket=bucket_key,
                   engine=engine, bytes_h2d=h2d, bytes_d2h=d2h,
                   rids=rids)
        if not self._priming:
            self._c_h2d.inc(h2d)
            self._c_d2h.inc(d2h)

    def _fail_batch(self, items, bucket, cause, done) -> None:
        self.m["engine_errors"] += 1
        self._event("engine_error", obs.monotonic())
        for p in items:
            self._finish(p, self._reply(p.rid, "unknown",
                                        cause=f"engine: {cause}",
                                        bucket=bucket.key), done)

    def _dispatch_wl_begin(self, bucket: WlBucket,
                           items: List[PendingRequest]):
        """Stage one wl-family bucket chunk: encode the column planes
        and launch ONE device program (``stage_wl_batch``'s finalize
        is the readback point) — same ring contract as
        :meth:`_dispatch_begin`. The bucket's sig pins the padded
        per-history axes and its ``model_key`` pinned the slot, so
        every item shares one encode model and one compiled
        program; the batch axis pow2-pads inside the stage by
        duplicating lane 0."""
        from ..checker.wl import batch as WLB

        t0 = obs.monotonic()
        rids = [p.rid for p in items]
        for p in items:
            p.stages["queue_wait_ms"] = (t0 - p.t_in) * 1e3
        family = bucket.family
        wlmodel = items[0].packed[1]
        hists = [p.packed[2] for p in items]
        d0 = WLB.DISPATCHES
        try:
            with obs.span("stage", kind="wl", bucket=bucket.key,
                          b=len(items), rids=rids):
                fin0 = WLB.stage_wl_batch(hists, family, wlmodel,
                                          dims=wl_dims_of(bucket))
        except Exception as e:                  # noqa: BLE001
            cause = f"{type(e).__name__}: {e}"
            return lambda done: self._fail_batch(items, bucket, cause,
                                                 done)
        n_disp = WLB.DISPATCHES - d0
        bp = WLB.bucket_of(len(items), WLB.WL_BATCH)
        t_staged = obs.monotonic()
        pack_ms = (t_staged - t0) * 1e3
        for p in items:
            p.stages["host_pack_ms"] = pack_ms

        def finish(done: list) -> None:
            t_fin = obs.monotonic()
            try:
                verdicts = fin0()
            except Exception as e:              # noqa: BLE001
                self._fail_batch(items, bucket,
                                 f"{type(e).__name__}: {e}", done)
                return
            if n_disp:
                self._sleep_remaining_tunnel(t_staged)
            t_done = obs.monotonic()
            # n_disp == 0 means the stage degraded the whole chunk to
            # the host oracle (encode-time overflow) — the verdicts
            # carry engine:"host" and no program accounting applies
            eng = ("wl-device" if n_disp
                   else verdicts[0].get("engine", "host"))
            if not n_disp:
                self.m["host_degraded"] += len(items)
            self._account_dispatch(bucket.key, t_staged, t_done, eng,
                                   {}, rids)
            bs = self._bstats(bucket.key)
            bs.dispatches += n_disp
            bs.batched += len(items)
            if n_disp:
                bs.occupancy_sum += len(items) / bp
                pk = ("wl", bucket.key, bp)
                if pk in self._programs:
                    self.m["program_hits"] += 1
                else:
                    self._programs.add(pk)
                    bs.compiles += 1
                    self.m["compiles"] += 1
                bs.programs.add(pk)
            bs.device_s += (t_staged - t0) + (t_done - t_fin)
            self.m["dispatches"] += n_disp
            with obs.span("finalize", kind="wl", bucket=bucket.key,
                          rids=rids):
                for p, v in zip(items, verdicts):
                    p.stages["device_ms"] = (t_done - t_staged) * 1e3
                    p.stages["finalize_ms"] = \
                        (obs.monotonic() - t_done) * 1e3
                    self._finish(p, self._wl_reply(
                        p.rid, v, family,
                        engine=v.get("engine", eng),
                        bucket=bucket.key, batched=len(items)), done)

        return finish

    def _dispatch_txn_begin(self, bucket: TxnBucket,
                            items: List[PendingRequest]):
        """Stage ONE device dispatch for a txn bucket's chunk (same
        ring contract as :meth:`_dispatch_begin`): every graph pads to
        the bucket's N, the batch axis pow2-pads with copies of the
        first adjacency, and the whole stack rides a single
        ``closure_diag_batch_async`` call (the per-item-dispatch
        rule) whose packed upload is donated into the squaring loop.
        Mixed realtime flags coexist in one batch — a request without
        realtime edges simply ships an all-zero rt plane."""
        import numpy as np

        from ..txn.closure_jax import closure_diag_batch_async

        t0 = obs.monotonic()
        rids = [p.rid for p in items]
        for p in items:
            p.stages["queue_wait_ms"] = (t0 - p.t_in) * 1e3
        with obs.span("stage", kind="txn", bucket=bucket.key,
                      b=len(items), rids=rids):
            adjs = [p.packed.padded(bucket.N) for p in items]
            # same shard-slot fill as the check kind: D | b_prog, pow2
            b_prog = max(_next_pow2(len(adjs)), self.shards)
            adjs = adjs + [adjs[0]] * (b_prog - len(adjs))
            stacked = np.stack(adjs)
        try:
            fin = closure_diag_batch_async(stacked, mesh=self.mesh)
        except Exception as e:                  # noqa: BLE001
            cause = f"{type(e).__name__}: {e}"

            def fail(done: list) -> None:
                self.m["engine_errors"] += 1
                self._event("engine_error", obs.monotonic())
                for p in items:
                    self._finish(p, self._reply(
                        p.rid, "unknown", kind="txn",
                        cause=f"engine: {cause}",
                        bucket=bucket.key), done)

            return fail
        t_staged = obs.monotonic()
        pack_ms = (t_staged - t0) * 1e3
        h2d = int(stacked.nbytes)

        def finish(done: list) -> None:
            from ..txn.check import verdict_map
            from ..txn.counterexample import decode

            t_fin = obs.monotonic()
            try:
                diag = fin()
            except Exception as e:              # noqa: BLE001
                self.m["engine_errors"] += 1
                self._event("engine_error", obs.monotonic())
                for p in items:
                    self._finish(p, self._reply(
                        p.rid, "unknown", kind="txn",
                        cause=f"engine: {type(e).__name__}: {e}",
                        bucket=bucket.key), done)
                return
            self._sleep_remaining_tunnel(t_staged)
            t_done = obs.monotonic()
            self._account_dispatch(
                bucket.key, t_staged, t_done, "closure",
                {"h2d": h2d, "d2h": int(diag.nbytes)}, rids)
            pk = ("txn", bucket.key, b_prog)
            bs = self._bstats(bucket.key)
            bs.dispatches += 1
            bs.batched += len(items)
            bs.occupancy_sum += len(items) / b_prog
            if self.shards > 1:
                from .sharding import shard_fill

                fills = shard_fill(len(items), b_prog, self.shards)
                bs.shard_fill_sum += (
                    sum(1 for f in fills if f > 0) / self.shards)
            bs.device_s += (t_staged - t0) + (t_done - t_fin)
            if pk in self._programs:
                self.m["program_hits"] += 1
            else:
                self._programs.add(pk)
                bs.compiles += 1
                self.m["compiles"] += 1
            bs.programs.add(pk)
            self.m["dispatches"] += 1
            with obs.span("finalize", kind="txn", bucket=bucket.key,
                          rids=rids):
                for i, p in enumerate(items):
                    g = p.packed
                    cex = decode(g, diag[i][:, :g.n],
                                 realtime=p.realtime)
                    p.stages["host_pack_ms"] = pack_ms
                    p.stages["device_ms"] = (t_done - t_staged) * 1e3
                    p.stages["finalize_ms"] = \
                        (obs.monotonic() - t_done) * 1e3
                    self._finish(p, self._txn_reply(
                        p.rid, verdict_map(g, cex), engine="closure",
                        bucket=bucket.key, batched=len(items)), done)

        return finish

    def _host_check_txn(self, p: PendingRequest, done: list) -> None:
        """Over-limit txn graphs degrade to the host SCC engine, one
        request at a time — same contract as the linear host route."""
        from ..txn import check_txn

        self.m["host_degraded"] += 1
        t0 = self._degrade_begin(p)
        try:
            with obs.span("host_degrade", kind="txn", rid=p.rid):
                result = check_txn((), graph=p.packed, backend="host",
                                   realtime=p.realtime)
            reply = self._txn_reply(p.rid, result, engine="host",
                                    degraded=True)
        except Exception as e:                  # noqa: BLE001
            reply = self._reply(p.rid, "unknown", kind="txn",
                                cause=f"host engine: {e}",
                                engine="host", degraded=True)
        p.stages["device_ms"] = (obs.monotonic() - t0) * 1e3
        self._finish(p, reply, done)

    def _host_check(self, p: PendingRequest, done: list) -> None:
        """Out-of-bucket degradation: the host engine checks this one
        request alone (``max_host_configs``-bounded — blowups answer
        ``unknown``, they don't wedge the pump)."""
        from ..checker import linear
        from ..models.model import MODELS

        self.m["host_degraded"] += 1
        t0 = self._degrade_begin(p)
        try:
            with obs.span("host_degrade", kind="check", rid=p.rid):
                a = linear.analysis(
                    MODELS[p.model](), p.packed, backend="host",
                    max_host_configs=self.max_host_configs)
            reply = self._reply(
                p.rid, a.valid,
                op_index=(-1 if a.op_index is None else a.op_index),
                engine="host", degraded=True)
        except Exception as e:                  # noqa: BLE001
            reply = self._reply(p.rid, "unknown",
                                cause=f"host engine: {e}",
                                engine="host", degraded=True)
        p.stages["device_ms"] = (obs.monotonic() - t0) * 1e3
        self._finish(p, reply, done)

    def _host_check_wl(self, p: PendingRequest, done: list) -> None:
        """Over-rung wl histories degrade to the demoted host oracle
        (checker/workloads.py), one request at a time — same contract
        as the linear/txn host routes."""
        from ..checker.wl.batch import _host_fallback

        self.m["host_degraded"] += 1
        t0 = self._degrade_begin(p)
        family, wlmodel, ops = p.packed
        try:
            with obs.span("host_degrade", kind="wl", rid=p.rid):
                v = _host_fallback([ops], family, wlmodel)[0]
            reply = self._wl_reply(p.rid, v, family, engine="host",
                                   degraded=True)
        except Exception as e:                  # noqa: BLE001
            reply = self._reply(p.rid, "unknown", kind="wl",
                                family=family,
                                cause=f"host engine: {e}",
                                engine="host", degraded=True)
        p.stages["device_ms"] = (obs.monotonic() - t0) * 1e3
        self._finish(p, reply, done)

    def _degrade_begin(self, p: PendingRequest) -> float:
        """Shared host-degrade stage bookkeeping: the engine run is
        attributed to the device stage (it is what the request waits
        on; the ``engine: "host"`` reply field disambiguates)."""
        t0 = obs.monotonic()
        p.stages["queue_wait_ms"] = (t0 - p.t_in) * 1e3
        self._event("host_degraded", t0)
        return t0

    # -- bookkeeping ---------------------------------------------------

    def _reply(self, rid, valid, **extra) -> dict:
        out = {"ok": True, "valid": valid, **extra}
        if rid is not None:
            out["id"] = rid
        return out

    def _finish(self, p: PendingRequest, reply: dict,
                done: list) -> None:
        now = obs.monotonic()
        lat_ms = (now - p.t_in) * 1e3
        reply.setdefault("latency_ms", round(lat_ms, 3))
        # absent stages observe as 0 so every stage histogram shares
        # the latency histogram's count and sum(stages) tiles
        # latency_ms on EVERY reply path — deadline expiries (pure
        # queue wait) included
        for s in STAGES:
            p.stages.setdefault(s, 0.0)
            self._observe(s, p.stages[s])
        # rounded ONCE, shared read-only by the reply, the timeline
        # row and the trace record (single-threaded core)
        stages = {k: round(v, 3) for k, v in p.stages.items()}
        reply.setdefault("stages", stages)
        self._latencies.append(lat_ms)
        self.m["completed"] += 1
        if not self._priming:
            self._h_latency.observe(lat_ms)
            self._drain_win.append(now)
            self._timeline.append({
                "t": round(p.t_in - self.t_boot, 4),
                "lat_ms": round(lat_ms, 3), "kind": p.kind,
                "valid": reply.get("valid"), "stages": stages})
        if obs.enabled():
            # one complete per-request row for the trace: admission
            # time to reply, rid-correlated, stage attribution in args
            obs.record("request", p.t_in, now, rid=p.rid,
                       kind=p.kind, valid=reply.get("valid"),
                       **stages)
        done.append((p, reply))

    def _observe(self, stage: str, ms: float) -> None:
        """Feed one stage histogram sample (priming traffic never
        pollutes the serving metrics)."""
        if not self._priming:
            self._stage_h[stage].observe(ms)

    def _event(self, kind: str, now: Optional[float] = None) -> None:
        if self._priming:
            return
        self._events.append({
            "t": round((obs.monotonic() if now is None else now)
                       - self.t_boot, 4),
            "event": kind})

    def timeline_records(self) -> Tuple[list, list]:
        """(per-request rows, event marks) for the timeline SVG."""
        return list(self._timeline), list(self._events)

    def _bstats(self, key: str) -> _BucketStats:
        bs = self._buckets.get(key)
        if bs is None:
            bs = self._buckets[key] = _BucketStats()
        return bs

    # -- warm-start ----------------------------------------------------

    def prime(self, specs=DEFAULT_PRIME, seed: int = 7) -> int:
        """Compile-cache warm-start: synthesize one history per spec
        and push it through the REAL dispatch path at B=1 and B=copies
        — with the persistent XLA cache on, a restarted daemon serves
        its first real request from a warm program. Returns the number
        of priming dispatches."""
        import random

        from ..ops.packed import pack_history
        from ..ops.synth import register_history

        n0 = self.m["dispatches"]
        sink: list = []
        self._priming = True       # priming must not pollute the
        try:                       # serving histograms/timeline
            for n_events, copies in specs:
                h = register_history(random.Random(seed), n_procs=3,
                                     n_events=n_events, p_info=0.0)
                packed = pack_history(h)
                bucket = bucket_for(packed, self.limits)
                if bucket is None:
                    continue
                now = obs.monotonic()
                items = [PendingRequest(rid=None, model=self.model,
                                        packed=packed, bucket=bucket,
                                        t_in=now)
                         for _ in range(max(1, copies))]
                for i in range(0, len(items), self.batch_cap):
                    self._dispatch(self.model, bucket,
                                   items[i:i + self.batch_cap], sink)
        finally:
            self._priming = False
        n = self.m["dispatches"] - n0
        self.m["primed"] += n
        # priming replies go nowhere: back their completion count and
        # latency samples out so the serving metrics stay honest
        self.m["completed"] -= len(sink)
        for _ in sink:
            if self._latencies:
                self._latencies.pop()
        return n

    # -- observability -------------------------------------------------

    def metrics_reply(self, rid=None) -> dict:
        """The ``kind:"metrics"`` scrape reply: the JSON snapshot AND
        the Prometheus text form in one frame (docs/service.md)."""
        self._sync_metrics()
        out = {"ok": True, "kind": "metrics",
               "metrics": self.metrics.snapshot(),
               "prometheus": self.metrics.render_prometheus()}
        if rid is not None:
            out["id"] = rid
        return out

    def _sync_metrics(self) -> None:
        """Mirror the scalar state into the registry at scrape time:
        the ``m`` counters (launch reasons included), queue depth,
        ring occupancy, per-bucket occupancy/shard_fill, and the
        process-global compile + carry-reuse counters
        (``XLA_COMPILES`` / ``MOSAIC_BUILDS`` / ``closure_jax.
        COMPILES`` / ``pallas_seg.CARRY_REUSES`` — so a scrape shows
        both a recompile storm and the donation hit rate as moving
        counters)."""
        m = self.metrics
        self._g_queue.set(self.queue_depth())
        self._g_ring.set(len(self._ring))
        self._g_sessions.set(len(self.sessions))
        self._g_carry_bytes.set(self.sessions.carry_bytes())
        self._g_epoch.set(self.ring_epoch)
        m.gauge(
            "stream_checkpoints_held",
            help="evicted sessions resumable from a host checkpoint"
        ).set(self.sessions.checkpoint_count())
        m.counter("service_stream_restores_total").value = \
            self.sessions.restores
        for k, v in self.m.items():
            m.counter(f"service_{k}_total").value = v
        for key, bs in self._buckets.items():
            occ = (bs.occupancy_sum / bs.dispatches
                   if bs.dispatches else 0.0)
            m.gauge("service_bucket_occupancy",
                    bucket=key).set(round(occ, 4))
            m.gauge("service_bucket_requests", bucket=key) \
                .set(bs.requests)
            m.gauge("service_bucket_dispatches", bucket=key) \
                .set(bs.dispatches)
            if self.shards > 1:
                fill = (bs.shard_fill_sum / bs.dispatches
                        if bs.dispatches else 0.0)
                m.gauge("service_bucket_shard_fill",
                        bucket=key).set(round(fill, 4))
        from ..checker import pallas_seg as PS
        from ..txn import closure_jax as CJ
        from ..utils import compile_guard as CG

        m.counter("compile_xla_lowerings_total").value = \
            CG.XLA_COMPILES
        m.counter("compile_mosaic_builds_total").value = \
            PS.MOSAIC_BUILDS
        m.counter("compile_closure_programs_total").value = \
            CJ.COMPILES
        m.counter(
            "service_carry_reuses_total",
            help="stream-kernel carry buffers recycled on device "
                 "instead of re-uploaded (pallas_seg carry pool)"
        ).value = PS.CARRY_REUSES

    def status(self, now: Optional[float] = None) -> dict:
        from ..checker import pallas_seg as PS

        now = obs.monotonic() if now is None else now
        lats = sorted(self._latencies)
        buckets = {}
        for key, bs in self._buckets.items():
            buckets[key] = {
                "requests": bs.requests,
                "dispatches": bs.dispatches,
                "batched": bs.batched,
                "compiles": bs.compiles,
                "programs": len(bs.programs),
                "occupancy": round(
                    bs.occupancy_sum / bs.dispatches, 4)
                if bs.dispatches else 0.0,
                "device_s": round(bs.device_s, 3),
            }
            if self.shards > 1:
                # fraction of the D shard slots holding at least one
                # live request, averaged over dispatches — the shard-
                # placement quality metric
                buckets[key]["shard_fill"] = round(
                    bs.shard_fill_sum / bs.dispatches, 4) \
                    if bs.dispatches else 0.0
        return {
            **self.m,
            "injected_dispatch_latency_ms":
                round(self.inject_dispatch_latency_s * 1e3, 3),
            "uptime_s": round(now - self.t_boot, 3),
            "queue_depth": self.queue_depth(),
            "inflight_ring": len(self._ring),
            "ring_depth": self.ring_depth,
            "fill_window_ms": round(self.fill_window_s * 1e3, 3),
            "carry_reuses": PS.CARRY_REUSES,
            "draining": self.draining,
            "ring_epoch": self.ring_epoch,
            "stream": {
                "sessions": len(self.sessions),
                "max_sessions": self.sessions.max_sessions,
                "carry_bytes": self.sessions.carry_bytes(),
                "idle_s": self.sessions.idle_s,
                "checkpoints_held": self.sessions.checkpoint_count(),
                "restores": self.sessions.restores,
            },
            "model": self.model,
            "engine": self.engine,
            "shards": self.shards,
            "frontier": self.F,
            "batch_cap": self.batch_cap,
            "max_queue": self.max_queue,
            "programs": len(self._programs),
            "latency_ms": {
                "p50": round(_percentile(lats, 0.50), 3),
                "p99": round(_percentile(lats, 0.99), 3),
                "n": len(lats),
            },
            # the stage-histogram quantiles ride the status artifact
            # (harness.store web status) so the p99/p50 gap is
            # attributable without a full metrics scrape
            "stage_ms": {
                s.replace("_ms", ""): {
                    "p50": round(h.quantile(0.50), 3),
                    "p95": round(h.quantile(0.95), 3),
                    "p99": round(h.quantile(0.99), 3),
                    "n": h.count,
                } for s, h in self._stage_h.items()},
            "transfer_bytes": {"h2d": self._c_h2d.value,
                               "d2h": self._c_d2h.value},
            "tracing": obs.enabled(),
            "buckets": buckets,
        }


__all__ = ["DEFAULT_FILL_WINDOW_S", "DEFAULT_PRIME",
           "DEFAULT_RING_DEPTH", "PendingRequest", "STAGES",
           "VerifierCore"]
