"""CLI: ``python -m comdb2_tpu.service`` — run the verifier daemon.

Prints one JSON ready-line (``{"ready": true, "port": N, ...}``) on
stdout once listening; scripts parse it instead of racing the port.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
from typing import List, Optional

from .bucketing import ServiceLimits
from .core import DEFAULT_PRIME, VerifierCore
from .daemon import PMUX_SERVICE, VerifierDaemon


def _force_backend(name: str) -> str:
    """Pick the JAX platform through the config API — env vars are
    read at import and the ambient startup hook may have imported jax
    already (CLAUDE.md); also turn on the persistent compile cache so
    a restarted daemon reuses every bucket's programs."""
    import jax

    from ..utils.platform import enable_compile_cache, ensure_backend

    enable_compile_cache()
    if name == "cpu":
        jax.config.update("jax_platforms", "cpu")
        backend = jax.default_backend()
        if backend != "cpu":
            raise SystemExit(
                f"requested cpu but got {backend!r} — a backend was "
                "initialized before the daemon could switch platforms")
        return backend
    # "auto"/"tpu": keep the ambient platform (the tunneled TPU
    # registers under the plugin's own name, e.g. "axon" — forcing the
    # literal string "tpu" would crash with "unknown backend")
    backend = ensure_backend()
    if name == "tpu" and backend == "cpu":
        raise SystemExit("requested a TPU backend but only cpu is "
                         "available in this environment")
    return backend


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m comdb2_tpu.service",
        description="batching checker-as-a-service daemon")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 = pick a free port (printed in the "
                        "ready line)")
    p.add_argument("--model", default="cas-register")
    p.add_argument("--engine", default="auto",
                   choices=["auto", "stream", "keys", "flat", "vmap"])
    p.add_argument("--backend", default="auto",
                   choices=["auto", "cpu", "tpu"],
                   help="JAX platform (config API, not env)")
    p.add_argument("--frontier", type=int, default=1024,
                   help="device frontier capacity F")
    p.add_argument("--batch-cap", type=int, default=64,
                   help="max live requests per device dispatch")
    p.add_argument("--shards", type=int, default=1,
                   help="shard-placement axis: shard every bucket "
                        "dispatch D ways over a device mesh (the "
                        "batch axis pads to a pow2 multiple of D; "
                        "1 = single-device path, no mesh)")
    p.add_argument("--max-queue", type=int, default=256,
                   help="admission cap; beyond it requests get "
                        "explicit overload replies (with a "
                        "retry_after_ms hint)")
    p.add_argument("--fill-ms", "--coalesce-ms", type=float,
                   default=5.0, dest="fill_ms",
                   help="cap on how long a forming batch may wait "
                        "for batch-mates (continuous batching: a "
                        "full batch launches immediately, an idle "
                        "wire launches everything; deadlines tighten "
                        "this per request). --coalesce-ms is the "
                        "legacy spelling")
    p.add_argument("--ring", type=int, default=3, metavar="N",
                   help="bounded in-flight dispatch ring depth: N "
                        "buckets staged/running/finalizing "
                        "concurrently (host-pack vs async device "
                        "overlap)")
    p.add_argument("--no-donate", action="store_true",
                   help="disable carry-buffer donation + the device "
                        "carry pool (parity/debugging; donation is "
                        "the production default)")
    p.add_argument("--max-ops", type=int, default=8192)
    p.add_argument("--max-segments", type=int, default=4096)
    p.add_argument("--max-sessions", type=int, default=64,
                   help="streaming-session cap (kind:\"stream\" — "
                        "each session holds a device-resident carry; "
                        "past the cap, open answers overload with "
                        "retry_after_ms)")
    p.add_argument("--session-idle-s", type=float, default=300.0,
                   help="idle TTL before a streaming session's carry "
                        "is checkpointed to host and evicted (the "
                        "next verb restores it with zero replay)")
    p.add_argument("--drain-s", type=float, default=10.0,
                   help="drain grace: after SIGTERM or kind:\"drain\" "
                        "the daemon deregisters from pmux, re-routes "
                        "queued work, finalizes staged dispatches, "
                        "and keeps serving session-checkpoint "
                        "handoffs this long before exiting")
    p.add_argument("--no-prime", action="store_true",
                   help="skip compile-cache warm-start at boot")
    p.add_argument("--interpret", action="store_true",
                   help="run the fused Pallas kernel in interpret "
                        "mode (exact kernel semantics as XLA ops on "
                        "any backend; per-spec compiles are slow)")
    p.add_argument("--inject-dispatch-latency-ms", type=float,
                   default=0.0, metavar="MS",
                   help="benchmarking: sleep MS per device dispatch, "
                        "modeling the tunneled TPU's ~100 ms "
                        "dispatch+readback round-trip on CPU; "
                        "reported in status as injected")
    p.add_argument("--pmux", type=int, nargs="?", const=5105,
                   default=None, metavar="PORT",
                   help="publish the port under sut/verifier via "
                        "ct_pmux at PORT (default 5105)")
    p.add_argument("--pmux-service", default=PMUX_SERVICE)
    p.add_argument("--pmux-shard", type=int, default=None,
                   metavar="IDX",
                   help="register as sut/verifier/IDX — one entry "
                        "per daemon of a horizontally scaled fleet; "
                        "RoutedClient consistent-hash routes over "
                        "all of them")
    p.add_argument("--store", default=None, metavar="DIR",
                   help="persist status snapshots under DIR/service/ "
                        "(served by the store web browser)")
    p.add_argument("--trace", action="store_true",
                   help="enable span tracing (obs.trace): with "
                        "--store, a Chrome/Perfetto trace.json is "
                        "written next to the status artifacts; "
                        "disabled-mode cost is one flag check per "
                        "span site (docs/observability.md)")
    args = p.parse_args(argv)

    if args.trace:
        from ..obs import trace as obs_trace

        obs_trace.enable()
    backend = _force_backend(args.backend)
    if args.interpret:
        from ..checker import pallas_seg

        pallas_seg.use_interpret(True)
    if args.no_donate:
        from ..checker import pallas_seg

        pallas_seg.use_carry_donation(False)
    limits = ServiceLimits(max_ops=args.max_ops,
                           max_segments=args.max_segments)
    core = VerifierCore(
        model=args.model, engine=args.engine,
        F=args.frontier, batch_cap=args.batch_cap,
        max_queue=args.max_queue, limits=limits,
        inject_dispatch_latency_s=args.inject_dispatch_latency_ms
        / 1e3, shards=args.shards,
        fill_window_s=args.fill_ms / 1e3, ring_depth=args.ring,
        max_sessions=args.max_sessions,
        session_idle_s=args.session_idle_s)
    pmux_service = args.pmux_service
    if args.pmux_shard is not None:
        pmux_service = f"{PMUX_SERVICE}/{args.pmux_shard}"
    daemon = VerifierDaemon(core, host=args.host, port=args.port,
                            pmux_port=args.pmux,
                            pmux_service=pmux_service,
                            store_root=args.store,
                            drain_grace_s=args.drain_s)
    # SIGTERM = graceful leave (deregister BEFORE the listener closes,
    # re-route queued work, serve checkpoint handoffs through the
    # grace window); SIGINT stays the immediate stop
    signal.signal(signal.SIGTERM, daemon.drain)
    signal.signal(signal.SIGINT, daemon.stop)
    primed = 0
    if not args.no_prime:
        primed = core.prime(DEFAULT_PRIME)
    # publish BEFORE the ready line: "ready" must mean discoverable.
    # Publish failure keeps the daemon serving (discovery is
    # additive) but the ready line then reports pmux_service null —
    # a fleet booter gating on it sees the truth instead of racing
    # RoutedClient.discover against a registration that never
    # happened.
    daemon._pmux_publish()
    print(json.dumps({"ready": True, "host": daemon.host,
                      "port": daemon.port, "backend": backend,
                      "model": args.model, "shards": args.shards,
                      "ring": args.ring,
                      "fill_ms": args.fill_ms,
                      "pmux_service": (pmux_service
                                       if daemon.published
                                       else None),
                      "primed": primed, "trace": args.trace}),
          flush=True)
    daemon.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
