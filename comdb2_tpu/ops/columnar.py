"""Columnar host ingest — the struct-of-arrays packer.

The per-op packer (:func:`~.packed.pack_history_legacy` plus
``history.complete``) walks every row as a Python object: attribute
reads, ``with_`` copies, dict bookkeeping — ~3.5 us/op, which at the
4096x2k-op batch shape is minutes of host time against ~70 s of device
time (BENCH_r05: ``host_pack_s = 278.2``). This module rebuilds the
same transformation as columnar NumPy over parallel arrays:

- one pass extracts the op columns (the ONLY per-op loop — the Op list
  is the API edge),
- invocation/completion pairing, double-pending validation, value
  back-fill bookkeeping, and transition-id assignment are vectorized
  (per-process runs via one stable argsort; first-occurrence interning
  via ``np.unique`` re-ranked by first index),
- ``f``/``process``/``value`` interning stays an exact dict pass over
  the columns (values are arbitrary Python objects; hashing them is
  the contract — see ``_Interner``), which no longer dominates once
  the object churn is gone.

Every output is BIT-IDENTICAL to the legacy packer — same arrays, same
table orders, same error classes on malformed input — enforced by the
golden parity tests (``tests/test_columnar_parity.py``) over the fuzz
corpus families. UNKNOWN-verdict comparability across engines depends
on that: a packer that reordered transition ids would shift frontier
contents and fail indices between releases.

Set ``COMDB2_TPU_LEGACY_PACK=1`` to route :func:`~.packed.pack_history`
(and ``make_segments``/the batch remap) through the per-op
implementations — kept for one release as a cross-check lever.
"""

from __future__ import annotations

from typing import Any, List, Tuple

import numpy as np

from .op import FAIL, INVOKE, OK, TYPE_CODES, Op


def _intern_column(column) -> Tuple[np.ndarray, List[Any]]:
    """First-occurrence interning of arbitrary hashable objects.
    Exact ``_Interner`` semantics (ids in first-appearance order) —
    the dict pass is kept because values mix types (``None``, ints,
    tuples) and any numpy coercion would silently merge ``1`` with
    ``"1"`` or unpack tuples into 2-D arrays."""
    ids: dict = {}
    table: List[Any] = []
    codes = np.empty(len(column), np.int32)
    get = ids.get
    for i, x in enumerate(column):
        j = get(x)
        if j is None:
            j = len(table)
            ids[x] = j
            table.append(x)
        codes[i] = j
    return codes, table


def _first_occurrence_codes(arr: np.ndarray):
    """Re-rank ``np.unique``'s sorted ids into first-appearance order
    so integer-keyed interning matches the dict interner exactly."""
    uniq, first, inv = np.unique(arr, return_index=True,
                                 return_inverse=True)
    order = np.argsort(first, kind="stable")
    rank = np.empty(order.size, np.int64)
    rank[order] = np.arange(order.size)
    return rank[inv.reshape(-1)], uniq[order]


def _per_process_prev(proc_codes: np.ndarray, sel_idx: np.ndarray,
                      is_inv: np.ndarray):
    """Per-process event chains via ONE stable argsort: for the
    selected rows, returns (sorted row ids, 'previous same-process
    event was an invoke' flags, previous same-process row ids)."""
    pr = proc_codes[sel_idx]
    order = np.argsort(pr, kind="stable")
    srt = sel_idx[order]
    psort = pr[order]
    start = np.empty(order.size, bool)
    if order.size:
        start[0] = True
        start[1:] = psort[1:] != psort[:-1]
    inv_flag = is_inv[srt]
    prev_inv = np.empty(order.size, bool)
    prev_row = np.empty(order.size, np.int64)
    if order.size:
        prev_inv[0] = False
        prev_inv[1:] = inv_flag[:-1]
        prev_row[0] = -1
        prev_row[1:] = srt[:-1]
        prev_inv[start] = False
        prev_row[start] = -1
    return srt, inv_flag, prev_inv, prev_row


def intern_transitions(f_codes: np.ndarray, value_codes: np.ndarray,
                       inv_rows: np.ndarray, n_values: int, n: int):
    """First-occurrence (f_id, value_id) transition interning over the
    non-failing invoke rows — THE id order every engine's key layout
    depends on. One implementation shared by the packer and the
    columnar generator (bit-parity is a contract between them).
    Returns ``(trans int32[n], transition_table)``."""
    trans = np.full(n, -1, np.int32)
    if inv_rows.size:
        combo = (f_codes[inv_rows].astype(np.int64) * n_values
                 + value_codes[inv_rows])
        tr_codes, tr_keys = _first_occurrence_codes(combo)
        trans[inv_rows] = tr_codes
        table = [(int(c // n_values), int(c % n_values))
                 for c in tr_keys]
    else:
        table = []
    return trans, table


def pack_history_columnar(history: List[Op], completed: bool = False):
    """Columnar :func:`~.packed.pack_history` — same contract, same
    arrays, same tables, same exceptions; see the module docstring."""
    from .packed import PackedHistory

    n = len(history)
    # the API-edge pass: Op objects -> parallel columns
    procs = [op.process for op in history]
    fs = [op.f for op in history]
    vals = [op.value for op in history]
    type_codes = np.fromiter((TYPE_CODES[op.type] for op in history),
                             np.int8, n)
    fails = np.fromiter((op.fails for op in history), np.bool_, n)
    time = np.fromiter((-1 if op.time is None else op.time
                        for op in history), np.int64, n)

    proc_codes, process_table = _intern_column(procs)
    f_codes, f_table = _intern_column(fs)

    is_inv = type_codes == INVOKE
    is_ok = type_codes == OK
    is_fail = type_codes == FAIL
    sel_idx = np.flatnonzero(is_inv | is_ok | is_fail)
    srt, inv_flag, prev_inv, prev_row = _per_process_prev(
        proc_codes, sel_idx, is_inv)

    if not completed:
        # history.complete's validation, vectorized: per process the
        # invoke/completion events must strictly alternate starting
        # with an invoke
        dbl = inv_flag & prev_inv
        if dbl.any():
            i = int(srt[dbl].min())
            j = int(prev_row[dbl][np.argmin(srt[dbl])])
            raise RuntimeError(
                f"process {history[i].process!r} already running "
                f"{history[j]}, yet invoked {history[i]}")
        orphan = ~inv_flag & ~prev_inv
        if orphan.any():
            i = int(srt[orphan].min())
            raise RuntimeError(
                f"{history[i].type} without invocation: {history[i]}")
    else:
        # legacy pack-loop semantics on pre-completed input: a later
        # invoke silently overwrites the pending one (its pair stays
        # -1); a completion with no pending invoke is a KeyError
        orphan = ~inv_flag & ~prev_inv
        if orphan.any():
            i = int(srt[orphan].min())
            raise KeyError(history[i].process)

    comp = ~inv_flag & prev_inv
    crow = srt[comp]
    irow = prev_row[comp]
    pair = np.full(n, -1, np.int32)
    pair[crow] = irow
    pair[irow] = crow

    if not completed:
        vals = list(vals)
        ok_pairs = is_ok[crow]
        for c, i in zip(crow[ok_pairs].tolist(),
                        irow[ok_pairs].tolist()):
            vals[i] = vals[c]           # back-fill from the ok
        for c, i in zip(crow[~ok_pairs].tolist(),
                        irow[~ok_pairs].tolist()):
            iv, fv = vals[i], vals[c]
            if iv is not None and fv is not None and iv != fv:
                raise RuntimeError(
                    f"invocation value {iv!r} and failure value "
                    f"{fv!r} don't match: {history[c]}")
            v = iv if iv is not None else fv
            vals[i] = v
            vals[c] = v
        fails = fails.copy()
        fails[irow[~ok_pairs]] = True
        fails[crow[~ok_pairs]] = True

    value_codes, value_table = _intern_column(vals)

    trans, transition_table = intern_transitions(
        f_codes, value_codes, np.flatnonzero(is_inv & ~fails),
        max(len(value_table), 1), n)

    return PackedHistory(
        process=proc_codes, type=type_codes, f=f_codes,
        value=value_codes, trans=trans, pair=pair, fails=fails,
        time=time, process_table=process_table, f_table=f_table,
        value_table=value_table, transition_table=transition_table,
        ops_list=(list(history) if completed else None))


def subset_packed(parent, keep: np.ndarray):
    """Row-sliced ``PackedHistory`` VIEW of ``parent`` — the shrink
    candidate fast path: one boolean gather per column, SHARED intern
    tables (process/f/value/transition ids keep their parent meaning,
    so a whole batch of candidates can ride the parent's memoized
    model without re-interning). ``keep`` must be pair-closed — both
    rows of every invoke/complete pair kept or dropped together
    (``ValueError`` otherwise): a half-op would desynchronize the
    per-process alternation every segment builder relies on."""
    from .packed import PackedHistory

    keep = np.asarray(keep, bool)
    n = len(parent.process)
    if keep.shape != (n,):
        raise ValueError(f"mask shape {keep.shape} != ({n},)")
    pair = np.asarray(parent.pair)
    kept_pair = pair[keep]
    has = kept_pair >= 0
    if has.any() and not keep[kept_pair[has]].all():
        raise ValueError("mask is not pair-closed: a kept op's "
                         "invoke/complete partner is dropped")
    idx_new = np.cumsum(keep, dtype=np.int64) - 1
    new_pair = np.where(
        has, idx_new[np.clip(kept_pair, 0, None)], -1).astype(np.int32)
    return PackedHistory(
        process=parent.process[keep], type=parent.type[keep],
        f=parent.f[keep], value=parent.value[keep],
        trans=parent.trans[keep], pair=new_pair,
        fails=parent.fails[keep], time=parent.time[keep],
        process_table=parent.process_table, f_table=parent.f_table,
        value_table=parent.value_table,
        transition_table=parent.transition_table)


__all__ = ["intern_transitions", "pack_history_columnar",
           "subset_packed"]
