"""Whole-batch columnar history generation — arrays in, arrays out.

:func:`comdb2_tpu.ops.synth.register_history` simulates one process
pool step-at-a-time in Python (~2.5 us/event); at the 4096x2k-op bench
shape that alone is ~50 s of host time. This module generates the SAME
workload class — linearizable-by-construction cas-register histories
over N single-threaded processes — for a whole batch at once, straight
into :class:`~.packed.PackedHistory` arrays, with no Op objects on the
way (they stay a lazy ``.ops`` view at the API edge).

Construction (the standard serial-schedule trick the porcupine-style
checkers use for synthetic load):

- op ``k`` of every history APPLIES at integer time ``k`` — the serial
  order is the op order, so register semantics reduce to one
  vectorized scan over op positions with the whole batch as lanes;
- each op's invoke/completion events get continuous jitter times
  strictly inside ``(previous same-process completion, k)`` and
  ``(k, next same-process op)`` — every op takes effect between its
  invoke and completion and each process stays single-threaded, hence
  linearizable by construction with up to ``n_procs`` calls in flight;
- the per-(history, process) chains (prev/next op, crash retirement
  pid renames) come from ONE flat ``np.lexsort`` over (history,
  process, op);
- events sort into history order with one batched argsort; process /
  f / value / transition interning re-ranks ``np.unique`` ids into
  first-occurrence order, matching the dict interner exactly.

Statistically this matches ``register_history(n_procs=N)`` (uniform
f/value mix, same crash-retirement discipline); it is NOT seed-
compatible with the Python generator — bit-parity is a PACKER
contract (tests/test_columnar_parity.py), not a generator one.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from .columnar import _first_occurrence_codes, intern_transitions
from .op import FAIL, INFO, INVOKE, OK
from .packed import PackedHistory

F_NAMES = ("read", "write", "cas")
_EPS = 1e-3


class RegisterBatchColumns(NamedTuple):
    """Per-EVENT columns for a batch of histories, in history order
    (axis 0 = history, axis 1 = the 2*n_ops events). ``vkey`` is the
    numeric value encoding (0 = nil, 1+x = int x, 1+V+a*V+b = the cas
    pair (a, b)); ``pair`` holds partner event positions (-1 for
    crashed ops)."""
    type: np.ndarray    # int8[B, 2n]
    pid: np.ndarray     # int64[B, 2n] — process names (post-retirement)
    f: np.ndarray       # int8[B, 2n]  — 0 read / 1 write / 2 cas
    vkey: np.ndarray    # int64[B, 2n]
    fails: np.ndarray   # bool[B, 2n]
    pair: np.ndarray    # int32[B, 2n]
    values: int         # the value-alphabet size (decodes vkey)


def register_batch_columns(seed: int, n_histories: int, n_ops: int,
                           n_procs: int = 5, values: int = 5,
                           p_info: float = 0.0) -> RegisterBatchColumns:
    """Generate ``n_histories`` distinct register histories of
    ``n_ops`` completed ops each, as one columnar event table."""
    B, n = n_histories, n_ops
    if n <= 0 or B <= 0:
        raise ValueError("need n_histories >= 1 and n_ops >= 1")
    rng = np.random.default_rng(seed)
    f = rng.integers(0, 3, (B, n)).astype(np.int8)
    wval = rng.integers(0, values, (B, n))
    casa = rng.integers(0, values, (B, n))
    casb = rng.integers(0, values, (B, n))
    proc = rng.integers(0, n_procs, (B, n))
    u = rng.random((B, n))
    v = rng.random((B, n))
    info = (rng.random((B, n)) < p_info) if p_info > 0 \
        else np.zeros((B, n), bool)

    # serial register semantics: op k applies at time k — one scan
    # over op positions, all histories as vector lanes (-1 = nil)
    state = np.full(B, -1, np.int64)
    readv = np.empty((B, n), np.int64)
    casok = np.zeros((B, n), bool)
    for k in range(n):
        readv[:, k] = state
        okm = (f[:, k] == 2) & (state == casa[:, k])
        casok[:, k] = okm
        state = np.where(f[:, k] == 1, wval[:, k],
                         np.where(okm, casb[:, k], state))

    # per-(history, process) chains via one flat lexsort
    flat_b = np.repeat(np.arange(B), n)
    flat_k = np.tile(np.arange(n), B)
    flat_p = proc.ravel()
    order = np.lexsort((flat_k, flat_p, flat_b))
    ks = flat_k[order].astype(np.float64)
    grp = np.empty(order.size, bool)
    grp[0] = True
    grp[1:] = (flat_b[order][1:] != flat_b[order][:-1]) \
        | (flat_p[order][1:] != flat_p[order][:-1])
    last = np.empty(order.size, bool)
    last[:-1] = grp[1:]
    last[-1] = True
    next_k = np.empty(order.size, np.float64)
    next_k[:-1] = ks[1:]
    next_k[last] = float(n)
    # completion strictly inside (k, next same-process op)
    comp_s = ks + _EPS + v.ravel()[order] * (next_k - ks - 2 * _EPS)
    prev_comp = np.empty(order.size, np.float64)
    prev_comp[1:] = comp_s[:-1]
    prev_comp[grp] = -1.0
    # invoke strictly inside (previous completion, k)
    span = np.maximum(ks - prev_comp - 2 * _EPS, 0.0)
    inv_s = ks - _EPS - u.ravel()[order] * span

    inv_t = np.empty(B * n, np.float64)
    comp_t = np.empty(B * n, np.float64)
    inv_t[order] = inv_s
    comp_t[order] = comp_s
    inv_t = inv_t.reshape(B, n)
    comp_t = comp_t.reshape(B, n)

    # crash retirement: after a process's c-th :info op, its later ops
    # carry a fresh pid = n_procs + (per-history crash counter)
    pid = proc
    if info.any():
        ret_rank = np.cumsum(info, axis=1) - 1          # per history
        flat_rank = np.where(info, ret_rank, -1).ravel()[order]
        run = np.where(grp, np.arange(order.size), 0)
        run = np.maximum.accumulate(run)                # group starts
        # carry the latest info rank forward WITHIN each group, shifted
        # one op (the rename applies after the crash completion); the
        # running max restarts at group boundaries via a per-group
        # base offset that dominates every in-group rank
        shifted = np.empty(order.size, np.int64)
        shifted[1:] = flat_rank[:-1]
        shifted[grp] = -1
        base = run * (n + 2)
        seen = np.maximum.accumulate(base + shifted + 1) - base - 1
        pid_s = np.where(seen >= 0,
                         n_procs + seen, flat_p[order])
        pid = np.empty(B * n, np.int64)
        pid[order] = pid_s
        pid = pid.reshape(B, n)

    # completion types and completed values
    ctype = np.where(info, INFO,
                     np.where((f == 2) & ~casok, FAIL,
                              OK)).astype(np.int8)
    op_fail = ctype == FAIL
    vk = np.empty((B, n), np.int64)
    rmask = f == 0
    vk[rmask] = np.where(info[rmask] | (readv[rmask] < 0),
                         0, 1 + readv[rmask])
    vk[f == 1] = 1 + wval[f == 1]
    cmask = f == 2
    vk[cmask] = 1 + values + casa[cmask] * values + casb[cmask]

    # event assembly: argsort the 2n event times per history
    ev_t = np.concatenate([inv_t, comp_t], axis=1)
    perm = np.argsort(ev_t, axis=1, kind="stable")
    rank = np.argsort(perm, axis=1, kind="stable")

    def gather(col):
        return np.take_along_axis(col, perm, axis=1)

    two = lambda a: np.concatenate([a, a], axis=1)
    ev_type = gather(np.concatenate(
        [np.full((B, n), INVOKE, np.int8), ctype], axis=1))
    ev_pid = gather(two(pid))
    ev_f = gather(two(f))
    ev_vk = gather(two(vk))
    ev_fail = gather(two(op_fail))
    pair = np.full((B, 2 * n), -1, np.int32)
    inv_pos = rank[:, :n]
    comp_pos = rank[:, n:]
    live = ~info
    bgrid = np.repeat(np.arange(B), n).reshape(B, n)
    pair[bgrid[live], inv_pos[live]] = comp_pos[live]
    pair[bgrid[live], comp_pos[live]] = inv_pos[live]
    return RegisterBatchColumns(ev_type, ev_pid, ev_f, ev_vk, ev_fail,
                                pair, values)


def _decode_vkey(key: int, values: int):
    if key == 0:
        return None
    if key <= values:
        return int(key - 1)
    k = key - 1 - values
    return (int(k // values), int(k % values))


def pack_register_columns(
        cols: RegisterBatchColumns) -> List[PackedHistory]:
    """Intern each history's event columns into a PackedHistory —
    first-occurrence table orders, exactly like the packer's."""
    B, m = cols.type.shape
    V = cols.values
    out: List[PackedHistory] = []
    is_inv = cols.type == INVOKE
    for b in range(B):
        pcodes, ptab = _first_occurrence_codes(cols.pid[b])
        fcodes, ftab = _first_occurrence_codes(cols.f[b])
        vcodes, vtab = _first_occurrence_codes(cols.vkey[b])
        fails = cols.fails[b]
        trans, ttab = intern_transitions(
            fcodes, vcodes, np.flatnonzero(is_inv[b] & ~fails),
            max(len(vtab), 1), m)
        out.append(PackedHistory(
            process=pcodes.astype(np.int32),
            type=cols.type[b].copy(),
            f=fcodes.astype(np.int32),
            value=vcodes.astype(np.int32),
            trans=trans, pair=cols.pair[b].copy(),
            fails=fails.copy(),
            time=np.full(m, -1, np.int64),
            process_table=[int(x) for x in ptab],
            f_table=[F_NAMES[x] for x in ftab],
            value_table=[_decode_vkey(int(k), V) for k in vtab],
            transition_table=ttab))
    return out


def register_batch_packed(seed: int, n_histories: int, n_ops: int,
                          n_procs: int = 5, values: int = 5,
                          p_info: float = 0.0) -> List[PackedHistory]:
    """One-call columnar generate + pack (see module docstring)."""
    return pack_register_columns(register_batch_columns(
        seed, n_histories, n_ops, n_procs=n_procs, values=values,
        p_info=p_info))


# --- genuinely-concurrent wide-P histories (MXU engine load) ---------------
#
# ``pinned_wide_history`` (ops/synth.py) exercises wide-P PackPlan
# coverage with crashed cas holding slots — it deliberately forks NO
# configs, so it can't exercise a wide-frontier engine. These waves
# do: every op of a wave is in flight at once (in-flight depth = P at
# the wave's first ok, and remap_slots reports P_eff = P), while the
# frontier stays CONTROLLED instead of the 2^P blow-up of unbounded
# concurrency:
#
# - ``n_chain`` cas ops form a strict chain (cas(v -> v+1 mod M)):
#   only one linearization order is consistent, so they contribute
#   chain-prefix configs, not subsets;
# - ``n_free`` reads all observe the chain's END value: each is
#   linearizable only once the chain completes, and then any SUBSET of
#   them may have linearized — 2^n_free configs.
#
# Peak frontier ~ n_chain + 2^n_free, tunable independently of P =
# n_chain + n_free. n_free = 16 with P = 24 exceeds the XLA ladder's
# 65536 cap (the honest-UNKNOWN threshold this engine raises) while
# fitting the MXU ladder's 131072; tier-1 tests use small n_free.
#
# Linearizable by construction: op k of the serial schedule applies at
# position k (chain ops first, then the reads), every op's
# invoke..completion window spans its whole wave, and each process
# runs exactly one op per wave (single-threaded: wave event blocks are
# disjoint in time). The seeded-violation twin makes ONE read of the
# last wave observe (end+1) mod M — a value the register never holds
# inside that wave's window (windows span n_chain+1 < M values), so
# the frontier dies exactly at that read's ok.

def wide_register_batch_columns(seed: int, n_histories: int,
                                n_waves: int, n_chain: int,
                                n_free: int, values: int = 16,
                                violation: bool = False
                                ) -> RegisterBatchColumns:
    """Columns for genuinely-concurrent bounded-in-flight register
    histories at P = ``n_chain + n_free`` (see the block comment)."""
    B = n_histories
    P = n_chain + n_free
    M = values
    if B <= 0 or n_waves <= 0 or n_chain < 1 or n_free < 0:
        raise ValueError("need n_histories/n_waves >= 1, n_chain >= 1")
    if n_chain + 1 >= M:
        raise ValueError(
            f"need values > n_chain + 1 (got {M} <= {n_chain + 1}): "
            "a wave window may not wrap the whole value alphabet, or "
            "the seeded violation value could be legitimately "
            "observable")
    if violation and n_free < 1:
        raise ValueError(
            "violation=True needs n_free >= 1: the seeded violation "
            "is a free READ observing a value outside the wave's "
            "reachable window — with no free reads the twin would "
            "silently be a valid history")
    rng = np.random.default_rng(seed)
    m = 2 * n_waves * P                      # events per history
    ev_type = np.empty((B, m), np.int8)
    ev_pid = np.empty((B, m), np.int64)
    ev_f = np.empty((B, m), np.int8)
    ev_vk = np.empty((B, m), np.int64)
    pair = np.full((B, m), -1, np.int32)
    brow = np.arange(B)

    cur = rng.integers(0, M, B)              # per-history start value
    for j in range(n_waves):
        # per-history op schedule for this wave, in SERIAL order:
        # chain ops 0..n_chain-1 then reads. Wave 0's chain starts
        # with a write (the register boots nil — a cas can't fire).
        f = np.empty((B, P), np.int8)
        vk = np.empty((B, P), np.int64)
        if j == 0:
            f[:, 0] = 1                      # write(cur)
            vk[:, 0] = 1 + cur
        else:
            f[:, 0] = 2                      # cas(cur -> cur+1)
            vk[:, 0] = 1 + M + cur * M + ((cur + 1) % M)
            cur = (cur + 1) % M
        for i in range(1, n_chain):
            f[:, i] = 2
            vk[:, i] = 1 + M + cur * M + ((cur + 1) % M)
            cur = (cur + 1) % M
        f[:, n_chain:] = 0                   # reads of the end value
        vk[:, n_chain:] = (1 + cur)[:, None]
        if violation and j == n_waves - 1 and n_free > 0:
            # the twin: one read observes a value outside the wave's
            # reachable window
            vk[:, P - 1] = 1 + ((cur + 1) % M)
        # each process runs exactly one wave op; which op lands on
        # which process is shuffled per history
        perm = np.argsort(rng.random((B, P)), axis=1)
        # event order inside the wave: all P invokes (shuffled), then
        # all P completions (shuffled; the violating read completes
        # LAST so the frontier still peaks before it dies). argsort of
        # uniform noise is a uniform permutation — its rows ARE the
        # event positions of ops 0..P-1.
        ok_order = rng.random((B, P))
        if violation and j == n_waves - 1 and n_free > 0:
            ok_order[:, P - 1] = 2.0         # sorts last
        ok_rank = np.argsort(np.argsort(ok_order, axis=1), axis=1)
        base = 2 * P * j
        inv_pos = base + np.argsort(rng.random((B, P)), axis=1)
        ok_pos = base + P + ok_rank
        for col, pos in ((inv_pos, True), (ok_pos, False)):
            idx = (brow[:, None], col)
            ev_type[idx] = INVOKE if pos else OK
            ev_pid[idx] = perm
            ev_f[idx] = f
            ev_vk[idx] = vk
        pair[brow[:, None], inv_pos] = ok_pos
        pair[brow[:, None], ok_pos] = inv_pos
    fails = np.zeros((B, m), bool)
    return RegisterBatchColumns(ev_type, ev_pid, ev_f, ev_vk, fails,
                                pair, M)


def wide_register_batch_packed(seed: int, n_histories: int,
                               n_waves: int, n_chain: int,
                               n_free: int, values: int = 16,
                               violation: bool = False
                               ) -> List[PackedHistory]:
    """One-call columnar generate + pack of the wide-P wave histories
    (see :func:`wide_register_batch_columns`)."""
    return pack_register_columns(wide_register_batch_columns(
        seed, n_histories, n_waves, n_chain, n_free, values=values,
        violation=violation))


__all__ = ["RegisterBatchColumns", "register_batch_columns",
           "pack_register_columns", "register_batch_packed",
           "wide_register_batch_columns", "wide_register_batch_packed"]
