"""Operation & history core.

Semantics follow the reference's knossos/op.clj and knossos/history.clj;
the packed struct-of-arrays form is the tensor representation consumed by
the TPU checker.
"""

from .op import (
    Op,
    INVOKE,
    OK,
    FAIL,
    INFO,
    TYPE_NAMES,
    invoke,
    ok,
    fail,
    info,
    is_invoke,
    is_ok,
    is_fail,
    is_info,
)
from .history import complete, index, pairs, pair_index, processes
from .edn import read_edn, read_edn_all, write_edn, Keyword, kw
from .packed import PackedHistory, pack_history

__all__ = [
    "Op", "INVOKE", "OK", "FAIL", "INFO", "TYPE_NAMES",
    "invoke", "ok", "fail", "info",
    "is_invoke", "is_ok", "is_fail", "is_info",
    "complete", "index", "pairs", "pair_index", "processes",
    "read_edn", "read_edn_all", "write_edn", "Keyword", "kw",
    "PackedHistory", "pack_history",
]
