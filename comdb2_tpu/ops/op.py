"""Operations.

An operation is the atom of a history. Mirrors the reference's
``knossos/op.clj:9-60``: an op has a ``process`` (a logical
single-threaded client, or a symbolic actor like ``"nemesis"``), a
``type`` (invoke / ok / fail / info), a function ``f``, a ``value``, and —
once indexed — an ``index`` into its history. ``time`` is wall-clock
nanoseconds relative to test start.

We keep ops as a small mutable dataclass on the host; the checker consumes
the packed tensor form (see ``comdb2_tpu.ops.packed``), never these
objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Hashable, Optional

# Op types. Integer codes are the on-tensor encoding.
INVOKE = 0
OK = 1
FAIL = 2
INFO = 3

TYPE_NAMES = ("invoke", "ok", "fail", "info")
TYPE_CODES = {name: code for code, name in enumerate(TYPE_NAMES)}


@dataclass
class Op:
    """One operation in a history.

    ``type`` is one of the string names in :data:`TYPE_NAMES`. ``fails``
    is back-filled by :func:`comdb2_tpu.ops.history.complete` on
    invocations whose completion is a ``fail`` — checkers skip those
    (reference: ``knossos/history.clj:165``).
    """

    process: Hashable
    type: str
    f: Hashable
    value: Any = None
    index: Optional[int] = None
    time: Optional[int] = None
    fails: bool = False
    extra: dict = field(default_factory=dict)

    def with_(self, **kw) -> "Op":
        # hand-rolled replace(): the dataclasses version re-runs
        # __init__ with type checks and dominates host-side history
        # packing (millions of calls on the 4096-history batch axis)
        bad = kw.keys() - self.__dict__.keys()
        if bad:     # replace() raised on unknown fields; keep that
            raise TypeError(f"unknown Op field(s): {sorted(bad)}")
        new = Op.__new__(Op)
        new.__dict__ = {**self.__dict__, **kw}
        return new

    @property
    def type_code(self) -> int:
        return TYPE_CODES[self.type]

    def to_map(self) -> dict:
        """As an EDN-style keyword map (for history files)."""
        from .edn import kw

        m = {
            kw("process"): self.process,
            kw("type"): kw(self.type),
            kw("f"): kw(self.f) if isinstance(self.f, str) else self.f,
            kw("value"): self.value,
        }
        if self.index is not None:
            m[kw("index")] = self.index
        if self.time is not None:
            m[kw("time")] = self.time
        return m


def invoke(process, f, value=None, **kw) -> Op:
    return Op(process, "invoke", f, value, **kw)


def ok(process, f, value=None, **kw) -> Op:
    return Op(process, "ok", f, value, **kw)


def fail(process, f, value=None, **kw) -> Op:
    return Op(process, "fail", f, value, **kw)


def info(process, f, value=None, **kw) -> Op:
    return Op(process, "info", f, value, **kw)


def is_invoke(op: Op) -> bool:
    return op.type == "invoke"


def is_ok(op: Op) -> bool:
    return op.type == "ok"


def is_fail(op: Op) -> bool:
    return op.type == "fail"


def is_info(op: Op) -> bool:
    return op.type == "info"
