"""History preprocessing.

Reimplements the semantics of the reference's ``knossos/history.clj``:

- :func:`pairs` / :func:`pair_index` — match invocations with their
  completions (``history.clj:36-67``).
- :func:`complete` — back-fill an invocation's ``value`` from its ``ok``
  completion, and mark invocations whose completion is a ``fail`` with
  ``fails=True`` so checkers can skip them (``history.clj:87-171``). This
  is load-bearing: get it wrong and verdicts silently diverge.
- :func:`index` — attach sequential indices (``history.clj:173-179``).

Also hosts conversion between EDN keyword-maps (the interchange format of
``ctest/register.c -j`` and ``filetest``) and :class:`~.op.Op`.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, Iterable, List, Optional, Tuple

from .op import Op
from .edn import Keyword, kw, write_edn


def processes(history: Iterable[Op]) -> set:
    """The set of processes appearing in a history."""
    return {op.process for op in history}


def pairs(history: Iterable[Op]) -> List[Tuple[Op, Optional[Op]]]:
    """Pair invocations with completions, in completion order. Yields
    ``(invoke, ok|fail)`` tuples and ``(info, None)`` singletons.
    Asserts the single-threaded process discipline the reference enforces
    (``history.clj:44-51``)."""
    inflight: Dict[Hashable, Op] = {}
    out: List[Tuple[Op, Optional[Op]]] = []
    for op in history:
        if op.type == "info":
            out.append((op, None))
        elif op.type == "invoke":
            if op.process in inflight:
                raise RuntimeError(
                    f"process {op.process!r} invoked concurrently with itself")
            inflight[op.process] = op
        else:  # ok | fail
            if op.process not in inflight:
                raise RuntimeError(f"completion without invocation: {op}")
            out.append((inflight.pop(op.process), op))
    return out


def pair_index(history: List[Op]) -> Dict[int, Optional[int]]:
    """Map each op's index to its counterpart's index (invocation ↔
    completion). Infos map to None. Requires an indexed history."""
    inflight: Dict[Hashable, Op] = {}
    out: Dict[int, Optional[int]] = {}
    for op in history:
        if op.type == "invoke":
            inflight[op.process] = op
            out[op.index] = None  # provisional; overwritten on completion
        elif op.type in ("ok", "fail"):
            inv = inflight.pop(op.process, None)
            if inv is None:
                raise RuntimeError(f"completion without invocation: {op}")
            out[inv.index] = op.index
            out[op.index] = inv.index
        else:
            out[op.index] = None
    return out


def complete(history: List[Op], index: bool = False) -> List[Op]:
    """Fill in invocation values from their completions.

    For ``ok`` completions the invocation's value becomes the completion's
    value — we construct a history in which we "already knew" the result.
    For ``fail`` completions, both carry whichever value is known and the
    invocation gets ``fails=True``. Info ops pass through unchanged; their
    invocations stay pending forever. (``knossos/history.clj:87-171``.)

    With ``index=True`` sequential ``index`` fields are attached in the
    same pass (fused :func:`index`): positions are final at append time,
    and one pass halves the object churn on large batches.
    """
    out: List[Op] = []
    inflight: Dict[Hashable, int] = {}  # process -> position in `out`
    for op in history:
        if op.type == "invoke":
            if op.process in inflight:
                raise RuntimeError(
                    f"process {op.process!r} already running "
                    f"{out[inflight[op.process]]}, yet invoked {op}")
            out.append(op.with_(index=len(out)) if index else op)
            inflight[op.process] = len(out) - 1
        elif op.type == "ok":
            i = inflight.pop(op.process, None)
            if i is None:
                raise RuntimeError(f"ok without invocation: {op}")
            out[i] = out[i].with_(value=op.value)
            out.append(op.with_(index=len(out)) if index else op)
        elif op.type == "fail":
            i = inflight.pop(op.process, None)
            if i is None:
                raise RuntimeError(f"fail without invocation: {op}")
            inv = out[i]
            if (inv.value is not None and op.value is not None
                    and inv.value != op.value):
                # the reference asserts these match (history.clj:132-137);
                # silently reconciling would let a buggy driver skew verdicts
                raise RuntimeError(
                    f"invocation value {inv.value!r} and failure value "
                    f"{op.value!r} don't match: {op}")
            value = inv.value if inv.value is not None else op.value
            out[i] = inv.with_(value=value, fails=True)
            upd = {"value": value, "fails": True}
            if index:
                upd["index"] = len(out)
            out.append(op.with_(**upd))
        else:  # info
            out.append(op.with_(index=len(out)) if index else op)
    return out


def index(history: List[Op]) -> List[Op]:
    """Attach sequential ``index`` fields."""
    return [op.with_(index=i) for i, op in enumerate(history)]


# --- EDN interchange -------------------------------------------------------

def _plain(x: Any) -> Any:
    """Normalize an EDN value: keywords → plain strings, lists/tuples →
    tuples, sets → frozensets, maps → sorted tuples of pairs, so values
    are hashable and compare naturally."""
    if isinstance(x, Keyword):
        return str.__str__(x)
    if isinstance(x, (list, tuple)):
        return tuple(_plain(e) for e in x)
    if isinstance(x, (set, frozenset)):
        return frozenset(_plain(e) for e in x)
    if isinstance(x, dict):
        return tuple(sorted(((_plain(k), _plain(v)) for k, v in x.items()),
                            key=repr))
    return x


def op_from_map(m: dict) -> Op:
    """Build an Op from an EDN keyword map like
    ``{:type :invoke, :f :cas, :value [0 3], :process 1, :time 1234}``
    (the format emitted by ``ctest/register.c:282-307``)."""
    get = lambda name: m.get(kw(name))
    return Op(
        process=_plain(get("process")),
        type=str(_plain(get("type"))),
        f=_plain(get("f")),
        value=_plain(get("value")),
        index=get("index"),
        time=get("time"),
    )


def history_from_edn(forms: Any) -> List[Op]:
    """Accept either one top-level vector of op maps, or a sequence of
    top-level maps (one per line)."""
    if isinstance(forms, dict):
        forms = [forms]
    if (isinstance(forms, list) and len(forms) == 1
            and isinstance(forms[0], list)):
        # read_edn_all of a file holding a single vector
        forms = forms[0]
    return [op_from_map(m) for m in forms]


def parse_history(text: str) -> List[Op]:
    """Parse an EDN history file (vector-of-maps or map-per-line)."""
    from .edn import read_edn_all

    return history_from_edn(read_edn_all(text))


def history_to_edn(history: List[Op]) -> str:
    """Serialize a history as one EDN op map per line (the format
    ``jepsen.store`` writes to ``history.txt`` readers can re-check)."""
    return "\n".join(write_edn(op.to_map()) for op in history)
