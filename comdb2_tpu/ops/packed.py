"""Packed struct-of-arrays history — the tensor form.

This is the TPU-native analog of the reference's indexed op maps: every
op becomes one row across parallel int arrays, with ``f`` and ``value``
interned into id tables (the tensor equivalent of
``knossos/model/memo.clj:40-59``'s ``canonical-history``). All checker
device code consumes this form; the Op objects never leave the host —
and since the columnar ingest rebuild they are not even MATERIALIZED
unless an API edge (counterexample decode, report rendering) asks for
``.ops``, which lazily rebuilds the completed indexed list from the
arrays.

The production packer is :mod:`comdb2_tpu.ops.columnar`; the per-op
implementation below (:func:`pack_history_legacy`) is kept for one
release behind ``COMDB2_TPU_LEGACY_PACK=1`` as a parity cross-check.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Hashable, List, Optional

import numpy as np

from .op import Op, TYPE_CODES, TYPE_NAMES
from . import history as hist


def legacy_pack_enabled() -> bool:
    """True when the per-op packer/segmenter should run instead of the
    columnar path (``COMDB2_TPU_LEGACY_PACK=1``; read per call so
    tests can toggle it)."""
    return os.environ.get("COMDB2_TPU_LEGACY_PACK") == "1"


@dataclass
class PackedHistory:
    """A completed, indexed history as flat arrays.

    Attributes
    ----------
    process:    int32[n]  — interned process ids (see ``process_table``).
    type:       int8[n]   — 0 invoke / 1 ok / 2 fail / 3 info.
    f:          int32[n]  — interned f id.
    value:      int32[n]  — interned value id (whole value; tuple values
                             are interned as tuples).
    trans:      int32[n]  — interned (f, value) transition id for
                             invocations, -1 elsewhere (the tensor form of
                             ``memo.clj:131-142``'s transition-index).
    pair:       int32[n]  — index of the op's invocation/completion
                             partner, -1 for infos.
    fails:      bool[n]   — invocation will fail (skip in checkers).
    time:       int64[n]  — wall-clock nanos, -1 if unknown.
    *_table:    id → original object lookup lists.
    ops_list:   the completed indexed Op list, or None — materialized
                lazily via ``.ops`` (reporting only; the checkers never
                read it).
    """

    process: np.ndarray
    type: np.ndarray
    f: np.ndarray
    value: np.ndarray
    trans: np.ndarray
    pair: np.ndarray
    fails: np.ndarray
    time: np.ndarray
    process_table: List[Hashable]
    f_table: List[Hashable]
    value_table: List[Any]
    transition_table: List[tuple]  # (f_id, value_id) per transition id
    ops_list: Optional[List[Op]] = field(default=None, repr=False)

    def __len__(self) -> int:
        return len(self.process)

    @property
    def n_transitions(self) -> int:
        return len(self.transition_table)

    @property
    def ops(self) -> List[Op]:
        """The completed, indexed Op list — an API-edge VIEW rebuilt
        from the arrays on first access. Checker/device code must
        consume the arrays, never this."""
        if self.ops_list is None:
            self.ops_list = _materialize_ops(self)
        return self.ops_list


def _materialize_ops(p: PackedHistory) -> List[Op]:
    out: List[Op] = []
    t = p.time.tolist()
    fl = p.fails.tolist()
    # the API edge: reporting needs real Op objects back
    for i, (pc, tc, fc, vc) in enumerate(zip(
            p.process.tolist(), p.type.tolist(), p.f.tolist(),
            p.value.tolist())):
        out.append(Op(
            process=p.process_table[pc], type=TYPE_NAMES[tc],
            f=p.f_table[fc], value=p.value_table[vc], index=i,
            time=None if t[i] < 0 else t[i], fails=fl[i]))
    return out


class _Interner:
    def __init__(self):
        self.ids: Dict[Any, int] = {}
        self.table: List[Any] = []

    def __call__(self, x: Any) -> int:
        # _plain guarantees hashability; an unhashable value here is a
        # driver bug and silently interning its repr would skew verdicts
        i = self.ids.get(x)
        if i is None:
            i = len(self.table)
            self.ids[x] = i
            self.table.append(x)
        return i


def pack_history(history: List[Op], completed: bool = False) -> PackedHistory:
    """Complete + index a history and pack it into arrays.

    Pass ``completed=True`` if the history already went through
    :func:`comdb2_tpu.ops.history.complete` and :func:`...history.index`.

    Runs the columnar packer (:mod:`comdb2_tpu.ops.columnar`) — the
    per-op implementation survives one release behind
    ``COMDB2_TPU_LEGACY_PACK=1``; outputs are bit-identical
    (tests/test_columnar_parity.py).
    """
    if legacy_pack_enabled():
        return pack_history_legacy(history, completed=completed)
    from .columnar import pack_history_columnar

    return pack_history_columnar(history, completed=completed)


def pack_history_legacy(history: List[Op],
                        completed: bool = False) -> PackedHistory:
    """The original per-op packer (see :func:`pack_history`)."""
    if not completed:
        history = hist.complete(history, index=True)
    n = len(history)
    process = np.empty(n, np.int32)
    type_ = np.empty(n, np.int8)
    f_arr = np.empty(n, np.int32)
    value = np.empty(n, np.int32)
    trans = np.full(n, -1, np.int32)
    pair = np.full(n, -1, np.int32)
    fails = np.zeros(n, bool)
    time = np.full(n, -1, np.int64)

    iproc, if_, ival = _Interner(), _Interner(), _Interner()
    itrans = _Interner()
    inflight: Dict[Hashable, int] = {}

    for i, op in enumerate(history):
        process[i] = iproc(op.process)
        type_[i] = TYPE_CODES[op.type]
        f_arr[i] = if_(op.f)
        value[i] = ival(op.value)
        fails[i] = op.fails
        if op.time is not None:
            time[i] = op.time
        if op.type == "invoke":
            # failing invokes never linearize (checkers skip them,
            # linear.clj:226), so their transitions must not enter the
            # table — they'd inflate the memoized state space for nothing
            if not op.fails:
                trans[i] = itrans((int(f_arr[i]), int(value[i])))
            inflight[op.process] = i
        elif op.type in ("ok", "fail"):
            j = inflight.pop(op.process)
            pair[i] = j
            pair[j] = i

    return PackedHistory(
        process=process, type=type_, f=f_arr, value=value, trans=trans,
        pair=pair, fails=fails, time=time,
        process_table=iproc.table, f_table=if_.table, value_table=ival.table,
        transition_table=itrans.table,
        ops_list=list(history))
