"""A small EDN reader/writer.

The reference's native workload drivers emit Jepsen histories as EDN — a
vector of keyword maps (``linearizable/ctest/register.c:282-307``) — and
the offline checker reads them back with ``read-string``
(``linearizable/filetest/src/jepsen/filetest.clj:8-21``). This module
gives the framework the same interchange format without a Clojure
dependency.

Supported: nil / true / false, integers, floats, strings, keywords,
symbols (as strings), vectors, lists, maps, sets, and ``;`` comments.
Tagged literals are read by dropping the tag. That covers everything the
reference's history files contain.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Tuple


class Keyword(str):
    """An EDN keyword. Subclasses str, so a keyword compares equal to its
    name: ``kw("read") == "read"`` is True. This is deliberate — host code
    never needs ``op[":type"]``-style juggling.
    """

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f":{str.__str__(self)}"


_KW_CACHE: dict = {}


def kw(name: str) -> Keyword:
    k = _KW_CACHE.get(name)
    if k is None:
        k = Keyword(name)
        _KW_CACHE[name] = k
    return k


_DELIMS = set('()[]{}"; \t\n\r,')


def _tokenize(s: str) -> Iterator[Tuple[str, Any]]:
    i, n = 0, len(s)
    while i < n:
        c = s[i]
        if c in " \t\n\r,":
            i += 1
        elif c == ";":
            while i < n and s[i] != "\n":
                i += 1
        elif c == '"':
            j = i + 1
            buf = []
            closed = False
            while j < n:
                ch = s[j]
                if ch == "\\":
                    if j + 1 >= n:
                        raise ValueError("truncated escape in string")
                    esc = s[j + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r", '"': '"',
                                "\\": "\\"}.get(esc, esc))
                    j += 2
                elif ch == '"':
                    closed = True
                    break
                else:
                    buf.append(ch)
                    j += 1
            if not closed:
                raise ValueError("unterminated string")
            yield ("str", "".join(buf))
            i = j + 1
        elif c in "([{":
            yield ("open", c)
            i += 1
        elif c in ")]}":
            yield ("close", c)
            i += 1
        elif c == "#":
            if i + 1 < n and s[i + 1] == "{":
                yield ("open", "#{")
                i += 2
            elif i + 1 < n and s[i + 1] == "_":
                yield ("discard", None)
                i += 2
            else:
                # tagged literal tag: read the symbol and drop it
                j = i + 1
                while j < n and s[j] not in _DELIMS:
                    j += 1
                yield ("tag", s[i + 1:j])
                i = j
        elif c == "\\":  # character literal
            j = i + 1
            while j < n and s[j] not in _DELIMS:
                j += 1
            name = s[i + 1:j]
            yield ("atom", {"newline": "\n", "space": " ", "tab": "\t"}.get(
                name, name[:1]))
            i = j
        else:
            j = i
            while j < n and s[j] not in _DELIMS:
                j += 1
            yield ("sym", s[i:j])
            i = j


def _parse_sym(tok: str) -> Any:
    if tok == "nil":
        return None
    if tok == "true":
        return True
    if tok == "false":
        return False
    if tok.startswith(":"):
        return kw(tok[1:])
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        if tok.endswith("N") or tok.endswith("M"):
            return int(tok[:-1])
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    return tok  # bare symbol → string


class _Reader:
    def __init__(self, tokens: List[Tuple[str, Any]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        t = self.tokens[self.pos]
        self.pos += 1
        return t

    def skip_discards(self):
        """Consume any number of ``#_ form`` pairs before the next real
        form; collections call this so a trailing discard (``[1 #_2]``)
        doesn't swallow the closing delimiter."""
        while True:
            p = self.peek()
            if p is None or p[0] != "discard":
                return
            self.next()
            self.read()  # the discarded form

    def read(self) -> Any:
        self.skip_discards()
        if self.peek() is None:
            raise ValueError("unexpected end of input")
        kind, val = self.next()
        if kind == "sym":
            return _parse_sym(val)
        if kind in ("str", "atom"):
            return val
        if kind == "tag":
            return self.read()  # drop the tag, keep the form
        if kind == "open":
            if val == "(" or val == "[":
                out = []
                while True:
                    self.skip_discards()
                    p = self.peek()
                    if p is None:
                        raise ValueError("unterminated collection")
                    if p[0] == "close":
                        self.next()
                        return out
                    out.append(self.read())
            if val == "{":
                items = []
                while True:
                    self.skip_discards()
                    p = self.peek()
                    if p is None:
                        raise ValueError("unterminated map")
                    if p[0] == "close":
                        self.next()
                        if len(items) % 2:
                            raise ValueError("odd number of map elements")
                        return {_hashable(items[i]): items[i + 1]
                                for i in range(0, len(items), 2)}
                    items.append(self.read())
            if val == "#{":
                out = set()
                while True:
                    self.skip_discards()
                    p = self.peek()
                    if p is None:
                        raise ValueError("unterminated set")
                    if p[0] == "close":
                        self.next()
                        return out
                    out.add(_hashable(self.read()))
        raise ValueError(f"unexpected token {kind} {val!r}")


def _hashable(x: Any) -> Any:
    return tuple(_hashable(e) for e in x) if isinstance(x, list) else x


def read_edn(s: str) -> Any:
    """Read one EDN form from a string."""
    return _Reader(list(_tokenize(s))).read()


def read_edn_all(s: str) -> List[Any]:
    """Read every top-level EDN form in a string (e.g. one-op-per-line
    history files)."""
    r = _Reader(list(_tokenize(s)))
    out = []
    while True:
        r.skip_discards()
        if r.peek() is None:
            return out
        out.append(r.read())


def write_edn(x: Any) -> str:
    """Serialize a Python value as EDN text."""
    if x is None:
        return "nil"
    if x is True:
        return "true"
    if x is False:
        return "false"
    if isinstance(x, Keyword):
        return f":{str.__str__(x)}"
    if isinstance(x, str):
        return '"' + x.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(x, (int, float)):
        return repr(x)
    if isinstance(x, (list, tuple)):
        return "[" + " ".join(write_edn(e) for e in x) + "]"
    if isinstance(x, (set, frozenset)):
        return "#{" + " ".join(write_edn(e) for e in sorted(x, key=repr)) + "}"
    if isinstance(x, dict):
        return "{" + ", ".join(
            f"{write_edn(k)} {write_edn(v)}" for k, v in x.items()) + "}"
    raise TypeError(f"cannot serialize {type(x)} as EDN")
