"""Keyed op values — the ``independent/tuple`` MapEntry analog
(``independent.clj:20-28``). Lives in ops so both the checker layer and
the models can type-test keyed values without import cycles."""

from __future__ import annotations

from typing import Any


class KVTuple(tuple):
    """A (key, value) pair distinguishable from ordinary tuple values."""

    __slots__ = ()

    def __new__(cls, k, v):
        return tuple.__new__(cls, (k, v))

    @property
    def key(self):
        return self[0]

    @property
    def value(self):
        return self[1]


def tuple_(k, v) -> KVTuple:
    return KVTuple(k, v)


def is_tuple(x: Any) -> bool:
    return isinstance(x, KVTuple)
