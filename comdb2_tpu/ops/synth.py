"""Synthetic concurrent-history generation for checker validation and
benchmarks.

Simulates N single-threaded processes against a genuinely atomic
register: each in-flight op takes effect at one random instant between
its invoke and its completion, so generated histories are linearizable
by construction. ``mutate`` then corrupts completions to produce
mostly-invalid variants. This plays the role the reference fills with
recorded known-good/known-bad EDN histories (`linearizable/filetest/`).
"""

from __future__ import annotations

import random
from typing import List, Optional

from . import op as O


class _Proc:
    __slots__ = ("name", "f", "value", "applied", "result")

    def __init__(self, name):
        self.name = name
        self.f = None          # in-flight op, or None if idle
        self.value = None
        self.applied = False
        self.result = None


def register_history(rng: random.Random, n_procs: int = 3, n_events: int = 12,
                     values: int = 3, fs=("read", "write", "cas"),
                     p_info: float = 0.05,
                     max_pending: Optional[int] = None) -> List[O.Op]:
    """A linearizable cas-register history with ~``n_events`` total ops.

    ``max_pending`` caps how many ops are in flight at once without
    narrowing the process table — wide-concurrency tests (the
    reference CLI default is 30 threads, ``cli.clj:52-91``) need wide
    slot tensors, but an op mix where half of 30 threads sit pending
    at every instant is a frontier the *reference* can't search either;
    real harness runs complete ops in milliseconds against a seconds-
    scale stagger, so in-flight stays far below thread count."""
    state: Optional[int] = None
    procs = [_Proc(i) for i in range(n_procs)]
    next_pid = n_procs
    h: List[O.Op] = []
    while len(h) < n_events:
        pool = procs
        if max_pending is not None:
            pending = [p for p in procs if p.f is not None]
            if len(pending) >= max_pending:
                pool = pending
        pr = rng.choice(pool)
        if pr.f is None:
            pr.f = rng.choice(fs)
            pr.applied = False
            if pr.f == "read":
                pr.value = None
            elif pr.f == "write":
                pr.value = rng.randrange(values)
            else:
                pr.value = (rng.randrange(values), rng.randrange(values))
            h.append(O.invoke(pr.name, pr.f, pr.value))
        elif not pr.applied:
            # linearization point: the op takes effect now
            pr.applied = True
            if pr.f == "read":
                pr.result = ("ok", state)
            elif pr.f == "write":
                state = pr.value
                pr.result = ("ok", pr.value)
            else:
                expected, new = pr.value
                if state == expected:
                    state = new
                    pr.result = ("ok", pr.value)
                else:
                    pr.result = ("fail", pr.value)
        else:
            if rng.random() < p_info:
                # crashed op: :info retires the process id; a fresh one
                # takes over the thread (jepsen/core.clj:178-200)
                h.append(O.info(pr.name, pr.f, pr.value))
                pr.name = next_pid
                next_pid += 1
            else:
                typ, v = pr.result
                h.append(O.Op(pr.name, typ, pr.f,
                              v if typ == "ok" else pr.value))
            pr.f = None
    # leave any still-in-flight ops pending (indeterminate) — that's legal
    return h


def mutate(rng: random.Random, history: List[O.Op],
           values: int = 3) -> List[O.Op]:
    """Corrupt one completed read/write value; usually breaks validity."""
    h = [op.with_() for op in history]
    oks = [i for i, op in enumerate(h) if op.type == "ok"]
    if not oks:
        return h
    i = rng.choice(oks)
    op = h[i]
    if op.f == "cas":
        a, b = op.value if op.value else (0, 0)
        h[i] = op.with_(value=((a + 1) % values, b))
    else:
        v = op.value if isinstance(op.value, int) else 0
        h[i] = op.with_(value=(v + 1) % values)
    return h


def pinned_wide_history(n_pinned: int = 18,
                        with_reads: bool = True) -> List[O.Op]:
    """A history whose EFFECTIVE slot count (max concurrent open
    calls, post slot-renaming) is ``n_pinned``+1 while the search
    frontier stays tiny: each pinned slot is a crashed (:info) cas
    whose expected value (9) is unreachable — forever open, so it
    holds its slot, but it can never linearize, so it forks no
    configs. The recipe that still drives the multi-word PackPlan
    dedup now that slot renaming collapses wide-but-shallow
    histories (a real concurrency-18 closure is a 2^18 frontier no
    engine — the reference included — can search). Used by both the
    ``dryrun_multichip`` wide-P gate stage and the CPU suite so they
    validate the same history shape."""
    h: List[O.Op] = []
    for i in range(n_pinned):
        h.append(O.invoke(2000 + i, "cas", (9, 1)))   # 9 unreachable
        h.append(O.info(2000 + i, "cas", (9, 1)))
        p = i % 3
        h.append(O.invoke(p, "write", i % 4))
        h.append(O.ok(p, "write", i % 4))
        if with_reads:
            h.append(O.invoke(p, "read", None))
            h.append(O.ok(p, "read", i % 4))
    return h
