"""Synthetic concurrent-history generation for checker validation and
benchmarks.

Simulates N single-threaded processes against a genuinely atomic
register: each in-flight op takes effect at one random instant between
its invoke and its completion, so generated histories are linearizable
by construction. ``mutate`` then corrupts completions to produce
mostly-invalid variants. This plays the role the reference fills with
recorded known-good/known-bad EDN histories (`linearizable/filetest/`).
"""

from __future__ import annotations

import random
from typing import List, Optional

from . import op as O


class _Proc:
    __slots__ = ("name", "f", "value", "applied", "result")

    def __init__(self, name):
        self.name = name
        self.f = None          # in-flight op, or None if idle
        self.value = None
        self.applied = False
        self.result = None


def register_history(rng: random.Random, n_procs: int = 3, n_events: int = 12,
                     values: int = 3, fs=("read", "write", "cas"),
                     p_info: float = 0.05,
                     max_pending: Optional[int] = None) -> List[O.Op]:
    """A linearizable cas-register history with ~``n_events`` total ops.

    ``max_pending`` caps how many ops are in flight at once without
    narrowing the process table — wide-concurrency tests (the
    reference CLI default is 30 threads, ``cli.clj:52-91``) need wide
    slot tensors, but an op mix where half of 30 threads sit pending
    at every instant is a frontier the *reference* can't search either;
    real harness runs complete ops in milliseconds against a seconds-
    scale stagger, so in-flight stays far below thread count."""
    state: Optional[int] = None
    procs = [_Proc(i) for i in range(n_procs)]
    next_pid = n_procs
    h: List[O.Op] = []
    while len(h) < n_events:
        pool = procs
        if max_pending is not None:
            pending = [p for p in procs if p.f is not None]
            if len(pending) >= max_pending:
                pool = pending
        pr = rng.choice(pool)
        if pr.f is None:
            pr.f = rng.choice(fs)
            pr.applied = False
            if pr.f == "read":
                pr.value = None
            elif pr.f == "write":
                pr.value = rng.randrange(values)
            else:
                pr.value = (rng.randrange(values), rng.randrange(values))
            h.append(O.invoke(pr.name, pr.f, pr.value))
        elif not pr.applied:
            # linearization point: the op takes effect now
            pr.applied = True
            if pr.f == "read":
                pr.result = ("ok", state)
            elif pr.f == "write":
                state = pr.value
                pr.result = ("ok", pr.value)
            else:
                expected, new = pr.value
                if state == expected:
                    state = new
                    pr.result = ("ok", pr.value)
                else:
                    pr.result = ("fail", pr.value)
        else:
            if rng.random() < p_info:
                # crashed op: :info retires the process id; a fresh one
                # takes over the thread (jepsen/core.clj:178-200)
                h.append(O.info(pr.name, pr.f, pr.value))
                pr.name = next_pid
                next_pid += 1
            else:
                typ, v = pr.result
                h.append(O.Op(pr.name, typ, pr.f,
                              v if typ == "ok" else pr.value))
            pr.f = None
    # leave any still-in-flight ops pending (indeterminate) — that's legal
    return h


def mutate(rng: random.Random, history: List[O.Op],
           values: int = 3) -> List[O.Op]:
    """Corrupt one completed read/write value; usually breaks validity."""
    h = [op.with_() for op in history]
    oks = [i for i, op in enumerate(h) if op.type == "ok"]
    if not oks:
        return h
    i = rng.choice(oks)
    op = h[i]
    if op.f == "cas":
        a, b = op.value if op.value else (0, 0)
        h[i] = op.with_(value=((a + 1) % values, b))
    else:
        v = op.value if isinstance(op.value, int) else 0
        h[i] = op.with_(value=(v + 1) % values)
    return h


#: anomaly kinds :func:`inject_anomaly` plants
ANOMALY_KINDS = ("stale-read", "lost-update", "dup-apply")


def inject_anomaly(history: List[O.Op], kind: str):
    """Plant one known-minimal register violation at the END of a
    valid history; returns ``(history2, truth)`` where ``truth`` is
    the exact minimal completed op set a 1-minimal shrinker must
    recover — so shrink tests can assert exact-minimum recovery, not
    just 1-minimality.

    The injected ops run sequentially on FRESH processes with FRESH
    values, so they never interfere with pending base ops. Kinds:

    - ``stale-read``   — ``w(A); w(B); r→A``: the read returns the
      overwritten value. Truth: the read pair alone (``r→A`` with no
      other ops can't be linearized from the initial state).
    - ``lost-update``  — ``w(A); cas(None→B) ok``: the cas observed
      the INITIAL state, so the write's update was lost. Truth: both
      pairs — each is valid alone (``cas(None→B)`` succeeds from the
      initial state; reads of ``None`` are model wildcards, so only
      the write+cas conjunction fails).
    - ``dup-apply``    — ``w(A); cas(A→B) ok; cas(A→B) ok``: the same
      cas applied twice (the ``-D`` no-dedup shape). Truth: one cas
      pair (a lone ``cas(A→B) ok`` asserts a state nothing
      established); the two copies are process/value-identical, so
      multiset comparison is deterministic.

    Exact-minimum recovery is provable when every sub-history of the
    base stays valid AND the base can't substitute for an injected
    op: write-only bases for stale-read/dup-apply, read-only bases
    (``r→None`` wildcards constrain nothing) for lost-update
    (``docs/shrink.md`` §ground truth). On mixed bases a smaller
    spurious minimum can exist — a read whose justifying write was
    dropped is still a violation.
    """
    ints = [v for op in history
            for v in (op.value if isinstance(op.value, tuple)
                      else (op.value,))
            if isinstance(v, int)]
    a = max(ints, default=0) + 1
    b = a + 1
    pids = [p for op in history for p in (op.process,)
            if isinstance(p, int)]
    p0 = max(pids, default=0) + 1

    def pair(p, f, inv_v, ok_v):
        return [O.invoke(p, f, inv_v), O.ok(p, f, ok_v)]

    if kind == "stale-read":
        extra = (pair(p0, "write", a, a) + pair(p0, "write", b, b)
                 + pair(p0 + 1, "read", None, a))
        # truth in COMPLETED form (invoke values back-filled from the
        # ok — the form shrink results and history.complete emit)
        truth = pair(p0 + 1, "read", a, a)
    elif kind == "lost-update":
        extra = (pair(p0, "write", a, a)
                 + pair(p0 + 1, "cas", (None, b), (None, b)))
        truth = extra[:]
    elif kind == "dup-apply":
        extra = (pair(p0, "write", a, a)
                 + pair(p0 + 1, "cas", (a, b), (a, b))
                 + pair(p0 + 1, "cas", (a, b), (a, b)))
        truth = extra[2:4]
    else:
        raise ValueError(f"unknown anomaly kind {kind!r} "
                         f"(one of {ANOMALY_KINDS})")
    return list(history) + extra, truth


def list_append_history(rng: random.Random, n_procs: int = 3,
                        n_txns: int = 12, n_keys: int = 3,
                        max_micro: int = 4, p_info: float = 0.0,
                        p_fail: float = 0.0) -> List[O.Op]:
    """A serializable-by-construction list-append txn history: each
    in-flight txn applies atomically at one random instant between
    its invoke and completion (so the serial order extends realtime —
    strictly serializable), reads return whole lists (version order
    is recoverable Elle-style), and appended values are unique per
    key. ``p_fail`` aborts a txn at its would-be apply point (nothing
    applies); ``p_info`` loses a completion after apply
    (indeterminate, writes visible)."""
    store = {k: [] for k in range(n_keys)}
    next_val = [0] * n_keys
    procs = [_Proc(i) for i in range(n_procs)]
    next_pid = n_procs
    started = 0
    h: List[O.Op] = []

    def plan(pr):
        mops = []
        for _ in range(rng.randrange(1, max_micro + 1)):
            k = rng.randrange(n_keys)
            if rng.random() < 0.5:
                mops.append(["append", k, None])   # value at apply
            else:
                mops.append(["r", k, None])
        pr.value = mops

    while True:
        open_ = [p for p in procs if p.f is not None]
        if started >= n_txns and not open_:
            break
        pr = rng.choice(open_ or procs) if started >= n_txns \
            else rng.choice(procs)
        if pr.f is None:
            pr.f = "txn"
            pr.applied = False
            plan(pr)
            h.append(O.invoke(
                pr.name, "txn",
                tuple((f, k, None) for f, k, _ in pr.value)))
            started += 1
        elif not pr.applied:
            pr.applied = True
            if p_fail and rng.random() < p_fail:
                pr.result = ("fail", tuple(
                    (f, k, None) for f, k, _ in pr.value))
                continue
            done = []
            for f, k, _ in pr.value:
                if f == "append":
                    v = next_val[k]
                    next_val[k] += 1
                    store[k].append(v)
                    done.append(("append", k, v))
                else:
                    done.append(("r", k, tuple(store[k])))
            pr.result = ("ok", tuple(done))
        else:
            typ, val = pr.result
            if p_info and rng.random() < p_info:
                h.append(O.info(pr.name, "txn", val))
                pr.name = next_pid
                next_pid += 1
            else:
                h.append(O.Op(pr.name, typ, "txn", val))
            pr.f = None
    return h


def txn_anomaly_history(kind: str) -> List[O.Op]:
    """Deterministic seeded txn histories, one per Adya anomaly class
    — the known-bad fixtures the serializability checker's tests and
    the check.sh smoke gate on. ``clean`` is the known-good twin."""
    def txn(p, mops, typ="ok"):
        inv = tuple((f, k, None if f == "r" else v) for f, k, v in mops)
        return [O.invoke(p, "txn", inv),
                O.Op(p, typ, "txn", tuple(mops))]

    if kind == "clean":
        return (txn(0, [("append", 0, 1)])
                + txn(1, [("r", 0, (1,)), ("append", 0, 2)])
                + txn(2, [("r", 0, (1, 2))]))
    if kind == "g0":
        # final reads disagree on who wrote first: ww cycle t0 <-> t1
        return (txn(0, [("append", 0, 1), ("append", 1, 2)])
                + txn(1, [("append", 0, 3), ("append", 1, 4)])
                + txn(2, [("r", 0, (1, 3)), ("r", 1, (4, 2))]))
    if kind == "g1c":
        # each txn reads the OTHER's append: wr cycle
        return (txn(0, [("append", 0, 1), ("r", 1, (2,))])
                + txn(1, [("append", 1, 2), ("r", 0, (1,))]))
    if kind == "g1a":
        # a failed txn's append observed by a committed read
        return (txn(0, [("append", 0, 1)], typ="fail")
                + txn(1, [("r", 0, (1,))]))
    if kind == "g2-item":
        # write skew: both read empty, each appends the other's key
        return (txn(0, [("r", 0, ()), ("append", 1, 1)])
                + txn(1, [("r", 1, ()), ("append", 0, 2)])
                + txn(2, [("r", 0, (2,)), ("r", 1, (1,))]))
    if kind == "duplicate":
        # the -D no-dedup shape: one append observed twice
        return (txn(0, [("append", 0, 1)])
                + txn(1, [("r", 0, (1, 1))]))
    raise ValueError(f"unknown anomaly kind {kind!r}")


def pinned_wide_history(n_pinned: int = 18,
                        with_reads: bool = True) -> List[O.Op]:
    """A history whose EFFECTIVE slot count (max concurrent open
    calls, post slot-renaming) is ``n_pinned``+1 while the search
    frontier stays tiny: each pinned slot is a crashed (:info) cas
    whose expected value (9) is unreachable — forever open, so it
    holds its slot, but it can never linearize, so it forks no
    configs. The recipe that still drives the multi-word PackPlan
    dedup now that slot renaming collapses wide-but-shallow
    histories (a real concurrency-18 closure is a 2^18 frontier no
    engine — the reference included — can search). Used by both the
    ``dryrun_multichip`` wide-P gate stage and the CPU suite so they
    validate the same history shape."""
    h: List[O.Op] = []
    for i in range(n_pinned):
        h.append(O.invoke(2000 + i, "cas", (9, 1)))   # 9 unreachable
        h.append(O.info(2000 + i, "cas", (9, 1)))
        p = i % 3
        h.append(O.invoke(p, "write", i % 4))
        h.append(O.ok(p, "write", i % 4))
        if with_reads:
            h.append(O.invoke(p, "read", None))
            h.append(O.ok(p, "read", i % 4))
    return h
