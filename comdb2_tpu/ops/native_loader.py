"""ctypes bindings for the native EDN history loader.

:func:`parse_history_fast` parses driver-format EDN (the ctest op-map
shape) through the C++ loader (~50x the Python reader) and falls back
to :func:`comdb2_tpu.ops.history.parse_history` for anything outside
the fast subset. Values reconstruct exactly as the Python reader builds
them: ``nil → None``, ints, ``[a b] → (a, b)``, ``[k [a b]] →
(k, (a, b))``; a ``nil`` inside a vector round-trips as ``None``.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

from .op import Op, TYPE_NAMES

_V_NIL, _V_INT, _V_VEC, _V_VECVEC = 0, 1, 2, 3
_NIL_SENTINEL = -(1 << 63)

_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False


def _find_lib() -> Optional[str]:
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    cand = os.path.join(root, "native", "build", "libct_sut.so")
    return cand if os.path.exists(cand) else None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = _find_lib()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.edn_load.restype = ctypes.c_void_p
        lib.edn_load.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                 ctypes.POINTER(ctypes.c_int)]
        lib.edn_load_free.argtypes = [ctypes.c_void_p]
        lib.edn_n_ops.restype = ctypes.c_longlong
        lib.edn_n_ops.argtypes = [ctypes.c_void_p]
        lib.edn_pool_len.restype = ctypes.c_longlong
        lib.edn_pool_len.argtypes = [ctypes.c_void_p]
        lib.edn_f_names.restype = ctypes.c_char_p
        lib.edn_f_names.argtypes = [ctypes.c_void_p]
        lib.edn_copy.argtypes = [ctypes.c_void_p] + \
            [np.ctypeslib.ndpointer(dt, flags="C_CONTIGUOUS")
             for dt in (np.int32, np.int8, np.int32, np.int64,
                        np.int8, np.int32, np.int32, np.int32,
                        np.int64)]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def native_available() -> bool:
    return _load_lib() is not None


def _decode_value(kind, off, ln, split, pool):
    if kind == _V_NIL:
        return None
    if kind == _V_INT:
        v = pool[off]
        return None if v == _NIL_SENTINEL else int(v)
    def elem(x):
        return None if x == _NIL_SENTINEL else int(x)
    if kind == _V_VEC:
        return tuple(elem(pool[off + i]) for i in range(ln))
    # V_VECVEC: outer ints with one inner vector at `split`
    inner_len = ln - split
    outer = [elem(pool[off + i]) for i in range(split)]
    inner = tuple(elem(pool[off + split + i]) for i in range(inner_len))
    return tuple(outer) + (inner,)


def parse_history_fast(text: str) -> List[Op]:
    """Parse an EDN history, preferring the native loader."""
    lib = _load_lib()
    if lib is None:
        from .history import parse_history

        return parse_history(text)

    raw = text.encode()
    rc = ctypes.c_int(0)
    handle = lib.edn_load(raw, len(raw), ctypes.byref(rc))
    if not handle:
        from .history import parse_history

        return parse_history(text)    # outside fast subset / malformed
    try:
        n = lib.edn_n_ops(handle)
        pool_n = lib.edn_pool_len(handle)
        process = np.empty(n, np.int32)
        type_ = np.empty(n, np.int8)
        f = np.empty(n, np.int32)
        time_us = np.empty(n, np.int64)
        val_kind = np.empty(n, np.int8)
        val_off = np.empty(n, np.int32)
        val_len = np.empty(n, np.int32)
        val_split = np.empty(n, np.int32)
        pool = np.empty(max(pool_n, 1), np.int64)
        lib.edn_copy(handle, process, type_, f, time_us, val_kind,
                     val_off, val_len, val_split, pool)
        f_names = lib.edn_f_names(handle).decode().split("\n")[:-1]
    finally:
        lib.edn_load_free(handle)

    out: List[Op] = []
    for i in range(n):
        out.append(Op(
            process=int(process[i]),
            type=TYPE_NAMES[type_[i]],
            f=f_names[f[i]],
            value=_decode_value(int(val_kind[i]), int(val_off[i]),
                                int(val_len[i]), int(val_split[i]),
                                pool),
            time=int(time_us[i]) if time_us[i] >= 0 else None,
        ))
    return out
