"""Static Pallas/Mosaic resource budgeting.

Checks kernel configurations against the measured v5e limits BEFORE a
40 s Mosaic compile fails (or worse, a 2048-step grid "Exceeded smem
capacity" lands in a fuzz loop):

- scalar-prefetch SMEM holds ~14336 int32 (~56 KB; 2048x10 fails);
- SMEM is ALSO bounded per grid step (~500 B/step toward the 1 MB
  space): a 2048-step grid fails compile while 1408 steps pass — the
  production CHUNK stays at 1024;
- grid-step blocks need (sublane, lane) dims that divide or are
  multiples of (8, 128), or equal the array dims;
- the fused kernel caps K (invokes per segment) at 8 and fixes the
  frontier capacity F at 128 (one vreg row).

Two layers:

- :func:`check_production` re-derives every ``spec_for`` tier the
  production bucket ladder can produce and budget-checks each
  (:func:`check_spec`); :func:`budget_table` renders the checked
  budgets as an artifact.
- :func:`scan_files` AST-scans ``pallas_call`` /
  ``PrefetchScalarGridSpec`` / ``BlockSpec`` sites (and ``spec_for``
  calls) for literally-bad configs, resolving module-level integer
  constants.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding, suppressed

SUBLANE, LANE = 8, 128
#: scalar-prefetch SMEM capacity in int32 words (~56 KB measured;
#: 2048x10 = 20480 words fails on v5e)
SMEM_PREFETCH_INT32 = 14336
#: approximate per-grid-step SMEM cost toward the 1 MB space
SMEM_STEP_BYTES = 500
SMEM_SPACE_BYTES = 1 << 20
#: fraction of the SMEM space the per-step cost may consume (the ~500
#: B/step figure is approximate; 0.7 rejects the measured-failing 2048
#: steps while accepting the measured-passing 1408)
SMEM_SAFETY = 0.7
#: longest grid measured to compile on v5e (2048 fails, 1408 passes)
MAX_GRID_STEPS = 1408
K_CAP = 8
F_CAP = 128

#: the production shape-bucket ladder (mirrors
#: scripts/fuzz_pallas_seg.py; jaxpr_audit cross-checks the mirror)
PRODUCTION_BUCKETS: Tuple[Tuple[int, int], ...] = (
    (8, 32), (16, 64), (64, 64), (128, 64), (256, 8))


def _pallas_seg():
    from ..checker import pallas_seg
    return pallas_seg


def check_config(*, grid_steps: Optional[int] = None,
                 prefetch_int32: Optional[int] = None,
                 block: Optional[Tuple[int, int]] = None,
                 K: Optional[int] = None, F: Optional[int] = None,
                 where: str = "<config>", path: str = "<config>",
                 line: int = 0) -> List[Finding]:
    """Budget-check one kernel configuration; any field may be left
    None (unchecked). The golden tests drive this directly."""
    out: List[Finding] = []
    if grid_steps is not None:
        if grid_steps > MAX_GRID_STEPS:
            out.append(Finding(
                "pallas-grid-steps", path, line,
                f"{where}: {grid_steps}-step grid exceeds the measured "
                f"Mosaic compile bound ({MAX_GRID_STEPS}; 2048 fails "
                "with 'Exceeded smem capacity') — chunk the stream at "
                "1024"))
        elif grid_steps * SMEM_STEP_BYTES > \
                SMEM_SAFETY * SMEM_SPACE_BYTES:
            out.append(Finding(
                "pallas-grid-steps", path, line,
                f"{where}: {grid_steps} grid steps x ~{SMEM_STEP_BYTES}"
                f" B/step exceeds {SMEM_SAFETY:.0%} of the 1 MB SMEM "
                "space"))
    if prefetch_int32 is not None and \
            prefetch_int32 > SMEM_PREFETCH_INT32:
        out.append(Finding(
            "pallas-prefetch-smem", path, line,
            f"{where}: {prefetch_int32} int32 of scalar prefetch "
            f"exceeds the ~56 KB SMEM budget ({SMEM_PREFETCH_INT32} "
            "words; 2048x10 fails) — chunk the segment stream"))
    if block is not None:
        sub, lane = block[-2], block[-1]
        if lane % LANE != 0:
            out.append(Finding(
                "pallas-block-shape", path, line,
                f"{where}: block lane dim {lane} is not a multiple of "
                f"{LANE} — grid-step blocks need last-two dims "
                "divisible by (8,128) or equal to the array dims"))
        if not (sub % SUBLANE == 0 or SUBLANE % sub == 0):
            out.append(Finding(
                "pallas-block-shape", path, line,
                f"{where}: block sublane dim {sub} neither divides "
                f"nor is a multiple of {SUBLANE}"))
    if K is not None and K > K_CAP:
        out.append(Finding(
            "pallas-k-cap", path, line,
            f"{where}: K={K} exceeds the kernel cap of {K_CAP} "
            "invokes per segment (spec_for must gate on it)"))
    if F is not None and F != F_CAP:
        out.append(Finding(
            "pallas-f-cap", path, line,
            f"{where}: kernel frontier capacity must be F={F_CAP} "
            f"(one vreg row), got {F}"))
    return out


def check_spec(spec, *, where: str = "spec") -> List[Finding]:
    """Budget-check one :class:`SegKernelSpec` (prefetch width is
    ``2 + 2K`` int32 per segment, blocks are ``(rows, 128)``)."""
    PS = _pallas_seg()
    path = PS.__file__
    width = 2 + 2 * spec.K
    out = check_config(
        grid_steps=spec.chunk, prefetch_int32=spec.chunk * width,
        block=(spec.rows, PS.LANES), K=spec.K, F=PS.F,
        where=where, path=path, line=0)
    if spec.rows not in (PS.ROWS, 2 * PS.ROWS):
        out.append(Finding(
            "pallas-block-shape", path, 0,
            f"{where}: buffer rows {spec.rows} not in the (8,128)/"
            "(16,128) tier set"))
    if spec.n_words > 3:
        out.append(Finding(
            "pallas-key-words", path, 0,
            f"{where}: {spec.n_words} key words exceed the 3-word "
            "packed-key budget"))
    if spec.table_rows_pad * PS.LANES > PS.MAX_TABLE:
        out.append(Finding(
            "pallas-table-budget", path, 0,
            f"{where}: table buffer {spec.table_rows_pad}x{PS.LANES} "
            f"exceeds MAX_TABLE={PS.MAX_TABLE}"))
    return out


def production_tiers() -> List[Tuple[Tuple[int, int], int, int, object]]:
    """Every distinct ``spec_for`` spec reachable from the production
    bucket ladder x P (1..15) x K (1..8), with one witness
    (bucket, P, K) each."""
    PS = _pallas_seg()
    seen: Dict[object, Tuple[Tuple[int, int], int, int]] = {}
    for bucket in PRODUCTION_BUCKETS:
        for P in range(1, 16):
            for K in range(1, K_CAP + 1):
                spec = PS.spec_for(bucket[0], bucket[1], P, K)
                if spec is not None and spec not in seen:
                    seen[spec] = (bucket, P, K)
    return [(b, P, K, spec) for spec, (b, P, K) in seen.items()]


def check_production() -> List[Finding]:
    """Budget-check every production tier, plus the meta-gates: the
    budgets in this module must still be ENFORCED by ``spec_for``
    (K > 8 and P > 15 must be rejected, F must be 128)."""
    PS = _pallas_seg()
    path = PS.__file__
    out: List[Finding] = []
    for bucket, P, K, spec in production_tiers():
        out += check_spec(
            spec, where=f"spec_for({bucket[0]},{bucket[1]},P={P},K={K})")
    if PS.spec_for(8, 32, 3, K_CAP + 1) is not None:  # analysis: ignore[pallas-k-cap]
        out.append(Finding(
            "pallas-k-cap", path, 0,
            f"spec_for accepts K={K_CAP + 1}: the kernel serves at "
            f"most {K_CAP} invokes per segment"))
    if PS.spec_for(8, 32, 16, 1) is not None:
        out.append(Finding(
            "pallas-block-shape", path, 0,
            "spec_for accepts P=16: the (16,128) tier serves P <= 15"))
    out += check_config(F=PS.F, where="pallas_seg.F", path=path)
    out += check_config(grid_steps=PS.CHUNK, where="pallas_seg.CHUNK",
                        path=path)
    return out


def budget_table() -> str:
    """The checked production budgets as a markdown artifact."""
    PS = _pallas_seg()
    rows = ["| bucket | P | K | rows | words | chunk | prefetch B "
            "| step-SMEM B | table rows |",
            "|---|---|---|---|---|---|---|---|---|"]
    for bucket, P, K, spec in sorted(
            production_tiers(),
            key=lambda t: (t[0], t[1], t[2])):
        width = 2 + 2 * spec.K
        rows.append(
            f"| {bucket[0]}x{bucket[1]} | {P} | {K} | {spec.rows} "
            f"| {spec.n_words} | {spec.chunk} "
            f"| {spec.chunk * width * 4} "
            f"| {spec.chunk * SMEM_STEP_BYTES} "
            f"| {spec.table_rows_pad} |")
    head = (f"# Pallas budget table (limits: prefetch <= "
            f"{SMEM_PREFETCH_INT32 * 4} B, grid <= {MAX_GRID_STEPS} "
            f"steps, K <= {K_CAP}, F = {F_CAP})\n\n")
    return head + "\n".join(rows) + "\n"


# --- AST scan ---------------------------------------------------------------

def _module_consts(tree: ast.Module) -> Dict[str, int]:
    env: Dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = _fold(node.value, env)
            if v is not None:
                env[node.targets[0].id] = v
    return env


def _fold(node: ast.AST, env: Dict[str, int]) -> Optional[int]:
    """Constant-fold ints through names and + - * // arithmetic."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.BinOp):
        a, b = _fold(node.left, env), _fold(node.right, env)
        if a is None or b is None:
            return None
        if isinstance(node.op, ast.Add):
            return a + b
        if isinstance(node.op, ast.Sub):
            return a - b
        if isinstance(node.op, ast.Mult):
            return a * b
        if isinstance(node.op, ast.FloorDiv) and b != 0:
            return a // b
    return None


def _fold_tuple(node: ast.AST,
                env: Dict[str, int]) -> Optional[Tuple[int, ...]]:
    if not isinstance(node, (ast.Tuple, ast.List)):
        return None
    vals = [_fold(e, env) for e in node.elts]
    if any(v is None for v in vals):
        return None
    return tuple(vals)   # type: ignore[arg-type]


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def scan_file(path: str, source: Optional[str] = None, *,
              apply_suppressions: bool = True) -> List[Finding]:
    """AST budget scan of one file."""
    if source is None:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []           # lint reports syntax errors
    lines = source.splitlines()
    env = _module_consts(tree)
    # the prefetch-budget rule applies only to allocations in a scope
    # that actually builds a PrefetchScalarGridSpec — a big working
    # buffer elsewhere in the file is not scalar prefetch
    spec_ids = {id(n) for n in ast.walk(tree)
                if isinstance(n, ast.Call)
                and _call_name(n) == "PrefetchScalarGridSpec"}
    fn_ids = []
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_ids.append({id(x) for x in ast.walk(n)})
    prefetch_scopes = [ids for ids in fn_ids if ids & spec_ids]
    module_prefetch = bool(
        spec_ids - set().union(*fn_ids) if fn_ids else spec_ids)

    def in_prefetch_scope(call: ast.Call) -> bool:
        return module_prefetch or any(id(call) in ids
                                      for ids in prefetch_scopes)

    raw: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in ("pallas_call", "PrefetchScalarGridSpec"):
            for kw in node.keywords:
                if kw.arg == "grid":
                    dims = _fold_tuple(kw.value, env)
                    if dims is None and kw.value is not None:
                        g = _fold(kw.value, env)
                        dims = (g,) if g is not None else None
                    if dims:
                        # grid steps run sequentially: the budget is
                        # the PRODUCT of the dims, not each dim alone
                        # (a (64, 64) grid is 4096 steps)
                        total = 1
                        for g in dims:
                            total *= g
                        raw += check_config(
                            grid_steps=total, where=name, path=path,
                            line=node.lineno)
        elif name == "BlockSpec" and node.args:
            shape = _fold_tuple(node.args[0], env)
            if shape is not None and len(shape) >= 2:
                raw += check_config(block=shape[-2:], where=name,
                                    path=path, line=node.lineno)
        elif name in ("zeros", "full", "empty", "ones") \
                and node.args and in_prefetch_scope(node):
            shape = _fold_tuple(node.args[0], env)
            if shape is not None and len(shape) >= 2:
                total = 1
                for d in shape:
                    total *= d
                raw += check_config(
                    prefetch_int32=total,
                    where=f"np.{name}{shape}", path=path,
                    line=node.lineno)
        elif name == "spec_for":
            k_node = None
            if len(node.args) >= 4:
                k_node = node.args[3]
            for kw in node.keywords:
                if kw.arg == "K":
                    k_node = kw.value
            k = _fold(k_node, env) if k_node is not None else None
            if k is not None:
                raw += check_config(K=k, where="spec_for", path=path,
                                    line=node.lineno)
    if not apply_suppressions:
        return raw
    return [f for f in raw if not suppressed(lines, f.line, f.rule)]


def scan_files(paths: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        if os.path.exists(p):
            out += scan_file(p)
    return out


from . import Pass, register_pass


def _repo_stage(ctx):
    return scan_files(ctx["files"]) + check_production()


register_pass(Pass(
    name="pallas-budget",
    scan_paths=scan_files,
    raw_file=lambda path, source: scan_file(
        path, source, apply_suppressions=False),
    repo_stage=_repo_stage,
))
