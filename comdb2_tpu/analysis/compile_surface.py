"""Pass 4 — the compile-surface prover.

The framework's serving discipline is that every jit/Pallas-visible
shape comes from a CLOSED, pow2-bucketed program set: the service
buckets admission (:mod:`comdb2_tpu.service.bucketing`), ``check_batch``
floors its segment/table axes, the shrink minimizer groups candidates
into pow2 kept-op buckets, the txn closure pads N pow2, and the fused
kernel compiles one Mosaic program per :class:`SegKernelSpec`. That
discipline existed only as prose and convention; the known failure mode
(per-seed shapes compiling one program per seed until LLVM OOMs) is
exactly what the multi-chip and continuous-batching roadmap items would
multiply. This pass turns "the program set seemed closed" into a
machine-checked statement, in three parts:

- :func:`static_inventory` — walk the DECLARED ladders (service bucket
  axes from :class:`ServiceLimits`, the ``check_batch`` shape floors,
  shrink pow2 kept-op buckets, txn pow2-N buckets, every ``spec_for``
  tier reachable from the production bucket ladder) and enumerate the
  finite set of compilable programs per dispatch site.
- :func:`trace_witnesses` — abstractly evaluate one witness rung per
  site through the REAL entry point via ``jax.eval_shape`` over
  ``ShapeDtypeStruct`` ladders (builds the jaxpr only — no XLA
  compile, no device): a ladder whose shapes no longer trace is a
  finding, not a 40 s compile failure.
- :func:`scan_files` — the ``unbucketed-dispatch-site`` rule: an AST
  scan of the batch/serving dispatch sites whose shape arguments must
  come from a declared ladder. INTERPROCEDURAL: a shape argument that
  is a function parameter is chased through the call graph to every
  call site, so a raw ``memo.n_states`` laundered through a helper is
  still caught. Only PROVABLY-raw values are flagged (``len(...)``,
  ``.shape[...]``, raw memo-count attributes, non-pow2 literals);
  values whose provenance is out of AST reach stay silent — the
  runtime guard (:mod:`comdb2_tpu.utils.compile_guard`) is the
  backstop for those.

``render_programs`` emits the inventory as the checked-in
``PROGRAMS.md`` artifact (same drift contract as the budget table:
tier-1 regenerates it and any diff is a failure). The runtime half —
observed-compile capture and the subset assertion — lives in
:mod:`comdb2_tpu.utils.compile_guard`; :meth:`Inventory.offenders`
is the bridge between the two.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding, suppressed

def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _pow2_count(lo: int, hi: int) -> int:
    return hi.bit_length() - lo.bit_length() + 1


# --- axis / site model ------------------------------------------------------

@dataclass(frozen=True)
class Axis:
    """One declared integer axis of a traced argument shape."""

    name: str
    kind: str                 # pow2 | enum | linear
    lo: int = 1
    hi: int = 1 << 20
    values: Tuple[int, ...] = ()

    def admits(self, v: int) -> bool:
        if self.kind == "enum":
            return v in self.values
        if self.kind == "pow2":
            return _is_pow2(v) and self.lo <= v <= self.hi
        if self.kind == "linear":
            return self.lo <= v <= self.hi
        raise ValueError(self.kind)

    @property
    def cardinality(self) -> Optional[int]:
        """Distinct admitted values, or None for linear axes (those
        compile one program per value BY DESIGN — chunked scans)."""
        if self.kind == "enum":
            return len(set(self.values))
        if self.kind == "pow2":
            return _pow2_count(self.lo, self.hi)
        return None

    def describe(self) -> str:
        if self.kind == "enum":
            return "{" + ",".join(str(v)
                                  for v in sorted(set(self.values))) + "}"
        if self.kind == "pow2":
            return f"pow2 {self.lo}..{self.hi}"
        return f"1..{self.hi} (linear: one program per value)"


@dataclass(frozen=True)
class Site:
    """One dispatch site: the jit names it compiles under plus the
    declared shape templates its programs are drawn from.

    ``templates``: tuple of argument-list templates; each template is
    a tuple of per-argument Axis tuples (scalar argument = empty
    tuple). A record matches when ANY template fits rank-for-rank and
    every dim is admitted. ``open_site=True`` matches any shapes —
    per-item programs by design (the single-history driver).
    """

    key: str
    jit_names: Tuple[str, ...]
    note: str
    templates: Tuple[Tuple[Tuple[Axis, ...], ...], ...] = ()
    open_site: bool = False    # per-item shapes by design (driver)
    axes_doc: Tuple[Axis, ...] = ()   # the distinct axes, for the doc
    bound_note: str = ""

    def matches(self, shapes: Sequence[Tuple[int, ...]]) -> bool:
        if self.open_site:
            return True
        for tmpl in self.templates:
            if len(tmpl) != len(shapes):
                continue
            ok = True
            for axes, shape in zip(tmpl, shapes):
                if len(axes) != len(shape) or \
                        any(not ax.admits(d)
                            for ax, d in zip(axes, shape)):
                    ok = False
                    break
            if ok:
                return True
        return False

    def program_bound(self) -> str:
        """Human-readable bound on distinct programs this site can
        compile (linear axes annotated, not multiplied in)."""
        if self.bound_note:
            return self.bound_note
        total = 1
        linear = []
        for ax in self.axes_doc:
            c = ax.cardinality
            if c is None:
                linear.append(ax.name)
            else:
                total *= c
        out = f"<= {total}"
        if linear:
            out += " x one per value of " + ",".join(linear)
        return out


@dataclass(frozen=True)
class Inventory:
    """The full static program inventory + the infra allowlist.

    ``infra_names``: jit names of O(1)-shaped host-glue programs
    (scalar converts, iota builders) that ride along with any
    workload; they are name-allowlisted, not shape-constrained — the
    prover's guarantee covers the ENGINE surface."""

    sites: Tuple[Site, ...]
    infra_names: frozenset

    def site_for(self, name: str) -> Optional[Site]:
        for s in self.sites:
            if name in s.jit_names:
                return s
        return None

    def matches(self, record) -> bool:
        """record: any object with ``.name`` and ``.shapes``."""
        site = self.site_for(record.name)
        if site is not None:
            return site.matches(record.shapes)
        return record.name in self.infra_names

    def offenders(self, records) -> list:
        """The records OUTSIDE the declared compile surface."""
        return [r for r in records if not self.matches(r)]


# --- the declared ladders ---------------------------------------------------

def _ladders() -> dict:
    """Every closed value set, derived from the DECLARED constants —
    never from observed traffic (the whole point is that traffic can't
    widen the set)."""
    from ..service.bucketing import ServiceLimits
    from ..service.sharding import MAX_SHARDS
    from ..shrink.verdicts import MAX_BATCH, MIN_BUCKET
    from ..txn.edges import TXN_N_FLOOR
    from ..stream import engine as stream_engine
    from ..utils import next_pow2
    from .pallas_budget import PRODUCTION_BUCKETS
    from .pallas_budget import production_tiers

    lim = ServiceLimits()
    specs = [spec for _, _, _, spec in production_tiers()]
    from ..checker import mxu
    from ..checker import pallas_seg
    from ..checker.linear_jax import make_pack_plan
    from ..checker.wl import batch as wl_batch

    # every PackPlan word count reachable inside the MXU table caps —
    # the chunk form's carry exposes one (F,)-shaped word column per
    # plan word, so the template set enumerates W
    mxu_words = sorted({
        plan.n_words
        for ns in (1 << i for i in range(mxu.S_CAP.bit_length()))
        for nt in (1 << i for i in range(mxu.T_CAP.bit_length()))
        for P in range(1, mxu.MAX_P + 1)
        for plan in (make_pack_plan(ns, nt, P),)
        if plan is not None})
    return {
        "limits": lim,
        "fuzz_buckets": tuple(PRODUCTION_BUCKETS),
        "specs": specs,
        "mesh_D": (1, MAX_SHARDS),
        "kernel_chunks": tuple(sorted({s.chunk for s in specs})),
        "kernel_widths": tuple(sorted({2 + 2 * s.K for s in specs})),
        "kernel_rows": tuple(sorted({s.rows for s in specs})),
        "kernel_table_rows": tuple(sorted({s.table_rows_pad
                                           for s in specs})),
        "kernel_words": tuple(sorted({s.n_words for s in specs})),
        "service_n_pad": (16, next_pow2(lim.max_ops)),
        "service_S": (8, next_pow2(lim.max_segments)),
        "service_K": (2, next_pow2(lim.max_invokes_per_seg)),
        "service_P": (2, next_pow2(lim.max_processes)),
        "txn_N": (TXN_N_FLOOR, 1 << 16),
        "shrink_bucket": (MIN_BUCKET, next_pow2(lim.max_ops)),
        "shrink_B": (1, MAX_BATCH),
        "batch_B": (1, 1 << 12),
        "memo_dim": (1, 1 << 20),
        "mxu_table": (mxu.S_CAP, mxu.T_CAP),
        "mxu_F": tuple(mxu.CAPACITIES),
        "mxu_chunk": (64, mxu.CHUNK),
        "mxu_P": (mxu.MIN_P, mxu.MAX_P),
        "mxu_words": tuple(mxu_words),
        "stream_delta": tuple(stream_engine.DELTA_PADS),
        "stream_F": tuple(stream_engine.STREAM_CAPACITIES),
        # session slot widths: even-bucketed like the driver, capped
        # by the MXU crossover ceiling (wider P has no engine)
        "stream_P": tuple(range(2, mxu.MAX_P + 1, 2)),
        # megabatch session-lane rungs (fused advance: N sessions,
        # one program) and the kernel rung's small-delta chunk rungs
        "stream_B": tuple(stream_engine.MEGABATCH_LANES),
        "stream_small_chunks": tuple(pallas_seg.STREAM_CHUNKS),
        # workload-family ladders (checker/wl/batch.py — the wl-bank/
        # wl-sets/wl-dirty sites; docs/workloads.md)
        "wl_batch": tuple(wl_batch.WL_BATCH),
        "wl_reads": tuple(wl_batch.WL_READS),
        "wl_accounts": tuple(wl_batch.WL_ACCOUNTS),
        "wl_snaps": tuple(wl_batch.WL_SNAPS),
        "wl_elems": tuple(wl_batch.WL_ELEMS),
        "wl_nodes": tuple(wl_batch.WL_NODES),
        "wl_values": tuple(wl_batch.WL_VALUES),
        "wl_delta": tuple(wl_batch.WL_DELTA_PADS),
    }


#: host-glue jit names observed on the engine workloads: scalar dtype
#: converts and tiny index builders XLA compiles once per (dtype,
#: rank-0/1 shape). Name-allowlisted (shapes unconstrained) — the
#: closure guarantee covers the engine sites above. ONLY jax-internal
#: primitive-wrapper names belong here: a generic user-function name
#: (e.g. "fn", "run" without its site) would exempt arbitrary engine
#: code from the guarantee.
INFRA_NAMES = frozenset({
    "convert_element_type", "_threefry_seed", "_uint32",
    "iota", "arange", "broadcast_in_dim", "reshape", "concatenate",
    "_power", "true_divide", "floor_divide", "remainder",
    # sharded-array readback glue: jax fetches a mesh-sharded output
    # through one _multi_slice program per (shape, sharding) — pure
    # host-transfer plumbing, shapes follow the (already constrained)
    # engine outputs
    "_multi_slice",
})


def static_inventory() -> Inventory:
    """Build the declared compile surface (pure host work — imports
    the ladder constants, never jax)."""
    L = _ladders()
    lane = Axis("lane", "enum", values=(128,))
    one = Axis("one", "enum", values=(1,))
    four = Axis("planes", "enum", values=(4,))

    memo = Axis("n_states/n_transitions", "pow2", *L["memo_dim"])
    S = Axis("S", "pow2", 1, L["service_S"][1] << 4)
    K = Axis("K", "pow2", 1, 8)
    B = Axis("B", "pow2", *L["batch_B"])
    n_pad = Axis("n_pad", "pow2", 1, L["service_n_pad"][1] << 4)

    xla_batch_seg = (
        (memo, memo), (S, B, K), (S, B, K), (S, B), (S,))
    xla_batch_vmap = (
        (memo, memo), (B, n_pad), (B, n_pad), (B, n_pad))

    n_chunks = Axis("n_chunks", "linear", 1, 1 << 16)
    chunk = Axis("chunk", "enum", values=L["kernel_chunks"] + (16,))
    width = Axis("2+2K", "enum", values=L["kernel_widths"])
    rows = Axis("rows", "enum", values=L["kernel_rows"])
    table_rows = Axis("table_rows", "enum",
                      values=L["kernel_table_rows"])
    b_pad = Axis("b_pad", "pow2", 8, 2048)
    mesh_D = Axis("D", "pow2", *L["mesh_D"])
    run_templates = []
    run_sharded_templates = []
    reset_templates = []
    for W in L["kernel_words"]:
        run_templates.append(
            ((n_chunks, chunk, width),)
            + ((rows, lane),) * W
            + ((one, lane), (b_pad, lane), (table_rows, lane), ()))
        # the shard_map form: every per-shard tensor gains the leading
        # mesh axis; per-shard shapes are the bucketed shapes divided
        # by D (global / D — both pow2, so the division stays on the
        # ladder). Table + stride stay replicated.
        run_sharded_templates.append(
            ((mesh_D, n_chunks, chunk, width),)
            + ((mesh_D, rows, lane),) * W
            + ((mesh_D, one, lane), (mesh_D, b_pad, lane),
               (table_rows, lane), ()))
        # the donated-carry reset (pallas_seg._reset_fn): re-fills a
        # recycled (ws, stat) carry set on device — inputs are the
        # scan's carry shapes, one program per (spec word/row class,
        # b_pad) already admitted by the run templates above
        reset_templates.append(
            ((rows, lane),) * W + ((one, lane),))

    N = Axis("N", "pow2", *L["txn_N"])
    N8 = Axis("N/8", "pow2", L["txn_N"][0] // 8, L["txn_N"][1] // 8)
    txn_B = Axis("B", "pow2", 1, 1 << 12)

    mxu_S = Axis("mxu_n_states", "pow2", 1, L["mxu_table"][0])
    mxu_T = Axis("mxu_n_transitions", "pow2", 1, L["mxu_table"][1])
    mxu_F = Axis("F", "enum", values=L["mxu_F"])
    mxu_chunk_ax = Axis("mxu_chunk", "pow2", *L["mxu_chunk"])
    mxu_words_ax = Axis("n_words", "enum", values=L["mxu_words"])
    # a genuinely concurrent wide-P wave puts up to P invokes in one
    # segment, so the engine's K axis runs to MAX_P (the kernel's
    # K <= 8 cap is a Mosaic budget, not an XLA/MXU one)
    mxu_K = Axis("mxu_K", "pow2", 1, L["mxu_P"][1])
    # batch form: succ + (S, B, K) segment tensors, like keys/flat
    mxu_batch_tmpl = ((mxu_S, mxu_T), (S, B, mxu_K), (S, B, mxu_K),
                      (S, B), (S,))
    # single-history form (the driver's non-chunked path)
    mxu_single_tmpl = ((mxu_S, mxu_T), (S, mxu_K), (S, mxu_K), (S,),
                       (S,))
    # chunk form: args + seg_offset scalar + the B=1 carry — n_words
    # (F,) packed word columns, (F,) valid, then n_b/status/fail (1,)
    mxu_chunk_tmpls = []
    for W in L["mxu_words"]:
        mxu_chunk_tmpls.append(
            ((mxu_S, mxu_T), (mxu_chunk_ax, mxu_K),
             (mxu_chunk_ax, mxu_K), (mxu_chunk_ax,), (mxu_chunk_ax,),
             ())
            + ((mxu_F,),) * W
            + ((mxu_F,), (one,), (one,), (one,)))

    # stream-delta site (docs/streaming.md): the session append's
    # delta tensors + the resident carry. The carry's (F,) / (F, P)
    # planes ride the STREAM_CAPACITIES x even-P ladders; scalars
    # (seg_offset, count, status, fail) are shape ()
    stream_delta_ax = Axis("delta_pad", "enum",
                           values=L["stream_delta"])
    stream_K = Axis("stream_K", "pow2", 1, L["mxu_P"][1])
    stream_F_ax = Axis("stream_F", "enum", values=L["stream_F"])
    stream_P_ax = Axis("stream_P", "enum", values=L["stream_P"])
    stream_templates = [
        ((memo, memo), (stream_delta_ax, stream_K),
         (stream_delta_ax, stream_K), (stream_delta_ax,),
         (stream_delta_ax,), (),
         (stream_F_ax,), (stream_F_ax, stream_P_ax), (stream_F_ax,),
         (), (), ()),
    ]
    # megabatch session-lane ladder (round 13): N same-shape-class
    # sessions advance in ONE program — B-tuples of per-lane memo
    # tables/carries plus lane-major delta tensors
    stream_B_ax = Axis("session_B", "enum", values=L["stream_B"])
    # the kernel rung's chunk axis gains the small-delta rungs
    # (pallas_seg.STREAM_CHUNKS via delta_spec) — stream jit names
    # only; pallas-stream-scan keeps the tight spec_for ladder
    stream_chunk_ax = Axis(
        "stream_chunk", "enum",
        values=tuple(sorted(set(L["kernel_chunks"]) | {16}
                            | set(L["stream_small_chunks"]))))
    stream_mb_templates = []
    for Bn in L["stream_B"]:
        stream_mb_templates.append(
            ((memo, memo),) * Bn
            + ((stream_B_ax, stream_delta_ax, stream_K),
               (stream_B_ax, stream_delta_ax, stream_K),
               (stream_B_ax, stream_delta_ax),
               (stream_B_ax, stream_delta_ax), (stream_B_ax,))
            + ((stream_F_ax,), (stream_F_ax, stream_P_ax),
               (stream_F_ax,), (), (), ()) * Bn)
    # MXU-rung megabatch: same lane-major deltas (pads floored to the
    # MXU chunk ladder) + B-tuples of the B=1 chunk-form carry
    mxu_mb_templates = []
    for Bn in L["stream_B"]:
        for W in L["mxu_words"]:
            mxu_mb_templates.append(
                ((mxu_S, mxu_T),) * Bn
                + ((stream_B_ax, mxu_chunk_ax, mxu_K),
                   (stream_B_ax, mxu_chunk_ax, mxu_K),
                   (stream_B_ax, mxu_chunk_ax),
                   (stream_B_ax, mxu_chunk_ax), (stream_B_ax,))
                + (((mxu_F,),) * W
                   + ((mxu_F,), (one,), (one,), (one,))) * Bn)
    # the kernel rung's chunk call: one spec chunk + offsets + the
    # (ws, stat, res) carry + packed table — same axes as the
    # pallas-stream-scan ladder, single-chunk form
    off2 = Axis("off", "enum", values=(2,))
    res8 = Axis("res_rows", "enum", values=(8,))
    stream_kernel_templates = []
    for W in L["kernel_words"]:
        stream_kernel_templates.append(
            ((stream_chunk_ax, width), (off2,))
            + ((rows, lane),) * W
            + ((one, lane), (res8, lane), (table_rows, lane)))
    # kernel-rung megabatch: lane-major packed chunks (B, chunk,
    # 2+2K), per-lane (offset, nt) rows, B-tuples of (ws, stat, res,
    # table) — one Mosaic build shared across lanes inside one jit
    stream_kernel_mb_templates = []
    for Bn in L["stream_B"]:
        for W in L["kernel_words"]:
            stream_kernel_mb_templates.append(
                ((stream_B_ax, stream_chunk_ax, width),
                 (stream_B_ax, off2))
                + (((rows, lane),) * W
                   + ((one, lane), (res8, lane),
                      (table_rows, lane))) * Bn)

    # workload-family sites (checker/wl, docs/workloads.md): batched
    # column-plane reductions — no frontier, every jit-visible dim an
    # enum rung of the WL_* ladders. The delta forms are the stream
    # rungs (stream/wl.py): solo advance + the megabatched advance
    # (per-lane carries pass as tuples and stack INSIDE the jit, delta
    # planes arrive lane-major on the MEGABATCH_LANES ladder).
    wl_B = Axis("wl_B", "enum", values=L["wl_batch"])
    wl_R = Axis("wl_reads", "enum", values=L["wl_reads"])
    wl_A = Axis("wl_accounts", "enum", values=L["wl_accounts"])
    wl_T = Axis("wl_snaps", "enum", values=L["wl_snaps"])
    wl_E = Axis("wl_elems", "enum", values=L["wl_elems"])
    wl_N = Axis("wl_nodes", "enum", values=L["wl_nodes"])
    wl_V = Axis("wl_values", "enum", values=L["wl_values"])
    wl_D = Axis("wl_delta", "enum", values=L["wl_delta"])
    # wl_bank_check(reads, read_mask, wrong_n, init, transfers, total)
    wl_bank_tmpl = ((wl_B, wl_R, wl_A), (wl_B, wl_R), (wl_B, wl_R),
                    (wl_B, wl_A), (wl_B, wl_T, wl_A), (wl_B,))
    # wl_bank_delta(balance, reads, read_mask, wrong_n, transfers,
    # total-scalar) — delta rows on the WL_DELTA_PADS ladder
    wl_bank_delta_tmpl = ((wl_A,), (wl_D, wl_A), (wl_D,), (wl_D,),
                          (wl_D, wl_A), ())
    wl_bank_mb_tmpls = []
    for Bn in L["stream_B"]:
        wl_bank_mb_tmpls.append(
            ((wl_A,),) * Bn
            + ((stream_B_ax, wl_D, wl_A), (stream_B_ax, wl_D),
               (stream_B_ax, wl_D), (stream_B_ax, wl_D, wl_A),
               (stream_B_ax,)))
    # wl_sets_check(attempts, adds, final_read, has_read)
    wl_sets_tmpl = ((wl_B, wl_E),) * 3 + ((wl_B,),)
    # wl_sets_delta(3 carry planes, 3 delta planes, 2 scalars)
    wl_sets_delta_tmpl = ((wl_E,),) * 6 + ((), ())
    wl_sets_mb_tmpls = []
    for Bn in L["stream_B"]:
        wl_sets_mb_tmpls.append(
            ((wl_E,),) * (3 * Bn)
            + ((stream_B_ax, wl_E),) * 3
            + ((stream_B_ax,), (stream_B_ax,)))
    # wl_dirty_check(failed, reads, node_mask, read_mask)
    wl_dirty_tmpl = ((wl_B, wl_V), (wl_B, wl_R, wl_N),
                     (wl_B, wl_R, wl_N), (wl_B, wl_R))

    sites = (
        Site(
            key="pallas-stream-scan",
            jit_names=("run", "run_sharded", "carry_reset"),
            note="fused-kernel chunk scan (checker/pallas_seg._scan_fn)"
                 ": one Mosaic program per (SegKernelSpec, b_pad, "
                 "stream); specs are drawn from the production tier "
                 "table (pallas_budget.production_tiers), b_pad is the "
                 "pow2 results-buffer bucket, chunk count is the "
                 "chunked-engine scan length (linear by design). "
                 "`run_sharded` (pallas_seg._sharded_scan_fn) is the "
                 "shard_map form: the SAME per-shard kernel body with "
                 "a leading mesh axis D on every per-shard tensor — "
                 "per-shard shapes are the global shapes divided by D. "
                 "`carry_reset` (pallas_seg._reset_fn) is the "
                 "donated-carry recycle program: constants into a "
                 "donated (ws, stat) carry set, one per (spec, b_pad) "
                 "the run ladder already admits",
            templates=tuple(run_templates)
            + tuple(run_sharded_templates)
            + tuple(reset_templates),
            axes_doc=(chunk, width, rows, table_rows, b_pad, mesh_D,
                      Axis("n_words", "enum",
                           values=L["kernel_words"]), n_chunks),
        ),
        Site(
            key="xla-batch-engines",
            jit_names=("check_device_keys", "check_device_flat",
                       "check_device_seg_batch",
                       "check_device_keys_sharded"),
            note="batched XLA engines (checker/linear_jax): segment "
                 "tensors (S, B, K) with every axis pow2 "
                 "(segment_batch pads, service buckets floor), memo "
                 "table dims pow2 (pad_succ). "
                 "`check_device_keys_sharded` shard_maps the keys/flat "
                 "body over the mesh batch axis: global shapes are "
                 "identical (B pow2, padded to a multiple of D), each "
                 "shard compiles B/D lanes",
            templates=(xla_batch_seg,),
            axes_doc=(memo, S, B, K),
        ),
        Site(
            key="mxu-frontier",
            jit_names=("check_device_mxu_batch", "check_device_mxu",
                       "check_device_mxu_chunk"),
            note="MXU frontier engine (checker/mxu): BFS-as-matmul "
                 "closure for wide-P histories — packed-word frontier, "
                 "bf16/f32 one-hot expansion on the MXU, exact "
                 "packed-key lexsort dedup. Batch form takes the same "
                 "(S, B, K) segment tensors as keys/flat; table dims "
                 "are pow2 inside the matmul caps (S_CAP x T_CAP). "
                 "The chunk form's carry exposes the frontier as "
                 "n_words (F,) word columns with F drawn from the "
                 "CAPACITIES ladder (in-place escalation rungs); P is "
                 "a static arg bucketed by the caller (driver "
                 "even-buckets, batch pow2-buckets, P <= MAX_P)",
            templates=(mxu_batch_tmpl, mxu_single_tmpl)
            + tuple(mxu_chunk_tmpls),
            axes_doc=(mxu_S, mxu_T, S, B, mxu_K, mxu_F, mxu_chunk_ax,
                      mxu_words_ax),
        ),
        Site(
            key="stream-delta",
            jit_names=("stream_delta_chunk", "stream_kernel_delta",
                       "stream_delta_megabatch",
                       "stream_kernel_delta_mb",
                       "check_device_mxu_megabatch"),
            note="streaming-session delta dispatch (stream/engine): "
                 "the ONE device entry an append reaches. "
                 "`stream_delta_chunk` is the XLA rung — delta "
                 "segment tensors on the DELTA_PADS pow2 ladder, K "
                 "pow2 up to the MXU P ceiling, the resident carry "
                 "(states/slots/valid + scalars) at a "
                 "STREAM_CAPACITIES frontier rung and an even-"
                 "bucketed slot width; memo dims pow2 (pad_sizes). "
                 "`stream_kernel_delta` is the kernel rung's chunk "
                 "call (same Mosaic program family as "
                 "pallas-stream-scan, re-jitted under a declared "
                 "serving name; delta_spec adds the STREAM_CHUNKS "
                 "small-delta rungs). The MXU rung rides the "
                 "mxu-frontier site's chunk form with delta pads "
                 "floored to its chunk ladder (MXU_DELTA_FLOOR). "
                 "The `*_megabatch`/`*_mb` forms are the round-13 "
                 "fused advance: a beat's same-shape-class lanes "
                 "stack onto the session_B pow2 ladder (pad = "
                 "duplicate lane 0) and run as ONE program per "
                 "rung — B-tuples of per-lane memo tables and "
                 "carries, lane-major delta tensors",
            templates=tuple(stream_templates)
            + tuple(stream_mb_templates)
            + tuple(mxu_mb_templates)
            + tuple(stream_kernel_templates)
            + tuple(stream_kernel_mb_templates),
            axes_doc=(stream_delta_ax, stream_K, stream_F_ax,
                      stream_P_ax, stream_B_ax, stream_chunk_ax,
                      memo),
        ),
        Site(
            key="wl-bank",
            jit_names=("wl_bank_check", "wl_bank_delta",
                       "wl_bank_delta_mb"),
            note="bank workload family (checker/wl/bank.py, "
                 "docs/workloads.md): balance tensors -> wrong-total/"
                 "wrong-n/snapshot-inconsistency in ONE program. "
                 "`wl_bank_check` is the post-hoc batch form — lanes "
                 "on the WL_BATCH ladder, reads/snapshots/accounts on "
                 "their WL_* rungs (stage_wl_batch buckets; over-rung "
                 "histories degrade to the host oracle). "
                 "`wl_bank_delta` is the stream rung's solo advance "
                 "(carry = the (A,) running balance; delta rows on "
                 "WL_DELTA_PADS); `wl_bank_delta_mb` is its fused "
                 "megabatch form — per-lane carries as tuples stacked "
                 "inside the jit, lane-major deltas on the "
                 "MEGABATCH_LANES ladder, vmapping the SAME body "
                 "(bit-identical per lane)",
            templates=(wl_bank_tmpl, wl_bank_delta_tmpl)
            + tuple(wl_bank_mb_tmpls),
            axes_doc=(wl_B, wl_R, wl_A, wl_T, wl_D, stream_B_ax),
        ),
        Site(
            key="wl-sets",
            jit_names=("wl_sets_check", "wl_sets_delta",
                       "wl_sets_delta_mb"),
            note="sets workload family (checker/wl/sets.py): "
                 "per-element bool membership planes — lost/phantom "
                 "as bitmap algebra. `wl_sets_check` is the post-hoc "
                 "batch form (element universe on the WL_ELEMS "
                 "ladder); `wl_sets_delta`/`wl_sets_delta_mb` are the "
                 "stream rungs (carry = three (E,) planes; in-place "
                 "element-rung escalation re-uploads on the next "
                 "dispatch, past the top rung the session answers "
                 "terminal UNKNOWN)",
            templates=(wl_sets_tmpl, wl_sets_delta_tmpl)
            + tuple(wl_sets_mb_tmpls),
            axes_doc=(wl_B, wl_E, stream_B_ax),
        ),
        Site(
            key="wl-dirty",
            jit_names=("wl_dirty_check",),
            note="dirty-reads workload family (checker/wl/dirty.py): "
                 "failed-write table joined against read-visibility "
                 "planes + per-node disagreement in one program. "
                 "Post-hoc ONLY (the verdict joins reads against the "
                 "FULL failed-write set — no O(delta) carry exists), "
                 "so there is no stream rung; value universe on the "
                 "WL_VALUES ladder, node views on WL_NODES",
            templates=(wl_dirty_tmpl,),
            axes_doc=(wl_B, wl_R, wl_N, wl_V),
        ),
        Site(
            key="xla-batch-vmap",
            jit_names=("check_device_batch",),
            note="vmap fallback engine: dense step streams (B, n_pad), "
                 "both axes pow2 (make_stream pads, service n_pad "
                 "bucket)",
            templates=(xla_batch_vmap,),
            axes_doc=(memo, B, n_pad),
        ),
        Site(
            key="xla-driver-engines",
            jit_names=("check_device", "check_device_seg",
                       "check_device_seg_chunk", "check_device_seg2",
                       "check_device_seg2_chunk", "pending_histogram"),
            note="single-history adaptive driver (checker/linear.py "
                 "and bench.py's 50k control): compiles per history "
                 "shape BY DESIGN — an OPEN site, outside the closure "
                 "guarantee. The per-item-dispatch lint rule keeps "
                 "serving traffic off this path; the closed serving "
                 "surface is the batch/stream/txn sites above",
            open_site=True,
            bound_note="open (one program per history shape; "
                       "single-history driver path only)",
        ),
        Site(
            key="txn-closure",
            jit_names=("closure_diag_kernel",
                       "closure_diag_kernel_sharded"),
            note="txn matrix-closure engine (txn/closure_jax): packed "
                 "adjacency planes (4, N, N/8) or (B, 4, N, N/8); N "
                 "pow2 >= TXN_N_FLOOR (service cap 4096, offline "
                 "shrink may go wider), B pow2 (service pads). The "
                 "sharded form (shard_map over the batch axis, B a "
                 "pow2 multiple of D) sees the same global shapes; "
                 "each shard squares B/D adjacency stacks",
            templates=(((four, N, N8),), ((txn_B, four, N, N8),)),
            axes_doc=(N, txn_B, mesh_D),
        ),
    )
    return Inventory(sites=sites, infra_names=INFRA_NAMES)


# --- eval_shape witnesses ---------------------------------------------------

def _witness_specs():
    """(site_key, describe, thunk) triples; each thunk builds the
    ShapeDtypeStruct args and runs ``jax.eval_shape`` on the REAL
    entry point (abstract trace only — no compile, no device)."""
    import functools

    import jax
    import numpy as np

    i32 = np.int32

    def st(shape, dt=i32):
        return jax.ShapeDtypeStruct(shape, dt)

    def kernel_witness():
        from ..checker import pallas_seg as PS

        spec = PS.spec_for(8, 32, 4, 2)
        assert spec is not None
        run = PS._scan_fn(spec, 8, True)
        W = spec.n_words
        return jax.eval_shape(
            run, st((2, spec.chunk, 2 + 2 * spec.K)),
            tuple(st((spec.rows, 128)) for _ in range(W)),
            st((1, 128)), st((8, 128)),
            st((spec.table_rows_pad, 128)), 32)

    def carry_reset_witness():
        from ..checker import pallas_seg as PS

        spec = PS.spec_for(8, 32, 4, 2)
        assert spec is not None
        reset = PS._reset_fn(spec, 8)
        W = spec.n_words
        return jax.eval_shape(
            reset, tuple(st((spec.rows, 128)) for _ in range(W)),
            st((1, 128)))

    def keys_witness():
        from ..checker import linear_jax as LJ

        fn = functools.partial(LJ.check_device_keys, B=4, F=64, P=2,
                               n_states=16, n_transitions=16)
        return jax.eval_shape(fn, st((16, 16)), st((8, 4, 2)),
                              st((8, 4, 2)), st((8, 4)), st((8,)))

    def flat_witness():
        from ..checker import linear_jax as LJ

        fn = functools.partial(LJ.check_device_flat, B=4, F=64, P=2,
                               n_states=16, n_transitions=16)
        return jax.eval_shape(fn, st((16, 16)), st((8, 4, 2)),
                              st((8, 4, 2)), st((8, 4)), st((8,)))

    def closure_witness():
        from ..txn import closure_jax as CJ

        return jax.eval_shape(CJ._jitted(16),
                              st((4, 16, 2), np.uint8))

    def mxu_witness():
        from ..checker import mxu as MXU

        fn = functools.partial(MXU.check_device_mxu_batch, B=2,
                               F=1024, P=16, n_states=32,
                               n_transitions=32)
        return jax.eval_shape(fn, st((32, 32)), st((8, 2, 2)),
                              st((8, 2, 2)), st((8, 2)), st((8,)))

    def stream_delta_witness():
        from ..stream import engine as SE

        fn = functools.partial(SE.stream_delta_chunk, F=256, Fs=32,
                               P=2, n_states=16, n_transitions=16)
        carry = (st((256,)), st((256, 2)), st((256,), np.bool_),
                 st(()), st(()), st(()))
        return jax.eval_shape(fn, st((16, 16)), st((16, 2)),
                              st((16, 2)), st((16,)), st((16,)),
                              st(()), carry)

    def stream_megabatch_witness():
        from ..stream import engine as SE

        fn = functools.partial(SE.stream_delta_megabatch, F=256,
                               Fs=32, P=2, n_states=16,
                               n_transitions=16)
        carry = (st((256,)), st((256, 2)), st((256,), np.bool_),
                 st(()), st(()), st(()))
        return jax.eval_shape(fn, (st((16, 16)),) * 2,
                              st((2, 16, 2)), st((2, 16, 2)),
                              st((2, 16)), st((2, 16)), st((2,)),
                              (carry, carry))

    def mxu_megabatch_witness():
        from ..checker import mxu as MXU

        lane = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            MXU.init_carry(1, 1024, 16, 32, 32))
        fn = functools.partial(MXU._megabatch_jit, F=1024, P=16,
                               n_states=32, n_transitions=32)
        return jax.eval_shape(fn, (st((32, 32)),) * 2,
                              st((2, 64, 2)), st((2, 64, 2)),
                              st((2, 64)), st((2, 64)), st((2,)),
                              (lane, lane))

    def stream_kernel_mb_witness():
        from ..checker import pallas_seg as PS
        from ..stream import engine as SE

        spec = PS.spec_for(8, 32, 4, 2)
        assert spec is not None
        dspec = PS.delta_spec(spec, 16)
        fn = SE.stream_kernel_megabatch(dspec, 2)
        W = spec.n_words
        lane = (tuple(st((spec.rows, 128)) for _ in range(W)),
                st((1, 128)), st((8, 128)),
                st((spec.table_rows_pad, 128)))
        return jax.eval_shape(
            fn, st((2, dspec.chunk, 2 + 2 * spec.K)), st((2, 2)),
            (lane, lane))

    def wl_bank_witness():
        from ..checker.wl import bank as WB

        fn = functools.partial(WB.wl_bank_check, n_reads=8,
                               n_accounts=8, n_snaps=8)
        return jax.eval_shape(fn, st((8, 8, 8)),
                              st((8, 8), np.bool_),
                              st((8, 8), np.bool_), st((8, 8)),
                              st((8, 8, 8)), st((8,)))

    def wl_bank_mb_witness():
        from ..checker.wl import bank as WB

        fn = functools.partial(WB.wl_bank_delta_mb, n_reads=8,
                               n_accounts=8, n_snaps=8)
        return jax.eval_shape(fn, (st((8,)),) * 2, st((2, 8, 8)),
                              st((2, 8), np.bool_),
                              st((2, 8), np.bool_), st((2, 8, 8)),
                              st((2,)))

    def wl_sets_witness():
        from ..checker.wl import sets as WS

        fn = functools.partial(WS.wl_sets_check, n_elems=128)
        return jax.eval_shape(fn, st((8, 128), np.bool_),
                              st((8, 128), np.bool_),
                              st((8, 128), np.bool_),
                              st((8,), np.bool_))

    def wl_sets_mb_witness():
        from ..checker.wl import sets as WS

        fn = functools.partial(WS.wl_sets_delta_mb, n_elems=128)
        lane = (st((128,), np.bool_),) * 3
        return jax.eval_shape(fn, (lane, lane),
                              st((2, 128), np.bool_),
                              st((2, 128), np.bool_),
                              st((2, 128), np.bool_),
                              st((2,), np.bool_), st((2,), np.bool_))

    def wl_dirty_witness():
        from ..checker.wl import dirty as WD

        fn = functools.partial(WD.wl_dirty_check, n_reads=8,
                               n_nodes=4, n_values=128)
        return jax.eval_shape(fn, st((8, 128), np.bool_),
                              st((8, 8, 4)),
                              st((8, 8, 4), np.bool_),
                              st((8, 8), np.bool_))

    def _witness_mesh():
        # a 1-device mesh: available on every platform, and the D=1
        # rung keeps the artifact deterministic across environments
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.devices()[:1]), ("batch",))

    def kernel_sharded_witness():
        from ..checker import pallas_seg as PS

        spec = PS.spec_for(8, 32, 4, 2)
        assert spec is not None
        run = PS._sharded_scan_fn(spec, 8, _witness_mesh(), "batch")
        W = spec.n_words
        return jax.eval_shape(
            run, st((1, 2, spec.chunk, 2 + 2 * spec.K)),
            tuple(st((1, spec.rows, 128)) for _ in range(W)),
            st((1, 1, 128)), st((1, 8, 128)),
            st((spec.table_rows_pad, 128)), 32)

    def keys_sharded_witness():
        from ..checker import linear_jax as LJ

        fn = LJ._sharded_keys_fn(_witness_mesh(), "batch", "keys",
                                 4, 64, 2, 16, 16)
        return jax.eval_shape(fn, st((16, 16)), st((8, 4, 2)),
                              st((8, 4, 2)), st((8, 4)), st((8,)))

    def closure_sharded_witness():
        from ..txn import closure_jax as CJ

        return jax.eval_shape(
            CJ._jitted_sharded(16, _witness_mesh()),
            st((2, 4, 16, 2), np.uint8))

    return (
        ("pallas-stream-scan",
         "spec_for(8,32,P=4,K=2), 2 chunks, b_pad=8", kernel_witness),
        ("pallas-stream-scan",
         "run_sharded: same spec, D=1 mesh rung",
         kernel_sharded_witness),
        ("pallas-stream-scan",
         "carry_reset: same spec carry shapes, b_pad=8",
         carry_reset_witness),
        ("xla-batch-engines",
         "check_device_keys at (ns,nt)=(16,16) S=8 B=4 K=2",
         keys_witness),
        ("xla-batch-engines",
         "check_device_flat at (ns,nt)=(16,16) S=8 B=4 K=2",
         flat_witness),
        ("xla-batch-engines",
         "check_device_keys_sharded: same shapes, D=1 mesh rung",
         keys_sharded_witness),
        ("mxu-frontier",
         "check_device_mxu_batch at (32,32) S=8 B=2 K=2 P=16 F=1024",
         mxu_witness),
        ("stream-delta",
         "stream_delta_chunk at (16,16) delta=16 K=2 F=256 P=2",
         stream_delta_witness),
        ("stream-delta",
         "stream_delta_megabatch: same rung fused at session_B=2",
         stream_megabatch_witness),
        ("stream-delta",
         "check_device_mxu_megabatch at (32,32) delta=64 P=16 "
         "F=1024, session_B=2",
         mxu_megabatch_witness),
        ("stream-delta",
         "stream_kernel_delta_mb: spec_for(8,32,P=4,K=2) at "
         "delta_spec chunk=64, session_B=2",
         stream_kernel_mb_witness),
        ("wl-bank",
         "wl_bank_check at B=8 R=8 A=8 T=8", wl_bank_witness),
        ("wl-bank",
         "wl_bank_delta_mb: delta=8 A=8 fused at session_B=2",
         wl_bank_mb_witness),
        ("wl-sets",
         "wl_sets_check at B=8 E=128", wl_sets_witness),
        ("wl-sets",
         "wl_sets_delta_mb: E=128 fused at session_B=2",
         wl_sets_mb_witness),
        ("wl-dirty",
         "wl_dirty_check at B=8 R=8 N=4 V=128", wl_dirty_witness),
        ("txn-closure", "closure bucket N=16", closure_witness),
        ("txn-closure",
         "closure_diag_kernel_sharded: B=2 N=16, D=1 mesh rung",
         closure_sharded_witness),
    )


def trace_witnesses() -> List[Finding]:
    """Abstractly trace one witness rung per site; a ladder whose
    shapes no longer trace is a ``compile-surface-trace`` finding."""
    from .jaxpr_audit import _force_cpu

    if not _force_cpu():
        return [Finding(
            "compile-surface-trace", __file__, 0,
            "a non-CPU jax backend was initialized before the prover "
            "could pin the platform — run with JAX_PLATFORMS=cpu")]
    out: List[Finding] = []
    for key, desc, thunk in _witness_specs():
        try:
            thunk()
        except Exception as e:          # a broken ladder IS a finding
            out.append(Finding(
                "compile-surface-trace", __file__, 0,
                f"site {key}: witness '{desc}' failed to trace: "
                f"{type(e).__name__}: {e}"))
    return out


def witness_table() -> List[Tuple[str, str, str]]:
    """(site, witness, out-shapes) rows for the artifact. Raises
    (rather than silently emitting an empty table) when the platform
    can't be pinned — a PROGRAMS.md missing its witness rows would
    fail the golden test as unexplained drift."""
    import jax

    from .jaxpr_audit import _force_cpu

    if not _force_cpu():
        raise RuntimeError(
            "cannot regenerate the witness table: a non-CPU jax "
            "backend was initialized before the prover could pin the "
            "platform — rerun with JAX_PLATFORMS=cpu in a fresh "
            "process")
    rows = []
    for key, desc, thunk in _witness_specs():
        try:
            out = thunk()
            shapes = jax.tree.map(lambda x: tuple(x.shape), out)
            rows.append((key, desc, str(shapes)))
        except Exception as e:
            rows.append((key, desc, f"TRACE FAILED: {type(e).__name__}"))
    return rows


# --- the PROGRAMS.md artifact -----------------------------------------------

def render_programs() -> str:
    """The compile-surface inventory as a deterministic markdown
    artifact (the drift contract of ``PROGRAMS.md``: tier-1
    regenerates this and any diff is a failure)."""
    L = _ladders()
    inv = static_inventory()
    lim = L["limits"]
    out = [
        "# Compile-surface inventory",
        "",
        "Generated by `python -m comdb2_tpu.analysis --programs "
        "PROGRAMS.md`; checked by `tests/test_compile_surface.py`",
        "(drift = failure, same contract as the budget table). Every",
        "program XLA or Mosaic may compile for the serving surface is",
        "drawn from the ladders below; the runtime guard",
        "(`comdb2_tpu.utils.compile_guard`) asserts observed compiles",
        "stay a subset.",
        "",
        "## Declared ladders",
        "",
        "| ladder | values | source |",
        "|---|---|---|",
        f"| fuzz kernel buckets | {list(L['fuzz_buckets'])} | "
        "`analysis.pallas_budget.PRODUCTION_BUCKETS` |",
        f"| service n_pad | pow2 {L['service_n_pad'][0]}.."
        f"{L['service_n_pad'][1]} | `ServiceLimits.max_ops="
        f"{lim.max_ops}` |",
        f"| service S | pow2 {L['service_S'][0]}..{L['service_S'][1]}"
        f" | `ServiceLimits.max_segments={lim.max_segments}` |",
        f"| service K | pow2 {L['service_K'][0]}..{L['service_K'][1]}"
        f" | `ServiceLimits.max_invokes_per_seg="
        f"{lim.max_invokes_per_seg}` |",
        f"| service P | pow2 {L['service_P'][0]}..{L['service_P'][1]}"
        f" | `ServiceLimits.max_processes={lim.max_processes}` |",
        f"| service P_eff | even 2..{lim.max_slots} | "
        f"`ServiceLimits.max_slots={lim.max_slots}` |",
        f"| txn closure N | pow2 {L['txn_N'][0]}..{L['txn_N'][1]} | "
        f"`txn.edges.TXN_N_FLOOR`, `ServiceLimits.max_txns="
        f"{lim.max_txns}` (service cap; offline shrink may go wider) |",
        f"| mesh shard axis D | pow2 {L['mesh_D'][0]}.."
        f"{L['mesh_D'][1]} | `service.sharding.MAX_SHARDS`; per-shard "
        "shapes are the bucketed global shapes divided by D (both "
        "pow2, so the division stays on the ladder) |",
        f"| shrink kept-op buckets | pow2 {L['shrink_bucket'][0]}.."
        f"{L['shrink_bucket'][1]} | `shrink.verdicts.MIN_BUCKET` |",
        f"| shrink batch B | pow2 {L['shrink_B'][0]}.."
        f"{L['shrink_B'][1]} | `shrink.verdicts.MAX_BATCH` |",
        f"| memo table dims | pow2 {L['memo_dim'][0]}.."
        f"{L['memo_dim'][1]} | `pad_succ(next_pow2(...))` at every "
        "dispatch path |",
        f"| kernel chunk | {list(L['kernel_chunks'])} (+16 interpret)"
        " | `spec_for` SMEM bound per K |",
        f"| kernel widths (2+2K) | {list(L['kernel_widths'])} | "
        "K = 1..8 |",
        f"| kernel buffer rows | {list(L['kernel_rows'])} | "
        "(8,128)/(16,128) tiers |",
        f"| kernel table rows | {list(L['kernel_table_rows'])} | "
        "`table_rows_pad` buckets |",
        f"| mxu table caps | pow2 1..{L['mxu_table'][0]} x pow2 1.."
        f"{L['mxu_table'][1]} | `checker.mxu.S_CAP/T_CAP` (bf16 "
        "value-plane exactness bound) |",
        f"| mxu frontier F | {list(L['mxu_F'])} | "
        "`checker.mxu.CAPACITIES` (in-place escalation rungs; top "
        "rung = the wide-P honest-UNKNOWN threshold) |",
        f"| mxu chunk | pow2 {L['mxu_chunk'][0]}..{L['mxu_chunk'][1]}"
        " | `checker.mxu.CHUNK` |",
        f"| mxu P crossover | {L['mxu_P'][0]}..{L['mxu_P'][1]} | "
        "`checker.mxu.MIN_P/MAX_P` (static arg — driver even-buckets, "
        "batch pow2-buckets) |",
        f"| mxu key words | {list(L['mxu_words'])} | "
        "`PackPlan.n_words` over the table caps x P |",
        f"| stream delta_pad | {list(L['stream_delta'])} | "
        "`stream.engine.DELTA_PADS` (session appends bucket onto it; "
        "larger deltas split; MXU rung floors at MXU_DELTA_FLOOR) |",
        f"| stream frontier F | {list(L['stream_F'])} | "
        "`stream.engine.STREAM_CAPACITIES` (in-place "
        "expand_seg_carry escalation rungs) |",
        f"| stream P | even {L['stream_P'][0]}..{L['stream_P'][-1]} |"
        " session slot width (renamed concurrency, even-bucketed; "
        "in-place expand_seg_carry_slots widening) |",
        f"| stream session B | {list(L['stream_B'])} | "
        "`stream.engine.MEGABATCH_LANES` (fused-advance lane rungs; "
        "short groups pad by duplicating lane 0, single lanes go "
        "solo) |",
        f"| stream kernel small chunks | "
        f"{list(L['stream_small_chunks'])} | "
        "`pallas_seg.STREAM_CHUNKS` (`delta_spec` small-delta rungs "
        "under the stream jit names; base chunks stay spec_for's) |",
        f"| wl batch B | {list(L['wl_batch'])} | "
        "`checker.wl.batch.WL_BATCH` (histories per dispatch; bigger "
        "batches chunk, short ones pad by duplicating lane 0) |",
        f"| wl reads | {list(L['wl_reads'])} | "
        "`checker.wl.batch.WL_READS` (bank + dirty ok-read rows per "
        "history; over-rung degrades to the host oracle) |",
        f"| wl accounts | {list(L['wl_accounts'])} | "
        "`checker.wl.batch.WL_ACCOUNTS` (bank balance-row width) |",
        f"| wl snapshots | {list(L['wl_snaps'])} | "
        "`checker.wl.batch.WL_SNAPS` (bank transfer rows; snapshot "
        "plane depth is T + 1) |",
        f"| wl elements | {list(L['wl_elems'])} | "
        "`checker.wl.batch.WL_ELEMS` (sets element universe; stream "
        "sessions escalate IN PLACE up this ladder) |",
        f"| wl nodes | {list(L['wl_nodes'])} | "
        "`checker.wl.batch.WL_NODES` (dirty per-read node views) |",
        f"| wl values | {list(L['wl_values'])} | "
        "`checker.wl.batch.WL_VALUES` (dirty distinct-value "
        "universe) |",
        f"| wl delta rows | {list(L['wl_delta'])} | "
        "`checker.wl.batch.WL_DELTA_PADS` (stream-rung per-append "
        "read/transfer row pads; oversized appends chunk solo) |",
        "",
        "## Dispatch sites",
        "",
    ]
    for site in inv.sites:
        out.append(f"### {site.key}")
        out.append("")
        out.append(f"- jit names: {', '.join(site.jit_names)}")
        out.append(f"- {site.note}")
        if site.axes_doc:
            out.append("- axes: " + "; ".join(
                f"{ax.name} in {ax.describe()}"
                for ax in site.axes_doc))
        out.append(f"- program bound: {site.program_bound()}")
        out.append("")
    nspecs = len(L["specs"])
    out += [
        "## Kernel spec tiers",
        "",
        f"{nspecs} distinct `SegKernelSpec` tiers are reachable from "
        "the production bucket ladder x P(1..15) x K(1..8) — the full "
        "per-tier budget table is the `--budget-table` artifact.",
        "",
        "## Abstract-trace witnesses (jax.eval_shape)",
        "",
        "| site | witness | out shapes |",
        "|---|---|---|",
    ]
    for key, desc, shapes in witness_table():
        out.append(f"| {key} | {desc} | {shapes} |")
    out += [
        "",
        "## Infra allowlist",
        "",
        "Host-glue programs (scalar converts, index builders) are",
        "name-allowlisted, not shape-constrained:",
        "",
        "`" + "`, `".join(sorted(INFRA_NAMES)) + "`",
        "",
    ]
    return "\n".join(out) + ""


# --- the unbucketed-dispatch-site AST rule ----------------------------------

#: sinks: callee name -> shape-carrying argument spec. Deliberately the
#: BATCH/SERVING surface only — the single-history driver's adaptive
#: path passes exact sizes on purpose (spec_for/pad_succ bucket them
#: downstream) and is declared an OPEN site in the runtime inventory.
SHAPE_SINKS: Dict[str, dict] = {
    "check_batch": {"kwargs": ("s_pad", "k_pad", "n_states_pad",
                               "n_transitions_pad", "p_eff_pad")},
    "check_batch_async": {"kwargs": ("s_pad", "k_pad",
                                     "n_states_pad",
                                     "n_transitions_pad",
                                     "p_eff_pad")},
    "segment_batch": {"kwargs": ("s_pad", "k_pad")},
    "pack_batch": {"kwargs": ("n_pad",)},
    "make_segments": {"kwargs": ("s_pad", "k_pad")},
    "pad_succ": {"kwargs": ("s_pad", "t_pad"), "pos": (1, 2)},
    "check_device_keys": {"kwargs": ("n_states", "n_transitions")},
    "check_device_flat": {"kwargs": ("n_states", "n_transitions")},
    "check_device_seg_batch": {"kwargs": ("n_states",
                                          "n_transitions")},
    "check_device_mxu_batch": {"kwargs": ("n_states",
                                          "n_transitions")},
    "check_device_batch": {"kwargs": ("n_states", "n_transitions")},
    "check_device_pallas_stream": {"kwargs": ("n_states",
                                              "n_transitions")},
    # mesh sinks: a shard_map body fed a shape not divided from a
    # declared bucket compiles one per-shard program per seed — B must
    # be a pow2 multiple of D, table dims pow2, like everywhere else
    "check_device_keys_sharded": {"kwargs": ("B", "n_states",
                                             "n_transitions")},
    "stream_dispatch_sharded": {"kwargs": ("n_states",
                                           "n_transitions")},
    "check_sharded": {"kwargs": ("n_states", "n_transitions")},
    # the streaming-session delta entrypoints: raw memo counts here
    # would compile one program per live history's alphabet — every
    # caller must route through stream.engine.pad_sizes. The fused
    # megabatch forms are the same sink (one unbucketed lane would
    # seed a program for the WHOLE group's shape class)
    "stream_delta_chunk": {"kwargs": ("n_states", "n_transitions")},
    "stream_delta_megabatch": {"kwargs": ("n_states",
                                          "n_transitions")},
    "check_device_mxu_megabatch": {"kwargs": ("n_states",
                                              "n_transitions")},
    # workload-family sinks (checker/wl): every static dim must come
    # off a WL_* ladder (stage_wl_batch/_dims bucket; stream/wl.py
    # sessions carry pre-bucketed pads) — a raw count here compiles
    # one program per distinct history shape, same hazard as the
    # frontier engines
    "wl_bank_check": {"kwargs": ("n_reads", "n_accounts",
                                 "n_snaps")},
    "wl_bank_delta": {"kwargs": ("n_reads", "n_accounts",
                                 "n_snaps")},
    "wl_bank_delta_mb": {"kwargs": ("n_reads", "n_accounts",
                                    "n_snaps")},
    "wl_sets_check": {"kwargs": ("n_elems",)},
    "wl_sets_delta": {"kwargs": ("n_elems",)},
    "wl_sets_delta_mb": {"kwargs": ("n_elems",)},
    "wl_dirty_check": {"kwargs": ("n_reads", "n_nodes",
                                  "n_values")},
    "check_wl_batch": {"kwargs": ("b_pad",)},
    "stage_wl_batch": {"kwargs": ("b_pad",)},
}

#: callables that PRODUCE bucketed values
SANCTIONERS = {"next_pow2", "_next_pow2", "bucket_of", "padded"}

#: attribute reads that are raw per-history counts (memo tables)
RAW_ATTRS = {"n_states", "n_transitions"}

#: attribute reads that are ladder-derived by construction
#: (service Bucket / TxnBucket fields)
BUCKETED_ATTRS = {"S", "K", "P", "P_eff", "n_pad", "N"}

_MAX_DEPTH = 5

BUCKETED, RAW, UNKNOWN = 0, 1, 2


@dataclass
class _FileInfo:
    path: str
    tree: ast.Module
    lines: List[str]
    funcs: Dict[str, ast.AST] = field(default_factory=dict)
    consts: Dict[str, int] = field(default_factory=dict)


class _Graph:
    """Cross-file call graph over the scanned set: function defs by
    name (chased only when unambiguous or same-file) and call sites by
    callee name."""

    def __init__(self, infos: List[_FileInfo]):
        self.infos = infos
        self.defs: Dict[str, List[Tuple[_FileInfo, ast.AST]]] = {}
        # callee name -> [(info, call node, enclosing funcdef | None)]
        self.calls: Dict[str, List[tuple]] = {}
        for info in infos:
            self._index(info)

    @staticmethod
    def _callee(call: ast.Call) -> str:
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
        return ""

    def _index(self, info: _FileInfo) -> None:
        from .pallas_budget import _module_consts

        info.consts = _module_consts(info.tree)
        stack: List[ast.AST] = []

        def walk(node, fn):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                info.funcs.setdefault(node.name, node)
                self.defs.setdefault(node.name, []).append((info, node))
                fn = node
            if isinstance(node, ast.Call):
                name = self._callee(node)
                if name:
                    self.calls.setdefault(name, []).append(
                        (info, node, fn))
            for child in ast.iter_child_nodes(node):
                walk(child, fn)

        walk(info.tree, None)

    def def_of(self, name: str,
               prefer: _FileInfo) -> Optional[Tuple[_FileInfo, ast.AST]]:
        cands = self.defs.get(name, [])
        same = [c for c in cands if c[0] is prefer]
        if same:
            return same[0]
        if len(cands) == 1:          # unambiguous across the repo
            return cands[0]
        return None                  # ambiguous: stay silent


def _classify(expr: ast.AST, info: _FileInfo,
              fn: Optional[ast.AST], graph: _Graph,
              depth: int, visited: set):
    """(verdict, detail, anchor_line) for a shape-valued expression.
    RAW means PROVABLY unbucketed; UNKNOWN means out of AST reach
    (silent — the runtime guard is the backstop)."""
    if depth > _MAX_DEPTH:
        return UNKNOWN, "", 0
    if isinstance(expr, ast.Constant):
        v = expr.value
        if v is None:
            return BUCKETED, "", 0       # no-floor sentinel
        if isinstance(v, bool) or not isinstance(v, int):
            return UNKNOWN, "", 0
        if v == 0 or _is_pow2(v):
            return BUCKETED, "", 0       # 0 = no-floor sentinel
        return RAW, f"literal {v} is not a power of two", expr.lineno
    if isinstance(expr, ast.Call):
        name = _Graph._callee(expr)
        if name in SANCTIONERS:
            return BUCKETED, "", 0
        if name == "len":
            return RAW, "a raw len(...) reaches the jit boundary", \
                expr.lineno
        if name in ("min", "max"):
            verdicts = [_classify(a, info, fn, graph, depth + 1,
                                  visited) for a in expr.args]
            if any(v[0] == RAW for v in verdicts):
                return next(v for v in verdicts if v[0] == RAW)
            if verdicts and all(v[0] == BUCKETED for v in verdicts):
                return BUCKETED, "", 0
        return UNKNOWN, "", 0
    if isinstance(expr, ast.Attribute):
        if expr.attr in RAW_ATTRS:
            return RAW, f"raw memo count .{expr.attr} reaches the " \
                "jit boundary (one program per distinct history " \
                "shape)", expr.lineno
        if expr.attr in BUCKETED_ATTRS:
            return BUCKETED, "", 0
        return UNKNOWN, "", 0
    if isinstance(expr, ast.Subscript):
        base = expr.value
        if isinstance(base, ast.Attribute) and base.attr == "shape":
            return RAW, "raw .shape[...] reaches the jit boundary " \
                "unbucketed", expr.lineno
        return UNKNOWN, "", 0
    if isinstance(expr, (ast.BoolOp, ast.IfExp)):
        parts = (expr.values if isinstance(expr, ast.BoolOp)
                 else [expr.body, expr.orelse])
        verdicts = [_classify(p, info, fn, graph, depth + 1, visited)
                    for p in parts]
        for v in verdicts:
            if v[0] == RAW:
                return v
        if verdicts and all(v[0] == BUCKETED for v in verdicts):
            return BUCKETED, "", 0
        return UNKNOWN, "", 0
    if isinstance(expr, ast.BinOp):
        for side in (expr.left, expr.right):
            v = _classify(side, info, fn, graph, depth + 1, visited)
            if v[0] == RAW:
                return v
        return UNKNOWN, "", 0
    if isinstance(expr, ast.Name):
        return _classify_name(expr.id, getattr(expr, "lineno", 0),
                              info, fn, graph, depth, visited)
    return UNKNOWN, "", 0


def _classify_name(name: str, use_line: int, info: _FileInfo,
                   fn: Optional[ast.AST], graph: _Graph, depth: int,
                   visited: set):
    # the LAST local assignment dominating the use site wins — the
    # first-match rule both flagged `n = len(xs); n = next_pow2(n)`
    # and waved through the reversed order
    if fn is not None:
        best = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == name \
                    and node.lineno < use_line \
                    and (best is None or node.lineno > best.lineno):
                best = node
        if best is not None:
            return _classify(best.value, info, fn, graph,
                             depth + 1, visited)
        # a parameter: chase every call site of the enclosing function
        args = getattr(fn, "args", None)
        if args is not None:
            names = [a.arg for a in args.args]
            if name in names:
                return _chase_param(fn, name, names.index(name),
                                    graph, depth, visited)
    if name in info.consts:
        v = info.consts[name]
        if v == 0 or _is_pow2(v):
            return BUCKETED, "", 0
        return RAW, f"module constant {name}={v} is not a power of " \
            "two", 0
    return UNKNOWN, "", 0


def _chase_param(fn: ast.AST, param: str, pos: int, graph: _Graph,
                 depth: int, visited: set):
    """Interprocedural step: classify the argument bound to ``param``
    at every call site of ``fn``. A single provably-raw call site
    makes the parameter RAW (anchored at that call site)."""
    key = (id(fn), param)
    if key in visited or depth > _MAX_DEPTH:
        return UNKNOWN, "", 0
    visited = visited | {key}
    sites = graph.calls.get(fn.name, [])
    if not sites:
        return UNKNOWN, "", 0
    defaults = getattr(fn, "args", None)
    n_pos = len(defaults.args) if defaults is not None else 0
    is_method = (defaults is not None and defaults.args
                 and defaults.args[0].arg in ("self", "cls"))
    any_unknown = not sites
    all_bucketed = bool(sites)
    for cinfo, call, cfn in sites:
        arg_expr = None
        for kw in call.keywords:
            if kw.arg == param:
                arg_expr = kw.value
        # positional mapping: methods called through an attribute drop
        # the self slot; other method call forms make no claim
        cpos = pos
        if is_method:
            if not isinstance(call.func, ast.Attribute):
                cpos = -1
            else:
                cpos = pos - 1
        if arg_expr is None and 0 <= cpos < len(call.args) \
                and not any(isinstance(a, ast.Starred)
                            for a in call.args[:cpos + 1]) \
                and pos < n_pos:
            arg_expr = call.args[cpos]
        if arg_expr is None:
            any_unknown = True       # default value / splat: no claim
            all_bucketed = False
            continue
        v, detail, anchor = _classify(arg_expr, cinfo, cfn, graph,
                                      depth + 1, visited)
        if v == RAW:
            return RAW, f"{detail} (via {fn.name}({param}=...) at " \
                f"{os.path.basename(cinfo.path)}:" \
                f"{anchor or call.lineno})", anchor or call.lineno
        if v != BUCKETED:
            any_unknown = True
            all_bucketed = False
    if all_bucketed and not any_unknown:
        return BUCKETED, "", 0
    return UNKNOWN, "", 0


def scan_files(paths: Sequence[str], *,
               apply_suppressions: bool = True) -> List[Finding]:
    """The ``unbucketed-dispatch-site`` rule over a file set (the
    call graph is built over exactly these files)."""
    infos: List[_FileInfo] = []
    for p in paths:
        if not os.path.exists(p):
            continue
        try:
            with open(p, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=p)
        except (OSError, SyntaxError):
            continue                 # lint owns syntax errors
        infos.append(_FileInfo(path=p, tree=tree,
                               lines=src.splitlines()))
    graph = _Graph(infos)
    out: List[Finding] = []
    for info in infos:
        for name, spec in SHAPE_SINKS.items():
            for cinfo, call, cfn in graph.calls.get(name, []):
                if cinfo is not info:
                    continue
                exprs: List[Tuple[str, ast.AST]] = []
                for kw in call.keywords:
                    if kw.arg in spec.get("kwargs", ()):
                        exprs.append((kw.arg, kw.value))
                for pos in spec.get("pos", ()):
                    if pos < len(call.args) and not any(
                            isinstance(a, ast.Starred)
                            for a in call.args[:pos + 1]):
                        exprs.append((f"arg{pos}", call.args[pos]))
                for argname, expr in exprs:
                    v, detail, anchor = _classify(
                        expr, info, cfn, graph, 0, set())
                    if v != RAW:
                        continue
                    line = call.lineno
                    out.append(Finding(
                        "unbucketed-dispatch-site", info.path, line,
                        f"{name}({argname}=...): {detail} — every "
                        "jit-visible shape must come from a declared "
                        "ladder (next_pow2 / service bucket / kernel "
                        "spec); an unbucketed shape compiles one "
                        "program per seed and can OOM LLVM"))
    if not apply_suppressions:
        return out
    # suppressions apply at the sink line
    by_path = {info.path: info.lines for info in infos}
    return [f for f in out
            if not suppressed(by_path.get(f.path, ()), f.line,
                              f.rule)]


__all__ = ["Axis", "Inventory", "Site", "SHAPE_SINKS",
           "static_inventory", "render_programs", "scan_files",
           "trace_witnesses", "witness_table"]


from . import Pass, filter_suppressed, register_pass


def _repo_stage(ctx):
    # raw once: the stage filters suppressions itself and deposits
    # the raw findings for the stale-suppression audit (one
    # call-graph build per run, not two)
    raw = scan_files(ctx["prod"], apply_suppressions=False)
    ctx["raw"]["compile-surface"] = raw
    out = filter_suppressed(raw)
    if ctx["trace"]:
        out += trace_witnesses()
    return out


register_pass(Pass(
    name="compile-surface",
    scan_paths=scan_files,
    raw_paths=lambda paths: scan_files(paths,
                                       apply_suppressions=False),
    repo_stage=_repo_stage,
))
