"""CLI: ``python -m comdb2_tpu.analysis [paths...]``.

With no paths: the full repo-wide run (lint over comdb2_tpu/, scripts/
and tests/; production Pallas budgets; jaxpr recompile audit; the
compile-surface prover; the stale-suppression audit). With explicit
paths: the file-level passes only — the mode the seeded violation
fixtures (tests/fixtures/analysis/) use. ``--changed [REF]`` checks
only the files that differ from a git ref (default HEAD) plus
untracked files — the pre-commit hook's incremental mode.

Exits non-zero when any finding survives suppression — including when
``--json`` writes the findings artifact (the artifact records the
failure, it never absorbs it). Each finding prints as ``rule-id
path:line message``; per-pass wall times go to stderr so a slow pass
is visible instead of smeared into one opaque run time.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from . import (Finding, changed_files, run_paths_staged,
               run_repo_staged)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m comdb2_tpu.analysis",
        description="repo-wide static invariant checker")
    p.add_argument("paths", nargs="*",
                   help="explicit files to check (default: whole repo)")
    p.add_argument("--changed", nargs="?", const="HEAD",
                   default=None, metavar="REF",
                   help="check only .py files changed vs REF "
                        "(git diff + untracked; default HEAD)")
    p.add_argument("--no-trace", action="store_true",
                   help="skip the jaxpr/eval_shape abstract-trace "
                        "stages")
    p.add_argument("--budget-table", metavar="PATH",
                   help="write the checked Pallas budget table "
                        "artifact (markdown) and continue")
    p.add_argument("--programs", metavar="PATH",
                   help="write the compile-surface program inventory "
                        "artifact (PROGRAMS.md) and continue")
    p.add_argument("--json", metavar="PATH", dest="json_out",
                   help="also write findings as JSON (does not change "
                        "the exit code)")
    args = p.parse_args(argv)

    if args.budget_table:
        from . import pallas_budget

        with open(args.budget_table, "w") as fh:
            fh.write(pallas_budget.budget_table())
        print(f"budget table written: {args.budget_table}")

    if args.programs:
        from . import compile_surface

        with open(args.programs, "w") as fh:
            fh.write(compile_surface.render_programs())
        print(f"program inventory written: {args.programs}")

    if args.changed is not None and args.paths:
        p.error("--changed and explicit paths are mutually exclusive")
    if args.json_out:
        import os
        if any(os.path.realpath(args.json_out) == os.path.realpath(pp)
               for pp in args.paths):
            p.error("--json PATH is the findings artifact to WRITE — "
                    "it matches one of the files under check")
    if args.changed is not None:
        try:
            paths = changed_files(args.changed)
        except RuntimeError as exc:
            print(f"--changed: {exc}", file=sys.stderr)
            return 2
        if not paths:
            if args.json_out:
                with open(args.json_out, "w") as fh:
                    json.dump([], fh)
            print(f"OK: 0 findings (no files changed vs "
                  f"{args.changed})")
            return 0
        print(f"--changed {args.changed}: {len(paths)} file(s)",
              file=sys.stderr)
        stages = run_paths_staged(paths)
    elif args.paths:
        stages = run_paths_staged(args.paths)
    else:
        stages = run_repo_staged(trace=not args.no_trace)

    findings: List[Finding] = [f for _, fs, _ in stages for f in fs]
    for f in findings:
        print(f.format())
    for name, fs, secs in stages:
        print(f"pass {name}: {len(fs)} finding(s) in {secs:.2f}s",
              file=sys.stderr)
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump([f.__dict__ for f in findings], fh, indent=1)
    if findings:
        print(f"FAIL: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("OK: 0 findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
