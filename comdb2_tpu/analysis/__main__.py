"""CLI: ``python -m comdb2_tpu.analysis [paths...]``.

With no paths: the full repo-wide run (lint over comdb2_tpu/, scripts/
and tests/; production Pallas budgets; jaxpr recompile audit). With
explicit paths: the file-level passes only — the mode the seeded
violation fixtures (tests/fixtures/analysis/) use.

Exits non-zero when any finding survives suppression; each finding
prints as ``rule-id path:line message``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from . import Finding, run_paths, run_repo


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m comdb2_tpu.analysis",
        description="repo-wide static invariant checker")
    p.add_argument("paths", nargs="*",
                   help="explicit files to check (default: whole repo)")
    p.add_argument("--no-trace", action="store_true",
                   help="skip the jaxpr abstract-trace stage")
    p.add_argument("--budget-table", metavar="PATH",
                   help="write the checked Pallas budget table "
                        "artifact (markdown) and continue")
    p.add_argument("--json", metavar="PATH", dest="json_out",
                   help="also write findings as JSON")
    args = p.parse_args(argv)

    if args.budget_table:
        from . import pallas_budget

        with open(args.budget_table, "w") as fh:
            fh.write(pallas_budget.budget_table())
        print(f"budget table written: {args.budget_table}")

    findings: List[Finding]
    if args.paths:
        findings = run_paths(args.paths)
    else:
        findings = run_repo(trace=not args.no_trace)

    for f in findings:
        print(f.format())
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump([f.__dict__ for f in findings], fh, indent=1)
    if findings:
        print(f"FAIL: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("OK: 0 findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
