"""Pass 5a — lifecycle/ordering checker for the fleet plane.

PR 12's review rounds were almost entirely hand-found ORDERING bugs in
the elastic-fleet protocols (drain, migration, checkpoint/replay,
zombie reaping). The serving layer's lifecycle protocols are as
delicate as the SUTs the harness tests — this pass machine-checks the
orderings those reviews fixed by hand, per function, as named rules:

- ``publish-before-ready`` — the pmux registration must precede the
  ready line: "ready" means DISCOVERABLE. A ready line printed first
  lets a supervisor (or bench) route to a daemon the ring cannot see,
  and a crash between the two leaves a client-visible server that
  discovery never lists.
- ``deregister-before-close`` — a withdrawing daemon must deregister
  (and bump the ring epoch) BEFORE closing its listener: clients
  re-route on the epoch bump; a listener closed first turns every
  in-flight ring walk into a connect error against a node the ring
  still advertises.
- ``log-after-success`` — checkpoint/replay logs (``IncrementalMemo``
  extend log, the stream client's retained-delta log) append only
  AFTER the guarded operation succeeded: a log entry for a failed
  call makes every restore/failover replay the failure.
- ``release-in-finally`` — in cleanup-named functions, pin/park/ring
  releases must sit in a ``try/finally``: a close that raises before
  its release leaks the pin forever (the PR-12 failed-close pin leak).
- ``fresh-deadline-timestamp`` — TTL/blacklist/park deadlines must be
  stamped where they are stored, never from a loop-entry timestamp: a
  hung connect burns its whole timeout before raising, so a deadline
  anchored at walk start is already expired when written (the node is
  never actually avoided).
- ``wait-after-kill`` — every ``.kill()``/``.terminate()`` is
  followed by ``.wait()`` on the SAME process: this container has no
  init reaper, so an unwaited child stays a zombie forever (pid-table
  leak, and ``kill -0``-style liveness probes lie).

All rules are AST/per-function (statement order by line number, nested
``def``/``lambda`` bodies excluded — deferred closures run at a
different time and are checked as their own functions). Tests are
exempt (they drive lifecycles out of order on purpose); seeded
fixtures under tests/fixtures/ are not.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from . import Finding, suppressed

#: function-name parts marking a cleanup path (release-in-finally)
CLEANUP_PARTS = ("close", "shutdown", "stop", "retire", "cleanup",
                 "__exit__")

#: callee names that release a pin/park/ring resource
RELEASE_NAMES = {"_unpin", "unpin", "release", "unpark"}

#: attribute names holding replay/checkpoint logs (log-after-success)
LOG_ATTRS = {"_log", "_deltas"}

#: logger-ish trailing callee names that may follow a log append
#: without implying more guarded work
_BENIGN_AFTER_LOG = {"info", "debug", "warning", "error", "exception",
                     "append"}

#: clock callables whose result must not anchor a later-stored deadline
CLOCK_FNS = {"monotonic", "_monotonic", "time", "perf_counter"}

#: identifier parts marking a TTL/blacklist/park deadline store
DEADLINE_PARTS = ("avoid", "deadline", "blacklist", "not_before",
                  "until", "expires", "park")

#: identifier parts naming a listener socket (deregister-before-close)
LISTENER_PARTS = ("lsock", "listen")

#: callee-name parts for pmux registration / withdrawal
PUBLISH_PARTS = ("publish",)
WITHDRAW_PARTS = ("withdraw", "deregister")


def _callee(call: ast.Call) -> str:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def _chain(node: ast.AST) -> List[str]:
    """Identifier chain of a Name/Attribute/Subscript expression
    (``self._avoid[name]`` -> ``["self", "_avoid"]``)."""
    out: List[str] = []

    def walk(n):
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            walk(n.value)
            out.append(n.attr)
        elif isinstance(n, ast.Subscript):
            walk(n.value)
        elif isinstance(n, ast.Call):
            walk(n.func)

    walk(node)
    return out


def _direct(fn: ast.AST) -> List[ast.AST]:
    """All descendant nodes of ``fn`` EXCLUDING nested function/lambda
    subtrees — a deferred closure runs at a different lifecycle point
    and is analyzed as its own function."""
    out: List[ast.AST] = []

    def walk(node):
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
                continue
            out.append(ch)
            walk(ch)

    walk(fn)
    return out


def _functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _finally_nodes(fn: ast.AST) -> set:
    """id()s of every node under some ``try``'s ``finally`` block."""
    out: set = set()
    for node in _direct(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                out.add(id(stmt))
                for sub in ast.walk(stmt):
                    out.add(id(sub))
    return out


def _ready_sink_line(fn: ast.AST) -> Optional[int]:
    """Line of the first print/write/sendall call carrying a "ready"
    payload (the daemon ready line), if any."""
    best: Optional[int] = None
    for node in _direct(fn):
        if not isinstance(node, ast.Call):
            continue
        if _callee(node) not in ("print", "write", "sendall"):
            continue
        ready = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str) \
                    and "ready" in sub.value:
                ready = True
            if isinstance(sub, ast.Dict):
                for k in sub.keys:
                    if isinstance(k, ast.Constant) and k.value == "ready":
                        ready = True
        if ready and (best is None or node.lineno < best):
            best = node.lineno
    return best


def _check_publish_before_ready(fn, raw, path):
    publish = [n.lineno for n in _direct(fn)
               if isinstance(n, ast.Call)
               and any(p in _callee(n) for p in PUBLISH_PARTS)]
    if not publish:
        return
    ready = _ready_sink_line(fn)
    if ready is not None and ready < min(publish):
        raw.append(Finding(
            "publish-before-ready", path, ready,
            "ready line emitted before the pmux publish — 'ready' "
            "must mean DISCOVERABLE; a supervisor that routes on this "
            "line reaches a daemon the ring cannot see"))


def _check_deregister_before_close(fn, raw, path):
    withdraws = [n.lineno for n in _direct(fn)
                 if isinstance(n, ast.Call)
                 and any(p in _callee(n) for p in WITHDRAW_PARTS)]
    if not withdraws:
        return
    for n in _direct(fn):
        if isinstance(n, ast.Call) and _callee(n) == "close" \
                and isinstance(n.func, ast.Attribute):
            chain = _chain(n.func.value)
            if any(any(p in part for p in LISTENER_PARTS)
                   for part in chain) and n.lineno < min(withdraws):
                raw.append(Finding(
                    "deregister-before-close", path, n.lineno,
                    "listener closed before the pmux withdraw/epoch "
                    "bump — clients re-route on the epoch bump; a "
                    "listener closed first turns every in-flight ring "
                    "walk into a connect error against a node the "
                    "ring still advertises"))


def _check_log_after_success(fn, raw, path):
    appends: List[Tuple[int, str]] = []
    for n in _direct(fn):
        if isinstance(n, ast.Call) and _callee(n) == "append" \
                and isinstance(n.func, ast.Attribute):
            recv = n.func.value
            if isinstance(recv, ast.Attribute) \
                    and (recv.attr in LOG_ATTRS
                         or recv.attr.endswith("_log")):
                appends.append((n.lineno, recv.attr))
    if not appends:
        return
    for ln, attr in appends:
        later = [n for n in _direct(fn)
                 if isinstance(n, ast.Call) and n.lineno > ln
                 and _callee(n) not in _BENIGN_AFTER_LOG]
        if later:
            raw.append(Finding(
                "log-after-success", path, ln,
                f"append to the replay log '{attr}' before the "
                "guarded work finished (calls follow at line "
                f"{later[0].lineno}) — log only AFTER success, or a "
                "failed call replays into every restore/failover"))


def _check_release_in_finally(fn, raw, path):
    name = fn.name.lower()
    if not any(p in name for p in CLEANUP_PARTS):
        return
    fin = _finally_nodes(fn)
    calls = [n for n in _direct(fn) if isinstance(n, ast.Call)]
    for n in calls:
        if _callee(n) not in RELEASE_NAMES or id(n) in fin:
            continue
        # risk only exists when fallible work precedes the release
        if any(c.lineno < n.lineno for c in calls
               if _callee(c) not in RELEASE_NAMES):
            raw.append(Finding(
                "release-in-finally", path, n.lineno,
                f"{_callee(n)}() on a cleanup path outside "
                "try/finally — an exception in the preceding calls "
                "leaks the pin/session forever (failover never "
                "re-routes, eviction never fires)"))


def _check_fresh_deadline(fn, raw, path):
    # clock-derived names: `now = monotonic()` and friends
    clock_assigns = {}
    for n in _direct(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and _callee(n.value) in CLOCK_FNS:
            for tgt in n.targets:
                if isinstance(tgt, ast.Name):
                    clock_assigns[tgt.id] = n.lineno
    if not clock_assigns:
        return
    loops = [n for n in _direct(fn) if isinstance(n, (ast.For,
                                                      ast.While))]
    for n in _direct(fn):
        if not isinstance(n, ast.Assign) \
                or not isinstance(n.value, ast.BinOp) \
                or not isinstance(n.value.op, ast.Add):
            continue
        tgt_parts = [p.lower() for t in n.targets for p in _chain(t)]
        if not any(any(d in part for d in DEADLINE_PARTS)
                   for part in tgt_parts):
            continue
        stale = [name for name in
                 {s.id for s in ast.walk(n.value)
                  if isinstance(s, ast.Name)} & set(clock_assigns)
                 if any(clock_assigns[name] < lp.lineno <= n.lineno
                        for lp in loops)]
        if stale:
            raw.append(Finding(
                "fresh-deadline-timestamp", path, n.lineno,
                f"deadline stored from loop-entry timestamp "
                f"'{stale[0]}' (taken at line "
                f"{clock_assigns[stale[0]]}) — a hung connect burns "
                "its whole timeout before raising, so this deadline "
                "is already expired when written; call the clock at "
                "the store site"))


def _check_wait_after_kill(fn, raw, path):
    calls = [n for n in _direct(fn) if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Attribute)]
    waits = [(ast.unparse(n.func.value), n.lineno) for n in calls
             if n.func.attr == "wait"]
    for n in calls:
        if n.func.attr not in ("kill", "terminate"):
            continue
        recv = ast.unparse(n.func.value)
        if not any(w == recv and ln > n.lineno for w, ln in waits):
            raw.append(Finding(
                "wait-after-kill", path, n.lineno,
                f"{recv}.{n.func.attr}() with no later {recv}.wait() "
                "in this function — no init reaper in this container: "
                "an unwaited child stays a zombie (pid-table leak; "
                "liveness probes lie)"))


def scan_file(path: str, source: Optional[str] = None, *,
              apply_suppressions: bool = True) -> List[Finding]:
    """All lifecycle findings for one file."""
    if source is None:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []                        # lint owns syntax errors
    parts = path.replace("\\", "/").split("/")
    base = parts[-1]
    # tests drive lifecycles out of order on purpose (crash-ordering
    # tests, teardown shortcuts); seeded fixtures are NOT exempt
    in_tests = (base.startswith("test_")
                or ("tests" in parts and "fixtures" not in parts))
    if in_tests:
        return []
    raw: List[Finding] = []
    for fn in _functions(tree):
        _check_publish_before_ready(fn, raw, path)
        _check_deregister_before_close(fn, raw, path)
        _check_log_after_success(fn, raw, path)
        _check_release_in_finally(fn, raw, path)
        _check_fresh_deadline(fn, raw, path)
        _check_wait_after_kill(fn, raw, path)
    if not apply_suppressions:
        return raw
    lines = source.splitlines()
    return [f for f in raw if not suppressed(lines, f.line, f.rule)]


def scan_files(paths) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        try:
            out += scan_file(p)
        except OSError:
            continue
    return out


__all__ = ["scan_file", "scan_files"]


from . import Pass, register_pass

register_pass(Pass(
    name="lifecycle",
    scan_paths=scan_files,
    raw_file=lambda path, source: scan_file(
        path, source, apply_suppressions=False),
))
