"""Repo-wide static invariant checker — ``python -m comdb2_tpu.analysis``.

The framework's fragile invariants (exact sort-adjacency dedup,
sentinel-mask frontier reads, (8,128) tiling, SMEM-per-grid-step
budgets, shape bucketing) historically lived as prose in CLAUDE.md and
were rediscovered via 40 s Mosaic compile failures or 38-minute wedged
test suites. This package checks them *before* compile time, as three
cooperating passes:

- :mod:`.lint` — AST lint rules over ``comdb2_tpu/``, ``scripts/`` and
  ``tests/`` (JAX env config after import, multiprocessing pools,
  hash-fingerprint dedup, duplicated closures under nested
  ``lax.cond``, EDN/history hygiene).
- :mod:`.pallas_budget` — static Pallas/Mosaic resource budgeting:
  every production ``spec_for`` tier is re-derived and checked against
  the measured v5e limits (SMEM prefetch <= ~56 KB, ~500 B of SMEM per
  grid step toward the 1 MB space, (8,128) block divisibility, K <= 8,
  F = 128), plus an AST scan of ``pallas_call`` sites for
  literally-bad configs.
- :mod:`.jaxpr_audit` — recompile-hazard analysis: the declared shape
  buckets must be closed (no unbucketed shape reaches a jit boundary
  from the fuzz script or the driver), and the engine entry points are
  abstractly traced per bucket to flag duplicated sub-jaxprs under
  ``cond`` branches (the CPU compile-time explosion of round 3).
- :mod:`.compile_surface` — pass 4, the compile-surface prover: the
  static program inventory (service buckets, ``check_batch`` floors,
  shrink/txn pow2 buckets, ``spec_for`` tiers) enumerated as the
  ``PROGRAMS.md`` artifact, eval_shape ladder witnesses, and the
  interprocedural ``unbucketed-dispatch-site`` rule. The runtime half
  — observed-compile capture and the subset assertion — is
  :mod:`comdb2_tpu.utils.compile_guard`.
- :func:`audit_suppressions` — the ``stale-suppression`` rule: a
  marker that no longer trips its rule is itself a finding.

Per-line suppression: append ``# analysis: ignore[rule-id]`` (or a
blanket ``# analysis: ignore``) to the flagged line. Each rule's
provenance is documented in ``docs/static_analysis.md``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence

#: directories (relative to the repo root) the default repo scan covers
SCAN_ROOTS = ("comdb2_tpu", "scripts", "tests")

#: path fragments excluded from the default scan (seeded-violation
#: fixtures live under tests/fixtures/ and MUST fail the checker when
#: passed explicitly — and must not fail the repo scan)
EXCLUDE_PARTS = ("fixtures",)


@dataclass(frozen=True)
class Finding:
    """One rule violation: ``rule path:line message``."""
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.rule} {self.path}:{self.line} {self.message}"


def repo_root() -> str:
    """The repository root (parent of the ``comdb2_tpu`` package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def suppressed(source_lines: Sequence[str], lineno: int,
               rule: str) -> bool:
    """True when ``lineno`` (1-based) carries an
    ``# analysis: ignore[rule]`` or blanket ``# analysis: ignore``
    marker."""
    if not (1 <= lineno <= len(source_lines)):
        return False
    line = source_lines[lineno - 1]
    if "analysis: ignore" not in line:
        return False
    marker = line.split("analysis: ignore", 1)[1]
    if marker.startswith("["):
        inside = marker[1:marker.index("]")] if "]" in marker else ""
        return rule in {r.strip() for r in inside.split(",")}
    return True


def collect_files(root: Optional[str] = None) -> List[str]:
    """All ``.py`` files under :data:`SCAN_ROOTS`, fixtures excluded."""
    root = root or repo_root()
    out: List[str] = []
    for sub in SCAN_ROOTS:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in EXCLUDE_PARTS
                           and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def _markers(source: str):
    """``(lineno, rules-or-None)`` per ``analysis: ignore`` marker in
    REAL comments (tokenize — marker text inside string literals is
    not a marker; ``suppressed`` string-matches at enforcement time,
    but the stale audit must not flag prose)."""
    import io
    import tokenize

    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT \
                    or "analysis: ignore" not in tok.string:
                continue
            rest = tok.string.split("analysis: ignore", 1)[1]
            if rest.startswith("["):
                inside = rest[1:rest.index("]")] if "]" in rest else ""
                rules = tuple(r.strip() for r in inside.split(",")
                              if r.strip())
            else:
                rules = None                 # blanket marker
            out.append((tok.start[0], rules))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass                                 # lint owns syntax errors
    return out


def audit_suppressions(paths: Iterable[str],
                       surface_raw: Optional[List[Finding]] = None
                       ) -> List[Finding]:
    """The ``stale-suppression`` rule: an ``# analysis: ignore[...]``
    marker on a line that no longer trips that rule is itself a
    finding — suppressions must not rot silently. Every file-level
    pass contributes its RAW findings (suppression off), so a marker
    is live iff some raw finding of its rule id lands on its line.
    Stale-suppression findings are deliberately NOT suppressible
    (a blanket marker would otherwise vouch for itself).

    ``surface_raw``: pre-computed raw ``unbucketed-dispatch-site``
    findings — the repo-staged runner passes the compile-surface
    stage's own raw scan so the interprocedural call graph is built
    once per run, not twice."""
    from . import compile_surface, jaxpr_audit, lint, pallas_budget

    paths = [p for p in paths if os.path.exists(p)]
    raw: dict = {p: [] for p in paths}
    srcs: dict = {}
    marked: List[str] = []
    for p in paths:
        try:
            srcs[p] = _read(p)
        except OSError:
            continue
        if "analysis: ignore" in srcs[p]:
            marked.append(p)
    # only marker-bearing files can produce stale-suppression
    # findings, so only they need the raw per-file re-scans (the
    # whole-repo re-scan measured 3 s against 1.2 s for every other
    # AST pass combined)
    for p in marked:
        raw[p] += lint.lint_file(p, srcs[p],
                                 apply_suppressions=False)
        raw[p] += pallas_budget.scan_file(p, srcs[p],
                                          apply_suppressions=False)
        raw[p] += jaxpr_audit.scan_file(p, srcs[p],
                                        apply_suppressions=False)
    if marked:
        if surface_raw is None:
            # the full path set: the interprocedural rule needs the
            # whole call graph even when only a few files carry
            # markers
            surface_raw = compile_surface.scan_files(
                paths, apply_suppressions=False)
        for f in surface_raw:
            raw.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for p in marked:
        if p not in srcs:
            continue
        hits = {(f.line, f.rule) for f in raw[p]}
        lines_hit = {f.line for f in raw[p]}
        for ln, rules in _markers(srcs[p]):
            if rules is None:
                if ln not in lines_hit:
                    out.append(Finding(
                        "stale-suppression", p, ln,
                        "blanket 'analysis: ignore' on a line no "
                        "rule trips — remove the marker (stale "
                        "suppressions hide future regressions)"))
                continue
            for r in rules:
                if (ln, r) not in hits:
                    out.append(Finding(
                        "stale-suppression", p, ln,
                        f"suppression for '{r}' no longer trips on "
                        "this line — remove the marker (stale "
                        "suppressions hide future regressions)"))
    return out


def _staged(stages) -> List[tuple]:
    """Run ``(name, thunk)`` stages, timing each; returns
    ``[(name, findings, seconds), ...]``."""
    import time

    out = []
    for name, thunk in stages:
        t0 = time.monotonic()
        findings = thunk()
        out.append((name, findings, time.monotonic() - t0))
    return out


def run_paths_staged(paths: Iterable[str]) -> List[tuple]:
    """Every file-level pass over explicit paths — the mode the
    seeded-violation fixtures use — as timed stages."""
    from . import compile_surface, jaxpr_audit, lint, pallas_budget

    paths = list(paths)
    return _staged([
        ("lint", lambda: lint.lint_files(paths)),
        ("pallas-budget", lambda: pallas_budget.scan_files(paths)),
        ("jaxpr-audit", lambda: jaxpr_audit.scan_files(paths)),
        ("compile-surface", lambda: compile_surface.scan_files(paths)),
        ("suppression-audit", lambda: audit_suppressions(paths)),
    ])


def run_repo_staged(root: Optional[str] = None, *,
                    trace: bool = True) -> List[tuple]:
    """The full repo-wide run as timed stages: lint over the scan
    roots; the production Pallas budget table; the jaxpr recompile
    audit (bucket-closure scan of the fuzz script and the driver,
    plus — with ``trace`` — abstract traces of the engine entry
    points); the compile-surface prover (pass 4: unbucketed-dispatch
    scan of the production modules + eval_shape ladder witnesses);
    and the stale-suppression audit."""
    from . import compile_surface, jaxpr_audit, lint, pallas_budget

    root = root or repo_root()
    files = collect_files(root)
    # pass 4's dispatch-site scan covers the production surface
    # (package + scripts); tests probe odd shapes on purpose
    prod = [p for p in files
            if "tests" not in p.replace("\\", "/").split("/")]

    def jaxpr_stage():
        out = jaxpr_audit.scan_files(
            [os.path.join(root, "scripts", "fuzz_pallas_seg.py"),
             os.path.join(root, "comdb2_tpu", "checker", "linear.py")])
        out += jaxpr_audit.check_bucket_closure()
        if trace:
            out += jaxpr_audit.trace_entry_points()
        return out

    surface_raw: List[Finding] = []

    def surface_stage():
        # raw once: the stage filters suppressions itself and hands
        # the raw findings to the audit (one call-graph build per run)
        raw = compile_surface.scan_files(prod,
                                         apply_suppressions=False)
        surface_raw.extend(raw)
        lines_of: dict = {}
        out = []
        for f in raw:
            if f.path not in lines_of:
                try:
                    lines_of[f.path] = _read(f.path).splitlines()
                except OSError:
                    lines_of[f.path] = []
            if not suppressed(lines_of[f.path], f.line, f.rule):
                out.append(f)
        if trace:
            out += compile_surface.trace_witnesses()
        return out

    return _staged([
        ("lint", lambda: lint.lint_files(files)),
        ("pallas-budget",
         lambda: pallas_budget.scan_files(files)
         + pallas_budget.check_production()),
        ("jaxpr-audit", jaxpr_stage),
        ("compile-surface", surface_stage),
        ("suppression-audit",
         lambda: audit_suppressions(files, surface_raw=surface_raw)),
    ])


def run_paths(paths: Iterable[str]) -> List[Finding]:
    """Flat view of :func:`run_paths_staged`."""
    return [f for _, fs, _ in run_paths_staged(paths) for f in fs]


def run_repo(root: Optional[str] = None, *,
             trace: bool = True) -> List[Finding]:
    """Flat view of :func:`run_repo_staged`."""
    return [f for _, fs, _ in run_repo_staged(root, trace=trace)
            for f in fs]


__all__ = ["Finding", "SCAN_ROOTS", "audit_suppressions",
           "collect_files", "repo_root", "run_paths",
           "run_paths_staged", "run_repo", "run_repo_staged",
           "suppressed"]
