"""Repo-wide static invariant checker — ``python -m comdb2_tpu.analysis``.

The framework's fragile invariants (exact sort-adjacency dedup,
sentinel-mask frontier reads, (8,128) tiling, SMEM-per-grid-step
budgets, shape bucketing) historically lived as prose in CLAUDE.md and
were rediscovered via 40 s Mosaic compile failures or 38-minute wedged
test suites. This package checks them *before* compile time, as three
cooperating passes:

- :mod:`.lint` — AST lint rules over ``comdb2_tpu/``, ``scripts/`` and
  ``tests/`` (JAX env config after import, multiprocessing pools,
  hash-fingerprint dedup, duplicated closures under nested
  ``lax.cond``, EDN/history hygiene).
- :mod:`.pallas_budget` — static Pallas/Mosaic resource budgeting:
  every production ``spec_for`` tier is re-derived and checked against
  the measured v5e limits (SMEM prefetch <= ~56 KB, ~500 B of SMEM per
  grid step toward the 1 MB space, (8,128) block divisibility, K <= 8,
  F = 128), plus an AST scan of ``pallas_call`` sites for
  literally-bad configs.
- :mod:`.jaxpr_audit` — recompile-hazard analysis: the declared shape
  buckets must be closed (no unbucketed shape reaches a jit boundary
  from the fuzz script or the driver), and the engine entry points are
  abstractly traced per bucket to flag duplicated sub-jaxprs under
  ``cond`` branches (the CPU compile-time explosion of round 3).

Per-line suppression: append ``# analysis: ignore[rule-id]`` (or a
blanket ``# analysis: ignore``) to the flagged line. Each rule's
provenance is documented in ``docs/static_analysis.md``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence

#: directories (relative to the repo root) the default repo scan covers
SCAN_ROOTS = ("comdb2_tpu", "scripts", "tests")

#: path fragments excluded from the default scan (seeded-violation
#: fixtures live under tests/fixtures/ and MUST fail the checker when
#: passed explicitly — and must not fail the repo scan)
EXCLUDE_PARTS = ("fixtures",)


@dataclass(frozen=True)
class Finding:
    """One rule violation: ``rule path:line message``."""
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.rule} {self.path}:{self.line} {self.message}"


def repo_root() -> str:
    """The repository root (parent of the ``comdb2_tpu`` package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def suppressed(source_lines: Sequence[str], lineno: int,
               rule: str) -> bool:
    """True when ``lineno`` (1-based) carries an
    ``# analysis: ignore[rule]`` or blanket ``# analysis: ignore``
    marker."""
    if not (1 <= lineno <= len(source_lines)):
        return False
    line = source_lines[lineno - 1]
    if "analysis: ignore" not in line:
        return False
    marker = line.split("analysis: ignore", 1)[1]
    if marker.startswith("["):
        inside = marker[1:marker.index("]")] if "]" in marker else ""
        return rule in {r.strip() for r in inside.split(",")}
    return True


def collect_files(root: Optional[str] = None) -> List[str]:
    """All ``.py`` files under :data:`SCAN_ROOTS`, fixtures excluded."""
    root = root or repo_root()
    out: List[str] = []
    for sub in SCAN_ROOTS:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in EXCLUDE_PARTS
                           and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def run_paths(paths: Iterable[str]) -> List[Finding]:
    """Run every file-level pass (lint + budget AST + jaxpr AST) over
    explicit paths — the mode seeded-violation fixtures use."""
    from . import jaxpr_audit, lint, pallas_budget

    paths = list(paths)
    findings: List[Finding] = []
    for p in paths:
        findings += lint.lint_file(p)
    findings += pallas_budget.scan_files(paths)
    findings += jaxpr_audit.scan_files(paths)
    return findings


def run_repo(root: Optional[str] = None, *,
             trace: bool = True) -> List[Finding]:
    """The full repo-wide run: lint over the scan roots, the
    production Pallas budget table, and the jaxpr recompile audit
    (bucket-closure scan of the fuzz script and the driver, plus —
    with ``trace`` — abstract traces of the engine entry points)."""
    from . import jaxpr_audit, lint, pallas_budget

    root = root or repo_root()
    files = collect_files(root)
    findings: List[Finding] = []
    for p in files:
        findings += lint.lint_file(p)
    findings += pallas_budget.scan_files(files)
    findings += pallas_budget.check_production()
    findings += jaxpr_audit.scan_files(
        [os.path.join(root, "scripts", "fuzz_pallas_seg.py"),
         os.path.join(root, "comdb2_tpu", "checker", "linear.py")])
    findings += jaxpr_audit.check_bucket_closure()
    if trace:
        findings += jaxpr_audit.trace_entry_points()
    return findings


__all__ = ["Finding", "SCAN_ROOTS", "collect_files", "repo_root",
           "run_paths", "run_repo", "suppressed"]
