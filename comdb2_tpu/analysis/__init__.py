"""Repo-wide static invariant checker — ``python -m comdb2_tpu.analysis``.

The framework's fragile invariants (exact sort-adjacency dedup,
sentinel-mask frontier reads, (8,128) tiling, SMEM-per-grid-step
budgets, shape bucketing) historically lived as prose in CLAUDE.md and
were rediscovered via 40 s Mosaic compile failures or 38-minute wedged
test suites. This package checks them *before* compile time, as six
cooperating passes:

- :mod:`.lint` — AST lint rules over ``comdb2_tpu/``, ``scripts/`` and
  ``tests/`` (JAX env config after import, multiprocessing pools,
  hash-fingerprint dedup, duplicated closures under nested
  ``lax.cond``, EDN/history hygiene).
- :mod:`.pallas_budget` — static Pallas/Mosaic resource budgeting:
  every production ``spec_for`` tier is re-derived and checked against
  the measured v5e limits (SMEM prefetch <= ~56 KB, ~500 B of SMEM per
  grid step toward the 1 MB space, (8,128) block divisibility, K <= 8,
  F = 128), plus an AST scan of ``pallas_call`` sites for
  literally-bad configs.
- :mod:`.jaxpr_audit` — recompile-hazard analysis: the declared shape
  buckets must be closed (no unbucketed shape reaches a jit boundary
  from the fuzz script or the driver), and the engine entry points are
  abstractly traced per bucket to flag duplicated sub-jaxprs under
  ``cond`` branches (the CPU compile-time explosion of round 3).
- :mod:`.compile_surface` — pass 4, the compile-surface prover: the
  static program inventory (service buckets, ``check_batch`` floors,
  shrink/txn pow2 buckets, ``spec_for`` tiers) enumerated as the
  ``PROGRAMS.md`` artifact, eval_shape ladder witnesses, and the
  interprocedural ``unbucketed-dispatch-site`` rule. The runtime half
  — observed-compile capture and the subset assertion — is
  :mod:`comdb2_tpu.utils.compile_guard`.
- :mod:`.lifecycle` — pass 5a, the fleet lifecycle/ordering checker
  (publish-before-ready, deregister-before-close, log-after-success,
  release-in-finally, fresh-deadline-timestamp, wait-after-kill):
  the orderings PR 12's review rounds fixed by hand, machine-checked.
- :mod:`.dataflow` — pass 5b, the host↔device taint pass over the
  serving plane (sync-readback-in-pump, per-item-transfer): the
  ring's dispatch/finalize decoupling and the ~100 ms tunnel
  round-trip discipline.
- :func:`audit_suppressions` — the ``stale-suppression`` rule: a
  marker that no longer trips its rule is itself a finding.

Every pass registers itself as a :class:`Pass` (``register_pass``);
the staged runners and the stale-suppression audit enumerate the ONE
registry, so a new pass is covered by the CLI timing lines, the raw
re-scan audit and ``--changed`` automatically instead of by
copy-paste.

Per-line suppression: append ``# analysis: ignore[rule-id]`` (or a
blanket ``# analysis: ignore``) to the flagged line. Each rule's
provenance is documented in ``docs/static_analysis.md``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import (Callable, Dict, Iterable, List, Optional,
                    Sequence)

#: directories (relative to the repo root) the default repo scan covers
SCAN_ROOTS = ("comdb2_tpu", "scripts", "tests")

#: path fragments excluded from the default scan (seeded-violation
#: fixtures live under tests/fixtures/ and MUST fail the checker when
#: passed explicitly — and must not fail the repo scan)
EXCLUDE_PARTS = ("fixtures",)


@dataclass(frozen=True)
class Finding:
    """One rule violation: ``rule path:line message``."""
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.rule} {self.path}:{self.line} {self.message}"


def repo_root() -> str:
    """The repository root (parent of the ``comdb2_tpu`` package)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


@dataclass(frozen=True)
class Pass:
    """One registered analyzer.

    - ``scan_paths(paths)`` — findings over explicit files,
      suppressions applied (the fixtures / ``--changed`` mode).
    - ``raw_file(path, source)`` — per-file RAW findings (suppression
      off) for the stale-suppression audit's marker re-scan; ``None``
      for interprocedural passes.
    - ``raw_paths(paths)`` — whole-set raw findings for passes whose
      rules need the full call graph (used by the audit when no
      precomputed raw was threaded in).
    - ``repo_stage(ctx)`` — optional repo-wide override; ``ctx`` is
      ``{"root", "files", "prod", "trace", "raw"}``, where ``prod``
      excludes tests and the stage may deposit its raw findings in
      ``ctx["raw"][name]`` so the audit reuses them (one call-graph
      build per run). Default: ``scan_paths(ctx["files"])``.
    """
    name: str
    scan_paths: Callable[[Sequence[str]], List["Finding"]]
    raw_file: Optional[Callable[[str, str], List["Finding"]]] = None
    raw_paths: Optional[Callable[[Sequence[str]],
                                 List["Finding"]]] = None
    repo_stage: Optional[Callable[[dict], List["Finding"]]] = None


#: registration order of the built-in passes (stage order in runs)
_PASS_ORDER = ("lint", "pallas-budget", "jaxpr-audit",
               "compile-surface", "lifecycle", "dataflow")

#: modules that self-register a Pass on import
_PASS_MODULES = ("lint", "pallas_budget", "jaxpr_audit",
                 "compile_surface", "lifecycle", "dataflow")

_REGISTRY: Dict[str, Pass] = {}


def register_pass(p: Pass) -> Pass:
    """Called by each analyzer module at import time."""
    _REGISTRY[p.name] = p
    return p


def passes() -> List[Pass]:
    """Every registered pass, in stage order (importing the built-in
    analyzer modules so they self-register)."""
    import importlib

    for m in _PASS_MODULES:
        importlib.import_module(f".{m}", __name__)
    ordered = [_REGISTRY[n] for n in _PASS_ORDER if n in _REGISTRY]
    extras = [p for n, p in _REGISTRY.items() if n not in _PASS_ORDER]
    return ordered + extras


def suppressed(source_lines: Sequence[str], lineno: int,
               rule: str) -> bool:
    """True when ``lineno`` (1-based) carries an
    ``# analysis: ignore[rule]`` or blanket ``# analysis: ignore``
    marker."""
    if not (1 <= lineno <= len(source_lines)):
        return False
    line = source_lines[lineno - 1]
    if "analysis: ignore" not in line:
        return False
    marker = line.split("analysis: ignore", 1)[1]
    if marker.startswith("["):
        inside = marker[1:marker.index("]")] if "]" in marker else ""
        return rule in {r.strip() for r in inside.split(",")}
    return True


def collect_files(root: Optional[str] = None) -> List[str]:
    """All ``.py`` files under :data:`SCAN_ROOTS`, fixtures excluded."""
    root = root or repo_root()
    out: List[str] = []
    for sub in SCAN_ROOTS:
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in EXCLUDE_PARTS
                           and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as fh:
        return fh.read()


def _markers(source: str):
    """``(lineno, rules-or-None)`` per ``analysis: ignore`` marker in
    REAL comments (tokenize — marker text inside string literals is
    not a marker; ``suppressed`` string-matches at enforcement time,
    but the stale audit must not flag prose)."""
    import io
    import tokenize

    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT \
                    or "analysis: ignore" not in tok.string:
                continue
            rest = tok.string.split("analysis: ignore", 1)[1]
            if rest.startswith("["):
                inside = rest[1:rest.index("]")] if "]" in rest else ""
                rules = tuple(r.strip() for r in inside.split(",")
                              if r.strip())
            else:
                rules = None                 # blanket marker
            out.append((tok.start[0], rules))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass                                 # lint owns syntax errors
    return out


def audit_suppressions(paths: Iterable[str],
                       surface_raw: Optional[List[Finding]] = None,
                       raw_by_pass: Optional[
                           Dict[str, List[Finding]]] = None
                       ) -> List[Finding]:
    """The ``stale-suppression`` rule: an ``# analysis: ignore[...]``
    marker on a line that no longer trips that rule is itself a
    finding — suppressions must not rot silently. Every registered
    pass contributes its RAW findings (suppression off), so a marker
    is live iff some raw finding of its rule id lands on its line.
    Stale-suppression findings are deliberately NOT suppressible
    (a blanket marker would otherwise vouch for itself).

    ``raw_by_pass``: pre-computed raw findings keyed by pass name —
    the repo-staged runner threads each interprocedural stage's own
    raw scan through so call graphs are built once per run, not
    twice. ``surface_raw`` is the legacy spelling for the
    compile-surface entry."""
    all_passes = passes()
    raw_by_pass = dict(raw_by_pass or {})
    if surface_raw is not None:
        raw_by_pass.setdefault("compile-surface", surface_raw)

    paths = [p for p in paths if os.path.exists(p)]
    raw: dict = {p: [] for p in paths}
    srcs: dict = {}
    marked: List[str] = []
    for p in paths:
        try:
            srcs[p] = _read(p)
        except OSError:
            continue
        if "analysis: ignore" in srcs[p]:
            marked.append(p)
    # only marker-bearing files can produce stale-suppression
    # findings, so only they need the raw per-file re-scans (the
    # whole-repo re-scan measured 3 s against 1.2 s for every other
    # AST pass combined)
    for p in marked:
        for ps in all_passes:
            if ps.raw_file is not None:
                raw[p] += ps.raw_file(p, srcs[p])
    if marked:
        for ps in all_passes:
            if ps.raw_file is not None:
                continue
            findings = raw_by_pass.get(ps.name)
            if findings is None and ps.raw_paths is not None:
                # the full path set: an interprocedural rule needs
                # the whole call graph even when only a few files
                # carry markers
                findings = ps.raw_paths(paths)
            for f in findings or []:
                raw.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for p in marked:
        if p not in srcs:
            continue
        hits = {(f.line, f.rule) for f in raw[p]}
        lines_hit = {f.line for f in raw[p]}
        for ln, rules in _markers(srcs[p]):
            if rules is None:
                if ln not in lines_hit:
                    out.append(Finding(
                        "stale-suppression", p, ln,
                        "blanket 'analysis: ignore' on a line no "
                        "rule trips — remove the marker (stale "
                        "suppressions hide future regressions)"))
                continue
            for r in rules:
                if (ln, r) not in hits:
                    out.append(Finding(
                        "stale-suppression", p, ln,
                        f"suppression for '{r}' no longer trips on "
                        "this line — remove the marker (stale "
                        "suppressions hide future regressions)"))
    return out


def _staged(stages) -> List[tuple]:
    """Run ``(name, thunk)`` stages, timing each; returns
    ``[(name, findings, seconds), ...]``."""
    import time

    out = []
    for name, thunk in stages:
        t0 = time.monotonic()
        findings = thunk()
        out.append((name, findings, time.monotonic() - t0))
    return out


def filter_suppressed(findings: Iterable[Finding]) -> List[Finding]:
    """Drop findings whose line carries a matching
    ``# analysis: ignore`` marker (reads each flagged file once)."""
    lines_of: dict = {}
    out: List[Finding] = []
    for f in findings:
        if f.path not in lines_of:
            try:
                lines_of[f.path] = _read(f.path).splitlines()
            except OSError:
                lines_of[f.path] = []
        if not suppressed(lines_of[f.path], f.line, f.rule):
            out.append(f)
    return out


def run_paths_staged(paths: Iterable[str]) -> List[tuple]:
    """Every registered pass over explicit paths — the mode the
    seeded-violation fixtures and ``--changed`` use — as timed
    stages, plus the stale-suppression audit."""
    paths = list(paths)
    stages = [(p.name, (lambda p=p: p.scan_paths(paths)))
              for p in passes()]
    stages.append(("suppression-audit",
                   lambda: audit_suppressions(paths)))
    return _staged(stages)


def run_repo_staged(root: Optional[str] = None, *,
                    trace: bool = True) -> List[tuple]:
    """The full repo-wide run as timed stages: every registered pass
    (a pass's ``repo_stage`` override widens the file scan with its
    repo-level obligations — the production Pallas budget table, the
    bucket-closure scan and abstract entry-point traces, the
    compile-surface prover's production-module scan plus eval_shape
    ladder witnesses) and the stale-suppression audit, which reuses
    any raw findings the stages deposited in the shared ctx."""
    root = root or repo_root()
    files = collect_files(root)
    # the interprocedural scans cover the production surface
    # (package + scripts); tests probe odd shapes on purpose
    prod = [p for p in files
            if "tests" not in p.replace("\\", "/").split("/")]
    ctx = {"root": root, "files": files, "prod": prod,
           "trace": trace, "raw": {}}

    stages = []
    for p in passes():
        if p.repo_stage is not None:
            stages.append((p.name, (lambda p=p: p.repo_stage(ctx))))
        else:
            stages.append((p.name,
                           (lambda p=p: p.scan_paths(files))))
    stages.append(("suppression-audit",
                   lambda: audit_suppressions(
                       files, raw_by_pass=ctx["raw"])))
    return _staged(stages)


def changed_files(ref: str = "HEAD",
                  root: Optional[str] = None) -> List[str]:
    """The ``--changed`` file set: ``.py`` files under the scan roots
    (fixtures excluded) that differ from ``ref`` per
    ``git diff --name-only`` plus untracked files. Raises
    ``RuntimeError`` when git can't resolve the ref."""
    import subprocess

    root = root or repo_root()
    names: set = set()
    for cmd in (["git", "diff", "--name-only", ref, "--"],
                ["git", "ls-files", "--others",
                 "--exclude-standard"]):
        res = subprocess.run(cmd, cwd=root, capture_output=True,
                             text=True, timeout=60)
        if res.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)}: {res.stderr.strip()}")
        names.update(ln.strip() for ln in res.stdout.splitlines()
                     if ln.strip())
    out: List[str] = []
    for name in sorted(names):
        parts = name.replace("\\", "/").split("/")
        if not name.endswith(".py") or parts[0] not in SCAN_ROOTS:
            continue
        if any(part in EXCLUDE_PARTS for part in parts):
            continue
        path = os.path.join(root, name)
        if os.path.exists(path):
            out.append(path)
    return out


def run_paths(paths: Iterable[str]) -> List[Finding]:
    """Flat view of :func:`run_paths_staged`."""
    return [f for _, fs, _ in run_paths_staged(paths) for f in fs]


def run_repo(root: Optional[str] = None, *,
             trace: bool = True) -> List[Finding]:
    """Flat view of :func:`run_repo_staged`."""
    return [f for _, fs, _ in run_repo_staged(root, trace=trace)
            for f in fs]


__all__ = ["Finding", "Pass", "SCAN_ROOTS", "audit_suppressions",
           "changed_files", "collect_files", "filter_suppressed",
           "passes", "register_pass", "repo_root", "run_paths",
           "run_paths_staged", "run_repo", "run_repo_staged",
           "suppressed"]
