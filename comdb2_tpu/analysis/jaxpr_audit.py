"""Jaxpr recompile-hazard analysis.

XLA compiles one program per distinct input shape, and compile time
scales with BOTH scan length and frontier width — so production code
buckets every shape that reaches a jit boundary (pow2 pads, the fixed
fuzz bucket ladder). This pass enforces that discipline statically:

- :func:`scan_files` — AST scan of the fuzz script and the driver:
  ``bucket = (a, b)`` literals must come from the declared ladder;
  literal ``s_pad``/``k_pad`` values at ``make_segments`` call sites
  must be powers of two (non-literal pads must route through
  ``next_pow2``); literal ``n_states``/``n_transitions`` at engine
  entry calls must be bucketed. An unbucketed shape means one
  compiled program PER SEED — fuzz runs recompile per seed and can
  OOM LLVM.
- :func:`check_bucket_closure` — the declared ladder must be closed
  under the kernel gate: every bucket must fit ``spec_for`` (else the
  fuzz silently skips whole families) and the table budget.
- :func:`trace_entry_points` — abstractly traces the engine entry
  points (``checker/linear_jax.py`` seg engines, ``checker/batch.py``)
  across the declared buckets on the CPU backend (tracing only — no
  compile, no TPU tunnel) and flags duplicated sub-jaxprs under
  ``cond`` branches: the same closure body inlined under two branches
  of nested ``lax.cond`` explodes CPU compile time (CLAUDE.md; the
  two-tier engine runs the small tier unconditionally for exactly
  this reason).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Tuple

from . import Finding, suppressed
from .pallas_budget import PRODUCTION_BUCKETS, _fold, _fold_tuple, \
    _module_consts

#: pads the fuzz script may use literally (everything else must route
#: through next_pow2)
DECLARED_PADS = {"s_pad": {64}, "k_pad": {8}}

#: engine entry points traced per bucket: (module, attr, P)
TRACE_ENTRY_POINTS = (
    ("comdb2_tpu.checker.linear_jax", "check_device_seg", 4),
    ("comdb2_tpu.checker.linear_jax", "check_device_seg2", 4),
)

#: a cond branch with at least this many equations is "non-trivial" —
#: pass-through branches (lambda _: carry) legitimately repeat
MIN_BRANCH_EQNS = 3

S_PAD, K_PAD = 64, 8


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


# --- AST scan ---------------------------------------------------------------

ENTRY_CALL_NAMES = {"check_device_seg", "check_device_seg2",
                    "check_device_pallas", "check_device_seg_batch",
                    "check_device_pallas_stream", "pad_succ"}


def scan_file(path: str, source: Optional[str] = None, *,
              apply_suppressions: bool = True) -> List[Finding]:
    if source is None:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return []
    lines = source.splitlines()
    env = _module_consts(tree)
    raw: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "bucket":
            dims = _fold_tuple(node.value, env)
            if dims is not None and len(dims) == 2 \
                    and tuple(dims) not in PRODUCTION_BUCKETS:
                raw.append(Finding(
                    "jaxpr-unbucketed-shape", path, node.lineno,
                    f"bucket {dims} is not in the declared ladder "
                    f"{list(PRODUCTION_BUCKETS)} — an unbucketed "
                    "shape compiles one program per seed (recompiles "
                    "can OOM LLVM)"))
            continue
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            (fn.id if isinstance(fn, ast.Name) else "")
        if name == "make_segments":
            for kw in node.keywords:
                if kw.arg in DECLARED_PADS:
                    v = _fold(kw.value, env)
                    if v is not None and not _is_pow2(v):
                        raw.append(Finding(
                            "jaxpr-unbucketed-shape", path,
                            node.lineno,
                            f"{kw.arg}={v} is not a power of two — "
                            "pads must be bucketed (next_pow2) so "
                            "histories share compiled programs"))
        elif name in ENTRY_CALL_NAMES:
            for kw in node.keywords:
                if kw.arg in ("n_states", "n_transitions"):
                    v = _fold(kw.value, env)
                    if v is not None and not _is_pow2(v):
                        raw.append(Finding(
                            "jaxpr-unbucketed-shape", path,
                            node.lineno,
                            f"{kw.arg}={v} at a jit boundary is not "
                            "a pow2 bucket — shape buckets must be "
                            "closed"))
    if not apply_suppressions:
        return raw
    return [f for f in raw if not suppressed(lines, f.line, f.rule)]


def scan_files(paths: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        if os.path.exists(p):
            out += scan_file(p)
    return out


# --- bucket closure ---------------------------------------------------------

def check_bucket_closure() -> List[Finding]:
    """The declared ladder must be kernel-eligible end to end: every
    bucket fits the fused kernel's table budget and ``spec_for``
    accepts it at the (8,128)-tier slot counts, so no family silently
    falls off the device path (round-2 Weak #1 was exactly that:
    10/120 queue seeds device-checked)."""
    from ..checker import pallas_seg as PS

    path = PS.__file__
    out: List[Finding] = []
    for ns, nt in PRODUCTION_BUCKETS:
        if ns * nt > PS.MAX_TABLE:
            out.append(Finding(
                "jaxpr-bucket-closure", path, 0,
                f"bucket ({ns},{nt}) exceeds the kernel table budget "
                f"MAX_TABLE={PS.MAX_TABLE}"))
            continue
        if PS.spec_for(ns, nt, 4, K_PAD) is None:
            out.append(Finding(
                "jaxpr-bucket-closure", path, 0,
                f"bucket ({ns},{nt}) is rejected by spec_for at "
                f"P=4/K={K_PAD} — the fuzz ladder and the kernel "
                "gate have drifted apart"))
    return out


# --- abstract tracing -------------------------------------------------------

def _force_cpu() -> bool:
    """Pin jax to the CPU backend (the ambient env may attach a
    tunneled TPU; tracing must never touch it). Returns False when a
    non-CPU backend was already initialized — callers then skip
    tracing instead of wedging in ep_poll."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    try:
        return jax.default_backend() == "cpu"
    except RuntimeError:
        from ..utils.platform import ensure_backend

        return ensure_backend() == "cpu"


def _walk_jaxprs(jaxpr):
    """Yield every (sub-)jaxpr reachable through eqn params."""
    seen = set()
    stack = [jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for v in eqn.params.values():
                for cand in (v if isinstance(v, (tuple, list))
                             else (v,)):
                    inner = getattr(cand, "jaxpr", cand)
                    if hasattr(inner, "eqns"):
                        stack.append(inner)


def duplicated_cond_branches(closed_jaxpr) -> List[str]:
    """Descriptions of cond equations whose non-trivial branches are
    structurally identical (each compiles separately: the nested-cond
    compile explosion)."""
    out: List[str] = []
    for j in _walk_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name != "cond":
                continue
            branches = eqn.params.get("branches", ())
            seen: Dict[str, int] = {}
            for bi, br in enumerate(branches):
                inner = getattr(br, "jaxpr", br)
                if len(inner.eqns) < MIN_BRANCH_EQNS:
                    continue
                key = str(inner)
                if key in seen:
                    out.append(
                        f"cond branches {seen[key]} and {bi} are "
                        f"structurally identical "
                        f"({len(inner.eqns)} eqns)")
                else:
                    seen[key] = bi
    return out


def trace_entry_points(
        buckets: Sequence[Tuple[int, int]] = PRODUCTION_BUCKETS
) -> List[Finding]:
    """Abstractly trace the engine entry points for every declared
    bucket; flag trace failures and duplicated cond sub-jaxprs.
    Tracing builds the jaxpr only — no XLA compile, no device."""
    import importlib

    if not _force_cpu():
        return [Finding(
            "jaxpr-trace-failure", __file__, 0,
            "a non-CPU jax backend was initialized before the audit "
            "could pin the platform — run with JAX_PLATFORMS=cpu")]
    import jax
    import numpy as np

    out: List[Finding] = []
    for mod_name, attr, P in TRACE_ENTRY_POINTS:
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, attr)
        path = mod.__file__
        for ns, nt in buckets:
            args = (np.zeros((ns, nt), np.int32),          # succ
                    np.zeros((S_PAD, K_PAD), np.int32),    # inv_proc
                    np.zeros((S_PAD, K_PAD), np.int32),    # inv_tr
                    np.zeros(S_PAD, np.int32),             # ok_proc
                    np.zeros(S_PAD, np.int32))             # depth
            kw = dict(F=128, P=P, n_states=ns, n_transitions=nt)
            try:
                jaxpr = jax.make_jaxpr(
                    lambda *a: fn(*a, **kw))(*args)
            except Exception as e:            # trace failure IS a finding
                out.append(Finding(
                    "jaxpr-trace-failure", path, 0,
                    f"{attr} failed to trace at bucket ({ns},{nt}): "
                    f"{type(e).__name__}: {e}"))
                continue
            for desc in duplicated_cond_branches(jaxpr):
                out.append(Finding(
                    "jaxpr-dup-cond", path, 0,
                    f"{attr} at bucket ({ns},{nt}): {desc} — run the "
                    "shared tier unconditionally and select with ONE "
                    "cond"))
    return out


from . import Pass, register_pass


def _repo_stage(ctx):
    # bucket-closure scan of the fuzz script and the chunked driver,
    # plus (with trace) abstract traces of the engine entry points
    out = scan_files(
        [os.path.join(ctx["root"], "scripts", "fuzz_pallas_seg.py"),
         os.path.join(ctx["root"], "comdb2_tpu", "checker",
                      "linear.py")])
    out += check_bucket_closure()
    if ctx["trace"]:
        out += trace_entry_points()
    return out


register_pass(Pass(
    name="jaxpr-audit",
    scan_paths=scan_files,
    raw_file=lambda path, source: scan_file(
        path, source, apply_suppressions=False),
    repo_stage=_repo_stage,
))
