"""Pass 5b — device-value dataflow across the host↔device seam.

The tunnel costs ~100 ms per dispatch+readback round trip (measured
1.5k ops/s per-item vs 93k streamed for the same work), and the
serving loop's throughput rests on the ring keeping dispatch and
readback DECOUPLED: stage halves upload and launch, the bounded ring
finalizes later. Two rules guard that seam:

- ``sync-readback-in-pump`` — a blocking readback (``np.asarray`` /
  ``np.array`` / ``float()``/``int()``/``bool()`` / ``.item()`` /
  ``.tolist()`` / ``block_until_ready`` / ``jax.device_get``) of a
  device value inside a hot-path function (``pump``/``submit``/
  ``tick`` or any ``*dispatch*``-named function, plus everything they
  call) serializes the ring's async overlap: the scheduler beat blocks
  on the tunnel instead of staging the next bucket. Readbacks belong
  in the deferred finalize closures the ring pops — nested ``def``/
  ``lambda`` bodies are therefore EXCLUDED from the caller's hot
  scope (they run at finalize time) and analyzed on their own merits.
- ``per-item-transfer`` — a host↔device transfer (``jax.device_put``/
  ``device_get``, or a tainted readback) inside a per-item ``for``/
  ``while`` loop: the data-movement generalization of the
  ``per-item-dispatch`` lint rule. N items looped through the tunnel
  pay N round trips; batch the items and ride ONE dispatch's jit
  transfer. Comprehensions are not flagged (the checkpoint/restore
  path legitimately rebuilds small carries element-wise — covered by
  ``host-numpy-checkpoint``).

Device values are tracked by an INTERPROCEDURAL taint pass reusing
the call-graph machinery built for ``unbucketed-dispatch-site``
(:mod:`.compile_surface`): producers are the engine entry points
(``check_device*``, ``stream_delta*``, ``closure_diag*``,
``cyclic_layers_device``, ``stream_kernel*``), ``jnp.*``/``lax.*``
calls and ``jax.device_put``; taint propagates through tuple unpack,
subscripts, arithmetic and same-function attribute stores. Ambiguous
callee names (``read``, ``checkpoint`` — many defs) stop the chase:
out-of-reach provenance stays silent, the compile guard and the bench
gates are the runtime backstop. Tests are exempt (parity tests read
back on purpose).

Both rules are scoped to the SERVING PLANE — ``comdb2_tpu/service/``
and ``comdb2_tpu/stream/`` (plus fixture-hook basenames): the
ring/session architecture mandates staged dispatch + deferred
finalize there, so no synchronous readback or loop transfer is ever
legitimate. The checker/txn/shrink engine entries are the sanctioned
BLOCKING BOUNDARY: their one-shot entries read back by contract
(``check_device_pallas``, ``check_txn``), their internal loops are
per-CHUNK batched escalation ladders, not per-item traffic, and the
service only crosses into them on the deliberate host-degrade tier —
so the hot-path chase stops at that boundary instead of flagging the
engines' own designed readback points.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import Finding, suppressed
from .compile_surface import _FileInfo, _Graph
from .lifecycle import _callee, _chain, _direct

#: callee-name prefixes whose results are device values
PRODUCER_PREFIXES = ("check_device", "stream_delta", "closure_diag",
                     "cyclic_layers_device", "stream_kernel")

#: hot-path roots: the scheduler beat and every dispatch stage half
HOT_NAMES = {"pump", "submit", "tick"}
HOT_PART = "dispatch"

_MAX_DEPTH = 5

#: directory parts of the serving plane (plus fixture-hook basenames)
PLANE_DIRS = {"service", "stream"}


def _is_hot(name: str) -> bool:
    return name in HOT_NAMES or HOT_PART in name


def _in_plane(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    base = parts[-1]
    return (bool(PLANE_DIRS & set(parts)) or "fixtures" in parts
            or "dispatch" in base or "transfer" in base)


def _attr_root(call: ast.Call) -> List[str]:
    if isinstance(call.func, ast.Attribute):
        return _chain(call.func)
    return []


def _is_producer(call: ast.Call) -> bool:
    name = _callee(call)
    if any(name.startswith(p) for p in PRODUCER_PREFIXES):
        return True
    if name == "device_put":
        return True
    chain = _attr_root(call)
    if chain:
        if chain[0] in ("jnp", "lax"):
            return True
        if chain[0] == "jax" and len(chain) > 2 \
                and chain[1] in ("numpy", "lax"):
            return True
    return False


class _FnScan:
    """Single-function forward taint scan over the DIRECT body
    (nested def/lambda subtrees excluded — deferred closures are the
    sanctioned readback points and are scanned as their own
    functions). Records readback sinks and loop-resident transfers."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.names: set = set()
        self.attrs: set = set()
        #: (lineno, kind, detail) — kind in {"readback", "transfer"}
        self.sinks: List[Tuple[int, str, str]] = []
        body = _direct(fn)
        loop_ids: set = set()
        for node in body:
            if isinstance(node, (ast.For, ast.While)):
                for sub in self._in_loop(node):
                    loop_ids.add(id(sub))
        for node in body:
            if isinstance(node, ast.Call):
                self._sink(node, in_loop=id(node) in loop_ids)
            if isinstance(node, ast.Assign):
                self._assign(node)

    @staticmethod
    def _in_loop(loop: ast.AST):
        out = []

        def walk(n):
            for ch in ast.iter_child_nodes(n):
                if isinstance(ch, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                out.append(ch)
                walk(ch)

        walk(loop)
        return out

    def _tainted(self, expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.names:
                return True
            if isinstance(sub, ast.Attribute):
                try:
                    if ast.unparse(sub) in self.attrs:
                        return True
                except Exception:       # noqa: BLE001
                    pass
            if isinstance(sub, ast.Call) and _is_producer(sub):
                return True
        return False

    def _mark(self, tgt: ast.AST) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._mark(el)
        elif isinstance(tgt, ast.Name):
            self.names.add(tgt.id)
        elif isinstance(tgt, ast.Attribute):
            try:
                self.attrs.add(ast.unparse(tgt))
            except Exception:           # noqa: BLE001
                pass
        elif isinstance(tgt, ast.Starred):
            self._mark(tgt.value)

    def _assign(self, node: ast.Assign) -> None:
        if self._tainted(node.value):
            for tgt in node.targets:
                self._mark(tgt)

    def _sink(self, call: ast.Call, *, in_loop: bool) -> None:
        name = _callee(call)
        chain = _attr_root(call)
        # transfers: direction-agnostic inside a loop
        if name in ("device_put", "device_get"):
            if in_loop:
                self.sinks.append((call.lineno, "transfer",
                                   f"jax.{name}"))
            if name == "device_get" and not in_loop:
                self.sinks.append((call.lineno, "readback",
                                   "jax.device_get"))
            return
        readback = None
        if name in ("asarray", "array") and chain \
                and chain[0] in ("np", "numpy") \
                and any(self._tainted(a) for a in call.args):
            readback = f"np.{name}(<device value>)"
        elif isinstance(call.func, ast.Name) \
                and name in ("float", "int", "bool") and call.args \
                and self._tainted(call.args[0]):
            readback = f"{name}(<device value>)"
        elif isinstance(call.func, ast.Attribute) \
                and name in ("item", "tolist") \
                and self._tainted(call.func.value):
            readback = f"<device value>.{name}()"
        elif isinstance(call.func, ast.Attribute) \
                and name == "block_until_ready":
            readback = "block_until_ready()"
        if readback is not None:
            self.sinks.append(
                (call.lineno, "transfer" if in_loop else "readback",
                 readback))


def _file_infos(paths) -> List[_FileInfo]:
    infos: List[_FileInfo] = []
    for p in paths:
        parts = p.replace("\\", "/").split("/")
        base = parts[-1]
        if base.startswith("test_") \
                or ("tests" in parts and "fixtures" not in parts):
            continue
        try:
            with open(p, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=p)
        except (OSError, SyntaxError):
            continue                     # lint owns syntax errors
        infos.append(_FileInfo(path=p, tree=tree,
                               lines=src.splitlines()))
    return infos


def _hot_reach(graph: _Graph) -> Dict[int, str]:
    """id(funcdef) -> hot root name, for every function reachable
    from a hot root through the direct (non-deferred) call graph."""
    reach: Dict[int, str] = {}
    queue: List[Tuple[_FileInfo, ast.AST, int, str]] = []
    for info in graph.infos:
        for node in ast.walk(info.tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)) \
                    and _is_hot(node.name):
                queue.append((info, node, 0, node.name))
    while queue:
        info, fn, depth, root = queue.pop()
        if id(fn) in reach:
            continue
        reach[id(fn)] = root
        if depth >= _MAX_DEPTH:
            continue
        for node in _direct(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _Graph._callee(node)
            tgt = graph.def_of(name, info) if name else None
            # the chase stops at the engine boundary: checker/txn/
            # shrink entries block by contract (the service crosses
            # into them only on the deliberate host-degrade tier)
            if tgt is not None and id(tgt[1]) not in reach \
                    and _in_plane(tgt[0].path):
                queue.append((tgt[0], tgt[1], depth + 1, root))
    return reach


def scan_files(paths, *,
               apply_suppressions: bool = True) -> List[Finding]:
    infos = _file_infos(paths)
    graph = _Graph(infos)
    reach = _hot_reach(graph)
    out: List[Finding] = []
    for info in infos:
        if not _in_plane(info.path):
            continue
        for fn in ast.walk(info.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            scan = _FnScan(fn)
            hot_root = reach.get(id(fn))
            for line, kind, detail in scan.sinks:
                if kind == "readback" and hot_root is not None:
                    via = ("" if _is_hot(fn.name)
                           else f" (reached from {hot_root}())")
                    out.append(Finding(
                        "sync-readback-in-pump", info.path, line,
                        f"blocking readback {detail} in hot path "
                        f"{fn.name}(){via} — the scheduler beat "
                        "stalls on the ~100 ms tunnel instead of "
                        "staging the next bucket; move the readback "
                        "into the ring's deferred finalize"))
                elif kind == "transfer":
                    out.append(Finding(
                        "per-item-transfer", info.path, line,
                        f"host<->device transfer {detail} inside a "
                        f"per-item loop in {fn.name}() — N items pay "
                        "N ~100 ms tunnel round-trips (measured 1.5k "
                        "vs 93k ops/s); batch the items and ride ONE "
                        "dispatch's jit transfer"))
    if not apply_suppressions:
        return out
    by_path = {info.path: info.lines for info in infos}
    return [f for f in out
            if not suppressed(by_path.get(f.path, ()), f.line,
                              f.rule)]


__all__ = ["scan_files"]


from . import Pass, filter_suppressed, register_pass


def _repo_stage(ctx):
    # deposit the raw scan for the stale-suppression audit so the
    # taint pass's call graph is built once per run
    raw = scan_files(ctx["prod"], apply_suppressions=False)
    ctx["raw"]["dataflow"] = raw
    return filter_suppressed(raw)


register_pass(Pass(
    name="dataflow",
    scan_paths=scan_files,
    raw_paths=lambda paths: scan_files(paths,
                                       apply_suppressions=False),
    repo_stage=_repo_stage,
))
