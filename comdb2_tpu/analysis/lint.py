"""AST lint rules for the codified CLAUDE.md invariants.

Each rule carries its provenance in ``docs/static_analysis.md``; the
short story per rule id:

- ``jax-env-after-import`` — the ambient interpreter-startup hook
  pre-imports jax, so JAX/XLA env vars written after a jax import are
  read too late (the platform silently stays on the tunneled TPU and a
  90 s suite takes 38 min in ``ep_poll``). Use ``jax.config.update``.
- ``no-multiprocessing`` — the container exposes ONE CPU; a spawn pool
  measured 322 s -> 566 s on the 4096x generation (pure IPC overhead).
- ``hash-dedup`` — device-checker dedup must be EXACT
  (sort-adjacency); hash-fingerprint ordering lets colliding
  non-identical rows break adjacency and balloon the frontier.
- ``dup-cond-closure`` — inlining the same closure body under two
  branches of nested ``lax.cond`` makes XLA compile the body per
  branch path; CPU compile time explodes. Run the shared tier
  unconditionally and select with ONE cond.
- ``keyed-history-wrap`` — EDN ``[k v]`` values parse as plain tuples
  (a bare 2-tuple is a cas pair); modules that parse histories and
  check them must route keyed histories through
  ``independent.wrap_keyed_history``.
- ``nemesis-info-completion`` — nemesis completions must stay type
  ``info`` (PassThrough client) or ``history.complete`` rejects the
  history; an ok/fail completion would let the nemesis affect the
  model.
- ``per-item-dispatch`` — a loop dispatching ``check_device_batch`` /
  ``check_device`` (or ``closure_diag``/``cyclic_layers_device`` on
  the txn axis, or the shrink serial control ``check_candidate``) per
  item is round-trip-bound: each dispatch pays the ~100 ms tunnel
  round-trip (measured 1.5k ops/s serial vs 93k streamed). Pack the
  items into ONE ``checker.batch.pack_batch`` / ``check_batch`` /
  ``shrink.verdicts.check_candidates`` call, or submit them to the
  ``comdb2_tpu.service`` verifier daemon, which coalesces callers
  into shared dispatches.
- ``per-op-host-loop`` — the pack/segment ingest path is columnar
  since round 6 (the per-op walk measured ``host_pack_s = 278.2``
  against ~70 s of device time at the 4096x bench shape); a ``for``
  loop over ``<x>.ops`` inside those modules reintroduces per-op
  Python on the hot path. Op objects are API-edge views only
  (counterexample decode, report rendering — suppression-listed).
- ``vmap-sharded-oracle`` — ``linear_jax.check_sharded`` (the vmap
  engine shard_mapped over a mesh) is a TEST ORACLE only: vmap lowers
  ~20x worse per lane than the flat-batch encodings, so sharding it
  scales a pessimized program. Production mesh traffic rides the
  stream/keys/flat sharded engines through ``check_batch``; any
  non-test call site routing serving traffic back onto the oracle is
  a finding (round 7 removed the last one).
- ``raw-clock-in-pipeline`` — ``time.time()``/``time.monotonic()``/
  ``time.perf_counter()`` read directly inside a dispatch-pipeline
  module (service/shrink/txn packages, checker ``linear.py``/
  ``batch.py``/``pallas_seg.py``). Timing there must go through
  ``comdb2_tpu.obs.trace`` (``monotonic()``, the span API): the
  per-request stage attribution (queue-wait / host-pack / device /
  finalize) only tiles the measured wall when every timestamp comes
  off ONE clock, and a raw ``time.time()`` (wall clock, steppable by
  the clock nemesis) silently corrupts device-time attribution.
  ``comdb2_tpu/obs`` itself and tests are exempt.
- ``host-numpy-checkpoint`` — session checkpoint/restore builders
  must be HOST numpy only (the round-11 ``_host_seg_carry`` rule
  generalized): a jnp-built checkpoint compiles infra programs
  OUTSIDE the declared inventory (scatter/pad per carry shape —
  one per session shape, per eviction), and eagerly round-trips the
  tunnel. ``np.asarray`` of a device array is a readback, never a
  compile; the restore upload rides the next delta dispatch's jit
  transfer. Scope: the ``stream`` package plus any
  "checkpoint"-named file (the fixture hook), functions whose name
  contains ``checkpoint``/``restore``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from . import Finding, suppressed

JAX_ENV_PREFIXES = ("JAX_", "XLA_")

CHECKER_ENTRY_NAMES = {"analysis", "check_history"}
PARSE_NAMES = {"parse_history", "parse_history_fast"}

#: single-batch device entry points that are round-trip-bound when
#: driven once per item from a host loop (``check_batch`` itself is
#: the batching API — a loop over BUCKETS of coalesced work is
#: legitimate, so only the per-history entries are flagged). The txn
#: closure engine's entries are covered too: one cycle check per
#: dependency graph must ride ``closure_diag_batch`` (or the service
#: txn kind), never a loop of ``closure_diag`` calls. The shrink
#: entry point ``check_candidate`` is covered for the same reason:
#: one verdict dispatch per ddmin candidate is the bug the shrink
#: subsystem exists to avoid — a round's candidates ride ONE
#: ``shrink.verdicts.check_candidates`` call per shape bucket.
PER_ITEM_DISPATCH_NAMES = {"check_device_batch", "check_device",
                           "closure_diag", "cyclic_layers_device",
                           "check_candidate"}

#: modules forming the columnar pack/segment ingest path — a per-op
#: ``for ... in <x>.ops`` loop there is the ``per-op-host-loop``
#: hazard (files whose basename contains "pack" are included so the
#: seeded fixture and future pack helpers are covered)
PACK_SEGMENT_MODULES = {"packed.py", "columnar.py",
                        "synth_columnar.py", "batch.py",
                        "linear_jax.py", "pallas_seg.py",
                        # the streaming delta ingest/segment path is
                        # columnar by the same contract (the session
                        # pays the pass PER APPEND, forever)
                        "ingest.py", "segment.py"}

#: package directories whose EVERY module is pack/segment scope —
#: checker/wl encodes whole batches into column planes (encoders,
#: delta builders, verdict decoders), so a ``.ops`` loop anywhere in
#: it is the same hazard
PACK_SEGMENT_DIRS = {"wl"}

#: the dispatch-pipeline scope of ``raw-clock-in-pipeline``: package
#: directories plus the checker dispatch modules (files whose
#: basename contains "dispatch" are included so the seeded fixture
#: and future dispatch helpers are covered); ``obs`` is the clock's
#: home and exempt
RAW_CLOCK_DIRS = {"service", "shrink", "txn", "stream", "wl"}
RAW_CLOCK_FILES = {"linear.py", "batch.py", "pallas_seg.py"}
RAW_CLOCK_FNS = {"time", "monotonic", "perf_counter"}

#: substrings naming the checkpoint/restore builders the
#: ``host-numpy-checkpoint`` rule audits (scope: the stream package
#: + "checkpoint"-named files, so the seeded fixture is covered)
CHECKPOINT_FN_PARTS = ("checkpoint", "restore")


def _name_of(node: ast.AST) -> str:
    """Trailing identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_os_environ(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and _name_of(node.value) == "os")


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class _ModuleInfo(ast.NodeVisitor):
    """One traversal collecting everything the rules need."""

    def __init__(self) -> None:
        self.jax_import_line: Optional[int] = None   # module level
        self.imports_jax = False                     # anywhere
        self.mp_imports: List[Tuple[int, str]] = []
        self.hash_uses: List[int] = []
        self.env_writes: List[Tuple[int, str, bool]] = []  # ln, key, in_fn
        self.parse_calls: List[int] = []
        self.checker_calls: List[int] = []
        self.wrap_refs = 0
        self.nemesis_bad_type: List[Tuple[int, str]] = []
        self.cond_calls: List[ast.Call] = []
        self.func_defs: Dict[str, ast.AST] = {}
        self.loop_dispatch: List[Tuple[int, str]] = []
        self.ops_loops: List[int] = []
        self.vmap_oracle_calls: List[int] = []
        self.clock_calls: List[Tuple[int, str]] = []
        self.jax_aliases: set = set()      # `import jax [as x]`
        self.jnp_aliases: set = set()      # `import jax.numpy as jnp`
        self._time_modnames: set = set()   # `import time [as x]`
        self._time_aliases: set = set()    # `from time import ...`
        self._fn_depth = 0
        self._loop_depth = 0

    def _note_ops_iter(self, lineno: int, iter_node) -> None:
        """Record a loop whose iterated expression reaches a ``.ops``
        attribute (incl. wrapped forms like ``enumerate(p.ops)``)."""
        for sub in ast.walk(iter_node):
            if isinstance(sub, ast.Attribute) and sub.attr == "ops":
                self.ops_loops.append(lineno)
                return

    # -- imports -------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            top = a.name.split(".")[0]
            if top == "jax":
                self.imports_jax = True
                if self._fn_depth == 0 and self.jax_import_line is None:
                    self.jax_import_line = node.lineno
                if a.name == "jax":
                    self.jax_aliases.add(a.asname or "jax")
                elif a.name == "jax.numpy" and a.asname:
                    self.jnp_aliases.add(a.asname)
                elif not a.asname:
                    # `import jax.numpy` (no asname) binds the NAME
                    # `jax`: `jax.numpy.zeros(...)` must resolve
                    # through the jax root like any other submodule
                    self.jax_aliases.add("jax")
            if top == "multiprocessing":
                self.mp_imports.append((node.lineno, a.name))
            if a.name == "time":
                self._time_modnames.add(a.asname or "time")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        top = (node.module or "").split(".")[0]
        if top == "jax":
            self.imports_jax = True
            if self._fn_depth == 0 and self.jax_import_line is None:
                self.jax_import_line = node.lineno
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        self.jnp_aliases.add(a.asname or "numpy")
            elif node.module == "jax.numpy":
                # `from jax.numpy import zeros` — any imported name
                # is a device-op constructor inside a checkpoint
                for a in node.names:
                    self.jnp_aliases.add(a.asname or a.name)
        if top == "multiprocessing":
            self.mp_imports.append((node.lineno, node.module or top))
        if top == "concurrent":
            for a in node.names:
                if a.name == "ProcessPoolExecutor":
                    self.mp_imports.append((node.lineno, a.name))
        if node.module == "time":
            for a in node.names:
                if a.name in RAW_CLOCK_FNS:
                    self._time_aliases.add(a.asname or a.name)
        self.generic_visit(node)

    # -- defs / scoping ------------------------------------------------

    def _visit_fn(self, node) -> None:
        self.func_defs.setdefault(node.name, node)
        self._fn_depth += 1
        self.generic_visit(node)
        self._fn_depth -= 1

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _visit_loop(self, node) -> None:
        it = getattr(node, "iter", None)        # For / AsyncFor
        if it is not None:
            self._note_ops_iter(node.lineno, it)
        for gen in getattr(node, "generators", ()):  # comprehensions
            self._note_ops_iter(node.lineno, gen.iter)
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop
    visit_ListComp = _visit_loop
    visit_SetComp = _visit_loop
    visit_DictComp = _visit_loop
    visit_GeneratorExp = _visit_loop

    # -- expressions ---------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (node.attr == "ProcessPoolExecutor"
                and _name_of(node.value) == "futures"):
            self.mp_imports.append((node.lineno, "ProcessPoolExecutor"))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if node.id == "wrap_keyed_history":
            self.wrap_refs += 1
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if (isinstance(t, ast.Subscript)
                    and _is_os_environ(t.value)):
                key = _const_str(t.slice)
                if key and key.startswith(JAX_ENV_PREFIXES):
                    self.env_writes.append(
                        (node.lineno, key, self._fn_depth > 0))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = _name_of(fn)
        if isinstance(fn, ast.Name) and name == "hash":
            self.hash_uses.append(node.lineno)
        if name == "setdefault" and isinstance(fn, ast.Attribute) \
                and _is_os_environ(fn.value) and node.args:
            key = _const_str(node.args[0])
            if key and key.startswith(JAX_ENV_PREFIXES):
                self.env_writes.append(
                    (node.lineno, key, self._fn_depth > 0))
        if name in PER_ITEM_DISPATCH_NAMES and self._loop_depth > 0:
            self.loop_dispatch.append((node.lineno, name))
        if isinstance(fn, ast.Attribute) and fn.attr in RAW_CLOCK_FNS \
                and _name_of(fn.value) in self._time_modnames:
            self.clock_calls.append(
                (node.lineno, f"{_name_of(fn.value)}.{fn.attr}"))
        elif isinstance(fn, ast.Name) and fn.id in self._time_aliases:
            self.clock_calls.append((node.lineno, fn.id))
        if name == "check_sharded":
            self.vmap_oracle_calls.append(node.lineno)
        if name in PARSE_NAMES:
            self.parse_calls.append(node.lineno)
        if name in CHECKER_ENTRY_NAMES:
            self.checker_calls.append(node.lineno)
        if name == "wrap_keyed_history":
            self.wrap_refs += 1
        if name in ("cond", "switch") \
                and _name_of(getattr(fn, "value", None)) in ("lax",
                                                            "jax"):
            self.cond_calls.append(node)
        # nemesis completion types: Op(..., type="ok"/"fail"),
        # op.with_(type=...), and {**op, "type": "ok"} dict displays
        # are caught in _nemesis_scan (dict displays aren't calls)
        if name in ("Op", "with_"):
            for kw in node.keywords:
                if kw.arg == "type":
                    v = _const_str(kw.value)
                    if v in ("ok", "fail"):
                        self.nemesis_bad_type.append((node.lineno, v))
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        for k, v in zip(node.keys, node.values):
            if k is not None and _const_str(k) == "type":
                val = _const_str(v)
                if val in ("ok", "fail"):
                    self.nemesis_bad_type.append((node.lineno, val))
        self.generic_visit(node)


def _hash_args(node: ast.Call) -> List[int]:
    """Lines where builtin ``hash`` is passed as a sort key
    (``key=hash``) — dedup by hash without even a call."""
    out = []
    for kw in node.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name) \
                and kw.value.id == "hash":
            out.append(node.lineno)
    return out


def _branches(call: ast.Call) -> List[ast.AST]:
    """Branch callables of a lax.cond/lax.switch call node."""
    name = _name_of(call.func)
    if name == "cond":
        return list(call.args[1:3])
    if name == "switch" and len(call.args) >= 2 \
            and isinstance(call.args[1], (ast.List, ast.Tuple)):
        return list(call.args[1].elts)
    return []


def _branch_key(branch: ast.AST,
                defs: Dict[str, ast.AST]) -> Optional[str]:
    """Structural fingerprint of a branch body; None for trivial
    branches (no call in the body — pass-through lambdas legitimately
    repeat)."""
    body: Optional[ast.AST] = None
    if isinstance(branch, ast.Lambda):
        body = branch.body
    elif isinstance(branch, ast.Name) and branch.id in defs:
        body = ast.Module(body=defs[branch.id].body, type_ignores=[])
    if body is None:
        return None
    if not any(isinstance(n, ast.Call) for n in ast.walk(body)):
        return None
    return ast.dump(body)


def _cond_subtree(call: ast.Call,
                  defs: Dict[str, ast.AST]) -> set:
    """Node-identity set of the cond call's subtree, with Name
    branches resolved to their local function definitions (so a cond
    inside a named branch counts as nested under this cond)."""
    nodes = set(map(id, ast.walk(call)))
    for br in _branches(call):
        if isinstance(br, ast.Name) and br.id in defs:
            nodes |= set(map(id, ast.walk(defs[br.id])))
    return nodes


def _dup_cond_findings(info: _ModuleInfo, path: str,
                       lines) -> List[Finding]:
    conds = info.cond_calls
    out: List[Finding] = []
    keyed = []
    for c in conds:
        keys = [(_branch_key(b, info.func_defs), b) for b in
                _branches(c)]
        keyed.append([k for k, _ in keys])
        # same non-trivial body twice under ONE cond
        seen = set()
        for k, _ in keys:
            if k is None:
                continue
            if k in seen:
                out.append(Finding(
                    "dup-cond-closure", path, c.lineno,
                    "identical closure body under two branches of one "
                    "lax.cond — hoist it and select inputs instead"))
            seen.add(k)
    subtrees = [_cond_subtree(c, info.func_defs) for c in conds]
    for i, ci in enumerate(conds):
        for j, cj in enumerate(conds):
            if i == j or id(cj) not in subtrees[i]:
                continue
            dup = set(k for k in keyed[i] if k) \
                & set(k for k in keyed[j] if k)
            if dup:
                out.append(Finding(
                    "dup-cond-closure", path, cj.lineno,
                    f"closure body duplicated between nested lax.cond "
                    f"branches (outer at line {ci.lineno}): XLA "
                    "compiles it once per branch path — run the "
                    "shared tier unconditionally, select with ONE "
                    "cond"))
    return out


def _checkpoint_findings(tree: ast.AST, info: _ModuleInfo,
                         path: str) -> List[Finding]:
    """``host-numpy-checkpoint``: device ops (jnp/jax attribute
    chains, or names imported from jax.numpy) inside a function whose
    name marks it a checkpoint/restore builder."""
    bases = info.jax_aliases | info.jnp_aliases
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        name = node.name.lower()
        if not any(p in name for p in CHECKPOINT_FN_PARTS):
            continue
        hits = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute):
                root = sub
                while isinstance(root.value, ast.Attribute):
                    root = root.value
                if isinstance(root.value, ast.Name) \
                        and root.value.id in bases:
                    hits.add(sub.lineno)
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id in info.jnp_aliases:
                hits.add(sub.lineno)
        for ln in sorted(hits):
            out.append(Finding(
                "host-numpy-checkpoint", path, ln,
                f"jax/jnp op inside {node.name}() — checkpoint/"
                "restore builders must be HOST numpy only: a "
                "jnp-built snapshot compiles infra programs outside "
                "the declared inventory (one per carry shape, per "
                "eviction) and eagerly round-trips the tunnel; "
                "np.asarray reads back, the next delta dispatch's "
                "jit transfer uploads"))
    return out


def lint_file(path: str, source: Optional[str] = None, *,
              apply_suppressions: bool = True) -> List[Finding]:
    """All lint findings for one file (suppressions applied unless
    ``apply_suppressions=False`` — the stale-suppression audit needs
    the raw findings to decide which markers still earn their keep)."""
    if source is None:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0, str(e))]
    lines = source.splitlines()
    info = _ModuleInfo()
    info.visit(tree)

    raw: List[Finding] = []

    for ln, key, in_fn in info.env_writes:
        after_import = (info.jax_import_line is not None
                        and ln > info.jax_import_line)
        if in_fn or after_import:
            raw.append(Finding(
                "jax-env-after-import", path, ln,
                f"os.environ[{key!r}] written after jax import — jax "
                "reads env only at import (the ambient hook may "
                "pre-import it); use jax.config.update"))

    for ln, what in info.mp_imports:
        raw.append(Finding(
            "no-multiprocessing", path, ln,
            f"{what}: the container exposes ONE CPU — a spawn pool is "
            "pure IPC overhead (measured 322 s -> 566 s); keep "
            "host-side work single-process"))

    if info.imports_jax:
        hash_lines = list(info.hash_uses)
        for c in ast.walk(tree):
            if isinstance(c, ast.Call):
                hash_lines += _hash_args(c)
        for ln in sorted(set(hash_lines)):
            raw.append(Finding(
                "hash-dedup", path, ln,
                "builtin hash() in a jax engine module — device "
                "dedup must be EXACT (sort-adjacency), never "
                "hash-fingerprint ordering"))
        raw += _dup_cond_findings(info, path, lines)

    parts = path.replace("\\", "/").split("/")
    base = parts[-1]
    # scoped to production code: tests parse histories THEY generated
    # (known non-keyed); the hazard is entry points fed arbitrary EDN.
    # Seeded fixtures under tests/fixtures/ are NOT exempt — they
    # exist to trip the rules
    in_tests = (base.startswith("test_")
                or ("tests" in parts and "fixtures" not in parts))
    if info.parse_calls and info.checker_calls and not info.wrap_refs \
            and not in_tests:
        raw.append(Finding(
            "keyed-history-wrap", path, info.parse_calls[0],
            "module parses EDN histories and runs a checker without "
            "referencing independent.wrap_keyed_history — EDN [k v] "
            "values parse as plain tuples (a bare 2-tuple is a cas "
            "pair)"))

    if not in_tests and base != "linear_jax.py":
        # check_sharded (the vmap-sharded oracle) may be DEFINED in
        # linear_jax and CALLED from tests; everything else routing
        # mesh traffic onto it is serving a 20x-pessimized engine
        for ln in info.vmap_oracle_calls:
            raw.append(Finding(
                "vmap-sharded-oracle", path, ln,
                "check_sharded is a test oracle — vmap lowers ~20x "
                "worse per lane, so sharding it scales a pessimized "
                "program; route mesh traffic through checker.batch."
                "check_batch (stream/keys/flat sharded engines)"))

    if not in_tests:
        # tests legitimately compare per-item vs batched results; the
        # hazard is production paths serving traffic one dispatch per
        # history (each pays the ~100 ms tunnel round-trip)
        for ln, fname in info.loop_dispatch:
            raw.append(Finding(
                "per-item-dispatch", path, ln,
                f"{fname} dispatched inside a loop — per-item device "
                "calls are round-trip-bound (measured 1.5k vs 93k "
                "ops/s); pack the items through checker.batch."
                "pack_batch/check_batch (shrink candidates: shrink."
                "verdicts.check_candidates) or submit them to the "
                "comdb2_tpu.service verifier daemon"))

    # dispatch-pipeline scope: the service/shrink/txn packages, the
    # checker dispatch modules, and any "dispatch"-named file (the
    # fixture hook); obs owns the clock, tests drive deadlines with
    # whatever clock they like
    in_pipeline = (not in_tests and "obs" not in parts
                   and ((set(parts) & RAW_CLOCK_DIRS
                         and "comdb2_tpu" in parts)
                        or base in RAW_CLOCK_FILES
                        or "dispatch" in base))
    if in_pipeline:
        for ln, what in info.clock_calls:
            raw.append(Finding(
                "raw-clock-in-pipeline", path, ln,
                f"{what}() read directly in a dispatch-pipeline "
                "module — route timing through comdb2_tpu.obs.trace "
                "(monotonic()/span()): stage sums only tile the "
                "measured wall when every timestamp shares ONE "
                "monotonic clock (docs/observability.md)"))

    # checkpoint/restore scope: the stream package (where the session
    # snapshot path lives) + any "checkpoint"-named file (fixture
    # hook); tests may build whatever debug snapshots they like
    if not in_tests and ("checkpoint" in base
                         or ("stream" in parts
                             and "comdb2_tpu" in parts)):
        raw += _checkpoint_findings(tree, info, path)

    if (base in PACK_SEGMENT_MODULES or "pack" in base
            or (not in_tests and set(parts) & PACK_SEGMENT_DIRS
                and "comdb2_tpu" in parts)):
        for ln in info.ops_loops:
            raw.append(Finding(
                "per-op-host-loop", path, ln,
                "for-loop over .ops inside the pack/segment ingest "
                "path — the packer is columnar (per-op Python "
                "measured host_pack_s=278.2 vs ~70 s device at the "
                "4096x shape); keep Op objects an API-edge view and "
                "work on the struct-of-arrays columns"))

    if "nemesis" in base:
        for ln, val in info.nemesis_bad_type:
            raw.append(Finding(
                "nemesis-info-completion", path, ln,
                f"nemesis completion typed {val!r} — nemesis ops must "
                "stay :info (PassThrough client) or history.complete "
                "rejects the history"))

    if not apply_suppressions:
        return raw
    return [f for f in raw if not suppressed(lines, f.line, f.rule)]


def lint_files(paths) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        out += lint_file(p)
    return out


from . import Pass, register_pass

register_pass(Pass(
    name="lint",
    scan_paths=lint_files,
    raw_file=lambda path, source: lint_file(
        path, source, apply_suppressions=False),
))
