"""Incremental columnar ingest — the delta form of the pack path.

A streaming session receives a live history as append-only op deltas;
this module grows the same struct-of-arrays columns the one-shot
packer (:mod:`comdb2_tpu.ops.columnar`) produces, delta by delta, and
never re-touches a row twice. Two invariants carry the whole design:

- **Settled rows are final.** ``history.complete`` back-fills an
  invocation's value (and ``fails`` bit) from its completion, which
  may arrive in a LATER delta — so a row only *settles* (gets its
  value/transition interned and becomes visible to segmentation) once
  every invoke at or before it is *resolved* (its completion arrived,
  or an ``:info`` row retired its process, pinning the invoked value
  forever). The settled prefix therefore grows monotonically behind a
  watermark (the earliest unresolved invoke), and everything emitted
  for the device is bit-identical to what the one-shot pack of the
  full history would have produced for those rows.
- **Intern order is row order.** process/f ids intern at arrival
  (arrival order == row order), value/transition ids intern at
  settlement in row order — exactly the first-occurrence order of the
  one-shot packer, so id tables are PREFIXES of the one-shot tables
  and every engine key layout agrees with a post-hoc re-check.

The arrival pass touches each Op object once (the API edge, same as
``pack_history_columnar``); pairing, double-pending validation and
back-fill bookkeeping ride the shared per-process chain machinery
(``ops.columnar._per_process_prev``) with the open-call state carried
across deltas. No ``.ops`` loops — the ``per-op-host-loop`` rule
covers this module.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..ops.columnar import _per_process_prev
from ..ops.op import FAIL, INFO, INVOKE, OK, TYPE_CODES, Op


class _Grow:
    """Capacity-doubling 1-D numpy buffer (amortized O(1) append —
    ``np.append`` per delta would make a long session O(n^2))."""

    __slots__ = ("_buf", "n")

    def __init__(self, dtype, cap: int = 64):
        self._buf = np.zeros(cap, dtype)
        self.n = 0

    def extend(self, arr) -> None:
        arr = np.asarray(arr)
        need = self.n + arr.shape[0]
        if need > self._buf.shape[0]:
            cap = self._buf.shape[0]
            while cap < need:
                cap *= 2
            nb = np.zeros(cap, self._buf.dtype)
            nb[:self.n] = self._buf[:self.n]
            self._buf = nb
        self._buf[self.n:need] = arr
        self.n = need

    @property
    def a(self) -> np.ndarray:
        """The live view (length ``n``)."""
        return self._buf[:self.n]

    def __len__(self) -> int:
        return self.n


class MalformedDelta(ValueError):
    """A delta violates the per-process invoke/complete discipline —
    the session's analog of ``history.complete``'s RuntimeErrors; the
    service answers ``unknown`` with a ``malformed:`` cause."""


class StreamIngest:
    """See module docstring. Drives: ``append(ops)`` ingests one delta
    and returns the newly settled row range ``(lo, hi)``;
    ``finalize()`` force-resolves the remaining open invokes (end of
    stream: their values stay as invoked, exactly like a one-shot pack
    of the full history) and settles the tail."""

    def __init__(self) -> None:
        self._proc_ids: Dict = {}
        self.process_table: List = []
        self._f_ids: Dict = {}
        self.f_table: List = []
        self._val_ids: Dict = {}
        self.value_table: List = []
        self._tr_ids: Dict = {}
        self.transition_table: List[tuple] = []
        # arrival columns (full history)
        self.type = _Grow(np.int8)
        self.proc = _Grow(np.int32)
        self.f = _Grow(np.int32)
        self.raw_values: List = []      # back-filled in place pre-settle
        self.fails = _Grow(np.bool_)
        self.time = _Grow(np.int64)
        self.pair = _Grow(np.int32)
        # settled columns (prefix)
        self.value = _Grow(np.int32)
        self.trans = _Grow(np.int32)
        self.settled = 0
        #: non-failing invokes among settled rows — the memo depth bound
        self.n_invokes_settled = 0
        # per-process open-call state: proc_id -> open invoke row
        self._open_row: Dict[int, int] = {}
        #: open invokes whose completion has NOT arrived (the watermark
        #: blockers); an :info retirement resolves without closing
        self._unresolved: Dict[int, int] = {}
        self.finalized = False

    def __len__(self) -> int:
        return self.type.n

    # -- arrival -------------------------------------------------------

    def _intern(self, ids: dict, table: list, column) -> np.ndarray:
        codes = np.empty(len(column), np.int32)
        get = ids.get
        for i, x in enumerate(column):
            j = get(x)
            if j is None:
                j = len(table)
                ids[x] = j
                table.append(x)
            codes[i] = j
        return codes

    def append(self, ops: List[Op]):
        """Ingest one delta; returns the newly settled ``(lo, hi)`` row
        range (``lo == hi`` when the watermark did not move). Raises
        :class:`MalformedDelta` on discipline violations."""
        if self.finalized:
            raise MalformedDelta("session already finalized")
        n0 = len(self)
        n = len(ops)
        if n == 0:
            return self._settle()
        # the API-edge pass: Op objects -> parallel columns (the only
        # per-op touch, same shape as pack_history_columnar's)
        procs = [op.process for op in ops]
        fs = [op.f for op in ops]
        vals = [op.value for op in ops]
        tcodes = np.fromiter((TYPE_CODES[op.type] for op in ops),
                             np.int8, n)
        fails = np.fromiter((op.fails for op in ops), np.bool_, n)
        times = np.fromiter((-1 if op.time is None else op.time
                             for op in ops), np.int64, n)
        # process/f interning happens before validation (the chain
        # machinery needs the codes) — snapshot so a raise can roll
        # the tables back and keep the leave-unchanged-on-raise
        # contract exact (a phantom entry would shift every later id
        # off the one-shot tables)
        n_proc0, n_f0 = len(self.process_table), len(self.f_table)
        pcodes = self._intern(self._proc_ids, self.process_table, procs)
        fcodes = self._intern(self._f_ids, self.f_table, fs)

        def _reject(msg: str):
            for x in self.process_table[n_proc0:]:
                del self._proc_ids[x]
            del self.process_table[n_proc0:]
            for x in self.f_table[n_f0:]:
                del self._f_ids[x]
            del self.f_table[n_f0:]
            raise MalformedDelta(msg)

        is_inv = tcodes == INVOKE
        is_ok = tcodes == OK
        is_fail = tcodes == FAIL
        sel_idx = np.flatnonzero(is_inv | is_ok | is_fail)
        srt, inv_flag, prev_inv, prev_row = _per_process_prev(
            pcodes, sel_idx, is_inv)
        # chain the delta's per-process event chains onto the carried
        # open-call state: the first selected event of a process in
        # this delta continues whatever the previous deltas left open
        first = prev_row < 0
        open0 = np.fromiter(
            (self._open_row.get(int(p), -1) for p in pcodes[srt]),
            np.int64, srt.size) if srt.size else np.empty(0, np.int64)
        prev_row_g = np.where(first, open0, prev_row + n0)
        prev_inv_g = np.where(first, open0 >= 0, prev_inv)
        dbl = inv_flag & prev_inv_g
        if dbl.any():
            i = int(srt[dbl].min())
            _reject(
                f"process {procs[i]!r} invokes at row {n0 + i} while "
                "an earlier invocation is still pending")
        orphan = ~inv_flag & ~prev_inv_g
        if orphan.any():
            i = int(srt[orphan].min())
            _reject(f"{ops[i].type} without invocation: {ops[i]}")

        # pairing + back-fill (global row ids; completions may pair
        # with invokes from earlier deltas)
        comp = ~inv_flag & prev_inv_g
        crow = srt[comp] + n0
        irow = prev_row_g[comp]
        # validate the fail-pair value reconciliation BEFORE any
        # column mutates (like the dbl/orphan checks above): a raise
        # here must leave the ingest exactly as it was — StreamIngest
        # is public API and a half-applied delta would corrupt every
        # later settled_slice/packed_history
        def _val(row: int):
            return (vals[row - n0] if row >= n0
                    else self.raw_values[row])

        for c, i in zip(crow.tolist(), irow.tolist()):
            if is_fail[c - n0]:
                iv, fv = _val(i), _val(c)
                if iv is not None and fv is not None and iv != fv:
                    _reject(
                        f"invocation value {iv!r} and failure value "
                        f"{fv!r} don't match at row {c}")
        pair = np.full(n, -1, np.int32)
        pair[crow - n0] = irow
        self.raw_values.extend(vals)
        local_inv = irow >= n0
        pair[irow[local_inv] - n0] = crow[local_inv]
        self.type.extend(tcodes)
        self.proc.extend(pcodes)
        self.f.extend(fcodes)
        self.fails.extend(fails)
        self.time.extend(times)
        self.pair.extend(pair)
        for i, c in zip(irow[~local_inv].tolist(),
                        (crow[~local_inv]).tolist()):
            self.pair.a[i] = c
        ok_pairs = is_ok[crow - n0]
        rv = self.raw_values
        for c, i in zip(crow[ok_pairs].tolist(),
                        irow[ok_pairs].tolist()):
            rv[i] = rv[c]                   # the ok's value wins
        fa = self.fails.a
        for c, i in zip(crow[~ok_pairs].tolist(),
                        irow[~ok_pairs].tolist()):
            iv, fv = rv[i], rv[c]       # mismatch pre-validated above
            v = iv if iv is not None else fv
            rv[i] = v
            rv[c] = v
            fa[i] = True
            fa[c] = True

        # open-call / resolution state updates, per process touched:
        # the LAST selected event decides open-ness (group tails of the
        # stable per-process sort)
        if srt.size:
            psort = pcodes[srt]
            tail = np.empty(srt.size, bool)
            tail[:-1] = psort[1:] != psort[:-1]
            tail[-1] = True
            for j in np.flatnonzero(tail).tolist():
                p = int(psort[j])
                row = int(srt[j])
                if inv_flag[j]:
                    self._open_row[p] = n0 + row
                    self._unresolved[p] = n0 + row
                else:
                    self._open_row.pop(p, None)
                    self._unresolved.pop(p, None)
            # a completion mid-delta resolves even when a LATER invoke
            # of the same process re-opens: drop stale unresolved rows
            # (only the tail invoke can be unresolved)
        # :info rows retire their process: the open invoke stays open
        # forever (it pins a slot) but its value is final — resolved.
        # Row order matters: an invoke AFTER the info row (one-shot
        # complete() allows it — info never touches inflight) is NOT
        # retired by it and must keep blocking the watermark until
        # its own completion back-fills its value.
        for i in np.flatnonzero(tcodes == INFO).tolist():
            p = int(pcodes[i])
            r = self._unresolved.get(p)
            if r is not None and r < n0 + i:
                self._unresolved.pop(p)
        return self._settle()

    def finalize(self):
        """End of stream: every open invoke keeps its invoked value
        (one-shot parity — ``complete`` leaves them pending), the tail
        settles, further appends are rejected."""
        self._unresolved.clear()
        self.finalized = True
        return self._settle()

    # -- settlement ----------------------------------------------------

    def _settle(self):
        lo = self.settled
        hi = min(self._unresolved.values(), default=len(self))
        if hi <= lo:
            return lo, lo
        # value interning in row order over the settled slice (the
        # back-filled values are final here — the watermark guarantees
        # every invoke in the slice is resolved)
        vals = self.raw_values[lo:hi]
        vcodes = self._intern(self._val_ids, self.value_table, vals)
        self.value.extend(vcodes)
        t = self.type.a[lo:hi]
        fl = self.fails.a[lo:hi]
        vinv = np.flatnonzero((t == INVOKE) & ~fl)
        trans = np.full(hi - lo, -1, np.int32)
        if vinv.size:
            fc = self.f.a[lo:hi][vinv]
            tr_ids = self._tr_ids
            table = self.transition_table
            codes = np.empty(vinv.size, np.int32)
            for j, key in enumerate(zip(fc.tolist(),
                                        vcodes[vinv].tolist())):
                c = tr_ids.get(key)
                if c is None:
                    c = len(table)
                    tr_ids[key] = c
                    table.append(key)
                codes[j] = c
            trans[vinv] = codes
        self.trans.extend(trans)
        self.n_invokes_settled += int(vinv.size)
        self.settled = hi
        return lo, hi

    # -- checkpoint / restore (docs/streaming.md "Checkpoint") ---------

    #: the _Grow columns a checkpoint snapshots, in restore order
    _COLS = ("type", "proc", "f", "fails", "time", "pair", "value",
             "trans")

    def checkpoint(self) -> dict:
        """Host snapshot of the ingest: id tables, columns, watermark
        and open-call state. The id-lookup dicts are NOT stored — they
        are pure functions of the tables and rebuild on restore."""
        return {
            "process_table": list(self.process_table),
            "f_table": list(self.f_table),
            "value_table": list(self.value_table),
            "transition_table": [tuple(t)
                                 for t in self.transition_table],
            "cols": {c: getattr(self, c).a.copy() for c in self._COLS},
            "raw_values": list(self.raw_values),
            "settled": int(self.settled),
            "n_invokes_settled": int(self.n_invokes_settled),
            "open_row": {int(k): int(v)
                         for k, v in self._open_row.items()},
            "unresolved": {int(k): int(v)
                           for k, v in self._unresolved.items()},
            "finalized": bool(self.finalized),
        }

    @classmethod
    def restore(cls, ck: dict) -> "StreamIngest":
        ing = cls()
        ing.process_table = list(ck["process_table"])
        ing._proc_ids = {x: i for i, x in
                         enumerate(ing.process_table)}
        ing.f_table = list(ck["f_table"])
        ing._f_ids = {x: i for i, x in enumerate(ing.f_table)}
        ing.value_table = list(ck["value_table"])
        ing._val_ids = {x: i for i, x in enumerate(ing.value_table)}
        ing.transition_table = [tuple(t)
                                for t in ck["transition_table"]]
        ing._tr_ids = {t: i for i, t in
                       enumerate(ing.transition_table)}
        for c in cls._COLS:
            col = getattr(ing, c)
            col.extend(np.asarray(ck["cols"][c], col._buf.dtype))
        ing.raw_values = list(ck["raw_values"])
        ing.settled = int(ck["settled"])
        ing.n_invokes_settled = int(ck["n_invokes_settled"])
        ing._open_row = {int(k): int(v)
                         for k, v in ck["open_row"].items()}
        ing._unresolved = {int(k): int(v)
                           for k, v in ck["unresolved"].items()}
        ing.finalized = bool(ck["finalized"])
        return ing

    # -- API edges -----------------------------------------------------

    def settled_slice(self, lo: int, hi: int):
        """(type, proc, trans, fails, pair) columns of a settled row
        range — the segmenter's input."""
        return (self.type.a[lo:hi], self.proc.a[lo:hi],
                self.trans.a[lo:hi], self.fails.a[lo:hi],
                self.pair.a[lo:hi])

    def transitions_of(self, lo: int, hi: int) -> List[tuple]:
        """(f, value) pairs of transition ids ``lo..hi`` (the memo
        extension's input, in interning order)."""
        return [(self.f_table[fi], self.value_table[vi])
                for fi, vi in self.transition_table[lo:hi]]

    def packed_history(self, end: Optional[int] = None):
        """A :class:`~comdb2_tpu.ops.packed.PackedHistory` view of the
        settled prefix (counterexample decode, failover replay — the
        retained columnar tables). Pairs pointing past the cut are
        open calls there and report -1."""
        from ..ops.packed import PackedHistory

        end = self.settled if end is None else min(end, self.settled)
        pair = self.pair.a[:end].copy()
        pair[pair >= end] = -1
        return PackedHistory(
            process=self.proc.a[:end].copy(),
            type=self.type.a[:end].copy(),
            f=self.f.a[:end].copy(),
            value=self.value.a[:end].copy(),
            trans=self.trans.a[:end].copy(),
            pair=pair,
            fails=self.fails.a[:end].copy(),
            time=self.time.a[:end].copy(),
            process_table=list(self.process_table),
            f_table=list(self.f_table),
            value_table=list(self.value_table),
            transition_table=list(self.transition_table))


__all__ = ["MalformedDelta", "StreamIngest"]
