"""Session table — ids, caps, TTL/idle eviction, carry accounting.

The service owns one :class:`SessionManager`; every verb resolves the
session id through it. Two production guards live here:

- ``max_sessions``: a carry is real device memory — the cap answers
  ``open`` with overload (+ ``retry_after_ms``) instead of silently
  OOMing the accelerator under a session flood.
- idle eviction: a session nobody appended to for ``idle_s`` releases
  its carry (the devices' analog of a KV-cache eviction); the client
  re-opens by replaying its retained deltas (session affinity +
  failover replay, docs/streaming.md).
"""

from __future__ import annotations

import itertools
import os
from typing import Dict, List, Optional, Tuple

from ..obs import trace as _obs
from .session import StreamSession


class SessionLimit(Exception):
    """``max_sessions`` reached — the service maps this to an
    overload reply with a ``retry_after_ms`` hint."""


class SessionManager:
    """See module docstring. All times are ``obs.trace.monotonic``
    floats passed in by the caller (the daemon owns the clock)."""

    def __init__(self, max_sessions: int = 64,
                 idle_s: float = 300.0):
        self.max_sessions = int(max_sessions)
        self.idle_s = float(idle_s)
        self._sessions: Dict[str, StreamSession] = {}
        self._touched: Dict[str, float] = {}
        self._seq = itertools.count()
        self.evictions = 0
        self.opened = 0

    def __len__(self) -> int:
        return len(self._sessions)

    def open(self, now: float, model: str = "cas-register",
             engine: str = "auto",
             max_states: int = 1 << 20) -> Tuple[str, StreamSession]:
        if len(self._sessions) >= self.max_sessions:
            raise SessionLimit(
                f"session table at cap ({self.max_sessions})")
        sid = f"s{next(self._seq)}-{os.urandom(3).hex()}"
        s = StreamSession(model=model, engine=engine,
                          max_states=max_states)
        self._sessions[sid] = s
        self._touched[sid] = now
        self.opened += 1
        return sid, s

    def get(self, sid, now: Optional[float] = None
            ) -> Optional[StreamSession]:
        s = self._sessions.get(sid)
        if s is not None and now is not None:
            self._touched[sid] = now
        return s

    def close(self, sid) -> Optional[dict]:
        s = self._sessions.pop(sid, None)
        self._touched.pop(sid, None)
        if s is None:
            return None
        return s.close()

    def evict_idle(self, now: float) -> List[str]:
        """Release every session idle past the TTL (carry freed; the
        session object dies — re-open replays client-side)."""
        out = []
        for sid, t in list(self._touched.items()):
            if now - t >= self.idle_s:
                s = self._sessions.pop(sid, None)
                self._touched.pop(sid, None)
                if s is not None:
                    s.release()         # forces any in-flight staged
                    out.append(sid)     # append through finalize
                    self.evictions += 1
                    _obs.record("stream.evict", now, now, sid=sid)
        return out

    def carry_bytes(self) -> int:
        return sum(s.carry_nbytes()
                   for s in self._sessions.values())


__all__ = ["SessionLimit", "SessionManager"]
