"""Session table — ids, caps, checkpoint eviction, carry accounting.

The service owns one :class:`SessionManager`; every verb resolves the
session id through it. Production guards:

- ``max_sessions``: a carry is real device memory — the cap answers
  ``open`` with overload (+ ``retry_after_ms``) instead of silently
  OOMing the accelerator under a session flood.
- idle eviction is **checkpoint-not-replay** (round 12): a session
  nobody appended to for ``idle_s`` snapshots to a host-numpy
  checkpoint (:meth:`~.session.StreamSession.checkpoint`) and
  releases its device carry; the next verb naming the id restores it
  transparently — the devices' analog of paging a KV-cache out to
  host, no client replay, no re-dispatch. Checkpoints are bounded
  (``max_checkpoints``, FIFO) — one aged fully out still falls back
  to the client's retained-delta replay (docs/streaming.md
  "Failover").
- migration: :meth:`checkpoint` (with ``release=True``) hands a
  session's snapshot out for a drain/leave handoff and
  :meth:`open_restored` accepts one on the new ring owner —
  O(carry) over the wire, zero device replay.
"""

from __future__ import annotations

import itertools
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..obs import trace as _obs
from . import wl as _wl
from .session import StreamSession


def _restore(ck: dict):
    """Checkpoint router: wl-family checkpoints carry the
    ``wl_family`` discriminator; everything else is a frontier
    session's."""
    if ck.get("wl_family"):
        return _wl.restore_session(ck)
    return StreamSession.restore(ck)


class SessionLimit(Exception):
    """``max_sessions`` reached — the service maps this to an
    overload reply with a ``retry_after_ms`` hint."""


class SessionManager:
    """See module docstring. All times are ``obs.trace.monotonic``
    floats passed in by the caller (the daemon owns the clock)."""

    def __init__(self, max_sessions: int = 64,
                 idle_s: float = 300.0,
                 max_checkpoints: int = 256):
        self.max_sessions = int(max_sessions)
        self.idle_s = float(idle_s)
        self.max_checkpoints = int(max_checkpoints)
        self._sessions: Dict[str, StreamSession] = {}
        self._touched: Dict[str, float] = {}
        #: evicted sessions' host checkpoints, FIFO-bounded
        self._checkpoints: "OrderedDict[str, dict]" = OrderedDict()
        self._seq = itertools.count()
        self.evictions = 0
        self.restores = 0
        self.opened = 0

    def __len__(self) -> int:
        return len(self._sessions)

    def open(self, now: float, model: str = "cas-register",
             engine: str = "auto", max_states: int = 1 << 20,
             wl: Optional[dict] = None) -> Tuple[str, StreamSession]:
        if len(self._sessions) >= self.max_sessions:
            raise SessionLimit(
                f"session table at cap ({self.max_sessions})")
        sid = self._new_sid()
        if model in _wl.WL_MODELS:
            # workload-family session (stream/wl.py): same table,
            # caps, eviction and checkpoint discipline
            s = _wl.make_session(model, wl)
        else:
            s = StreamSession(model=model, engine=engine,
                              max_states=max_states)
        self._sessions[sid] = s
        self._touched[sid] = now
        self.opened += 1
        return sid, s

    def open_restored(self, now: float,
                      ck: dict) -> Tuple[str, StreamSession]:
        """Admit a migrated session from its checkpoint (the
        open-with-checkpoint handoff). Same cap as :meth:`open` — a
        shed migration surfaces as overload and the client falls back
        to retained-delta replay elsewhere."""
        if len(self._sessions) >= self.max_sessions:
            raise SessionLimit(
                f"session table at cap ({self.max_sessions})")
        s = _restore(ck)
        sid = self._new_sid()
        self._sessions[sid] = s
        self._touched[sid] = now
        self.opened += 1
        return sid, s

    def _new_sid(self) -> str:
        return f"s{next(self._seq)}-{os.urandom(3).hex()}"

    def get(self, sid, now: Optional[float] = None
            ) -> Optional[StreamSession]:
        s = self._sessions.get(sid)
        if s is None and sid in self._checkpoints:
            # checkpoint eviction's other half: restore transparently.
            # Deliberately allowed to run the table transiently past
            # max_sessions — the cap gates NEW carries (opens); a
            # restore re-admits state a client already owns, and
            # bouncing it would only trade a cheap upload for a full
            # client replay.
            ck = self._checkpoints.pop(sid)
            s = _restore(ck)
            self._sessions[sid] = s
            self.restores += 1
            if now is not None:
                _obs.record("stream.restore", now, now, sid=sid)
        if s is not None and now is not None:
            self._touched[sid] = now
        return s

    def close(self, sid) -> Optional[dict]:
        # a checkpointed session still closes cleanly: restore (via
        # get) settles nothing by itself; close() then runs the final
        # tail settle against the restored carry
        s = self.get(sid)
        self._sessions.pop(sid, None)
        self._touched.pop(sid, None)
        if s is None:
            return None
        return s.close()

    def checkpoint(self, sid) -> Optional[dict]:
        """Snapshot one session (the migration handoff's read half).
        The caller :meth:`drop`s it AFTER the snapshot is safely
        encoded/delivered — a handoff MOVES the session (both daemons
        serving it would double-serve its appends), but releasing
        before the checkpoint provably left this process would LOSE
        it on an encode failure."""
        ck = self._checkpoints.get(sid)
        if ck is not None:
            # idle-evicted: the held host snapshot IS the requested
            # artifact. Restoring just to re-snapshot would replay
            # the memo extend log (and, kernel rung, a device
            # re-route) on the single-threaded drain path — and
            # migration-during-drain is exactly when sessions sit
            # evicted. The caller's drop() discards this entry on
            # release like any resident session.
            return ck
        s = self.get(sid)
        if s is None:
            return None
        return s.checkpoint()

    def drop(self, sid) -> None:
        """Remove a session and free its carry WITHOUT the final tail
        settle (the handoff's release half; also discards any held
        checkpoint under the same id)."""
        s = self._sessions.pop(sid, None)
        self._touched.pop(sid, None)
        self._checkpoints.pop(sid, None)
        if s is not None:
            s.release()

    def evict_idle(self, now: float) -> List[str]:
        """Checkpoint-and-release every session idle past the TTL
        (device carry freed; the host checkpoint keeps the session
        resumable with zero replay)."""
        out = []
        for sid, t in list(self._touched.items()):
            if now - t >= self.idle_s:
                s = self._sessions.pop(sid, None)
                self._touched.pop(sid, None)
                if s is not None:
                    # the snapshot itself forces any in-flight staged
                    # append through its (idempotent) finalize — a
                    # ring-resident dispatch never reads a released
                    # engine
                    self._checkpoints[sid] = s.checkpoint()
                    while len(self._checkpoints) > self.max_checkpoints:
                        self._checkpoints.popitem(last=False)
                    s.release()
                    out.append(sid)
                    self.evictions += 1
                    _obs.record("stream.evict", now, now, sid=sid)
        return out

    def carry_bytes(self) -> int:
        """DEVICE bytes held by resident carries (checkpointed
        sessions hold host memory only — see
        :meth:`checkpoint_count`)."""
        return sum(s.carry_nbytes()
                   for s in self._sessions.values())

    def checkpoint_count(self) -> int:
        return len(self._checkpoints)


__all__ = ["SessionLimit", "SessionManager"]
