"""Workload-family stream rungs — live bank / sets sessions.

The non-frontier siblings of :class:`~.session.StreamSession`
(docs/streaming.md "Workload sessions"). A wl session owns a
DEVICE-RESIDENT carry — bank: the (A,) running balance; sets: the
three (E,) membership planes — and each append dispatches ONLY its
delta (``wl_bank_delta`` / ``wl_sets_delta``), so per-append device
work is O(delta) regardless of history length. Deltas join the
service beat's :class:`~.engine.MegaBatch` under ``("wl-bank",
a_pad)`` / ``("wl-sets", e_pad)`` fuse keys; the fused forms vmap the
SAME per-lane body, so a megabatched advance is bit-identical to the
solo one.

Verdict discipline:

- bank LATCHES INVALID immediately — a wrong-total / wrong-n read
  stays wrong under every extension. The snapshot plane stays
  diagnostic (and is windowed per delta: reads match snapshots
  reachable within their append, counting from the carry).
- sets latches only malformed deltas (UNKNOWN) mid-stream: the final
  read is last-read-wins, so ``lost``/``unexpected`` are PROVISIONAL
  until close. The terminal verdict lands at close and matches a
  one-shot ``check_wl_batch`` of the full history.

Checkpoint/restore is host numpy only (rule
``host-numpy-checkpoint``); restoring resumes with the same carry
bits and interning table, so eviction and migration cost zero device
replay. Sets escalate the element rung IN PLACE up ``WL_ELEMS``
(host readback + pad, re-upload on the next dispatch); past the top
rung the session answers terminal UNKNOWN — no open-ended program
may compile. This module deliberately never imports jax: carries
pass into the family jits as-is (numpy before the first dispatch,
device arrays after), and array building stays host-side.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..checker.wl import bank as _WLB
from ..checker.wl import sets as _WLS
from ..checker.wl.batch import (WL_ACCOUNTS, WL_DELTA_PADS, WL_ELEMS,
                                bucket_of)
from ..obs import trace as _obs
from . import engine as _ENG
from .ingest import MalformedDelta

#: the stream-served wl models. Dirty-reads stays post-hoc only: its
#: verdict joins reads against the FULL failed-write set, so there is
#: no O(delta) carry for it — ``check_wl_batch`` serves it.
WL_MODELS = ("wl-bank", "wl-sets")


class WlLadderOverflow(Exception):
    """A session axis grew past its ladder top — the session answers
    terminal UNKNOWN instead of compiling an open-ended program."""


def make_session(model: str, params: Optional[dict] = None):
    """Session factory for :class:`~.manager.SessionManager`.
    ``params`` is the open request's ``wl`` map (the bank model)."""
    if model == "wl-bank":
        p = dict(params or {})
        if "n" not in p or "total" not in p:
            raise ValueError("wl-bank needs {'n': .., 'total': ..}")
        return WlBankSession(p)
    if model == "wl-sets":
        return WlSetsSession()
    raise ValueError(f"unknown wl model {model!r}")


def restore_session(ck: dict):
    """Checkpoint router (the ``wl_family`` discriminator)."""
    fam = ck.get("wl_family")
    if fam == "bank":
        return WlBankSession.restore(ck)
    if fam == "sets":
        return WlSetsSession.restore(ck)
    raise ValueError(f"unknown wl_family {fam!r}")


class _WlLane:
    """One wl session's staged delta inside a forming megabatch (the
    wl analog of ``engine._Lane`` — exposes ``.sess`` so the flush
    failure latch covers wl lanes too)."""

    __slots__ = ("sess", "delta", "out")

    def __init__(self, sess, delta):
        self.sess = sess
        self.delta = delta
        self.out = None


class _WlSessionBase:
    """The session protocol the service dispatch/manager paths are
    generic over — mirrors :class:`~.session.StreamSession`'s
    surface: ``append_stage(ops, collector=)`` returning an
    idempotent finalize, poll/close/checkpoint/restore/release,
    ``dispatches``/``appends`` counters, and the latch."""

    family = "?"
    keyed = False

    def __init__(self):
        self.valid = True
        self.cause = None
        self.fail_index = -1
        self.appends = 0
        self.dispatches = 0      # programs this session's deltas rode
        self.op_count = 0
        self.closed = False
        self._inflight = None

    @property
    def model_name(self) -> str:
        return f"wl-{self.family}"

    def _latched(self) -> bool:
        return self.valid is not True

    def _latch_unknown(self, cause: str) -> None:
        # guarded (unlike StreamSession's): a group flush failure must
        # never downgrade an already-latched INVALID to unknown
        if self.valid is True:
            self.valid = "unknown"
            self.cause = cause

    # -- append / finalize ---------------------------------------------

    def append(self, ops) -> dict:
        fin = self.append_stage(ops)
        return fin()

    def append_stage(self, ops, collector=None):
        """Stage one delta and return a zero-arg idempotent finalize
        producing the verdict map. With ``collector`` the delta parks
        as a megabatch lane (carry advances at flush, device-only);
        the finalize flushes first and then ABSORBS the lane's
        readback flags — all host↔device readback is deferred there.
        Appends to one session serialize (staging forces the previous
        finalize), so a session holds at most one lane per beat."""
        if self._inflight is not None:
            self._inflight()
        if self.closed:
            out = self._verdict_map()
            out["cause"] = "session closed"
            return lambda: out
        self.appends += 1
        if self._latched():
            out = self._verdict_map()
            out["latched"] = True
            return lambda: out
        try:
            deltas = self._encode_delta(list(ops))
        except MalformedDelta as e:
            self._latch_unknown(f"malformed: {e}")
            return lambda: self._verdict_map()
        except WlLadderOverflow as e:
            self._latch_unknown(str(e))
            return lambda: self._verdict_map()
        if not deltas:
            # nothing checkable in the delta — a legitimate
            # 0-dispatch beat, same as a watermark-held append
            return lambda: self._verdict_map()
        lanes = [_WlLane(self, d) for d in deltas]
        key = self._fuse_key()
        if collector is not None and len(lanes) == 1:
            collector.add_wl(key, lanes[0])
        else:
            # oversized appends chunk: each chunk's carry feeds the
            # next, so they launch sequentially solo inside the beat
            # (the same out-of-band rule as oversized frontier deltas)
            for ln in lanes:
                launch_wl_group(None, key, [ln])
        done = {}

        def fin():
            if "out" in done:
                return done["out"]
            if collector is not None \
                    and any(ln.out is None for ln in lanes):
                collector.flush()
            self._inflight = None
            if not self._latched():
                for ln in lanes:
                    if ln.out is None:       # flush died before us
                        self._latch_unknown(
                            "megabatch lane never launched")
                        break
                    self._absorb(ln)
            done["out"] = self._verdict_map()
            return done["out"]

        self._inflight = fin
        return fin

    def poll(self) -> dict:
        if self._inflight is not None:
            self._inflight()
        return self._verdict_map()

    def finalize_input(self) -> dict:
        if self._inflight is not None:
            self._inflight()
        if not self.closed and not self._latched():
            self._settle_final()
        return self._verdict_map()

    def close(self) -> dict:
        """Final verdict + carry release. The release rides
        ``finally`` (rule ``release-in-finally``): a settle that
        raises must still free the carry."""
        try:
            out = self.finalize_input()
        finally:
            self.release()
        return out

    def release(self) -> None:
        if self._inflight is not None:
            self._inflight()
        self._drop_carry()
        self.closed = True

    # -- verdict -------------------------------------------------------

    def _verdict_map(self) -> dict:
        out = {
            "valid": self.valid,
            "op_index": self.fail_index,
            "op_count": self.op_count,
            # wl deltas settle at dispatch — no invoke watermark
            "checked_through": self.op_count,
            "engine": self.model_name,
            "family": self.family,
            "dispatches": self.dispatches,
            "appends": self.appends,
        }
        if self.cause:
            out["cause"] = self.cause
        out.update(self._family_fields())
        return out


class WlBankSession(_WlSessionBase):
    """Live bank: the carry is the (A,) running balance. INVALID
    latches immediately; the snapshot-inconsistency plane stays
    diagnostic (and windowed to each append — see module
    docstring)."""

    family = "bank"

    def __init__(self, model: dict):
        super().__init__()
        self.n = int(model["n"])
        self.total = int(model["total"])
        if self.n < 1:
            raise ValueError("bank model needs n >= 1 accounts")
        if abs(self.total) >= 1 << 30:
            raise ValueError("bank totals must fit int32 (no x64)")
        self.a_pad = bucket_of(self.n, WL_ACCOUNTS)
        if self.a_pad is None:
            raise ValueError(
                f"bank n {self.n} exceeds the WL_ACCOUNTS ladder")
        init = _WLB.default_init({"n": self.n, "total": self.total,
                                  **({"init": model["init"]}
                                     if "init" in model else {})})
        bal = np.zeros(self.a_pad, np.int32)
        bal[:self.n] = init
        self._balance = bal         # numpy until the first dispatch
        self.bad_reads = 0
        self.snap_inconsistent = 0

    @property
    def shape_class(self) -> str:
        return f"wl-bank-a{self.a_pad}"

    def _fuse_key(self):
        return ("wl-bank", self.a_pad)

    def _encode_delta(self, ops) -> List[dict]:
        """Host encode into (reads, transfers) row lists, chunked at
        the ``WL_DELTA_PADS`` top so no open-ended program compiles;
        arrival order is preserved across chunk cuts."""
        top = WL_DELTA_PADS[-1]
        deltas: List[dict] = []
        r_rows: list = []
        t_rows: list = []

        def cut():
            if r_rows or t_rows:
                deltas.append({"reads": list(r_rows),
                               "transfers": list(t_rows)})
                r_rows.clear()
                t_rows.clear()

        for op in ops:
            idx = self.op_count if op.index is None else op.index
            self.op_count += 1
            if op.type != "ok" or op.value is None:
                continue
            if op.f == "read":
                v = op.value
                if isinstance(v, (str, bytes)) \
                        or not isinstance(v, (list, tuple)):
                    raise MalformedDelta(
                        f"bank read value must be a balance row, "
                        f"got {type(v).__name__} (op {idx})")
                row = [int(x) for x in v]
                if any(abs(x) >= 1 << 30 for x in row):
                    raise MalformedDelta(
                        f"bank balance overflows int32 (op {idx})")
                r_rows.append((row, idx))
                if len(r_rows) >= top:
                    cut()
            elif op.f == "transfer":
                try:
                    frm, to, amt = op.value
                    frm, to, amt = int(frm), int(to), int(amt)
                except (TypeError, ValueError):
                    raise MalformedDelta(
                        f"bank transfer value must be "
                        f"(from, to, amount) (op {idx})")
                if not (0 <= frm < self.n and 0 <= to < self.n):
                    raise MalformedDelta(
                        f"bank transfer names an unknown account "
                        f"(op {idx})")
                d = np.zeros(self.a_pad, np.int32)
                d[frm] -= amt
                d[to] += amt
                t_rows.append(d)
                if len(t_rows) >= top:
                    cut()
        cut()
        return deltas

    def _absorb(self, lane) -> None:
        # the carry already advanced at launch (device-only); here we
        # read back this delta's verdict flags — deferred-closure
        # territory, the one sanctioned sync-readback point
        _bal, any_bad, first_bad, n_bad, n_snap = lane.out
        self.snap_inconsistent += int(n_snap)
        if bool(any_bad):
            self.bad_reads += int(n_bad)
            if self.valid is True:
                self.valid = False
                row, idx = lane.delta["reads"][int(first_bad)]
                self.fail_index = idx
                self.cause = ("wrong-n read" if len(row) != self.n
                              else "wrong-total read")

    def _settle_final(self) -> None:
        pass                 # bank verdicts are already settled

    def _family_fields(self) -> dict:
        return {"bad_reads": self.bad_reads,
                "snapshot_inconsistent": self.snap_inconsistent}

    def _drop_carry(self) -> None:
        self._balance = None

    def carry_nbytes(self) -> int:
        b = self._balance
        if b is None or isinstance(b, np.ndarray):
            return 0         # not (or no longer) device-resident
        return int(b.nbytes)

    # -- checkpoint / restore (host numpy ONLY) ------------------------

    def checkpoint(self) -> dict:
        if self._inflight is not None:
            self._inflight()
        return {
            "v": 1,
            "wl_family": "bank",
            "model": {"n": self.n, "total": self.total},
            "a_pad": int(self.a_pad),
            "balance": (None if self._balance is None
                        else np.asarray(self._balance)),
            "appends": int(self.appends),
            "dispatches": int(self.dispatches),
            "op_count": int(self.op_count),
            "bad_reads": int(self.bad_reads),
            "snapshot_inconsistent": int(self.snap_inconsistent),
            "valid": self.valid,
            "cause": self.cause,
            "fail_index": int(self.fail_index),
            "closed": bool(self.closed),
        }

    @classmethod
    def restore(cls, ck: dict) -> "WlBankSession":
        s = cls(dict(ck["model"]))
        s.a_pad = int(ck["a_pad"])
        bal = ck["balance"]
        s._balance = (None if bal is None
                      else np.asarray(bal, np.int32))
        s.appends = int(ck["appends"])
        s.dispatches = int(ck["dispatches"])
        s.op_count = int(ck["op_count"])
        s.bad_reads = int(ck["bad_reads"])
        s.snap_inconsistent = int(ck["snapshot_inconsistent"])
        s.valid = ck["valid"]
        s.cause = ck["cause"]
        s.fail_index = int(ck["fail_index"])
        s.closed = bool(ck["closed"])
        return s


class WlSetsSession(_WlSessionBase):
    """Live sets: the carry is the three (E,) membership planes over
    a host first-occurrence interning table (exactly the one-shot
    encoder's id space). Only malformed deltas latch mid-stream;
    ``lost``/``unexpected`` are provisional until close."""

    family = "sets"

    def __init__(self):
        super().__init__()
        self.e_pad = WL_ELEMS[0]
        self._ids: dict = {}
        self._att = np.zeros(self.e_pad, bool)
        self._add = np.zeros(self.e_pad, bool)
        self._fr = np.zeros(self.e_pad, bool)
        self.has_read = False
        self.escalations = 0
        self._prov_valid = None    # last dispatch's valid-now flag
        self.lost = 0              # CURRENT totals vs the last read,
        self.unexpected = 0        # not cumulative

    @property
    def shape_class(self) -> str:
        return f"wl-sets-e{self.e_pad}"

    def _fuse_key(self):
        return ("wl-sets", self.e_pad)

    def _eid(self, v) -> int:
        from ..checker.workloads import freeze_value

        v = freeze_value(v)
        i = self._ids.get(v)
        if i is None:
            i = self._ids[v] = len(self._ids)
        return i

    def _escalate_to(self, e_pad: int) -> None:
        """In-place element-rung escalation: host readback + pad; the
        device re-upload rides the next dispatch. O(E), never
        O(history) — the planes ARE the full state."""
        for name in ("_att", "_add", "_fr"):
            plane = np.asarray(getattr(self, name))
            setattr(self, name,
                    np.pad(plane, (0, e_pad - plane.shape[0])))
        self.e_pad = e_pad
        self.escalations += 1

    def _encode_delta(self, ops) -> List[dict]:
        att_ids: list = []
        add_ids: list = []
        read_ids: list = []
        saw_read = False
        for op in ops:
            idx = self.op_count if op.index is None else op.index
            self.op_count += 1
            if op.value is None:
                continue
            if op.f == "add":
                if op.type == "invoke":
                    att_ids.append(self._eid(op.value))
                elif op.type == "ok":
                    i = self._eid(op.value)
                    att_ids.append(i)
                    add_ids.append(i)
            elif op.f == "read" and op.type == "ok":
                v = op.value
                if isinstance(v, (str, bytes)) or \
                        not isinstance(v, (list, tuple, set,
                                           frozenset)):
                    raise MalformedDelta(
                        f"set read value must be a collection, got "
                        f"{type(v).__name__} (op {idx})")
                saw_read = True
                read_ids = [self._eid(x) for x in v]
        if not att_ids and not add_ids and not saw_read:
            return []
        rung = bucket_of(max(len(self._ids), 1), WL_ELEMS)
        if rung is None:
            raise WlLadderOverflow(
                f"element universe exceeds the WL_ELEMS ladder "
                f"({len(self._ids)} > {WL_ELEMS[-1]})")
        if rung > self.e_pad:
            self._escalate_to(rung)
        e = self.e_pad
        att_d = np.zeros(e, bool)
        att_d[att_ids] = True
        add_d = np.zeros(e, bool)
        add_d[add_ids] = True
        read_d = np.zeros(e, bool)
        if saw_read:
            read_d[read_ids] = True
        return [{"att": att_d, "add": add_d, "read": read_d,
                 "has_read_d": saw_read}]

    def _absorb(self, lane) -> None:
        _att, _add, _fr, valid_now, n_lost, n_unexp = lane.out
        self.has_read = self.has_read or lane.delta["has_read_d"]
        self._prov_valid = bool(valid_now)
        self.lost = int(n_lost)
        self.unexpected = int(n_unexp)

    def _settle_final(self) -> None:
        if not self.has_read:
            self.valid = "unknown"
            self.cause = "Set was never read"
        elif self._prov_valid is False:
            self.valid = False
            self.cause = (f"lost={self.lost} "
                          f"unexpected={self.unexpected}")

    def _family_fields(self) -> dict:
        out = {"elements": len(self._ids),
               "e_pad": self.e_pad,
               "escalations": self.escalations,
               "has_read": self.has_read,
               "lost": self.lost,
               "unexpected": self.unexpected}
        if not self.closed and self.valid is True:
            out["provisional_valid"] = (self._prov_valid
                                        if self.has_read else None)
        return out

    def _drop_carry(self) -> None:
        self._att = self._add = self._fr = None

    def carry_nbytes(self) -> int:
        return sum(int(p.nbytes)
                   for p in (self._att, self._add, self._fr)
                   if p is not None and not isinstance(p, np.ndarray))

    # -- checkpoint / restore (host numpy ONLY) ------------------------

    def checkpoint(self) -> dict:
        if self._inflight is not None:
            self._inflight()
        return {
            "v": 1,
            "wl_family": "sets",
            "e_pad": int(self.e_pad),
            "table": list(self._ids),    # first-occurrence order
            "att": (None if self._att is None
                    else np.asarray(self._att)),
            "add": (None if self._add is None
                    else np.asarray(self._add)),
            "fr": (None if self._fr is None
                   else np.asarray(self._fr)),
            "has_read": bool(self.has_read),
            "escalations": int(self.escalations),
            "prov_valid": self._prov_valid,
            "lost": int(self.lost),
            "unexpected": int(self.unexpected),
            "appends": int(self.appends),
            "dispatches": int(self.dispatches),
            "op_count": int(self.op_count),
            "valid": self.valid,
            "cause": self.cause,
            "fail_index": int(self.fail_index),
            "closed": bool(self.closed),
        }

    @classmethod
    def restore(cls, ck: dict) -> "WlSetsSession":
        s = cls()
        s.e_pad = int(ck["e_pad"])
        s._ids = {v: i for i, v in enumerate(ck["table"])}
        for name, k in (("_att", "att"), ("_add", "add"),
                        ("_fr", "fr")):
            p = ck[k]
            setattr(s, name,
                    None if p is None else np.asarray(p, bool))
        s.has_read = bool(ck["has_read"])
        s.escalations = int(ck["escalations"])
        s._prov_valid = ck["prov_valid"]
        s.lost = int(ck["lost"])
        s.unexpected = int(ck["unexpected"])
        s.appends = int(ck["appends"])
        s.dispatches = int(ck["dispatches"])
        s.op_count = int(ck["op_count"])
        s.valid = ck["valid"]
        s.cause = ck["cause"]
        s.fail_index = int(ck["fail_index"])
        s.closed = bool(ck["closed"])
        return s


# -- launch forms (called by MegaBatch._launch_group) ------------------


def launch_wl_group(mb, key, lanes) -> None:
    """Launch one wl fuse-key group (``mb`` is the collecting
    MegaBatch; None for direct solo launches): chunks at the
    megabatch lane-ladder top, fusing >= 2 lanes into one vmapped
    program — the wl analog of ``MegaBatch._launch_delta``."""
    top = _ENG.MEGABATCH_LANES[-1]
    launch = _launch_bank if key[0] == "wl-bank" else _launch_sets
    for i in range(0, len(lanes), top):
        launch(mb, key, lanes[i:i + top])


def _bank_pads(delta):
    return (bucket_of(max(len(delta["reads"]), 1), WL_DELTA_PADS),
            bucket_of(max(len(delta["transfers"]), 1),
                      WL_DELTA_PADS))


def _bank_build(sess, delta, r_pad: int, t_pad: int):
    reads = np.zeros((r_pad, sess.a_pad), np.int32)
    read_mask = np.zeros(r_pad, bool)
    wrong_n = np.zeros(r_pad, bool)
    for r, (row, _idx) in enumerate(delta["reads"]):
        read_mask[r] = True
        if len(row) != sess.n:
            wrong_n[r] = True
        else:
            reads[r, :sess.n] = row
    transfers = np.zeros((t_pad, sess.a_pad), np.int32)
    for t, d in enumerate(delta["transfers"]):
        transfers[t] = d
    return reads, read_mask, wrong_n, transfers


def _launch_bank(mb, key, chunk) -> None:
    t0 = _obs.monotonic()
    a_pad = key[1]
    b_real = len(chunk)
    if b_real == 1:
        ln = chunk[0]
        s = ln.sess
        r_pad, t_pad = _bank_pads(ln.delta)
        reads, rm, wn, tr = _bank_build(s, ln.delta, r_pad, t_pad)
        _ENG.DISPATCHES += 1
        b_pad = 1
        outs = (_WLB.wl_bank_delta(
            s._balance, reads, rm, wn, tr, np.int32(s.total),
            n_reads=r_pad, n_accounts=a_pad, n_snaps=t_pad),)
    else:
        b_pad = next(b for b in _ENG.MEGABATCH_LANES if b >= b_real)
        r_pad = max(_bank_pads(ln.delta)[0] for ln in chunk)
        t_pad = max(_bank_pads(ln.delta)[1] for ln in chunk)
        arrs = [_bank_build(ln.sess, ln.delta, r_pad, t_pad)
                for ln in chunk]
        arrs += [arrs[0]] * (b_pad - b_real)
        reads, rm, wn, tr = (np.stack([a[j] for a in arrs])
                             for j in range(4))
        # carries pass as a per-lane tuple and stack INSIDE the jit
        bals = tuple(ln.sess._balance for ln in chunk)
        bals += (bals[0],) * (b_pad - b_real)
        totals = np.array([ln.sess.total for ln in chunk]
                          + [chunk[0].sess.total] * (b_pad - b_real),
                          np.int32)
        _ENG.DISPATCHES += 1
        _ENG.MEGABATCHES += 1
        outs = _WLB.wl_bank_delta_mb(
            bals, reads, rm, wn, tr, totals, n_reads=r_pad,
            n_accounts=a_pad, n_snaps=t_pad)
    for ln, out in zip(chunk, outs):
        ln.out = out
        ln.sess._balance = out[0]    # device carry advance — no
        ln.sess.dispatches += 1      # readback until the finalize
    if mb is not None:
        mb._stat("wl-bank", b_real, b_pad, t0)


def _launch_sets(mb, key, chunk) -> None:
    t0 = _obs.monotonic()
    e_pad = key[1]
    b_real = len(chunk)

    def hr(ln):
        return bool(ln.sess.has_read or ln.delta["has_read_d"])

    if b_real == 1:
        ln = chunk[0]
        s = ln.sess
        d = ln.delta
        _ENG.DISPATCHES += 1
        b_pad = 1
        outs = (_WLS.wl_sets_delta(
            s._att, s._add, s._fr, d["att"], d["add"], d["read"],
            np.bool_(d["has_read_d"]), np.bool_(hr(ln)),
            n_elems=e_pad),)
    else:
        b_pad = next(b for b in _ENG.MEGABATCH_LANES if b >= b_real)
        carries = tuple((ln.sess._att, ln.sess._add, ln.sess._fr)
                        for ln in chunk)
        carries += (carries[0],) * (b_pad - b_real)
        ds = [ln.delta for ln in chunk]
        ds += [ds[0]] * (b_pad - b_real)
        att = np.stack([d["att"] for d in ds])
        add = np.stack([d["add"] for d in ds])
        rd = np.stack([d["read"] for d in ds])
        hrd = np.array([d["has_read_d"] for d in ds], bool)
        hrs = np.array([hr(ln) for ln in chunk]
                       + [hr(chunk[0])] * (b_pad - b_real), bool)
        _ENG.DISPATCHES += 1
        _ENG.MEGABATCHES += 1
        outs = _WLS.wl_sets_delta_mb(carries, att, add, rd, hrd,
                                     hrs, n_elems=e_pad)
    for ln, out in zip(chunk, outs):
        ln.out = out
        s = ln.sess
        s._att, s._add, s._fr = out[0], out[1], out[2]
        s.dispatches += 1
    if mb is not None:
        mb._stat("wl-sets", b_real, b_pad, t0)


__all__ = ["WL_MODELS", "WlBankSession", "WlLadderOverflow",
           "WlSetsSession", "launch_wl_group", "make_session",
           "restore_session"]
