"""Device-resident carry rungs for streaming sessions.

A session's engine carry lives ON DEVICE between ``append``s — the
O(1)-per-step carried-state discipline of autoregressive-decode
caches applied to verification: each delta dispatch consumes only the
NEW segments against the resident frontier, so per-append device work
is O(delta), never O(history). Three rungs share one interface:

- **kernel** (``pallas_seg``): the fused Mosaic kernel's (ws, stat)
  word carry, chunk calls offset into the session's global segment
  stream. F is fixed at 128; overflow re-routes the session to the
  next rung by replaying the RETAINED renamed segments (the one
  O(history) event a session can pay, amortized over its life).
- **xla** (``stream_delta_chunk`` below — the bucketed, closed-site
  twin of ``check_device_seg2_chunk``): the (states, slots, valid, …)
  carry; capacity escalates IN PLACE via ``expand_seg_carry`` (widen
  the pre-delta carry, re-run only the delta) and the slot axis
  widens in place via ``expand_seg_carry_slots`` when the live
  history's concurrency grows. The carry is shape-portable across
  memo-table bucket growth: state ids are stable
  (:class:`~comdb2_tpu.models.memo.IncrementalMemo`) and the packed
  dedup key layout is internal to the program.
- **mxu** (``checker.mxu``): the packed-word carry for wide-P
  sessions; ``expand_carry`` escalates in place up to the 131072
  rung. The word layout bakes in (n_states, n_transitions, P), so
  table-bucket or P growth re-plans via replay.

Every delta shape rides the ``DELTA_PADS`` pow2 ladder (PROGRAMS.md
``stream-delta`` site) so the compiled-program set stays closed no
matter how a live history's appends are sized; deltas larger than the
top rung split into top-rung chunks.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..checker import linear_jax as LJ
from ..checker import mxu as MXU
from ..checker import pallas_seg as PSEG
from ..obs import trace as _obs
from ..utils import next_pow2 as _next_pow2

#: padded segments per delta dispatch — the pow2 ladder every append
#: is bucketed onto (floor 16: tiny appends share one program; top
#: 1024: larger appends split). The MXU rung floors at its declared
#: chunk ladder's minimum (64).
DELTA_PADS = (16, 64, 256, 1024)
MXU_DELTA_FLOOR = 64

#: the XLA rung's frontier ladder (same rungs as the driver's default
#: ``analysis(capacities=...)``) — in-place escalation, overflow at
#: the top is the honest UNKNOWN for P below the MXU crossover
STREAM_CAPACITIES = (256, 1024, 8192, 65536)

#: small-tier capacity of the adaptive closure (see check_device_seg2)
STREAM_FS = 32

#: stream delta dispatches this process (all rungs) — the O(delta)
#: counter tests and benches assert on. Counts launched PROGRAMS, not
#: session lanes: a megabatched advance of 8 sessions is ONE dispatch
DISPATCHES = 0

#: fused megabatch launches this process (each also counts once in
#: DISPATCHES) — the amortization counter
MEGABATCHES = 0

#: session-lane pow2 ladder of the fused megabatch entries (PROGRAMS.md
#: ``stream-delta`` session_B axis): a beat's same-shape-class lanes
#: pad up to the next rung by duplicating lane 0 (outputs discarded);
#: more than the top rung splits into top-rung launches; a single lane
#: falls back to the solo entries (no padded-lane waste)
MEGABATCH_LANES = (2, 4, 8, 16)

#: ladder ceilings (PROGRAMS.md stream-delta axes): a session whose
#: renamed concurrency or per-segment invoke burst outgrows them has
#: no declared program to run — it latches UNKNOWN (the one-shot
#: path's analog is bucket_for's host-degrade rejection; crash-heavy
#: histories pin :info slots forever and CAN get here)
STREAM_MAX_P = MXU.MAX_P
STREAM_MAX_K = 32


def bucket_delta(n_segments: int, floor: int = 0) -> int:
    """The delta_pad rung for one append's segment count (top rung
    when it exceeds the ladder — the caller then splits)."""
    for p in DELTA_PADS:
        if p >= max(n_segments, floor):
            return p
    return DELTA_PADS[-1]


@functools.partial(jax.jit, static_argnames=("F", "Fs", "P",
                                             "n_states",
                                             "n_transitions"))
def stream_delta_chunk(succ, inv_proc, inv_tr, ok_proc, depth,
                       seg_offset, carry, *, F: int, Fs: int, P: int,
                       n_states: int, n_transitions: int):
    """One delta dispatch of the XLA session rung: the adaptive
    two-tier segmented scan resumed from (and returning) a
    device-resident carry. Identical semantics to
    :func:`~comdb2_tpu.checker.linear_jax.check_device_seg2_chunk`;
    a separate jit name because THIS entry is serving surface — its
    shapes are drawn from the closed ``stream-delta`` ladder
    (PROGRAMS.md), where the driver chunk entry is an open site."""
    bits = LJ._bits_for(n_states, n_transitions, P)
    S = inv_proc.shape[0]
    segs = (inv_proc, inv_tr, ok_proc,
            seg_offset + jnp.arange(S, dtype=jnp.int32), depth)
    step = LJ._make_seg_step(succ, F, P, inv_proc.shape[1], bits,
                             Fs=LJ._seg2_tier(Fs, F))
    carry2, _ = lax.scan(step, carry, segs)
    return carry2


def _host_seg_carry(F: int, P: int):
    """Host-numpy initial carry (init_seg_carry's values): the first
    delta's jit transfers it — building it with eager jnp ops would
    compile infra programs OUTSIDE the declared surface (scatter/
    squeeze per carry shape), and the guard would rightly flag them."""
    valid = np.zeros(F, bool)
    valid[0] = True
    return (np.zeros(F, np.int32),
            np.full((F, P), LJ.IDLE, np.int32), valid,
            np.int32(1), np.int32(LJ.VALID), np.int32(-1))


def _host_expand(carry, F_new: int):
    """``expand_seg_carry`` in host numpy (escalations are rare; the
    one-time readback is cheaper than an off-inventory pad program)."""
    states, slots, valid, count, _s, _f = (np.asarray(x)
                                           for x in carry)
    pad = F_new - states.shape[0]
    if pad < 0:
        raise ValueError("carry wider than target capacity")
    return (np.pad(states, (0, pad)),
            np.pad(slots, ((0, pad), (0, 0)),
                   constant_values=LJ.IDLE),
            np.pad(valid, (0, pad)), count,
            np.int32(LJ.VALID), np.int32(-1))


class XlaCarry:
    """The XLA rung (see module docstring). ``sizes`` are the
    POW2-BUCKETED memo dims (the static shape args — raw counts here
    would compile per history, the ``unbucketed-dispatch-site``
    hazard)."""

    name = "stream-xla"

    def __init__(self, n_states: int, n_transitions: int, P2: int,
                 cap_ix: int = 0):
        self.ns = n_states
        self.nt = n_transitions
        self.P2 = P2
        self.cap_ix = cap_ix
        self.F = STREAM_CAPACITIES[cap_ix]
        self.carry = _host_seg_carry(self.F, P2)
        self._pre = self.carry          # pre-delta snapshot

    def begin_delta(self) -> None:
        self._pre = self.carry

    def dispatch(self, succ, ip, it, okp, dp, seg_offset) -> None:
        global DISPATCHES
        DISPATCHES += 1
        self.carry = stream_delta_chunk(
            succ, ip, it, okp, dp, np.int32(seg_offset), self.carry,
            F=self.F, Fs=STREAM_FS, P=self.P2, n_states=self.ns,
            n_transitions=self.nt)

    def read(self) -> Tuple[int, int, int]:
        """(status, fail_seg_global, n_final) — blocks on the device."""
        return (int(self.carry[4]), int(self.carry[5]),
                int(self.carry[3]))

    def escalate(self) -> bool:
        """Widen the PRE-delta carry to the next rung; the caller
        re-dispatches the same delta. False at the ladder top."""
        if self.cap_ix + 1 >= len(STREAM_CAPACITIES):
            return False
        self.cap_ix += 1
        self.F = STREAM_CAPACITIES[self.cap_ix]
        self.carry = _host_expand(self._pre, self.F)
        self._pre = self.carry
        return True

    def widen_slots(self, P2_new: int) -> bool:
        """Slot-axis growth IN PLACE (the rung survives concurrency
        growth without replay)."""
        self.carry = LJ.expand_seg_carry_slots(self.carry, P2_new)
        self._pre = LJ.expand_seg_carry_slots(self._pre, P2_new)
        self.P2 = P2_new
        return True

    def rebucket(self, n_states: int, n_transitions: int) -> bool:
        """Memo-table bucket growth: the carry is portable (state ids
        stable, key layout internal) — just retarget the static dims."""
        self.ns, self.nt = n_states, n_transitions
        return True

    def nbytes(self) -> int:
        st, sl, va = self.carry[0], self.carry[1], self.carry[2]
        return int(st.size * 4 + sl.size * 4 + va.size)

    def checkpoint(self) -> dict:
        """HOST-numpy snapshot of the resident carry (np.asarray is a
        readback, never a compile — the host-numpy-checkpoint rule).
        Restore's re-upload rides the next delta dispatch's jit
        transfer exactly like the initial ``_host_seg_carry``, so no
        new program joins the inventory."""
        return {"rung": "xla", "ns": self.ns, "nt": self.nt,
                "P2": self.P2, "cap_ix": self.cap_ix,
                "carry": tuple(np.asarray(x) for x in self.carry)}

    @classmethod
    def restore(cls, ck: dict) -> "XlaCarry":
        eng = cls(int(ck["ns"]), int(ck["nt"]), int(ck["P2"]),
                  cap_ix=int(ck["cap_ix"]))
        eng.carry = tuple(np.asarray(x) for x in ck["carry"])
        eng._pre = eng.carry
        return eng


class MxuCarry:
    """The MXU rung: packed-word carry, B=1 chunk form."""

    name = "stream-mxu"

    def __init__(self, n_states: int, n_transitions: int, P2: int,
                 cap_ix: int = 0):
        self.ns = n_states
        self.nt = n_transitions
        self.P2 = P2
        self.cap_ix = cap_ix
        self.F = MXU.CAPACITIES[cap_ix]
        self.carry = MXU.init_carry(1, self.F, P2,
                                    n_states=n_states,
                                    n_transitions=n_transitions)
        self._pre = self.carry

    def begin_delta(self) -> None:
        self._pre = self.carry

    def dispatch(self, succ, ip, it, okp, dp, seg_offset) -> None:
        global DISPATCHES
        DISPATCHES += 1
        self.carry = MXU.check_device_mxu_chunk(
            succ, ip, it, okp, dp, np.int32(seg_offset), self.carry,
            F=self.F, P=self.P2, n_states=self.ns,
            n_transitions=self.nt)

    def read(self) -> Tuple[int, int, int]:
        return (int(self.carry[3][0]), int(self.carry[4][0]),
                int(self.carry[2][0]))

    def escalate(self) -> bool:
        if self.cap_ix + 1 >= len(MXU.CAPACITIES):
            return False
        self.cap_ix += 1
        self.F = MXU.CAPACITIES[self.cap_ix]
        self.carry = MXU.expand_carry(self._pre, self.F)
        self._pre = self.carry
        return True

    def widen_slots(self, P2_new: int) -> bool:
        return False                    # word layout bakes P: replay

    def rebucket(self, n_states: int, n_transitions: int) -> bool:
        return False                    # PackPlan re-plans: replay

    def nbytes(self) -> int:
        words, valid = self.carry[0], self.carry[1]
        return int(sum(w.size * 4 for w in words) + valid.size)

    def checkpoint(self) -> dict:
        words, valid, n_b, status, fail = self.carry
        return {"rung": "mxu", "ns": self.ns, "nt": self.nt,
                "P2": self.P2, "cap_ix": self.cap_ix,
                "carry": (tuple(np.asarray(w) for w in words),
                          np.asarray(valid), np.asarray(n_b),
                          np.asarray(status), np.asarray(fail))}

    @classmethod
    def restore(cls, ck: dict) -> "MxuCarry":
        eng = cls(int(ck["ns"]), int(ck["nt"]), int(ck["P2"]),
                  cap_ix=int(ck["cap_ix"]))
        words, valid, n_b, status, fail = ck["carry"]
        eng.carry = (tuple(np.asarray(w) for w in words),
                     np.asarray(valid), np.asarray(n_b),
                     np.asarray(status), np.asarray(fail))
        eng._pre = eng.carry
        return eng


class KernelCarry:
    """The fused-kernel rung: (ws, stat) word carry threaded through
    per-chunk Mosaic calls at the session's global segment offset.
    F is the kernel's fixed 128; any overflow or growth event
    re-routes (replay on the next rung)."""

    name = "stream-kernel"

    def __init__(self, spec, n_states: int, n_transitions: int):
        self.spec = spec
        self.ns = n_states
        self.nt = n_transitions
        self.ws = tuple(jnp.asarray(w)
                        for w in PSEG.initial_frontier(spec))
        self.stat = jnp.asarray(PSEG._init_stat())
        self._res = jnp.zeros((8, PSEG.LANES), jnp.int32)
        self._pre = (self.ws, self.stat)

    def begin_delta(self) -> None:
        self._pre = (self.ws, self.stat)

    def dispatch(self, table, chunks, seg_offset, spec=None) -> None:
        """``chunks``: (n_chunks, chunk, 2+2K) from ``pack_segments``;
        the offsets bias fail indices into session-global segment
        coordinates. ``spec`` selects a small-delta chunk rung
        (``pallas_seg.delta_spec``) — same carry geometry (rows and
        n_words are chunk-independent), smaller grid."""
        global DISPATCHES
        sp = spec or self.spec
        call = stream_kernel_chunk(sp)
        for c in range(chunks.shape[0]):
            DISPATCHES += 1
            off = np.array([seg_offset + c * sp.chunk,
                            self.nt], np.int32)
            self.ws, self.stat, self._res = call(
                jnp.asarray(chunks[c]), jnp.asarray(off), self.ws,
                self.stat, self._res, table)

    def read(self) -> Tuple[int, int, int]:
        st = np.asarray(self.stat)
        return int(st[0, 0]), int(st[0, 1]), int(st[0, 2])

    def escalate(self) -> bool:
        return False                    # F fixed at 128: re-route

    def widen_slots(self, P2_new: int) -> bool:
        return False                    # spec bakes P: re-route

    def rebucket(self, n_states: int, n_transitions: int) -> bool:
        return False                    # spec bakes the table: re-route

    def nbytes(self) -> int:
        return int(sum(w.size * 4 for w in self.ws)
                   + self.stat.size * 4)

    def checkpoint(self) -> dict:
        """The (ws, stat) word carry + result tile; K rides along so
        restore can re-derive the identical spec (specs are pure
        functions of (ns, nt, P2, K))."""
        return {"rung": "kernel", "ns": self.ns, "nt": self.nt,
                "K": int(self.spec.K),
                "ws": tuple(np.asarray(w) for w in self.ws),
                "stat": np.asarray(self.stat),
                "res": np.asarray(self._res)}

    @classmethod
    def restore(cls, spec, ck: dict) -> "KernelCarry":
        eng = cls(spec, int(ck["ns"]), int(ck["nt"]))
        eng.ws = tuple(np.asarray(w) for w in ck["ws"])
        eng.stat = np.asarray(ck["stat"])
        eng._res = np.asarray(ck["res"])
        eng._pre = (eng.ws, eng.stat)
        return eng


@functools.lru_cache(maxsize=16)
def stream_kernel_chunk(spec):
    """Jitted single-chunk kernel call under the session rung's OWN
    compile-log name (``_chunk_call``'s inner ``call`` is the open
    driver path; serving-surface programs must carry a declared
    name — PROGRAMS.md ``stream-delta``)."""
    call = PSEG._chunk_call(spec)

    def stream_kernel_delta(seg, off, ws, stat, res, table):
        return call(seg, off, ws, stat, res, table)

    return jax.jit(stream_kernel_delta)


@functools.partial(jax.jit, static_argnames=("F", "Fs", "P",
                                             "n_states",
                                             "n_transitions"))
def stream_delta_megabatch(succs, inv_proc, inv_tr, ok_proc, depth,
                           seg_offset, carries, *, F: int, Fs: int,
                           P: int, n_states: int, n_transitions: int):
    """B session-lanes of :func:`stream_delta_chunk` fused into ONE
    program (docs/streaming.md "Megabatched advance"): ``succs`` and
    ``carries`` are B-tuples (every session owns its memo table and
    resident carry), delta tensors are lane-major ``(B, S, K)`` /
    ``(B, S)``, ``seg_offset`` is ``(B,)``. The lane body IS the solo
    chunk scan, so vmap of its deterministic integer ops — padding
    lanes included — returns carries bit-equal to B solo dispatches
    (dead ``ok_proc=-1`` segments and latched lanes select the old
    carry inside ``_make_seg_step``). Returns a B-tuple of carries."""
    bits = LJ._bits_for(n_states, n_transitions, P)
    S, K = inv_proc.shape[1], inv_proc.shape[2]
    succ_b = jnp.stack(succs)
    carry_b = jax.tree.map(lambda *xs: jnp.stack(xs), *carries)

    def lane(succ_l, ip, it, okp, dp, off, carry):
        segs = (ip, it, okp, off + jnp.arange(S, dtype=jnp.int32), dp)
        step = LJ._make_seg_step(succ_l, F, P, K, bits,
                                 Fs=LJ._seg2_tier(Fs, F))
        carry2, _ = lax.scan(step, carry, segs)
        return carry2

    out = jax.vmap(lane)(succ_b, inv_proc, inv_tr, ok_proc, depth,
                         seg_offset, carry_b)
    return tuple(jax.tree.map(lambda x: x[i], out)
                 for i in range(len(carries)))


@functools.lru_cache(maxsize=16)
def stream_kernel_megabatch(spec, B: int):
    """B kernel-rung lanes fused into ONE jitted program: the Mosaic
    chunk program (shared via the ``_chunk_call`` cache — one build)
    is invoked once per lane INSIDE one jit, so the batch costs one
    dispatch round-trip. ``lanes`` is a B-tuple of per-lane
    ``(ws, stat, res, table)``; ``segs`` is ``(B, chunk, 2+2K)`` and
    ``offs`` ``(B, 2)`` (global segment offset + the lane's runtime
    table stride nt)."""
    call = PSEG._chunk_call(spec)

    def stream_kernel_delta_mb(segs, offs, lanes):
        out = []
        for i in range(B):
            ws, stat, res, table = lanes[i]
            out.append(call(segs[i], offs[i], ws, stat, res, table))
        return tuple(out)

    return jax.jit(stream_kernel_delta_mb)


class _Lane:
    """One session's pending delta inside a forming megabatch. The
    pack/pad closures defer array building to flush time, when the
    GROUP's pad rung (max over lanes) is known."""

    __slots__ = ("sess", "eng", "n", "k_pad", "pad_fn", "succ",
                 "seg_offset", "pack_fn", "table")

    def __init__(self, sess, eng, n, seg_offset, k_pad=0, pad_fn=None,
                 succ=None, pack_fn=None, table=None):
        self.sess = sess
        self.eng = eng
        self.n = n
        self.seg_offset = seg_offset
        self.k_pad = k_pad
        self.pad_fn = pad_fn
        self.succ = succ
        self.pack_fn = pack_fn
        self.table = table


class MegaBatch:
    """Per-beat collector fusing same-shape-class session deltas into
    one device dispatch (the tentpole of docs/streaming.md
    "Megabatched advance"). Sessions JOIN during staging
    (:meth:`~comdb2_tpu.stream.session.StreamSession.append_stage`
    with ``collector=``) and the service flushes once per beat;
    every staged finalize also flushes first, so a second append to
    one session (which forces the first's finalize) can never read a
    carry whose delta is still parked here. ``flush`` DRAINS the
    queue and is repeat-callable — later joins start a new round.

    Group keys pin everything jit-static: ``(rung, F, P2, k_pad, ns,
    nt)`` for the XLA/MXU rungs, ``("kernel", spec)`` for the fused
    kernel (nt rides per-lane in the runtime offs row). Lane counts
    pad onto the ``MEGABATCH_LANES`` pow2 ladder by duplicating lane
    0 (outputs discarded); a lone lane falls back to the solo entry.
    A group launch failure latches every joined session UNKNOWN —
    their carries never saw the delta, so letting their finalizes
    read the stale (pre-delta) carry would report a verdict for work
    that never ran."""

    def __init__(self):
        self._groups: dict = {}
        self.launches = 0        # device programs launched (all forms)
        self.fused_launches = 0  # megabatched programs (>= 2 lanes)
        self.fused_lanes = 0     # real lanes riding fused programs
        self.masked_lanes = 0    # duplicated pad lanes (discarded)
        self.solo_lanes = 0      # single-lane fallbacks
        self.lane_counts: list = []   # real lanes per launched program

    def add_delta(self, rung: str, sess, eng, n: int, k_pad: int,
                  pad_fn, succ, seg_offset: int) -> None:
        """Queue one XLA/MXU-rung delta; ``pad_fn(s_pad)`` builds the
        (ip, it, okp, dp) host arrays at the group's pad rung."""
        key = (rung, eng.F, eng.P2, k_pad, eng.ns, eng.nt)
        self._groups.setdefault(key, []).append(
            _Lane(sess, eng, n, seg_offset, k_pad=k_pad,
                  pad_fn=pad_fn, succ=succ))

    def add_kernel(self, sess, eng, n: int, pack_fn, table,
                   seg_offset: int) -> None:
        """Queue one kernel-rung delta; ``pack_fn(dspec)`` packs the
        single scalar chunk at the group's delta-chunk rung."""
        key = ("kernel", eng.spec)
        self._groups.setdefault(key, []).append(
            _Lane(sess, eng, n, seg_offset, pack_fn=pack_fn,
                  table=table))

    def add_wl(self, key: tuple, lane) -> None:
        """Queue one workload-family session delta
        (:mod:`comdb2_tpu.stream.wl`). ``key`` is the wl fuse key —
        ``("wl-bank", a_pad)`` / ``("wl-sets", e_pad)``, pinning the
        carry width the lanes must share — and ``lane`` the wl
        module's staged-lane record (it exposes ``.sess`` so the
        flush-failure latch covers wl lanes too)."""
        self._groups.setdefault(key, []).append(lane)

    def flush(self) -> None:
        while self._groups:
            groups, self._groups = self._groups, {}
            for key, lanes in groups.items():
                try:
                    self._launch_group(key, lanes)
                except Exception as e:      # noqa: BLE001 — engine
                    cause = f"engine: {type(e).__name__}: {e}"
                    for ln in lanes:
                        ln.sess._latch_unknown(cause)

    # -- launch forms --------------------------------------------------

    def _launch_group(self, key, lanes) -> None:
        if isinstance(key[0], str) and key[0].startswith("wl-"):
            from . import wl as _WL
            _WL.launch_wl_group(self, key, lanes)
            return
        top = MEGABATCH_LANES[-1]
        for i in range(0, len(lanes), top):
            chunk = lanes[i:i + top]
            if len(chunk) == 1:
                self._launch_solo(key, chunk[0])
            elif key[0] == "kernel":
                self._launch_kernel(key[1], chunk)
            else:
                self._launch_delta(key, chunk)

    def _stat(self, rung: str, b_real: int, b_pad: int, t0: float
              ) -> None:
        self.launches += 1
        self.lane_counts.append(b_real)
        if b_real == 1:
            self.solo_lanes += 1
        else:
            self.fused_launches += 1
            self.fused_lanes += b_real
            self.masked_lanes += b_pad - b_real
        _obs.record("stream.megabatch", t0, _obs.monotonic(),
                    rung=rung, lanes=b_real, masked=b_pad - b_real)

    def _launch_solo(self, key, ln) -> None:
        t0 = _obs.monotonic()
        if key[0] == "kernel":
            dspec = PSEG.delta_spec(key[1], ln.n)
            ln.eng.dispatch(ln.table, ln.pack_fn(dspec),
                            ln.seg_offset, spec=dspec)
        else:
            floor = MXU_DELTA_FLOOR if key[0] == "mxu" else 0
            s_pad = bucket_delta(ln.n, floor)
            ip, it, okp, dp = ln.pad_fn(s_pad)
            ln.eng.dispatch(ln.succ, ip, it, okp, dp, ln.seg_offset)
        ln.sess.dispatches += 1
        self._stat(key[0], 1, 1, t0)

    def _launch_kernel(self, spec, chunk) -> None:
        global DISPATCHES, MEGABATCHES
        t0 = _obs.monotonic()
        b_real = len(chunk)
        b_pad = next(b for b in MEGABATCH_LANES if b >= b_real)
        dspec = PSEG.delta_spec(spec, max(ln.n for ln in chunk))
        packs = []
        for ln in chunk:
            p = ln.pack_fn(dspec)
            if p.shape[0] != 1:         # join gate guarantees this
                raise ValueError("megabatch kernel lane spans chunks")
            packs.append(p[0])
        segs = np.stack(packs + [packs[0]] * (b_pad - b_real))
        offs = np.array(
            [[ln.seg_offset, ln.eng.nt] for ln in chunk]
            + [[chunk[0].seg_offset, chunk[0].eng.nt]]
            * (b_pad - b_real), np.int32)
        lanes_in = tuple((ln.eng.ws, ln.eng.stat, ln.eng._res,
                          ln.table) for ln in chunk)
        lanes_in += (lanes_in[0],) * (b_pad - b_real)
        DISPATCHES += 1
        MEGABATCHES += 1
        outs = stream_kernel_megabatch(dspec, b_pad)(
            jnp.asarray(segs), jnp.asarray(offs), lanes_in)
        for ln, out in zip(chunk, outs):
            ln.eng.ws, ln.eng.stat, ln.eng._res = out
            ln.sess.dispatches += 1
        self._stat("kernel", b_real, b_pad, t0)

    def _launch_delta(self, key, chunk) -> None:
        global DISPATCHES, MEGABATCHES
        t0 = _obs.monotonic()
        rung, F, P2, _k_pad, ns, nt = key
        b_real = len(chunk)
        b_pad = next(b for b in MEGABATCH_LANES if b >= b_real)
        floor = MXU_DELTA_FLOOR if rung == "mxu" else 0
        s_pad = max(bucket_delta(ln.n, floor) for ln in chunk)
        arrs = [ln.pad_fn(s_pad) for ln in chunk]
        arrs += [arrs[0]] * (b_pad - b_real)
        ip, it, okp, dp = (np.stack([a[j] for a in arrs])
                           for j in range(4))
        offs = np.array([ln.seg_offset for ln in chunk]
                        + [chunk[0].seg_offset] * (b_pad - b_real),
                        np.int32)
        succs = tuple(ln.succ for ln in chunk)
        succs += (succs[0],) * (b_pad - b_real)
        carries = tuple(ln.eng.carry for ln in chunk)
        carries += (carries[0],) * (b_pad - b_real)
        DISPATCHES += 1
        MEGABATCHES += 1
        if rung == "mxu":
            outs = MXU.check_device_mxu_megabatch(
                succs, ip, it, okp, dp, offs, carries, F=F, P=P2,
                n_states=ns, n_transitions=nt)
        else:
            outs = stream_delta_megabatch(
                succs, ip, it, okp, dp, offs, carries, F=F,
                Fs=STREAM_FS, P=P2, n_states=ns, n_transitions=nt)
        for ln, carry in zip(chunk, outs):
            ln.eng.carry = carry
            ln.sess.dispatches += 1
        self._stat(rung, b_real, b_pad, t0)


def kernel_spec(n_states: int, n_transitions: int, P2: int,
                K: int) -> Optional[object]:
    """The session's kernel spec, or None when the shape can't run
    fused (the caller then picks the MXU/XLA rung)."""
    if not PSEG.available():
        return None
    return PSEG.spec_for(n_states, n_transitions, P2, K + (K & 1))


def pick_rung(n_states: int, n_transitions: int, P2: int, K: int,
              engine: str = "auto") -> str:
    """Rung policy, mirroring the driver ladder: kernel when the
    fused spec serves the shape, MXU for wide P, XLA otherwise.
    ``engine`` forces a specific rung (tests / ``--engine``)."""
    if engine in ("kernel", "mxu", "xla"):
        return engine
    if P2 <= 2 * PSEG.ROWS - 1 and K <= 8 \
            and kernel_spec(n_states, n_transitions, P2, K) is not None:
        return "kernel"
    if MXU.serves(n_states, n_transitions, P2):
        return "mxu"
    return "xla"


def pad_sizes(n_states: int, n_transitions: int) -> Tuple[int, int]:
    """Pow2 memo-dim buckets (the ``stream-delta`` site's table axes —
    every dispatch must route raw counts through here)."""
    return _next_pow2(n_states), _next_pow2(n_transitions)


__all__ = ["DELTA_PADS", "DISPATCHES", "KernelCarry", "MEGABATCHES",
           "MEGABATCH_LANES", "MXU_DELTA_FLOOR", "MegaBatch",
           "MxuCarry", "STREAM_CAPACITIES", "STREAM_MAX_K",
           "STREAM_MAX_P", "XlaCarry", "bucket_delta", "kernel_spec",
           "pad_sizes", "pick_rung", "stream_delta_chunk",
           "stream_delta_megabatch", "stream_kernel_chunk",
           "stream_kernel_megabatch"]
