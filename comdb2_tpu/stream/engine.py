"""Device-resident carry rungs for streaming sessions.

A session's engine carry lives ON DEVICE between ``append``s — the
O(1)-per-step carried-state discipline of autoregressive-decode
caches applied to verification: each delta dispatch consumes only the
NEW segments against the resident frontier, so per-append device work
is O(delta), never O(history). Three rungs share one interface:

- **kernel** (``pallas_seg``): the fused Mosaic kernel's (ws, stat)
  word carry, chunk calls offset into the session's global segment
  stream. F is fixed at 128; overflow re-routes the session to the
  next rung by replaying the RETAINED renamed segments (the one
  O(history) event a session can pay, amortized over its life).
- **xla** (``stream_delta_chunk`` below — the bucketed, closed-site
  twin of ``check_device_seg2_chunk``): the (states, slots, valid, …)
  carry; capacity escalates IN PLACE via ``expand_seg_carry`` (widen
  the pre-delta carry, re-run only the delta) and the slot axis
  widens in place via ``expand_seg_carry_slots`` when the live
  history's concurrency grows. The carry is shape-portable across
  memo-table bucket growth: state ids are stable
  (:class:`~comdb2_tpu.models.memo.IncrementalMemo`) and the packed
  dedup key layout is internal to the program.
- **mxu** (``checker.mxu``): the packed-word carry for wide-P
  sessions; ``expand_carry`` escalates in place up to the 131072
  rung. The word layout bakes in (n_states, n_transitions, P), so
  table-bucket or P growth re-plans via replay.

Every delta shape rides the ``DELTA_PADS`` pow2 ladder (PROGRAMS.md
``stream-delta`` site) so the compiled-program set stays closed no
matter how a live history's appends are sized; deltas larger than the
top rung split into top-rung chunks.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..checker import linear_jax as LJ
from ..checker import mxu as MXU
from ..checker import pallas_seg as PSEG
from ..utils import next_pow2 as _next_pow2

#: padded segments per delta dispatch — the pow2 ladder every append
#: is bucketed onto (floor 16: tiny appends share one program; top
#: 1024: larger appends split). The MXU rung floors at its declared
#: chunk ladder's minimum (64).
DELTA_PADS = (16, 64, 256, 1024)
MXU_DELTA_FLOOR = 64

#: the XLA rung's frontier ladder (same rungs as the driver's default
#: ``analysis(capacities=...)``) — in-place escalation, overflow at
#: the top is the honest UNKNOWN for P below the MXU crossover
STREAM_CAPACITIES = (256, 1024, 8192, 65536)

#: small-tier capacity of the adaptive closure (see check_device_seg2)
STREAM_FS = 32

#: stream delta dispatches this process (all rungs) — the O(delta)
#: counter tests and benches assert on
DISPATCHES = 0

#: ladder ceilings (PROGRAMS.md stream-delta axes): a session whose
#: renamed concurrency or per-segment invoke burst outgrows them has
#: no declared program to run — it latches UNKNOWN (the one-shot
#: path's analog is bucket_for's host-degrade rejection; crash-heavy
#: histories pin :info slots forever and CAN get here)
STREAM_MAX_P = MXU.MAX_P
STREAM_MAX_K = 32


def bucket_delta(n_segments: int, floor: int = 0) -> int:
    """The delta_pad rung for one append's segment count (top rung
    when it exceeds the ladder — the caller then splits)."""
    for p in DELTA_PADS:
        if p >= max(n_segments, floor):
            return p
    return DELTA_PADS[-1]


@functools.partial(jax.jit, static_argnames=("F", "Fs", "P",
                                             "n_states",
                                             "n_transitions"))
def stream_delta_chunk(succ, inv_proc, inv_tr, ok_proc, depth,
                       seg_offset, carry, *, F: int, Fs: int, P: int,
                       n_states: int, n_transitions: int):
    """One delta dispatch of the XLA session rung: the adaptive
    two-tier segmented scan resumed from (and returning) a
    device-resident carry. Identical semantics to
    :func:`~comdb2_tpu.checker.linear_jax.check_device_seg2_chunk`;
    a separate jit name because THIS entry is serving surface — its
    shapes are drawn from the closed ``stream-delta`` ladder
    (PROGRAMS.md), where the driver chunk entry is an open site."""
    bits = LJ._bits_for(n_states, n_transitions, P)
    S = inv_proc.shape[0]
    segs = (inv_proc, inv_tr, ok_proc,
            seg_offset + jnp.arange(S, dtype=jnp.int32), depth)
    step = LJ._make_seg_step(succ, F, P, inv_proc.shape[1], bits,
                             Fs=LJ._seg2_tier(Fs, F))
    carry2, _ = lax.scan(step, carry, segs)
    return carry2


def _host_seg_carry(F: int, P: int):
    """Host-numpy initial carry (init_seg_carry's values): the first
    delta's jit transfers it — building it with eager jnp ops would
    compile infra programs OUTSIDE the declared surface (scatter/
    squeeze per carry shape), and the guard would rightly flag them."""
    valid = np.zeros(F, bool)
    valid[0] = True
    return (np.zeros(F, np.int32),
            np.full((F, P), LJ.IDLE, np.int32), valid,
            np.int32(1), np.int32(LJ.VALID), np.int32(-1))


def _host_expand(carry, F_new: int):
    """``expand_seg_carry`` in host numpy (escalations are rare; the
    one-time readback is cheaper than an off-inventory pad program)."""
    states, slots, valid, count, _s, _f = (np.asarray(x)
                                           for x in carry)
    pad = F_new - states.shape[0]
    if pad < 0:
        raise ValueError("carry wider than target capacity")
    return (np.pad(states, (0, pad)),
            np.pad(slots, ((0, pad), (0, 0)),
                   constant_values=LJ.IDLE),
            np.pad(valid, (0, pad)), count,
            np.int32(LJ.VALID), np.int32(-1))


class XlaCarry:
    """The XLA rung (see module docstring). ``sizes`` are the
    POW2-BUCKETED memo dims (the static shape args — raw counts here
    would compile per history, the ``unbucketed-dispatch-site``
    hazard)."""

    name = "stream-xla"

    def __init__(self, n_states: int, n_transitions: int, P2: int,
                 cap_ix: int = 0):
        self.ns = n_states
        self.nt = n_transitions
        self.P2 = P2
        self.cap_ix = cap_ix
        self.F = STREAM_CAPACITIES[cap_ix]
        self.carry = _host_seg_carry(self.F, P2)
        self._pre = self.carry          # pre-delta snapshot

    def begin_delta(self) -> None:
        self._pre = self.carry

    def dispatch(self, succ, ip, it, okp, dp, seg_offset) -> None:
        global DISPATCHES
        DISPATCHES += 1
        self.carry = stream_delta_chunk(
            succ, ip, it, okp, dp, np.int32(seg_offset), self.carry,
            F=self.F, Fs=STREAM_FS, P=self.P2, n_states=self.ns,
            n_transitions=self.nt)

    def read(self) -> Tuple[int, int, int]:
        """(status, fail_seg_global, n_final) — blocks on the device."""
        return (int(self.carry[4]), int(self.carry[5]),
                int(self.carry[3]))

    def escalate(self) -> bool:
        """Widen the PRE-delta carry to the next rung; the caller
        re-dispatches the same delta. False at the ladder top."""
        if self.cap_ix + 1 >= len(STREAM_CAPACITIES):
            return False
        self.cap_ix += 1
        self.F = STREAM_CAPACITIES[self.cap_ix]
        self.carry = _host_expand(self._pre, self.F)
        self._pre = self.carry
        return True

    def widen_slots(self, P2_new: int) -> bool:
        """Slot-axis growth IN PLACE (the rung survives concurrency
        growth without replay)."""
        self.carry = LJ.expand_seg_carry_slots(self.carry, P2_new)
        self._pre = LJ.expand_seg_carry_slots(self._pre, P2_new)
        self.P2 = P2_new
        return True

    def rebucket(self, n_states: int, n_transitions: int) -> bool:
        """Memo-table bucket growth: the carry is portable (state ids
        stable, key layout internal) — just retarget the static dims."""
        self.ns, self.nt = n_states, n_transitions
        return True

    def nbytes(self) -> int:
        st, sl, va = self.carry[0], self.carry[1], self.carry[2]
        return int(st.size * 4 + sl.size * 4 + va.size)

    def checkpoint(self) -> dict:
        """HOST-numpy snapshot of the resident carry (np.asarray is a
        readback, never a compile — the host-numpy-checkpoint rule).
        Restore's re-upload rides the next delta dispatch's jit
        transfer exactly like the initial ``_host_seg_carry``, so no
        new program joins the inventory."""
        return {"rung": "xla", "ns": self.ns, "nt": self.nt,
                "P2": self.P2, "cap_ix": self.cap_ix,
                "carry": tuple(np.asarray(x) for x in self.carry)}

    @classmethod
    def restore(cls, ck: dict) -> "XlaCarry":
        eng = cls(int(ck["ns"]), int(ck["nt"]), int(ck["P2"]),
                  cap_ix=int(ck["cap_ix"]))
        eng.carry = tuple(np.asarray(x) for x in ck["carry"])
        eng._pre = eng.carry
        return eng


class MxuCarry:
    """The MXU rung: packed-word carry, B=1 chunk form."""

    name = "stream-mxu"

    def __init__(self, n_states: int, n_transitions: int, P2: int,
                 cap_ix: int = 0):
        self.ns = n_states
        self.nt = n_transitions
        self.P2 = P2
        self.cap_ix = cap_ix
        self.F = MXU.CAPACITIES[cap_ix]
        self.carry = MXU.init_carry(1, self.F, P2,
                                    n_states=n_states,
                                    n_transitions=n_transitions)
        self._pre = self.carry

    def begin_delta(self) -> None:
        self._pre = self.carry

    def dispatch(self, succ, ip, it, okp, dp, seg_offset) -> None:
        global DISPATCHES
        DISPATCHES += 1
        self.carry = MXU.check_device_mxu_chunk(
            succ, ip, it, okp, dp, np.int32(seg_offset), self.carry,
            F=self.F, P=self.P2, n_states=self.ns,
            n_transitions=self.nt)

    def read(self) -> Tuple[int, int, int]:
        return (int(self.carry[3][0]), int(self.carry[4][0]),
                int(self.carry[2][0]))

    def escalate(self) -> bool:
        if self.cap_ix + 1 >= len(MXU.CAPACITIES):
            return False
        self.cap_ix += 1
        self.F = MXU.CAPACITIES[self.cap_ix]
        self.carry = MXU.expand_carry(self._pre, self.F)
        self._pre = self.carry
        return True

    def widen_slots(self, P2_new: int) -> bool:
        return False                    # word layout bakes P: replay

    def rebucket(self, n_states: int, n_transitions: int) -> bool:
        return False                    # PackPlan re-plans: replay

    def nbytes(self) -> int:
        words, valid = self.carry[0], self.carry[1]
        return int(sum(w.size * 4 for w in words) + valid.size)

    def checkpoint(self) -> dict:
        words, valid, n_b, status, fail = self.carry
        return {"rung": "mxu", "ns": self.ns, "nt": self.nt,
                "P2": self.P2, "cap_ix": self.cap_ix,
                "carry": (tuple(np.asarray(w) for w in words),
                          np.asarray(valid), np.asarray(n_b),
                          np.asarray(status), np.asarray(fail))}

    @classmethod
    def restore(cls, ck: dict) -> "MxuCarry":
        eng = cls(int(ck["ns"]), int(ck["nt"]), int(ck["P2"]),
                  cap_ix=int(ck["cap_ix"]))
        words, valid, n_b, status, fail = ck["carry"]
        eng.carry = (tuple(np.asarray(w) for w in words),
                     np.asarray(valid), np.asarray(n_b),
                     np.asarray(status), np.asarray(fail))
        eng._pre = eng.carry
        return eng


class KernelCarry:
    """The fused-kernel rung: (ws, stat) word carry threaded through
    per-chunk Mosaic calls at the session's global segment offset.
    F is the kernel's fixed 128; any overflow or growth event
    re-routes (replay on the next rung)."""

    name = "stream-kernel"

    def __init__(self, spec, n_states: int, n_transitions: int):
        self.spec = spec
        self.ns = n_states
        self.nt = n_transitions
        self.ws = tuple(jnp.asarray(w)
                        for w in PSEG.initial_frontier(spec))
        self.stat = jnp.asarray(PSEG._init_stat())
        self._res = jnp.zeros((8, PSEG.LANES), jnp.int32)
        self._pre = (self.ws, self.stat)

    def begin_delta(self) -> None:
        self._pre = (self.ws, self.stat)

    def dispatch(self, table, chunks, seg_offset) -> None:
        """``chunks``: (n_chunks, chunk, 2+2K) from ``pack_segments``;
        the offsets bias fail indices into session-global segment
        coordinates."""
        global DISPATCHES
        call = stream_kernel_chunk(self.spec)
        for c in range(chunks.shape[0]):
            DISPATCHES += 1
            off = np.array([seg_offset + c * self.spec.chunk,
                            self.nt], np.int32)
            self.ws, self.stat, self._res = call(
                jnp.asarray(chunks[c]), jnp.asarray(off), self.ws,
                self.stat, self._res, table)

    def read(self) -> Tuple[int, int, int]:
        st = np.asarray(self.stat)
        return int(st[0, 0]), int(st[0, 1]), int(st[0, 2])

    def escalate(self) -> bool:
        return False                    # F fixed at 128: re-route

    def widen_slots(self, P2_new: int) -> bool:
        return False                    # spec bakes P: re-route

    def rebucket(self, n_states: int, n_transitions: int) -> bool:
        return False                    # spec bakes the table: re-route

    def nbytes(self) -> int:
        return int(sum(w.size * 4 for w in self.ws)
                   + self.stat.size * 4)

    def checkpoint(self) -> dict:
        """The (ws, stat) word carry + result tile; K rides along so
        restore can re-derive the identical spec (specs are pure
        functions of (ns, nt, P2, K))."""
        return {"rung": "kernel", "ns": self.ns, "nt": self.nt,
                "K": int(self.spec.K),
                "ws": tuple(np.asarray(w) for w in self.ws),
                "stat": np.asarray(self.stat),
                "res": np.asarray(self._res)}

    @classmethod
    def restore(cls, spec, ck: dict) -> "KernelCarry":
        eng = cls(spec, int(ck["ns"]), int(ck["nt"]))
        eng.ws = tuple(np.asarray(w) for w in ck["ws"])
        eng.stat = np.asarray(ck["stat"])
        eng._res = np.asarray(ck["res"])
        eng._pre = (eng.ws, eng.stat)
        return eng


@functools.lru_cache(maxsize=16)
def stream_kernel_chunk(spec):
    """Jitted single-chunk kernel call under the session rung's OWN
    compile-log name (``_chunk_call``'s inner ``call`` is the open
    driver path; serving-surface programs must carry a declared
    name — PROGRAMS.md ``stream-delta``)."""
    call = PSEG._chunk_call(spec)

    def stream_kernel_delta(seg, off, ws, stat, res, table):
        return call(seg, off, ws, stat, res, table)

    return jax.jit(stream_kernel_delta)


def kernel_spec(n_states: int, n_transitions: int, P2: int,
                K: int) -> Optional[object]:
    """The session's kernel spec, or None when the shape can't run
    fused (the caller then picks the MXU/XLA rung)."""
    if not PSEG.available():
        return None
    return PSEG.spec_for(n_states, n_transitions, P2, K + (K & 1))


def pick_rung(n_states: int, n_transitions: int, P2: int, K: int,
              engine: str = "auto") -> str:
    """Rung policy, mirroring the driver ladder: kernel when the
    fused spec serves the shape, MXU for wide P, XLA otherwise.
    ``engine`` forces a specific rung (tests / ``--engine``)."""
    if engine in ("kernel", "mxu", "xla"):
        return engine
    if P2 <= 2 * PSEG.ROWS - 1 and K <= 8 \
            and kernel_spec(n_states, n_transitions, P2, K) is not None:
        return "kernel"
    if MXU.serves(n_states, n_transitions, P2):
        return "mxu"
    return "xla"


def pad_sizes(n_states: int, n_transitions: int) -> Tuple[int, int]:
    """Pow2 memo-dim buckets (the ``stream-delta`` site's table axes —
    every dispatch must route raw counts through here)."""
    return _next_pow2(n_states), _next_pow2(n_transitions)


__all__ = ["DELTA_PADS", "DISPATCHES", "KernelCarry", "MXU_DELTA_FLOOR",
           "MxuCarry", "STREAM_CAPACITIES", "STREAM_MAX_K",
           "STREAM_MAX_P", "XlaCarry", "bucket_delta", "kernel_spec",
           "pad_sizes", "pick_rung", "stream_delta_chunk",
           "stream_kernel_chunk"]
