"""StreamSession — one monitored live history, one resident carry.

The session composes the incremental layers into the streaming
verification loop (docs/streaming.md):

    append(ops) -> ingest delta        (columnar, watermark-settled)
               -> extend memo          (state ids stable)
               -> segment + rename     (tail + renamer carried)
               -> dispatch NEW segments against the resident carry
               -> verdict-so-far       (latched once terminal)

Per-append device work is O(delta). The only O(history) events are
engine RE-ROUTES (kernel frontier overflow, MXU re-plan after table
or concurrency growth), which replay the session's retained renamed
segment stream onto a fresh rung — the same retained tables a
failover re-open replays (docs/streaming.md "Failover").

Verdicts LATCH: linearizability of a prefix is monotone — once a
prefix is non-linearizable every extension is, so an INVALID (or a
terminal UNKNOWN) answers later appends immediately without touching
the device.
"""

from __future__ import annotations

from typing import List, Optional, Union

from ..models.memo import IncrementalMemo, MemoOverflow
from ..models.model import MODELS, Model
from ..obs import trace as _obs
from ..utils import next_pow2 as _next_pow2
from . import engine as ENG
from .ingest import MalformedDelta, StreamIngest
from .segment import StreamSegmenter

VALID, INVALID, UNKNOWN = 0, 1, 2


def _even(p: int) -> int:
    p = max(p, 2)
    return p + (p & 1)


class StreamSession:
    """See module docstring. ``engine`` forces a rung ("kernel" /
    "mxu" / "xla"); "auto" follows the driver ladder. ``max_states``
    caps the incremental memo (overflow latches UNKNOWN, the honest
    tri-state)."""

    def __init__(self, model: Union[str, Model] = "cas-register",
                 engine: str = "auto", max_states: int = 1 << 20):
        if isinstance(model, str):
            if model not in MODELS:
                raise ValueError(f"unknown model {model!r}")
            self.model_name = model
            model = MODELS[model]()
        else:
            self.model_name = type(model).__name__
        self.engine_policy = engine
        self.ingest = StreamIngest()
        self.seg = StreamSegmenter()
        self.memo = IncrementalMemo(model, max_states=max_states)
        self._eng = None
        self._rung: Optional[str] = None
        self._succ_dev = None
        self._succ_key = None
        self._table_dev = None        # kernel rung's packed table
        self._table_key = None
        self.P2 = 2
        self.dispatched_segments = 0  # prefix already on the carry
        self.appends = 0
        self.dispatches = 0           # session-local delta dispatches
        self.replays = 0
        self.valid: Union[bool, str, None] = True
        self.cause: Optional[str] = None
        self.fail_index: int = -1
        self.final_count: int = 1
        self.engines_tried: List[dict] = []
        self.closed = False
        self._inflight = None

    # -- public API ----------------------------------------------------

    def append(self, ops) -> dict:
        """Ingest one delta, dispatch its new segments, return the
        verdict-so-far map (synchronous form)."""
        fin = self.append_stage(ops)
        return fin()

    def append_stage(self, ops, collector=None):
        """Stage one append (ingest + async dispatch) and return a
        zero-arg finalize producing the verdict map — the service tick
        overlaps other sessions' host work with this one's device run.
        Appends to one session serialize: staging while an earlier
        append is unfinalized finalizes it first.

        ``collector`` (an :class:`~comdb2_tpu.stream.engine.MegaBatch`)
        parks this delta in the beat's forming megabatch instead of
        dispatching solo; the finalize flushes the collector before
        reading the carry, so callers may finalize in any order."""
        if self._inflight is not None:
            self._inflight()
        if self.closed:
            out = self._verdict_map()
            out["cause"] = "session closed"
            return lambda: out
        self.appends += 1
        if self._latched():
            # the latch: a non-linearizable prefix stays
            # non-linearizable under every extension — answer without
            # ingesting or touching the device
            out = self._verdict_map()
            out["latched"] = True
            return lambda: out
        try:
            with _obs.span("stream.ingest", n=len(ops)):
                lo, hi = self.ingest.append(list(ops))
        except MalformedDelta as e:
            self._latch_unknown(f"malformed: {e}")
            return lambda: self._verdict_map()
        return self._stage_settled(lo, hi, collector)

    def finalize_input(self) -> dict:
        """End of stream: settle the tail (open invokes keep their
        invoked values, one-shot parity) and dispatch whatever oks
        that unblocks. The final verdict map is bit-identical to a
        one-shot ``check_batch`` of the full history."""
        if self._inflight is not None:
            self._inflight()
        if self.closed or self._latched():
            return self._verdict_map()
        lo, hi = self.ingest.finalize()
        return self._stage_settled(lo, hi)()

    def poll(self) -> dict:
        if self._inflight is not None:
            self._inflight()
        return self._verdict_map()

    def close(self) -> dict:
        """Finalize, release the device carry, reject further work.
        The release rides ``finally``: a finalize that raises (engine
        error, rung re-route failure) must still free the carry, or
        the session leaks device memory until idle eviction."""
        try:
            out = self.finalize_input()
        finally:
            self.release()
        return out

    def release(self) -> None:
        """Drop the device carry WITHOUT the final tail settle — the
        eviction path. Forces any in-flight staged append through its
        (idempotent) finalize first, so a ring-resident dispatch can
        never read a released engine."""
        if self._inflight is not None:
            self._inflight()
        self._eng = None
        self._succ_dev = None
        self._table_dev = None
        self.closed = True

    def carry_nbytes(self) -> int:
        return self._eng.nbytes() if self._eng is not None else 0

    @property
    def shape_class(self) -> str:
        """The session's compiled-shape class — service slot
        coalescing keys on it (same forming batches as one-shot
        traffic with the same programs)."""
        ns, nt = ENG.pad_sizes(max(self.memo.n_states, 1),
                               max(self.memo.n_transitions, 1))
        return (f"stream-{self._rung or 'new'}-p{self.P2}"
                f"-k{self._k_bucket()}-t{ns}x{nt}")

    # -- checkpoint / restore (docs/streaming.md "Checkpoint") ---------

    def checkpoint(self) -> dict:
        """Host-numpy snapshot of the whole session: the engine carry
        (the device-resident piece — O(carry)), the ingest watermark +
        columns, the segment tail + renamer + retained renamed stream,
        and the memo's extend log. Restoring from it resumes with the
        SAME state ids, segment coordinates and carry bits as the live
        session (golden-tested), so eviction and migration cost zero
        device replay — per-append dispatches stay O(delta) after a
        handoff. Forces any staged append through its finalize first
        (a snapshot must never be mid-dispatch)."""
        if self._inflight is not None:
            self._inflight()
        return {
            "v": 1,
            "model": self.model_name,
            "engine_policy": self.engine_policy,
            "keyed": bool(getattr(self, "keyed", False)),
            "P2": int(self.P2),
            "rung": self._rung,
            "dispatched_segments": int(self.dispatched_segments),
            "appends": int(self.appends),
            "dispatches": int(self.dispatches),
            "replays": int(self.replays),
            "valid": self.valid,
            "cause": self.cause,
            "fail_index": int(self.fail_index),
            "final_count": int(self.final_count),
            "engines_tried": list(self.engines_tried),
            "closed": bool(self.closed),
            "memo": self.memo.checkpoint(),
            "ingest": self.ingest.checkpoint(),
            "seg": self.seg.checkpoint(),
            "eng": (self._eng.checkpoint()
                    if self._eng is not None else None),
        }

    @classmethod
    def restore(cls, ck: dict) -> "StreamSession":
        """Rebuild a session from :meth:`checkpoint`. The memo replays
        its extend log (state ids bit-identical — the carry stores
        them), the engine carry re-uploads on the next delta dispatch
        (no extra program, no replay), and a kernel-rung checkpoint
        restored where the fused kernel is unavailable re-routes onto
        a host-serviceable rung by replaying the retained segments —
        the same O(history) event a live crossing pays."""
        if ck.get("v") != 1:
            raise ValueError(f"unknown checkpoint version {ck.get('v')!r}")
        model = ck["model"]
        if model not in MODELS:
            raise ValueError(f"unknown model {model!r} in checkpoint")
        s = cls(model, engine=ck["engine_policy"],
                max_states=int(ck["memo"]["max_states"]))
        s.keyed = bool(ck["keyed"])
        s.memo = IncrementalMemo.restore(MODELS[model](), ck["memo"])
        from .ingest import StreamIngest as _SI
        from .segment import StreamSegmenter as _SS

        s.ingest = _SI.restore(ck["ingest"])
        s.seg = _SS.restore(ck["seg"])
        s.P2 = int(ck["P2"])
        s._rung = ck["rung"]
        s.dispatched_segments = int(ck["dispatched_segments"])
        s.appends = int(ck["appends"])
        s.dispatches = int(ck["dispatches"])
        s.replays = int(ck["replays"])
        s.valid = ck["valid"]
        s.cause = ck["cause"]
        s.fail_index = int(ck["fail_index"])
        s.final_count = int(ck["final_count"])
        s.engines_tried = list(ck["engines_tried"])
        s.closed = bool(ck["closed"])
        eng_ck = ck["eng"]
        if eng_ck is None:
            return s
        rung = eng_ck["rung"]
        if rung == "xla":
            s._eng = ENG.XlaCarry.restore(eng_ck)
        elif rung == "mxu":
            s._eng = ENG.MxuCarry.restore(eng_ck)
        else:
            spec = ENG.kernel_spec(int(eng_ck["ns"]),
                                   int(eng_ck["nt"]), s.P2,
                                   int(eng_ck["K"]))
            if spec is None:
                # fused kernel unavailable here (e.g. restored onto a
                # CPU daemon without interpret mode): replay the
                # retained segments onto a serviceable rung
                s._eng = None
                s._reroute(note="kernel unavailable at restore")
                return s
            s._eng = ENG.KernelCarry.restore(spec, eng_ck)
        return s

    def counterexample(self, F: int = 4096):
        """Bounded failing-config reconstruction on the retained
        columnar tables (the owner-map decode path — API edge)."""
        if self.valid is not False:
            return None
        from ..checker import counterexample as CE

        packed = self.ingest.packed_history()
        return CE.reconstruct(self.memo.as_memoized(), packed,
                              F=max(256, min(F, 65536)))

    # -- staging -------------------------------------------------------

    def _stage_settled(self, lo: int, hi: int, collector=None):
        try:
            self._extend_memo()
            with _obs.span("stream.segment", lo=lo, hi=hi):
                s_lo, s_hi = self.seg.feed(self.ingest, lo, hi)
        except MemoOverflow as e:
            self._latch_unknown(f"memo overflow: {e}")
            return lambda: self._verdict_map()
        except ValueError as e:
            self._latch_unknown(f"malformed: {e}")
            return lambda: self._verdict_map()
        if s_hi == s_lo:
            return lambda: self._verdict_map()
        if _even(self.seg.p_eff) > ENG.STREAM_MAX_P \
                or self._k_bucket() > ENG.STREAM_MAX_K:
            # past the declared stream-delta ladder there is no
            # program to run (and a genuinely concurrent P>32 closure
            # is a 2^P frontier nothing searches anyway): the honest
            # tri-state, latched — NOT an off-inventory compile per
            # growth step
            self._latch_unknown(
                f"concurrency beyond the stream ladder (P_eff="
                f"{self.seg.p_eff} > {ENG.STREAM_MAX_P} or K="
                f"{self.seg.k_max} > {ENG.STREAM_MAX_K})")
            return lambda: self._verdict_map()
        try:
            self._maintain_shapes()
            with _obs.span("stream.dispatch", s_lo=s_lo, s_hi=s_hi,
                           engine=self._rung):
                self._dispatch_range(s_lo, s_hi, collector)
        except Exception as e:          # noqa: BLE001 — engine blowup
            self._latch_unknown(f"engine: {type(e).__name__}: {e}")
            return lambda: self._verdict_map()

        done: dict = {}

        def finalize():
            # idempotent: the service's batch finish() calls every
            # staged fin, but an append staged AFTER this one in the
            # same batch already forced it through the session's
            # inflight serialization — a second _finalize_range
            # against the later delta's carry would re-apply segments
            if "out" in done:
                return done["out"]
            self._inflight = None
            try:
                if collector is not None:
                    # the delta may still be parked in the beat's
                    # forming megabatch (a second append to this
                    # session forces THIS finalize before the
                    # service's own flush) — drain it first, and
                    # skip the carry read when the flush latched us
                    # (a failed group launch never ran this delta)
                    collector.flush()
                if not self._latched():
                    self._finalize_range(s_lo, s_hi)
            except Exception as e:      # noqa: BLE001
                self._latch_unknown(
                    f"engine: {type(e).__name__}: {e}")
            done["out"] = self._verdict_map()
            return done["out"]

        self._inflight = finalize
        return finalize

    # -- shape maintenance ---------------------------------------------

    def _k_bucket(self) -> int:
        return _next_pow2(self.seg.k_max, 2)

    def _extend_memo(self) -> None:
        known = self.memo.n_transitions
        new = self.ingest.transitions_of(known,
                                         len(self.ingest
                                             .transition_table))
        self.memo.extend(new, self.ingest.n_invokes_settled)

    def _maintain_shapes(self) -> None:
        """Grow-events between appends: concurrency (P_eff), table
        buckets, K. Rungs that absorb growth in place do; the rest
        replay the retained segments onto a re-picked rung."""
        ns, nt = ENG.pad_sizes(max(self.memo.n_states, 1),
                               max(self.memo.n_transitions, 1))
        P2 = _even(self.seg.p_eff)
        if self._eng is None:
            self.P2 = P2
            self._rung = ENG.pick_rung(ns, nt, P2, self.seg.k_max,
                                       self.engine_policy)
            self._eng = self._make_engine(self._rung, ns, nt, P2)
            return
        replay = False
        if P2 > self.P2:
            # concurrency growth can cross an engine crossover (the
            # kernel's P<=15 tiers, the MXU's P>=16 ownership) — a
            # rung change is a replay, widening in place is not
            preferred = ENG.pick_rung(ns, nt, P2, self.seg.k_max,
                                      self.engine_policy)
            if preferred != self._rung or not self._eng.widen_slots(P2):
                replay = True
            self.P2 = P2
        if (ns, nt) != self._eng_sizes():
            if not self._eng.rebucket(ns, nt):
                replay = True
        if self._rung == "kernel" \
                and self.seg.k_max > self._eng.spec.K:
            replay = True               # spec bakes K
        if replay:
            self._reroute(note="growth")

    def _eng_sizes(self):
        return self._eng.ns, self._eng.nt

    def _make_engine(self, rung: str, ns: int, nt: int, P2: int):
        if rung == "kernel":
            spec = ENG.kernel_spec(ns, nt, P2, self.seg.k_max)
            if spec is None:            # shape outgrew the kernel —
                # attributed, so a forced engine="kernel" caller can
                # see the substitution instead of silently measuring
                # the wrong rung
                self.engines_tried.append(
                    {"engine": "stream-kernel",
                     "note": "spec unavailable for shape",
                     "frontier_capacity": None})
                rung = ("mxu" if ENG.MXU.serves(ns, nt, P2)
                        else "xla")
                self._rung = rung
            else:
                self._table_dev = None
                return ENG.KernelCarry(spec, ns, nt)
        if rung == "mxu":
            if ENG.MXU.serves(ns, nt, P2):
                return ENG.MxuCarry(ns, nt, P2)
            # same attribution contract as the kernel branch: a
            # forced engine="mxu" caller must see the substitution
            self.engines_tried.append(
                {"engine": "stream-mxu",
                 "note": "engine does not serve this shape",
                 "frontier_capacity": None})
        self._rung = "xla"
        return ENG.XlaCarry(ns, nt, P2)

    # -- dispatch ------------------------------------------------------

    def _succ_device(self):
        import jax

        from ..checker import linear_jax as LJ

        ns, nt = self._eng_sizes()
        key = (self.memo.version, ns, nt)
        if self._succ_key != key:
            self._succ_dev = jax.device_put(
                LJ.pad_succ(self.memo.succ, ns, nt))
            self._succ_key = key
            self._table_dev = None
        return self._succ_dev

    def _kernel_table(self):
        import jax.numpy as jnp

        from ..checker import linear_jax as LJ
        from ..checker import pallas_seg as PSEG

        # keyed on memo.version: a new transition interned WITHIN the
        # same pow2 bucket changes table content without any shape
        # event, and a stale table would misdecode its successors.
        # The table packs the BUCKET-padded succ because the kernel's
        # runtime flat-index stride is the rung's declared nt
        # (KernelCarry off[1]) — packing the exact-width memo.succ
        # against a padded stride would misalign every state>0 row.
        key = (self.memo.version, self._eng.ns, self._eng.nt)
        if self._table_dev is None or self._table_key != key:
            spec = self._eng.spec
            padded = LJ.pad_succ(self.memo.succ, self._eng.ns,
                                 self._eng.nt)
            self._table_dev = jnp.asarray(PSEG.pack_table(
                padded, spec.table_rows_pad))
            self._table_key = key
        return self._table_dev

    def _dispatch_range(self, s_lo: int, s_hi: int,
                        collector=None) -> None:
        """Dispatch segments [s_lo, s_hi) against the resident carry,
        bucketed on the delta_pad ladder (one pre-delta snapshot for
        the whole range — escalation re-runs the range). With a
        ``collector`` the delta joins the beat's forming megabatch
        instead (flushed before any joined finalize reads a carry);
        deltas too large for one fused lane dispatch solo."""
        self._eng.begin_delta()
        if collector is not None \
                and self._megabatch_join(collector, s_lo, s_hi):
            return
        self._dispatch_chunks(s_lo, s_hi)

    def _megabatch_join(self, collector, s_lo: int,
                        s_hi: int) -> bool:
        """Park [s_lo, s_hi) as one lane of the beat's megabatch.
        The pack/pad closures run at FLUSH time with the group's pad
        rung — safe because appends to one session serialize through
        the inflight finalize, which flushes the collector before the
        segmenter can advance past this range."""
        n = s_hi - s_lo
        if self._rung == "kernel":
            from ..checker import linear_jax as LJ
            from ..checker import pallas_seg as PSEG

            if n > self._eng.spec.chunk:
                return False            # multi-chunk: solo path

            def pack(dspec):
                ip, it, okp, dp = self.seg.padded(s_lo, s_hi, n,
                                                  dspec.K)
                segs = LJ.SegmentStream(
                    ip, it, okp, self.seg.seg_row.a[s_lo:s_hi], dp)
                return PSEG.pack_segments(segs, dspec)

            collector.add_kernel(self, self._eng, n, pack,
                                 self._kernel_table(), s_lo)
            return True
        if n > ENG.DELTA_PADS[-1]:
            return False                # splits across rungs: solo
        k_pad = self._k_bucket()

        def pad(s_pad):
            return self.seg.padded(s_lo, s_hi, s_pad, k_pad)

        collector.add_delta(self._rung, self, self._eng, n, k_pad,
                            pad, self._succ_device(), s_lo)
        return True

    def _dispatch_chunks(self, s_lo: int, s_hi: int) -> None:
        if self._rung == "kernel":
            from ..checker import linear_jax as LJ
            from ..checker import pallas_seg as PSEG

            # small deltas ride the delta-chunk rungs: same carry
            # geometry, a grid sized to the append instead of the
            # full spec.chunk scan
            spec = PSEG.delta_spec(self._eng.spec, s_hi - s_lo)
            ip, it, okp, dp = self.seg.padded(
                s_lo, s_hi, s_hi - s_lo, spec.K)
            segs = LJ.SegmentStream(ip, it, okp,
                                    self.seg.seg_row.a[s_lo:s_hi], dp)
            chunks = PSEG.pack_segments(segs, spec)
            self._eng.dispatch(self._kernel_table(), chunks, s_lo,
                               spec=spec)
            self.dispatches += chunks.shape[0]
            return
        succ = self._succ_device()
        floor = ENG.MXU_DELTA_FLOOR if self._rung == "mxu" else 0
        k_pad = self._k_bucket()
        pos = s_lo
        while pos < s_hi:
            n = min(s_hi - pos, ENG.DELTA_PADS[-1])
            s_pad = ENG.bucket_delta(n, floor)
            n = min(n, s_pad)
            ip, it, okp, dp = self.seg.padded(pos, pos + n, s_pad,
                                              k_pad)
            self._eng.dispatch(succ, ip, it, okp, dp, pos)
            self.dispatches += 1
            pos += n

    def _finalize_range(self, s_lo: int, s_hi: int) -> None:
        st, fail_seg, n = self._eng.read()
        while st == UNKNOWN:
            if self._eng.escalate():
                # in-place capacity escalation: the pre-delta carry
                # widened, only this append's segments re-run
                self._dispatch_chunks(s_lo, s_hi)
                st, fail_seg, n = self._eng.read()
                continue
            nxt = self._next_rung()
            if nxt is None:
                self._latch(UNKNOWN, fail_seg, n)
                return
            self._reroute(note="frontier overflow", rung=nxt,
                          through=s_hi)
            st, fail_seg, n = self._eng.read()
        self.dispatched_segments = s_hi
        self._latch(st, fail_seg, n)

    def _next_rung(self) -> Optional[str]:
        ns, nt = ENG.pad_sizes(max(self.memo.n_states, 1),
                               max(self.memo.n_transitions, 1))
        if self._rung == "kernel":
            return ("mxu" if ENG.MXU.serves(ns, nt, self.P2)
                    else "xla")
        if self._rung == "xla" \
                and ENG.MXU.serves(ns, nt, self.P2):
            return "mxu"                # 2x the XLA top rung
        return None

    def _reroute(self, note: str, rung: Optional[str] = None,
                 through: Optional[int] = None) -> None:
        """The one O(history) event: rebuild the carry on a new (or
        re-shaped) rung and replay the RETAINED renamed segments.
        Amortized over the session's life; counted + attributed."""
        if self._eng is not None:
            self.engines_tried.append({
                "engine": self._eng.name, "note": note,
                "frontier_capacity": getattr(self._eng, "F", 128)})
        ns, nt = ENG.pad_sizes(max(self.memo.n_states, 1),
                               max(self.memo.n_transitions, 1))
        self._rung = rung or ENG.pick_rung(ns, nt, self.P2,
                                           self.seg.k_max,
                                           self.engine_policy)
        self._succ_key = None
        self._eng = self._make_engine(self._rung, ns, nt, self.P2)
        self.replays += 1
        end = self.dispatched_segments if through is None else through
        with _obs.span("stream.replay", rung=self._rung, through=end):
            pos = 0
            while pos < end:
                n = min(end - pos, ENG.DELTA_PADS[-1])
                self._eng.begin_delta()
                self._dispatch_chunks(pos, pos + n)
                st, _, _ = self._eng.read()
                if st == UNKNOWN:
                    if self._eng.escalate():
                        continue        # same chunk, wider frontier
                    nxt = self._next_rung()
                    if nxt is None:
                        return          # caller's read sees UNKNOWN
                    return self._reroute(note="frontier overflow",
                                         rung=nxt, through=end)
                if st != VALID:
                    return              # caller's read latches it
                pos += n

    # -- verdict -------------------------------------------------------

    def _latched(self) -> bool:
        return self.valid is not True

    def _latch(self, st: int, fail_seg: int, n: int) -> None:
        self.final_count = int(n)
        if st == VALID:
            return
        self.fail_index = (int(self.seg.seg_row.a[fail_seg])
                           if 0 <= fail_seg < self.seg.n_segments
                           else -1)
        if st == INVALID:
            self.valid = False
        else:
            self.valid = "unknown"
            self.cause = (f"frontier overflow (engine="
                          f"{self._eng.name if self._eng else '?'}, "
                          f"capacity="
                          f"{getattr(self._eng, 'F', 128)})")

    def _latch_unknown(self, cause: str) -> None:
        self.valid = "unknown"
        self.cause = cause

    def _verdict_map(self) -> dict:
        out = {
            "valid": self.valid,
            "op_index": self.fail_index,
            "final_count": self.final_count,
            "op_count": len(self.ingest),
            "checked_through": self.ingest.settled,
            "segments": self.seg.n_segments,
            "engine": self._rung or "none",
            "dispatches": self.dispatches,
            "appends": self.appends,
            "replays": self.replays,
        }
        if self._eng is not None:
            out["frontier_capacity"] = getattr(self._eng, "F", 128)
        if self.cause:
            out["cause"] = self.cause
        if self.engines_tried:
            out["engines_tried"] = self.engines_tried
        return out


__all__ = ["StreamSession"]
