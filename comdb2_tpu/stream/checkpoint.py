"""Checkpoint wire codec — JSON-safe encoding of session snapshots.

A :meth:`~.session.StreamSession.checkpoint` is a host-side dict of
numpy arrays, tuples and id tables. Two forms exist:

- the **in-process** form (the dict itself) — what
  :class:`~.manager.SessionManager` retains for eviction-without-
  replay; zero serialization cost.
- the **wire** form (:func:`to_wire` / :func:`from_wire`) — a pure
  JSON document that rides inside the service's newline-JSON protocol
  (``kind:"stream"`` ``verb:"checkpoint"`` replies, open-with-
  checkpoint requests), so a drain/leave handoff moves a session
  between daemons THROUGH the client with no side channel.

The encoding is self-describing and reversible: numpy arrays ship as
base64 ``.npy`` payloads (dtype + shape preserved, ``allow_pickle``
off on both sides), tuples are tagged (EDN ``[k v]`` values parse as
plain tuples and the id tables key on them — a JSON round-trip that
lowered tuples to lists would silently re-intern every keyed value),
and dicts with non-string keys ship as tagged item lists. Everything
here is HOST data — the ``host-numpy-checkpoint`` analysis rule keeps
jnp out of this path.
"""

from __future__ import annotations

import base64
import io
import json
from typing import Any

import numpy as np

_ND, _TU, _DI = "__nd__", "__tu__", "__di__"
_TAGS = (_ND, _TU, _DI)


def _enc_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, a, allow_pickle=False)
    return {_ND: base64.b64encode(buf.getvalue()).decode("ascii")}


def _dec_array(payload: str) -> np.ndarray:
    buf = io.BytesIO(base64.b64decode(payload.encode("ascii")))
    return np.load(buf, allow_pickle=False)


def to_wire(obj: Any) -> Any:
    """Checkpoint dict -> JSON-safe document (see module docstring)."""
    if isinstance(obj, np.ndarray):
        return _enc_array(obj)
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, tuple):
        return {_TU: [to_wire(x) for x in obj]}
    if isinstance(obj, list):
        return [to_wire(x) for x in obj]
    if isinstance(obj, dict):
        if all(isinstance(k, str) and k not in _TAGS for k in obj):
            return {k: to_wire(v) for k, v in obj.items()}
        return {_DI: [[to_wire(k), to_wire(v)]
                      for k, v in obj.items()]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    raise TypeError(
        f"checkpoint value of type {type(obj).__name__} is not "
        "wire-encodable")


def from_wire(obj: Any) -> Any:
    """Inverse of :func:`to_wire` (tuples and non-string dict keys
    come back as the hashables the id tables key on)."""
    if isinstance(obj, dict):
        if _ND in obj:
            return _dec_array(obj[_ND])
        if _TU in obj:
            return tuple(from_wire(x) for x in obj[_TU])
        if _DI in obj:
            return {from_wire(k): from_wire(v) for k, v in obj[_DI]}
        return {k: from_wire(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [from_wire(x) for x in obj]
    return obj


def wire_nbytes(wire: Any) -> int:
    """Size of the encoded document — the ``checkpoint_bytes``
    metric's honest number (what actually crosses the socket)."""
    return len(json.dumps(wire, separators=(",", ":")).encode())


__all__ = ["from_wire", "to_wire", "wire_nbytes"]
