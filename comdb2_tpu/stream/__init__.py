"""Streaming verification sessions — device-resident incremental
checking of live histories (docs/streaming.md).

Every other surface in the repo is post-hoc batch (collect, then
verify — the Jepsen/knossos shape); this subsystem verifies traffic
*as it happens*: a long-lived :class:`StreamSession` owns a
device-resident frontier carry, ``append(ops)`` packs only the delta
as a columnar slice, segments only the new suffix, and dispatches
only the new segments against the resident carry — per-append cost is
O(delta), never O(history). Served as service ``kind:"stream"``
(:mod:`comdb2_tpu.service`) and offline as ``filetest --follow``.
"""

from .ingest import MalformedDelta, StreamIngest
from .manager import SessionLimit, SessionManager
from .segment import StreamSegmenter
from .session import StreamSession

__all__ = ["MalformedDelta", "SessionLimit", "SessionManager",
           "StreamIngest", "StreamSegmenter", "StreamSession"]
