"""Incremental segmentation + slot renaming — the delta form of
``make_segments`` + ``remap_slots``.

Feeds on newly SETTLED row slices (:class:`~.ingest.StreamIngest`):
each settled ok-op closes one segment carrying the invokes since the
previous ok, with two pieces of state carried across deltas —

- the **tail**: settled invokes after the last settled ok. One-shot
  ``make_segments`` drops invokes after the FINAL ok (a pending call
  only adds orders); mid-stream they are simply the next segment's
  prefix, so the tail re-attaches at the front of the next delta's
  first segment and the concatenated segment stream is bit-identical
  to a one-shot segmentation of the full history.
- the **renamer**: ``remap_slots``' sequential lowest-free-slot
  allocation state (open slot per process, free heap, owner rows).
  The assignment is a pure function of the segment sequence, so
  carrying it across deltas reproduces the one-shot renaming
  bit-for-bit — and P_eff (the engines' slot width) grows only when
  the live history's real concurrency does.

Depth bookkeeping (the exact closure-iteration bound per ok) carries
the running pending count the same way. Everything retained here —
the renamed segment stream and the per-segment owner maps — IS the
session's replay/decode source: engine re-routes (kernel overflow,
MXU re-plan) re-dispatch these arrays, and counterexample decode maps
renamed slots back through the owner rows.
"""

from __future__ import annotations

import heapq
from typing import Dict, List

import numpy as np

from ..ops.op import FAIL, INVOKE, OK
from .ingest import StreamIngest, _Grow


class _Grow2:
    """Row-growable, width-widenable 2-D int32 buffer (segments are
    retained for the session's lifetime; K/P widen on demand)."""

    __slots__ = ("_buf", "n", "fill")

    def __init__(self, width: int = 1, fill: int = -1, cap: int = 64):
        self.fill = fill
        self._buf = np.full((cap, max(width, 1)), fill, np.int32)
        self.n = 0

    @property
    def width(self) -> int:
        return self._buf.shape[1]

    def widen(self, width: int) -> None:
        if width > self._buf.shape[1]:
            pad = width - self._buf.shape[1]
            self._buf = np.pad(self._buf, ((0, 0), (0, pad)),
                               constant_values=self.fill)

    def extend(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows, np.int32)
        self.widen(rows.shape[1])
        need = self.n + rows.shape[0]
        if need > self._buf.shape[0]:
            cap = self._buf.shape[0]
            while cap < need:
                cap *= 2
            nb = np.full((cap, self._buf.shape[1]), self.fill,
                         np.int32)
            nb[:self.n] = self._buf[:self.n]
            self._buf = nb
        self._buf[self.n:need, :rows.shape[1]] = rows
        self._buf[self.n:need, rows.shape[1]:] = self.fill
        self.n = need

    @property
    def a(self) -> np.ndarray:
        return self._buf[:self.n]


class StreamSegmenter:
    """See module docstring."""

    def __init__(self) -> None:
        self.pending = 0
        self._tail_proc: List[int] = []
        self._tail_tr: List[int] = []
        # renamer state (remap_slots', carried across deltas)
        self._slot_of: Dict[int, int] = {}
        self._free: List[int] = []
        self._owners: List[int] = []
        self.p_eff = 0
        # retained renamed segment stream
        self.inv_slot = _Grow2(1, fill=-1)
        self.inv_tr = _Grow2(1, fill=0)
        self.ok_slot = _Grow(np.int32)
        self.depth = _Grow(np.int32)
        self.seg_row = _Grow(np.int64)      # segment -> history row
        self.owner_map = _Grow2(1, fill=-1)  # segment -> proc of slot

    @property
    def n_segments(self) -> int:
        return self.ok_slot.n

    @property
    def k_max(self) -> int:
        return max(self.inv_slot.width, 1)

    def feed(self, ing: StreamIngest, lo: int, hi: int):
        """Consume the settled rows ``[lo, hi)``; returns the new
        segment range ``(s_lo, s_hi)``."""
        s_lo = self.n_segments
        if hi <= lo:
            return s_lo, s_lo
        t, proc, trans, fails, pair = ing.settled_slice(lo, hi)
        vinv = (t == INVOKE) & ~fails
        okm = t == OK
        # a completion removes a pending call iff its paired invoke is
        # a NON-FAILING invoke (make_segments' removal flags, resolved
        # through the global pair column — the invoke may sit in an
        # earlier settled batch)
        compm = (okm | (t == FAIL)) & (pair >= 0)
        removal = np.zeros(hi - lo, bool)
        if compm.any():
            prows = pair[compm]
            removal[compm] = ((ing.type.a[prows] == INVOKE)
                              & ~ing.fails.a[prows])
        cv = np.cumsum(vinv)
        cr = np.cumsum(removal)
        ok_idx = np.flatnonzero(okm)
        n_ok = ok_idx.size
        depth = (self.pending + cv[ok_idx]
                 - (cr[ok_idx] - removal[ok_idx])).astype(np.int32)
        self.pending += int(cv[-1] - cr[-1]) if hi > lo else 0
        inv_rows = np.flatnonzero(vinv)
        seg_of = (np.cumsum(okm) - okm)[inv_rows]
        keep = seg_of < n_ok
        if n_ok == 0:
            self._tail_proc.extend(proc[inv_rows].tolist())
            self._tail_tr.extend(trans[inv_rows].tolist())
            return s_lo, s_lo
        # per-segment invoke lists: tail + this slice's invokes, in
        # row order (columnar split; the rename below is the only
        # sequential pass, exactly like remap_slots)
        ip = proc[inv_rows[keep]].tolist()
        it = trans[inv_rows[keep]].tolist()
        bounds = np.searchsorted(seg_of[keep], np.arange(n_ok + 1))
        seg_proc: List[List[int]] = []
        seg_tr: List[List[int]] = []
        for s in range(n_ok):
            a, b = int(bounds[s]), int(bounds[s + 1])
            if s == 0:
                seg_proc.append(self._tail_proc + ip[a:b])
                seg_tr.append(self._tail_tr + it[a:b])
            else:
                seg_proc.append(ip[a:b])
                seg_tr.append(it[a:b])
        # invokes after the slice's last ok become the new tail
        tail_rows = inv_rows[~keep]
        self._tail_proc = proc[tail_rows].tolist()
        self._tail_tr = trans[tail_rows].tolist()
        self._rename(seg_proc, seg_tr, proc[ok_idx].tolist(),
                     depth, (ok_idx + lo).astype(np.int64))
        return s_lo, self.n_segments

    # -- the carried remap_slots loop ----------------------------------

    def _rename(self, seg_proc, seg_tr, ok_procs, depth, rows) -> None:
        """Port of :func:`~comdb2_tpu.checker.linear_jax.remap_slots`
        with persistent allocation state — identical output to the
        one-shot pass over the concatenated segment stream."""
        n_ok = len(ok_procs)
        K_new = max(max((len(s) for s in seg_proc), default=1), 1)
        out_ip = np.full((n_ok, max(K_new, self.inv_slot.width)),
                         -1, np.int32)
        out_it = np.zeros_like(out_ip)
        out_ok = np.empty(n_ok, np.int32)
        owners_rows = []
        for s in range(n_ok):
            for k, p in enumerate(seg_proc[s]):
                if p in self._slot_of:
                    raise ValueError(
                        f"process {p} invokes in segment "
                        f"{self.n_segments + s} while an earlier "
                        "invocation is still open")
                if self._free:
                    sl = heapq.heappop(self._free)
                else:
                    sl = self.p_eff
                    self.p_eff += 1
                    self._owners.append(-1)
                self._slot_of[p] = sl
                self._owners[sl] = p
                out_ip[s, k] = sl
                out_it[s, k] = seg_tr[s][k]
            o = ok_procs[s]
            sl = self._slot_of.pop(o, None)
            if sl is None:
                # ok without an open invocation: any free slot is IDLE
                # in every config — reference one (fresh if none),
                # leaving it free (remap_slots' unmatched-ok branch)
                if self._free:
                    out_ok[s] = self._free[0]
                else:
                    out_ok[s] = self.p_eff
                    self.p_eff += 1
                    self._owners.append(-1)
                    heapq.heappush(self._free, int(out_ok[s]))
            else:
                out_ok[s] = sl
                self._owners[sl] = -1
                heapq.heappush(self._free, sl)
            owners_rows.append(self._owners[:])
        self.inv_slot.extend(out_ip)
        self.inv_tr.extend(out_it)
        self.ok_slot.extend(out_ok)
        self.depth.extend(depth)
        self.seg_row.extend(rows)
        om = np.full((n_ok, max(self.p_eff, 1)), -1, np.int32)
        for s, row in enumerate(owners_rows):
            if row:
                om[s, :len(row)] = row
        self.owner_map.extend(om)

    # -- checkpoint / restore (docs/streaming.md "Checkpoint") ---------

    def checkpoint(self) -> dict:
        """Host snapshot: the carried renamer/tail state plus the
        retained renamed segment stream (the session's replay/decode
        source — without it a restored session could never re-route)."""
        return {
            "pending": int(self.pending),
            "tail_proc": list(self._tail_proc),
            "tail_tr": list(self._tail_tr),
            "slot_of": {int(k): int(v)
                        for k, v in self._slot_of.items()},
            "free": [int(x) for x in self._free],
            "owners": [int(x) for x in self._owners],
            "p_eff": int(self.p_eff),
            "inv_slot": self.inv_slot.a.copy(),
            "inv_tr": self.inv_tr.a.copy(),
            "ok_slot": self.ok_slot.a.copy(),
            "depth": self.depth.a.copy(),
            "seg_row": self.seg_row.a.copy(),
            "owner_map": self.owner_map.a.copy(),
        }

    @classmethod
    def restore(cls, ck: dict) -> "StreamSegmenter":
        seg = cls()
        seg.pending = int(ck["pending"])
        seg._tail_proc = [int(x) for x in ck["tail_proc"]]
        seg._tail_tr = [int(x) for x in ck["tail_tr"]]
        seg._slot_of = {int(k): int(v)
                        for k, v in ck["slot_of"].items()}
        # a copied heap list keeps the heap invariant — no re-heapify
        seg._free = [int(x) for x in ck["free"]]
        seg._owners = [int(x) for x in ck["owners"]]
        seg.p_eff = int(ck["p_eff"])
        for name in ("inv_slot", "inv_tr", "ok_slot", "depth",
                     "seg_row", "owner_map"):
            buf = getattr(seg, name)
            buf.extend(np.asarray(ck[name]).astype(buf.a.dtype))
        return seg

    # -- dispatch views ------------------------------------------------

    def padded(self, s_lo: int, s_hi: int, s_pad: int, k_pad: int):
        """(inv_slot, inv_tr, ok_slot, depth) of segments
        ``[s_lo, s_hi)`` padded to ``(s_pad, k_pad)`` — the delta
        tensors one dispatch consumes (dead segments are ok=-1
        no-ops, exactly the batch path's padding)."""
        n = s_hi - s_lo
        assert n <= s_pad and self.k_max <= k_pad
        ip = np.full((s_pad, k_pad), -1, np.int32)
        it = np.zeros((s_pad, k_pad), np.int32)
        okp = np.full(s_pad, -1, np.int32)
        dp = np.zeros(s_pad, np.int32)
        w = self.inv_slot.width
        ip[:n, :w] = self.inv_slot.a[s_lo:s_hi]
        it[:n, :w] = self.inv_tr.a[s_lo:s_hi]
        okp[:n] = self.ok_slot.a[s_lo:s_hi]
        dp[:n] = self.depth.a[s_lo:s_hi]
        return ip, it, okp, dp


__all__ = ["StreamSegmenter"]
