"""Counterexample rendering for the serializability checker — the
dependency cycle as a ring of txn nodes with typed edges, the
``render-analysis!`` role the linear checker's SVG plays
(``knossos/linear/report.clj``), but over the txn graph."""

from __future__ import annotations

import math
from typing import Optional

_EDGE_COLOR = {"ww": "#1f77b4", "wr": "#2ca02c", "rw": "#d62728",
               "rt": "#7f7f7f", "?": "#000000"}


def _esc(s: str) -> str:
    return (str(s).replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))


def render_cycle(cex: dict, path: Optional[str] = None,
                 size: int = 460) -> str:
    """One SVG: cycle txns on a ring, arrows labeled with edge type
    and key. Returns the SVG text; writes it when ``path`` given."""
    steps = cex["cycle"]
    n = len(steps)
    cx = cy = size / 2
    r = size / 2 - 90
    pos = []
    for i in range(n):
        a = -math.pi / 2 + 2 * math.pi * i / max(n, 1)
        pos.append((cx + r * math.cos(a), cy + r * math.sin(a)))
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{size}" '
        f'height="{size}" font-family="monospace" font-size="11">',
        f'<text x="{cx}" y="18" text-anchor="middle" '
        f'font-size="14">{_esc(cex["class"])} cycle '
        f'({n} txns)</text>',
        '<defs><marker id="arr" markerWidth="8" markerHeight="8" '
        'refX="7" refY="3" orient="auto">'
        '<path d="M0,0 L7,3 L0,6 z"/></marker></defs>',
    ]
    for i, s in enumerate(steps):
        x0, y0 = pos[i]
        x1, y1 = pos[(i + 1) % n]
        dx, dy = x1 - x0, y1 - y0
        d = math.hypot(dx, dy) or 1.0
        # pull endpoints off the node circles
        x0e, y0e = x0 + 24 * dx / d, y0 + 24 * dy / d
        x1e, y1e = x1 - 24 * dx / d, y1 - 24 * dy / d
        e = s["edge"]
        color = _EDGE_COLOR.get(e["type"], "#000")
        parts.append(
            f'<line x1="{x0e:.1f}" y1="{y0e:.1f}" x2="{x1e:.1f}" '
            f'y2="{y1e:.1f}" stroke="{color}" stroke-width="1.5" '
            'marker-end="url(#arr)"/>')
        mx, my = (x0e + x1e) / 2, (y0e + y1e) / 2
        label = e["type"] if e["key"] is None \
            else f'{e["type"]} k={e["key"]}'
        parts.append(
            f'<text x="{mx:.1f}" y="{my - 4:.1f}" fill="{color}" '
            f'text-anchor="middle">{_esc(label)}</text>')
    for i, s in enumerate(steps):
        x, y = pos[i]
        parts.append(
            f'<circle cx="{x:.1f}" cy="{y:.1f}" r="22" fill="#fff" '
            'stroke="#333"/>')
        parts.append(
            f'<text x="{x:.1f}" y="{y + 4:.1f}" '
            f'text-anchor="middle">T{s["txn"]}</text>')
        meta = f'p{s["process"]} {s["status"]}'
        parts.append(
            f'<text x="{x:.1f}" y="{y + 36:.1f}" fill="#555" '
            f'text-anchor="middle">{_esc(meta)}</text>')
    parts.append("</svg>")
    svg = "\n".join(parts)
    if path:
        with open(path, "w") as fh:
            fh.write(svg)
    return svg


__all__ = ["render_cycle"]
