"""Performance graphs: raw latency points, latency quantiles, throughput.

The semantics of ``jepsen/checker/perf.clj`` — same bucketing (latency
quantiles q ∈ {0.5, 0.95, 0.99, 1} over 30 s windows, ``perf.clj:246-260``;
rates over 10 s buckets, ``:293-331``; nemesis activity shading,
``:189-201``) — rendered as native SVG instead of gnuplot PNGs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..ops.op import Op
from .svg import SVG, Axes

TYPE_COLORS = {"ok": "#1a8f3c", "info": "#c28f00", "fail": "#c0392b"}
Q_COLORS = {0.5: "#1a8f3c", 0.95: "#c28f00", 0.99: "#c0392b", 1: "#7d3c98"}
F_SHAPES = ("circle", "square", "diamond")


def nanos_to_secs(t) -> float:
    return t / 1e9


def history_latencies(history: Sequence[Op]) -> List[Tuple[Op, Op]]:
    """Pair invocations with their completions, yielding
    ``(invoke, completion)`` tuples carrying times (the data behind
    ``util/history->latencies``, ``util.clj:553-587``). Unpaired
    invocations are dropped."""
    inflight: Dict = {}
    out = []
    for op in history:
        if op.type == "invoke":
            inflight[op.process] = op
        elif op.process in inflight:
            out.append((inflight.pop(op.process), op))
    return out


def nemesis_intervals(history: Sequence[Op],
                      final_time: Optional[float] = None
                      ) -> List[Tuple[float, float]]:
    """(start, stop) second pairs where the nemesis was active
    (``util.clj:589-606``): starts and stops pair up queue-wise, an
    unmatched start extends to the end of the history."""
    if final_time is None:
        times = [op.time for op in history if op.time is not None]
        final_time = nanos_to_secs(max(times)) if times else 0.0
    starts: List[Op] = []
    pairs: List[Tuple[float, float]] = []
    for op in history:
        if op.process != "nemesis":
            continue
        if op.f == "start":
            starts.append(op)
        elif op.f == "stop" and starts:
            first = starts.pop(0)
            if first.time is not None and op.time is not None:
                pairs.append((nanos_to_secs(first.time),
                              nanos_to_secs(op.time)))
    for op in starts:
        if op.time is not None:
            pairs.append((nanos_to_secs(op.time), final_time))
    return pairs


def bucket_time(dt: float, t: float) -> float:
    """Midpoint of the dt-wide bucket containing t (``perf.clj:15-25``)."""
    return (t // dt) * dt + dt / 2


def quantiles(qs: Sequence[float], xs: Sequence[float]) -> Dict[float, float]:
    """Floor-index quantiles, exactly as ``perf.clj:45-56``."""
    s = sorted(xs)
    if not s:
        return {}
    n = len(s)
    return {q: s[min(n - 1, int(n * q))] for q in qs}


def latencies_to_quantiles(dt: float, qs: Sequence[float],
                           points: Sequence[Tuple[float, float]]
                           ) -> Dict[float, List[Tuple[float, float]]]:
    """Per-window quantile curves from (time, latency) points
    (``perf.clj:58-80``)."""
    buckets: Dict[float, List[float]] = {}
    for t, l in points:
        buckets.setdefault(bucket_time(dt, t), []).append(l)
    out: Dict[float, List[Tuple[float, float]]] = {q: [] for q in qs}
    for bt in sorted(buckets):
        qv = quantiles(qs, buckets[bt])
        for q in qs:
            out[q].append((bt, qv[q]))
    return out


def _latency_points(history) -> Dict[str, Dict[str, List[Tuple[float, float]]]]:
    """f -> completion-type -> [(invoke-time-s, latency-ms)]."""
    out: Dict[str, Dict[str, List[Tuple[float, float]]]] = {}
    for inv, comp in history_latencies(history):
        if inv.time is None or comp.time is None:
            continue
        t = nanos_to_secs(inv.time)
        lat_ms = (comp.time - inv.time) / 1e6
        out.setdefault(str(inv.f), {}).setdefault(comp.type, []) \
           .append((t, max(lat_ms, 1e-3)))
    return out


def _shade_nemesis(svg: SVG, ax: Axes, history):
    for t0, t1 in nemesis_intervals(history):
        x0, x1 = ax.x(t0), ax.x(max(t1, t0))
        svg.rect(x0, ax.mt, max(x1 - x0, 1),
                 svg.height - ax.mt - ax.mb, fill="#000", opacity=0.06)


def _legend(svg: SVG, entries: List[Tuple[str, str]]):
    x = svg.width - 150
    y = 24
    for label, color in entries[:12]:
        svg.rect(x, y - 8, 9, 9, fill=color)
        svg.text(x + 13, y, label, size=9)
        y += 13


def point_graph(test: dict, history: Sequence[Op],
                path: Optional[str] = None) -> str:
    """Raw latency scatter (``perf.clj:220-244``); returns the SVG."""
    data = _latency_points(history)
    pts = [p for by_t in data.values() for ps in by_t.values() for p in ps]
    tmax = max((t for t, _ in pts), default=1.0)
    lmax = max((l for _, l in pts), default=1.0)
    svg = SVG(900, 400)
    ax = Axes(svg, (0, tmax * 1.02), (0.1, lmax * 1.5), log_y=True)
    _shade_nemesis(svg, ax, history)
    ax.frame("Time (s)", "Latency (ms)",
             f"{test.get('name', 'test')} latency")
    legend = []
    for f, by_type in sorted(data.items()):
        for typ, ps in sorted(by_type.items()):
            color = TYPE_COLORS.get(typ, "#555")
            for t, l in ps:
                svg.circle(ax.x(t), ax.y(l), 1.6, fill=color,
                           title=f"{f} {typ} {l:.2f} ms")
            legend.append((f"{f} {typ}", color))
    _legend(svg, legend)
    return _emit(svg, path)


def quantiles_graph(test: dict, history: Sequence[Op],
                    path: Optional[str] = None, dt: float = 30,
                    qs=(0.5, 0.95, 0.99, 1)) -> str:
    """Latency quantile curves per f over dt-second windows
    (``perf.clj:246-291``)."""
    data = _latency_points(history)
    svg = SVG(900, 400)
    all_pts = [p for by_t in data.values() for ps in by_t.values()
               for p in ps]
    tmax = max((t for t, _ in all_pts), default=1.0)
    lmax = max((l for _, l in all_pts), default=1.0)
    ax = Axes(svg, (0, tmax * 1.02), (0.1, lmax * 1.5), log_y=True)
    _shade_nemesis(svg, ax, history)
    ax.frame("Time (s)", "Latency (ms)",
             f"{test.get('name', 'test')} latency quantiles")
    legend = []
    for f, by_type in sorted(data.items()):
        pts = [p for ps in by_type.values() for p in ps]
        curves = latencies_to_quantiles(dt, qs, pts)
        for q in qs:
            color = Q_COLORS.get(q, "#555")
            curve = [(ax.x(t), ax.y(l)) for t, l in curves[q]]
            if curve:
                svg.polyline(curve, stroke=color)
            legend.append((f"{f} q{q}", color))
    _legend(svg, legend)
    return _emit(svg, path)


def rate_graph(test: dict, history: Sequence[Op],
               path: Optional[str] = None, dt: float = 10) -> str:
    """Completion rate by f and type over dt-second buckets, nemesis ops
    excluded (``perf.clj:293-331``)."""
    rates: Dict[Tuple[str, str], Dict[float, float]] = {}
    tmax = 1.0
    for op in history:
        if op.type == "invoke" or not isinstance(op.process, int):
            continue
        if op.time is None:
            continue
        t = nanos_to_secs(op.time)
        tmax = max(tmax, t)
        b = bucket_time(dt, t)
        key = (str(op.f), op.type)
        rates.setdefault(key, {})
        rates[key][b] = rates[key].get(b, 0.0) + 1.0 / dt
    rmax = max((v for m in rates.values() for v in m.values()), default=1.0)
    svg = SVG(900, 400)
    ax = Axes(svg, (0, tmax * 1.02), (0, rmax * 1.2))
    _shade_nemesis(svg, ax, history)
    ax.frame("Time (s)", "Throughput (hz)",
             f"{test.get('name', 'test')} rate")
    legend = []
    for (f, typ), m in sorted(rates.items()):
        color = TYPE_COLORS.get(typ, "#555")
        xs = []
        b = dt / 2
        while b <= tmax + dt / 2:
            xs.append((ax.x(b), ax.y(m.get(b, 0.0))))
            b += dt
        svg.polyline(xs, stroke=color)
        legend.append((f"{f} {typ}", color))
    _legend(svg, legend)
    return _emit(svg, path)


def _emit(svg: SVG, path: Optional[str]) -> str:
    out = svg.render()
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(out)
    return out
