"""Minimal SVG document builder for the reporting layer.

The reference shells out to gnuplot for PNGs (``checker/perf.clj``) and
hand-writes SVG for counterexamples (``knossos/linear/report.clj``); we
render everything as self-contained SVG with no external processes.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple
from xml.sax.saxutils import escape, quoteattr


def _attrs(attrs: dict) -> str:
    return " ".join(f"{k.replace('_', '-')}={quoteattr(str(v))}"
                    for k, v in attrs.items() if v is not None)


class SVG:
    def __init__(self, width: int, height: int):
        self.width = width
        self.height = height
        self.parts: List[str] = []

    def elem(self, tag: str, body: Optional[str] = None, **attrs):
        a = _attrs(attrs)
        if body is None:
            self.parts.append(f"<{tag} {a}/>")
        else:
            self.parts.append(f"<{tag} {a}>{body}</{tag}>")

    def line(self, x1, y1, x2, y2, stroke="#333", width=1, dash=None):
        self.elem("line", x1=round(x1, 2), y1=round(y1, 2),
                  x2=round(x2, 2), y2=round(y2, 2), stroke=stroke,
                  stroke_width=width, stroke_dasharray=dash)

    def rect(self, x, y, w, h, fill="#000", opacity=None, stroke=None,
             title=None):
        body = f"<title>{escape(title)}</title>" if title else None
        self.elem("rect", body, x=round(x, 2), y=round(y, 2),
                  width=round(w, 2), height=round(h, 2), fill=fill,
                  fill_opacity=opacity, stroke=stroke)

    def circle(self, cx, cy, r, fill="#000", title=None):
        body = f"<title>{escape(title)}</title>" if title else None
        self.elem("circle", body, cx=round(cx, 2), cy=round(cy, 2),
                  r=r, fill=fill)

    def text(self, x, y, s, size=11, fill="#111", anchor="start",
             family="monospace"):
        self.elem("text", escape(str(s)), x=round(x, 2), y=round(y, 2),
                  font_size=size, fill=fill, text_anchor=anchor,
                  font_family=family)

    def polyline(self, pts: Sequence[Tuple[float, float]], stroke="#333",
                 width=1.5, title=None, opacity=None, cls=None):
        p = " ".join(f"{round(x, 2)},{round(y, 2)}" for x, y in pts)
        body = f"<title>{escape(title)}</title>" if title else None
        attrs = {"points": p, "fill": "none", "stroke": stroke,
                 "stroke_width": width, "stroke_opacity": opacity}
        if cls:
            attrs["class"] = cls
        self.elem("polyline", body, **attrs)

    def style(self, css: str) -> None:
        """Embed a stylesheet (hover interactivity — the reference's
        counterexample SVGs highlight on hover, ``report.clj:540+``)."""
        self.parts.append(f"<style>{css}</style>")

    def open_group(self, **attrs) -> None:
        self.parts.append(f"<g {_attrs(attrs)}>")

    def close_group(self) -> None:
        self.parts.append("</g>")

    def render(self) -> str:
        return (f'<svg xmlns="http://www.w3.org/2000/svg" '
                f'width="{self.width}" height="{self.height}" '
                f'viewBox="0 0 {self.width} {self.height}">'
                f'<rect width="100%" height="100%" fill="white"/>'
                + "".join(self.parts) + "</svg>")


class Axes:
    """Linear (or log-y) data→pixel mapping with margins and ticks."""

    def __init__(self, svg: SVG, x_range, y_range, margin=(50, 15, 20, 35),
                 log_y: bool = False):
        self.svg = svg
        self.ml, self.mr, self.mt, self.mb = margin
        self.x0, self.x1 = x_range
        self.y0, self.y1 = y_range
        self.log_y = log_y
        if log_y:
            self.y0 = max(self.y0, 1e-9)
            self.y1 = max(self.y1, self.y0 * 10)
        if self.x1 <= self.x0:
            self.x1 = self.x0 + 1
        if self.y1 <= self.y0:
            self.y1 = self.y0 + 1

    def x(self, v) -> float:
        w = self.svg.width - self.ml - self.mr
        return self.ml + w * (v - self.x0) / (self.x1 - self.x0)

    def y(self, v) -> float:
        h = self.svg.height - self.mt - self.mb
        if self.log_y:
            v = max(v, self.y0)
            frac = ((math.log10(v) - math.log10(self.y0))
                    / (math.log10(self.y1) - math.log10(self.y0)))
        else:
            frac = (v - self.y0) / (self.y1 - self.y0)
        return self.svg.height - self.mb - h * frac

    def frame(self, xlabel="", ylabel="", title=""):
        s = self.svg
        s.line(self.ml, s.height - self.mb, s.width - self.mr,
               s.height - self.mb)
        s.line(self.ml, self.mt, self.ml, s.height - self.mb)
        if title:
            s.text(s.width / 2, 14, title, size=13, anchor="middle")
        if xlabel:
            s.text(s.width / 2, s.height - 6, xlabel, anchor="middle")
        if ylabel:
            s.text(12, self.mt - 4, ylabel, size=10)
        for v in self._ticks_x():
            s.line(self.x(v), s.height - self.mb, self.x(v),
                   s.height - self.mb + 4)
            s.text(self.x(v), s.height - self.mb + 16, _fmt(v), size=9,
                   anchor="middle")
        for v in self._ticks_y():
            s.line(self.ml - 4, self.y(v), self.ml, self.y(v))
            s.text(self.ml - 6, self.y(v) + 3, _fmt(v), size=9,
                   anchor="end")

    def _ticks_x(self, n=8):
        return _nice_ticks(self.x0, self.x1, n)

    def _ticks_y(self, n=6):
        if self.log_y:
            lo = math.floor(math.log10(self.y0))
            hi = math.ceil(math.log10(self.y1))
            return [10.0 ** e for e in range(int(lo), int(hi) + 1)]
        return _nice_ticks(self.y0, self.y1, n)


def _fmt(v: float) -> str:
    if v == 0:
        return "0"
    if abs(v) >= 1000 or abs(v) < 0.01:
        return f"{v:.0e}"
    if float(v).is_integer():
        return str(int(v))
    return f"{v:.2g}"


def _nice_ticks(lo: float, hi: float, n: int) -> List[float]:
    span = hi - lo
    if span <= 0:
        return [lo]
    step = 10 ** math.floor(math.log10(span / max(n, 1)))
    for mult in (1, 2, 5, 10):
        if span / (step * mult) <= n:
            step *= mult
            break
    start = math.ceil(lo / step) * step
    out = []
    v = start
    while v <= hi + step * 1e-9:
        out.append(round(v, 10))
        v += step
    return out
