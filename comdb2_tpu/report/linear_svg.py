"""Counterexample SVG for failed linearizability analyses.

The role of ``knossos/linear/report.clj`` (``render-analysis!``,
``report.clj:629``): a process/time grid of the operations surrounding
the point where the frontier died, the crashing op highlighted, and the
surviving frontier's model states at death listed alongside. Rendered on
a rank-based (time-warped) x axis like the reference, so dense regions
stay readable."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ..ops.op import Op
from .svg import SVG

BAR = {"ok": "#B7FFB7", "fail": "#FFD4D5", "info": "#FEFFC1",
       None: "#C1DEFF"}
ROW_H = 22
WINDOW = 40  # ops of context on each side of the failure


def render_analysis(history: Sequence[Op], analysis,
                    path: Optional[str] = None) -> str:
    """``analysis`` is a :class:`comdb2_tpu.checker.linear.Analysis`
    (or any object with ``op_index`` and ``configs``)."""
    ops = list(history)
    fail_at = getattr(analysis, "op_index", None)
    lo = max(0, (fail_at or 0) - WINDOW)
    hi = min(len(ops), (fail_at or 0) + WINDOW)
    window = ops[lo:hi]

    # pair invocations with completions inside the window
    spans = []      # (process, f, value, start-rank, end-rank, type)
    inflight = {}
    for rank, op in enumerate(window):
        if op.type == "invoke":
            inflight[op.process] = (rank, op)
        elif op.process in inflight:
            r0, inv = inflight.pop(op.process)
            spans.append((op.process, inv.f, inv.value, r0, rank, op.type))
    for p, (r0, inv) in inflight.items():
        spans.append((p, inv.f, inv.value, r0, len(window), None))

    procs = sorted({s[0] for s in spans}, key=repr)
    prow = {p: i for i, p in enumerate(procs)}
    n = max(len(window), 1)

    width, left = 980, 90
    lane = (width - left - 240) / n
    height = 60 + ROW_H * max(len(procs), 1) + 16 * 12
    svg = SVG(width, int(height))
    svg.text(width / 2, 16, "linearizability counterexample", size=13,
             anchor="middle")

    for p in procs:
        y = 40 + prow[p] * ROW_H
        svg.text(8, y + ROW_H / 2 + 3, f"proc {p}", size=10)
        svg.line(left, y + ROW_H / 2, width - 240, y + ROW_H / 2,
                 stroke="#eee")

    fail_rank = (fail_at - lo) if fail_at is not None else None
    for (p, f, value, r0, r1, typ) in spans:
        y = 40 + prow[p] * ROW_H + 2
        x0 = left + r0 * lane
        w = max((r1 - r0) * lane, 3)
        crashing = fail_rank is not None and r0 <= fail_rank <= r1 \
            and typ == "ok"
        svg.rect(x0, y, w, ROW_H - 6,
                 fill=BAR.get(typ, "#C1DEFF"),
                 stroke="#c0392b" if crashing else "#999",
                 title=f"{p} {f} {value!r} -> {typ or 'pending'}")
        label = f"{f} {value!r}" if value is not None else str(f)
        svg.text(x0 + 2, y + ROW_H - 10, label[: max(int(w / 6), 4)],
                 size=9)

    if fail_rank is not None:
        x = left + (fail_rank + 0.5) * lane
        svg.line(x, 32, x, 40 + ROW_H * len(procs), stroke="#c0392b",
                 width=1.5, dash="4,3")
        svg.text(x, 30, "frontier died here", size=9, fill="#c0392b",
                 anchor="middle")

    y = 52 + ROW_H * max(len(procs), 1)
    svg.text(left, y, "surviving configs at death:", size=10)
    configs = list(getattr(analysis, "configs", []) or [])[:10]
    for i, cfg in enumerate(configs):
        svg.text(left, y + 14 + 13 * i, f"  {cfg}", size=9, fill="#444")
    if not configs:
        svg.text(left, y + 14, "  (none recorded)", size=9, fill="#444")

    out = svg.render()
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(out)
    return out
