"""Counterexample SVG for failed linearizability analyses.

The role of ``knossos/linear/report.clj`` (``render-analysis!``,
``report.clj:629``): a process/time grid of the operations surrounding
the point where the frontier died, the crashing op highlighted, and the
surviving frontier's model states at death listed alongside. Rendered on
a rank-based (time-warped) x axis like the reference, so dense regions
stay readable.

Failed linearization orders are drawn SPATIALLY (``report.clj:385-647``):
each path is an arrow chain over the time grid, hopping from op bar to
op bar in linearization order with the resulting model state labeled on
each hop and the inconsistent step in red — plus a per-path mini
timeline beneath for paths whose ops fall outside the window."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ..ops.op import Op
from .svg import SVG

BAR = {"ok": "#B7FFB7", "fail": "#FFD4D5", "info": "#FEFFC1",
       None: "#C1DEFF"}
PATH_COLORS = ["#7A4DD8", "#0B7285", "#B8860B", "#C2255C"]
ROW_H = 22
WINDOW = 40  # ops of context on each side of the failure


def render_analysis(history: Sequence[Op], analysis,
                    path: Optional[str] = None) -> str:
    """``analysis`` is a :class:`comdb2_tpu.checker.linear.Analysis`
    (or any object with ``op_index`` and ``configs``)."""
    ops = list(history)
    fail_at = getattr(analysis, "op_index", None)
    lo = max(0, (fail_at or 0) - WINDOW)
    hi = min(len(ops), (fail_at or 0) + WINDOW)
    window = ops[lo:hi]

    # pair invocations with completions inside the window; keep BOTH
    # the invoked and the completed value — final paths describe ops
    # by their back-filled (completed) values, the bar label by the
    # invoked one
    spans = []  # (process, f, inv_value, comp_value, r0, r1, type)
    inflight = {}
    for rank, op in enumerate(window):
        if op.type == "invoke":
            inflight[op.process] = (rank, op)
        elif op.process in inflight:
            r0, inv = inflight.pop(op.process)
            spans.append((op.process, inv.f, inv.value, op.value,
                          r0, rank, op.type))
    for p, (r0, inv) in inflight.items():
        spans.append((p, inv.f, inv.value, inv.value, r0, len(window),
                      None))

    procs = sorted({s[0] for s in spans}, key=repr)
    prow = {p: i for i, p in enumerate(procs)}
    n = max(len(window), 1)

    width, left = 980, 90
    lane = (width - left - 240) / n
    paths = list(_paths_of(analysis))[:4]
    # anchor paths to grid bars up front: anchorable paths draw over
    # the grid, the rest get mini timelines (and size the canvas)
    anchors = _span_anchors(spans, prow, left, lane)
    anchored, rest = [], []
    for p in paths:
        op_steps = [s for s in p
                    if isinstance(s, dict)
                    and isinstance(s.get("op"), dict)]
        pts = [_anchor_for(s, anchors) for s in op_steps]
        if pts and all(pts):
            anchored.append((p, op_steps, pts))
        else:
            rest.append(p)
    rest_lines = _layout_paths(rest, left, width - 30)
    height = (60 + ROW_H * max(len(procs), 1) + 16 * 12
              + (60 + 18 * len(rest_lines) if rest_lines else 20))
    svg = SVG(width, int(height))
    svg.text(width / 2, 16, "linearizability counterexample", size=13,
             anchor="middle")

    for p in procs:
        y = 40 + prow[p] * ROW_H
        svg.text(8, y + ROW_H / 2 + 3, f"proc {p}", size=10)
        svg.line(left, y + ROW_H / 2, width - 240, y + ROW_H / 2,
                 stroke="#eee")

    fail_rank = (fail_at - lo) if fail_at is not None else None
    for (p, f, value, _cv, r0, r1, typ) in spans:
        y = 40 + prow[p] * ROW_H + 2
        x0 = left + r0 * lane
        w = max((r1 - r0) * lane, 3)
        crashing = fail_rank is not None and r0 <= fail_rank <= r1 \
            and typ == "ok"
        svg.rect(x0, y, w, ROW_H - 6,
                 fill=BAR.get(typ, "#C1DEFF"),
                 stroke="#c0392b" if crashing else "#999",
                 title=f"{p} {f} {value!r} -> {typ or 'pending'}")
        label = f"{f} {value!r}" if value is not None else str(f)
        svg.text(x0 + 2, y + ROW_H - 10, label[: max(int(w / 6), 4)],
                 size=9)

    if fail_rank is not None:
        x = left + (fail_rank + 0.5) * lane
        svg.line(x, 32, x, 40 + ROW_H * len(procs), stroke="#c0392b",
                 width=1.5, dash="4,3")
        svg.text(x, 30, "frontier died here", size=9, fill="#c0392b",
                 anchor="middle")

    # --- failed linearization orders, spatially ----------------------
    # (knossos/linear/report.clj:385-647): each path hops across the
    # op bars of the grid in linearization order; every hop is labeled
    # with the model state it produced and the inconsistent step is
    # red. Paths whose ops can't all be anchored to a bar in the
    # window fall back to a per-path mini timeline below.
    overlaid = 0
    for pi, (p, op_steps, pts) in enumerate(anchored):
        color = PATH_COLORS[pi % len(PATH_COLORS)]
        # a path may start with string "prologue" steps describing the
        # entry state ("(state before N returns)")
        prologue = [s for s in p if s not in op_steps]
        overlaid += 1
        prev = None
        for si, (step, (ax, ay)) in enumerate(zip(op_steps, pts)):
            dead = step.get("model") == "inconsistent"
            # nudge per path so overlapping chains stay tellable
            ax += (pi - len(anchored) / 2) * 3
            if prev is None:
                if prologue:
                    # entry state from the prologue, at the first dot
                    svg.text(ax, ay - 9 - 4 * pi,
                             "from " + _state_label(
                                 prologue[-1].get("model")),
                             size=8, fill=color, anchor="middle")
            else:
                px, py_ = prev
                svg.line(px, py_, ax, ay,
                         stroke="#c0392b" if dead else color,
                         width=1.4 if dead else 1.1)
            # the model state this hop produced, beside the dot
            svg.text(ax + 5, ay - 5,
                     _state_label(step.get("model")), size=8,
                     fill="#c0392b" if dead else color)
            svg.circle(ax, ay, 3.4 if dead else 2.6,
                       fill="#c0392b" if dead else color,
                       title=f"{step.get('op')!r} -> "
                             f"{step.get('model')!r}")
            prev = (ax, ay)

    y = 52 + ROW_H * max(len(procs), 1)
    if overlaid:
        svg.text(left, y, f"{overlaid} failed linearization orders "
                          "drawn over the grid — each hop is labeled "
                          "with the model state it produced; the red "
                          "hop made the model inconsistent",
                 size=9, fill="#555")
        y += 14

    svg.text(left, y, "surviving configs at death:", size=10)
    configs = list(getattr(analysis, "configs", []) or [])[:10]
    for i, cfg in enumerate(configs):
        svg.text(left, y + 14 + 13 * i, f"  {cfg}", size=9, fill="#444")
    if not configs:
        svg.text(left, y + 14, "  (none recorded)", size=9, fill="#444")
    y += 20 + 13 * max(len(configs), 1)

    # per-path mini timelines for unanchorable paths
    if rest_lines:
        svg.text(left, y, "failed linearization orders "
                          "(each order dies at the red step):",
                 size=10)
        y += 8
        for li, line in enumerate(rest_lines):
            py = y + 18 * (li + 1)
            for (x, w, label, dead, arrow, title) in line:
                svg.rect(x, py - 11, w, 15,
                         fill="#FFD4D5" if dead else "#EDF3FF",
                         stroke="#c0392b" if dead else "#aab",
                         title=title)
                svg.text(x + 3, py, label, size=9,
                         fill="#c0392b" if dead else "#223")
                if arrow:
                    svg.line(x + w + 2, py - 4, x + w + 11, py - 4,
                             stroke="#888")

    out = svg.render()
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(out)
    return out


def _span_anchors(spans, prow, left: float, lane: float):
    """(process, f, value) -> (x, y) canvas anchor at the CENTER of
    that op's bar in the grid; registered under both the invoked and
    the completed value (final paths use back-filled values). Pending
    (still-open) spans win over completed ones with the same
    signature: final paths linearize pending calls."""
    anchors = {}          # key -> (x, y, was_pending)
    for (p, f, inv_v, comp_v, r0, r1, typ) in spans:
        y = 40 + prow[p] * ROW_H + (ROW_H - 6) / 2 + 2
        x = left + (r0 + r1) / 2 * lane
        for value in {repr(inv_v), repr(comp_v)}:
            key = (repr(p), repr(f), value)
            prev = anchors.get(key)
            # pending beats completed (final paths linearize pending
            # calls); among equals the LATEST occurrence wins — a
            # retried identical op's path step refers to the most
            # recent call, not the first
            if prev is None or typ is None or not prev[2]:
                anchors[key] = (x, y, typ is None)
    return {k: (x, y) for k, (x, y, _) in anchors.items()}


def _anchor_for(step, anchors):
    op_d = step.get("op") if isinstance(step, dict) else None
    if not isinstance(op_d, dict):
        return None
    return anchors.get((repr(op_d.get("process")), repr(op_d.get("f")),
                        repr(op_d.get("value"))))


def _state_label(model) -> str:
    return "⊥" if model == "inconsistent" else str(model)[:18]


def _paths_of(analysis):
    """Final paths from an Analysis (info dict) or a plain mapping."""
    info = getattr(analysis, "info", None)
    if isinstance(info, dict) and info.get("paths"):
        return info["paths"]
    if isinstance(analysis, dict):
        return analysis.get("paths", [])
    return getattr(analysis, "paths", []) or []


def _layout_paths(paths, left: float, right: float):
    """Pre-layout path chips into wrapped display lines. Each line is a
    list of (x, w, label, dead, draw_arrow, title) chips; a path whose
    chips exceed the canvas width continues (indented) on the next
    line."""
    lines = []
    for p in paths:
        line = []
        x = left
        for si, step in enumerate(p):
            op_d = step.get("op")
            model = step.get("model")
            dead = model == "inconsistent"
            label = _step_label(op_d, model)
            w = 7 + 5.2 * len(label)
            if x + w > right and line:      # wrap; keep chip intact
                lines.append(line)
                line = []
                x = left + 24
            arrow = si < len(p) - 1
            line.append((x, w, label, dead, arrow,
                         f"{op_d!r} -> {model!r}"))
            x += w + 14
        if line:
            lines.append(line)
    return lines


def _step_label(op_d, model) -> str:
    if isinstance(op_d, dict):
        op_s = f"{op_d.get('f')} {op_d.get('value')!r}"
    else:
        op_s = str(op_d)
    m_s = "⊥" if model == "inconsistent" else str(model)
    return f"{op_s} → {m_s}"[:46]
