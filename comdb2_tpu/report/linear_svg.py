"""Counterexample SVG for failed linearizability analyses.

The role of ``knossos/linear/report.clj`` (``render-analysis!``,
``report.clj:629``): a process/time grid of the operations surrounding
the point where the frontier died, the crashing op highlighted, and the
surviving frontier's model states at death listed alongside.

The x axis uses the ops' REAL timestamps warped by density
(``warp-time-coordinates``, ``report.clj:385-410``): per unit region
the scale is that region's bar density over the maximum density, and
offsets accumulate — dead stretches of the timeline compress while the
contended region around the failure keeps full resolution. Histories
without timestamps fall back to rank coordinates (uniform density —
the same map with every region at scale 1).

ALL final paths are drawn SPATIALLY (``report.clj:385-647``): each
path is an arrow chain over the time grid, hopping from op bar to op
bar in linearization order with the resulting model state labeled on
each hop and the inconsistent step in red. Segments shared by several
paths are drawn ONCE (the ``merge-lines`` role, ``report.clj:300-351``
— final paths of one frontier share long prefixes, and overdrawing
them N times makes the plot unreadable). Paths whose ops fall outside
the window get per-path mini timelines beneath."""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from ..ops.op import Op
from .svg import SVG

BAR = {"ok": "#B7FFB7", "fail": "#FFD4D5", "info": "#FEFFC1",
       None: "#C1DEFF"}
PATH_COLORS = ["#7A4DD8", "#0B7285", "#B8860B", "#C2255C",
               "#2F9E44", "#E8590C", "#1971C2", "#862E9C"]
ROW_H = 22
WINDOW = 40  # ops of context on each side of the failure


def warp_time_coordinates(span_times, tmin: float, tmax: float,
                          n_buckets: int = 96):
    """Density-warped time map (``report.clj:385-410``): returns
    ``f(t) -> [0, 1]`` monotone over ``[tmin, tmax]``. The axis is cut
    into unit regions; each region's scale is its bar-endpoint density
    over the max density, and offsets accumulate — so empty stretches
    collapse to slivers while the densest region keeps full width.

    ``span_times``: iterable of (process, t0, t1) bar extents (the
    per-process max count per region is the density, like the
    reference's ``coordinate-density``)."""
    if tmax <= tmin:
        return lambda t: 0.0
    unit = (tmax - tmin) / n_buckets
    counts: dict = {}
    for (p, t0, t1) in span_times:
        for t in (t0, t1):
            b = min(int((t - tmin) / unit), n_buckets - 1)
            key = (b, p)
            counts[key] = counts.get(key, 0) + 1
    density = [0] * n_buckets
    for (b, _p), c in counts.items():
        density[b] = max(density[b], c)
    dmax = max(max(density), 1)
    # empty regions keep a QUARTER-bar floor (the reference floors at
    # one bar, report.clj:399 — which barely compresses sparse
    # histories where dmax is 1-2; a smaller floor keeps the map
    # monotone and readable while actually collapsing dead time)
    scales = [max(d, 0.25) / dmax for d in density]
    offsets = [0.0] * (n_buckets + 1)
    for b in range(n_buckets):
        offsets[b + 1] = offsets[b] + scales[b]
    total = offsets[n_buckets] or 1.0

    def f(t: float) -> float:
        x = (t - tmin) / unit
        b = min(max(int(x), 0), n_buckets - 1)
        frac = min(max(x - b, 0.0), 1.0)
        return (offsets[b] + scales[b] * frac) / total

    return f


def render_analysis(history: Sequence[Op], analysis,
                    path: Optional[str] = None) -> str:
    """``analysis`` is a :class:`comdb2_tpu.checker.linear.Analysis`
    (or any object with ``op_index`` and ``configs``)."""
    ops = list(history)
    fail_at = getattr(analysis, "op_index", None)
    lo = max(0, (fail_at or 0) - WINDOW)
    hi = min(len(ops), (fail_at or 0) + WINDOW)
    window = ops[lo:hi]

    # pair invocations with completions inside the window; keep BOTH
    # the invoked and the completed value — final paths describe ops
    # by their back-filled (completed) values, the bar label by the
    # invoked one. Coordinates are REAL op times (density-warped
    # below); rank is the fallback when the history carries none.
    times = [getattr(op, "time", None) for op in window]
    use_time = all(t is not None for t in times) and len(window) > 1 \
        and max(times) > min(times)
    coord = (lambda r: float(times[r])) if use_time else float
    spans = []  # (process, f, inv_value, comp_value, t0, t1, type)
    inflight = {}
    for rank, op in enumerate(window):
        if op.type == "invoke":
            inflight[op.process] = (rank, op)
        elif op.process in inflight:
            r0, inv = inflight.pop(op.process)
            spans.append((op.process, inv.f, inv.value, op.value,
                          coord(r0), coord(rank), op.type))
    end_t = coord(len(window) - 1) if window else 0.0
    for p, (r0, inv) in inflight.items():
        spans.append((p, inv.f, inv.value, inv.value, coord(r0),
                      end_t, None))

    procs = sorted({s[0] for s in spans}, key=repr)
    prow = {p: i for i, p in enumerate(procs)}

    width, left = 980, 90
    plot_w = width - left - 240
    tmin = min((s[4] for s in spans), default=0.0)
    tmax = max((s[5] for s in spans), default=1.0)
    warp = warp_time_coordinates(
        [(s[0], s[4], s[5]) for s in spans], tmin, tmax)

    def X(t: float) -> float:
        return left + warp(t) * plot_w

    paths = list(_paths_of(analysis))
    # anchor paths to grid bars up front: anchorable paths draw over
    # the grid, the rest get mini timelines (and size the canvas)
    anchors = _span_anchors(spans, prow, X)
    anchored, rest = [], []
    for p in paths:
        op_steps = [s for s in p
                    if isinstance(s, dict)
                    and isinstance(s.get("op"), dict)]
        pts = [_anchor_for(s, anchors) for s in op_steps]
        if pts and all(pts):
            anchored.append((p, op_steps, pts))
        else:
            rest.append(p)
    rest_lines = _layout_paths(rest, left, width - 30)
    height = (60 + ROW_H * max(len(procs), 1) + 16 * 12
              + (60 + 18 * len(rest_lines) if rest_lines else 20))
    svg = SVG(width, int(height))
    svg.text(width / 2, 16, "linearizability counterexample", size=13,
             anchor="middle")

    for p in procs:
        y = 40 + prow[p] * ROW_H
        svg.text(8, y + ROW_H / 2 + 3, f"proc {p}", size=10)
        svg.line(left, y + ROW_H / 2, width - 240, y + ROW_H / 2,
                 stroke="#eee")

    fail_t = (coord(fail_at - lo)
              if fail_at is not None and 0 <= fail_at - lo < len(window)
              else None)
    for (p, f, value, _cv, t0, t1, typ) in spans:
        y = 40 + prow[p] * ROW_H + 2
        x0 = X(t0)
        w = max(X(t1) - x0, 3)
        crashing = fail_t is not None and t0 <= fail_t <= t1 \
            and typ == "ok"
        svg.rect(x0, y, w, ROW_H - 6,
                 fill=BAR.get(typ, "#C1DEFF"),
                 stroke="#c0392b" if crashing else "#999",
                 title=f"{p} {f} {value!r} -> {typ or 'pending'}")
        label = f"{f} {value!r}" if value is not None else str(f)
        svg.text(x0 + 2, y + ROW_H - 10, label[: max(int(w / 6), 4)],
                 size=9)

    if fail_t is not None:
        x = X(fail_t)
        svg.line(x, 32, x, 40 + ROW_H * len(procs), stroke="#c0392b",
                 width=1.5, dash="4,3")
        svg.text(x, 30, "frontier died here", size=9, fill="#c0392b",
                 anchor="middle")

    # --- failed linearization orders, spatially ----------------------
    # (knossos/linear/report.clj:385-647): each path hops across the
    # op bars of the grid in linearization order; every hop is labeled
    # with the model state it produced and the inconsistent step is
    # red. Final paths of one frontier share long prefixes, so shared
    # SEGMENTS (same endpoints + same resulting state) draw exactly
    # once — the merge-lines role (report.clj:300-351) — which is what
    # keeps "render ALL paths" readable. Paths whose ops can't all be
    # anchored to a bar in the window fall back to a per-path mini
    # timeline below.
    overlaid = 0
    drawn_segs: set = set()
    drawn_marks: set = set()
    if anchored:
        # hover interactivity (the reference highlights paths on
        # hover, report.clj:540+): each path carries an invisible
        # thick hit-polyline through ALL its anchors; hovering it
        # halos the WHOLE path — which also disambiguates segments
        # that several paths share (drawn once below)
        svg.style(".cpath .hit{stroke-opacity:0}"
                  ".cpath:hover .hit{stroke-opacity:.3}")
    hit_bands = []            # emitted AFTER the visible marks: the
    for pi, (p, op_steps, pts) in enumerate(anchored):
        color = PATH_COLORS[pi % len(PATH_COLORS)]
        if len(pts) >= 2:     # hit band must be topmost or hovering
            order = " -> ".join(  # exactly ON a mark never triggers it
                _step_label(s.get("op"), s.get("model"))
                for s in op_steps)
            hit_bands.append(
                (pts, color, f"linearization order {pi}: {order}"))
        # a path may start with string "prologue" steps describing the
        # entry state ("(state before N returns)")
        prologue = [s for s in p if s not in op_steps]
        overlaid += 1
        prev = None
        for si, (step, (ax, ay)) in enumerate(zip(op_steps, pts)):
            dead = step.get("model") == "inconsistent"
            state = _state_label(step.get("model"))
            if prev is None:
                entry = ("from " + _state_label(
                    prologue[-1].get("model")) if prologue else None)
                ekey = (round(ax), round(ay), entry)
                if entry and ekey not in drawn_marks:
                    # entry state from the prologue, at the first dot;
                    # distinct entry states at the same anchor stack
                    stacked = sum(1 for (mx, my, t) in drawn_marks
                                  if (mx, my) == ekey[:2]
                                  and isinstance(t, str)
                                  and t.startswith("from "))
                    drawn_marks.add(ekey)
                    svg.text(ax, ay - 9 - 9 * stacked, entry,
                             size=8, fill=color, anchor="middle")
            else:
                px, py_ = prev
                seg = (round(px), round(py_), round(ax), round(ay),
                       state)
                if seg not in drawn_segs:
                    drawn_segs.add(seg)
                    svg.line(px, py_, ax, ay,
                             stroke="#c0392b" if dead else color,
                             width=1.4 if dead else 1.1)
            mark = (round(ax), round(ay), state)
            if mark not in drawn_marks:
                drawn_marks.add(mark)
                # the model state this hop produced, beside the dot
                svg.text(ax + 5, ay - 5, state, size=8,
                         fill="#c0392b" if dead else color)
                svg.circle(ax, ay, 3.4 if dead else 2.6,
                           fill="#c0392b" if dead else color,
                           title=f"{step.get('op')!r} -> "
                                 f"{step.get('model')!r}")
            prev = (ax, ay)

    for pts, color, title in hit_bands:
        svg.open_group(**{"class": "cpath"})
        # opacity=0 as a PRESENTATION attribute too: renderers that
        # ignore embedded CSS must not draw a thick opaque band
        # (browser :hover CSS still overrides it)
        svg.polyline(pts, stroke=color, width=7, cls="hit", opacity=0,
                     title=title)
        svg.close_group()

    y = 52 + ROW_H * max(len(procs), 1)
    if overlaid:
        svg.text(left, y, f"{overlaid} failed linearization orders "
                          "drawn over the grid — each hop is labeled "
                          "with the model state it produced; the red "
                          "hop made the model inconsistent",
                 size=9, fill="#555")
        y += 14

    svg.text(left, y, "surviving configs at death:", size=10)
    configs = list(getattr(analysis, "configs", []) or [])[:10]
    for i, cfg in enumerate(configs):
        svg.text(left, y + 14 + 13 * i, f"  {cfg}", size=9, fill="#444")
    if not configs:
        svg.text(left, y + 14, "  (none recorded)", size=9, fill="#444")
    y += 20 + 13 * max(len(configs), 1)

    # per-path mini timelines for unanchorable paths
    if rest_lines:
        svg.text(left, y, "failed linearization orders "
                          "(each order dies at the red step):",
                 size=10)
        y += 8
        for li, line in enumerate(rest_lines):
            py = y + 18 * (li + 1)
            for (x, w, label, dead, arrow, title) in line:
                svg.rect(x, py - 11, w, 15,
                         fill="#FFD4D5" if dead else "#EDF3FF",
                         stroke="#c0392b" if dead else "#aab",
                         title=title)
                svg.text(x + 3, py, label, size=9,
                         fill="#c0392b" if dead else "#223")
                if arrow:
                    svg.line(x + w + 2, py - 4, x + w + 11, py - 4,
                             stroke="#888")

    out = svg.render()
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(out)
    return out


def _span_anchors(spans, prow, X):
    """(process, f, value) -> (x, y) canvas anchor at the CENTER of
    that op's bar in the grid; registered under both the invoked and
    the completed value (final paths use back-filled values). Pending
    (still-open) spans win over completed ones with the same
    signature: final paths linearize pending calls."""
    anchors = {}          # key -> (x, y, was_pending)
    for (p, f, inv_v, comp_v, t0, t1, typ) in spans:
        y = 40 + prow[p] * ROW_H + (ROW_H - 6) / 2 + 2
        x = (X(t0) + X(t1)) / 2
        for value in {repr(inv_v), repr(comp_v)}:
            key = (repr(p), repr(f), value)
            prev = anchors.get(key)
            # pending beats completed (final paths linearize pending
            # calls); among equals the LATEST occurrence wins — a
            # retried identical op's path step refers to the most
            # recent call, not the first
            if prev is None or typ is None or not prev[2]:
                anchors[key] = (x, y, typ is None)
    return {k: (x, y) for k, (x, y, _) in anchors.items()}


def _anchor_for(step, anchors):
    op_d = step.get("op") if isinstance(step, dict) else None
    if not isinstance(op_d, dict):
        return None
    return anchors.get((repr(op_d.get("process")), repr(op_d.get("f")),
                        repr(op_d.get("value"))))


def _state_label(model) -> str:
    return "⊥" if model == "inconsistent" else str(model)[:18]


def _paths_of(analysis):
    """Final paths from an Analysis (info dict) or a plain mapping."""
    info = getattr(analysis, "info", None)
    if isinstance(info, dict) and info.get("paths"):
        return info["paths"]
    if isinstance(analysis, dict):
        return analysis.get("paths", [])
    return getattr(analysis, "paths", []) or []


def _layout_paths(paths, left: float, right: float):
    """Pre-layout path chips into wrapped display lines. Each line is a
    list of (x, w, label, dead, draw_arrow, title) chips; a path whose
    chips exceed the canvas width continues (indented) on the next
    line."""
    lines = []
    for p in paths:
        line = []
        x = left
        for si, step in enumerate(p):
            op_d = step.get("op")
            model = step.get("model")
            dead = model == "inconsistent"
            label = _step_label(op_d, model)
            w = 7 + 5.2 * len(label)
            if x + w > right and line:      # wrap; keep chip intact
                lines.append(line)
                line = []
                x = left + 24
            arrow = si < len(p) - 1
            line.append((x, w, label, dead, arrow,
                         f"{op_d!r} -> {model!r}"))
            x += w + 14
        if line:
            lines.append(line)
    return lines


def _step_label(op_d, model) -> str:
    if isinstance(op_d, dict):
        op_s = f"{op_d.get('f')} {op_d.get('value')!r}"
    else:
        op_s = str(op_d)
    m_s = "⊥" if model == "inconsistent" else str(model)
    return f"{op_s} → {m_s}"[:46]
