"""Reporting/UI layer: latency & rate graphs (``jepsen/checker/perf.clj``),
HTML timelines (``checker/timeline.clj``), and counterexample SVG
(``knossos/linear/report.clj``) — all rendered natively as SVG/HTML,
no gnuplot or external processes.

The graph checkers mirror ``checker.clj``'s ``latency-graph``,
``rate-graph``, and ``perf``."""

from __future__ import annotations

import os
from typing import Optional

from ..checker.checkers import Checker, compose
from . import perf, timeline, linear_svg, txn_svg


def _outdir(test: dict, opts: Optional[dict]) -> Optional[str]:
    base = (opts or {}).get("dir") or test.get("dir")
    if base is None and test.get("name") and test.get("start-time"):
        # default to the test's store directory, like store/path!
        from ..harness import store
        base = store.path(test)
    sub = (opts or {}).get("subdirectory")
    if base is None:
        return None
    return os.path.join(base, sub) if sub else base


class LatencyGraph(Checker):
    """Writes latency-raw.svg and latency-quantiles.svg
    (``checker.clj:288-295``)."""

    def check(self, test, model, history, opts=None):
        d = _outdir(test, opts)
        perf.point_graph(test, history,
                         os.path.join(d, "latency-raw.svg") if d else None)
        perf.quantiles_graph(
            test, history,
            os.path.join(d, "latency-quantiles.svg") if d else None)
        return {"valid?": True}


class RateGraph(Checker):
    """Writes rate.svg (``checker.clj:297-302``)."""

    def check(self, test, model, history, opts=None):
        d = _outdir(test, opts)
        perf.rate_graph(test, history,
                        os.path.join(d, "rate.svg") if d else None)
        return {"valid?": True}


class Timeline(Checker):
    """Writes timeline.html (``timeline.clj:92-111``)."""

    def check(self, test, model, history, opts=None):
        d = _outdir(test, opts)
        timeline.html(test, history,
                      os.path.join(d, "timeline.html") if d else None)
        return {"valid?": True}


def latency_graph() -> LatencyGraph:
    return LatencyGraph()


def rate_graph() -> RateGraph:
    return RateGraph()


def perf_checker():
    """latency + rate graphs composed (``checker.clj:304-308``)."""
    return compose({"latency-graph": latency_graph(),
                    "rate-graph": rate_graph()})


__all__ = ["perf", "timeline", "linear_svg", "latency_graph", "rate_graph",
           "perf_checker", "LatencyGraph", "RateGraph", "Timeline"]
