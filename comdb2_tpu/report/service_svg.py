"""Service-run latency/rate timeline — queue-wait vs device-time.

The ``jepsen.checker.perf``-style graph (:mod:`.perf`) for the
verifier daemon: instead of history ops, the input is the per-request
stage records the service core accumulates
(``VerifierCore.timeline_records()`` — one row per completed request
with the STAGES attribution, plus overload/deadline/degrade event
marks). Each ``dt``-second window renders the MEAN per-stage latency
as a stacked area — queue-wait at the bottom, then host-pack, device,
finalize — so the p99-vs-p50 story is visible at a glance: a fat
queue-wait band is an admission problem, a fat device band is a
dispatch problem. Overload/deadline events draw as vertical markers;
the request rate rides as a scaled overlay line (its peak is printed
in the legend — stage latency owns the y axis).

Written to ``<store>/service/timeline.svg`` by the daemon's artifact
pass and linked from the store web index (:mod:`..harness.web`).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from .svg import SVG, Axes

#: stacking order bottom-up — matches service.core.STAGES
STAGE_ORDER = ("queue_wait_ms", "host_pack_ms", "device_ms",
               "finalize_ms")
STAGE_COLORS = {"queue_wait_ms": "#c28f00", "host_pack_ms": "#2471a3",
                "device_ms": "#1a8f3c", "finalize_ms": "#7d3c98"}
EVENT_COLORS = {"overload": "#c0392b", "deadline": "#e67e22",
                "host_degraded": "#2c3e50", "engine_error": "#c0392b"}
RATE_COLOR = "#555"


def _windows(records: Sequence[dict], dt: float):
    """Window index -> per-stage latency sums + request count."""
    by_w: Dict[int, dict] = {}
    for r in records:
        w = int(r.get("t", 0.0) // dt)
        acc = by_w.setdefault(
            w, {"n": 0, **{s: 0.0 for s in STAGE_ORDER}})
        acc["n"] += 1
        stages = r.get("stages") or {}
        for s in STAGE_ORDER:
            acc[s] += float(stages.get(s, 0.0))
    return by_w


def render_service_timeline(records: Sequence[dict],
                            events: Sequence[dict] = (),
                            path: Optional[str] = None,
                            dt: float = 1.0,
                            title: str = "verifier service") -> str:
    """Render the stacked stage-latency timeline; returns the SVG
    text (and writes it when ``path`` is given)."""
    svg = SVG(900, 400)
    by_w = _windows(records, dt)
    tmax = max([ (w + 1) * dt for w in by_w ]
               + [e.get("t", 0.0) for e in events] + [1.0])
    stacks: Dict[int, List[float]] = {}
    ymax = 1.0
    for w, acc in by_w.items():
        tot, cum = 0.0, []
        for s in STAGE_ORDER:
            tot += acc[s] / max(acc["n"], 1)
            cum.append(tot)
        stacks[w] = cum
        ymax = max(ymax, tot)
    rmax = max([acc["n"] / dt for acc in by_w.values()] + [1.0])
    ax = Axes(svg, (0, tmax * 1.02), (0, ymax * 1.25))
    ax.frame("Time since boot (s)", "Latency (ms, mean per window)",
             f"{title}: per-stage latency + rate")
    ws = sorted(stacks)
    # stacked areas bottom-up: each band is the polygon between the
    # previous cumulative curve and this stage's
    if ws:
        xs = [w * dt + dt / 2 for w in ws]
        prev = [0.0] * len(ws)
        for i, s in enumerate(STAGE_ORDER):
            cur = [stacks[w][i] for w in ws]
            pts = ([(ax.x(x), ax.y(v)) for x, v in zip(xs, cur)]
                   + [(ax.x(x), ax.y(v))
                      for x, v in zip(reversed(xs), reversed(prev))])
            poly = " ".join(f"{round(x, 2)},{round(y, 2)}"
                            for x, y in pts)
            svg.elem("polygon", points=poly, fill=STAGE_COLORS[s],
                     fill_opacity=0.7, stroke="none")
            prev = cur
        # request rate, scaled into the top 40% of the plot (its own
        # unit — the legend carries the peak)
        rate_pts = [(ax.x(x), ax.y(by_w[w]["n"] / dt / rmax
                                   * ymax * 0.4))
                    for x, w in zip(xs, ws)]
        svg.polyline(rate_pts, stroke=RATE_COLOR, width=1.2,
                     title="req/s (scaled)")
    for e in events:
        x = ax.x(e.get("t", 0.0))
        svg.line(x, ax.mt, x, svg.height - ax.mb,
                 stroke=EVENT_COLORS.get(e.get("event"), "#999"),
                 width=1, dash="4,3")
    legend = ([(s.replace("_ms", ""), STAGE_COLORS[s])
               for s in STAGE_ORDER]
              + [(f"req/s (peak {rmax:.1f})", RATE_COLOR)]
              + [(k, c) for k, c in EVENT_COLORS.items()
                 if any(e.get("event") == k for e in events)])
    x0, y0 = svg.width - 170, 24
    for label, color in legend[:10]:
        svg.rect(x0, y0 - 8, 9, 9, fill=color)
        svg.text(x0 + 13, y0, label, size=9)
        y0 += 13
    out = svg.render()
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(out)
    return out


__all__ = ["STAGE_ORDER", "render_service_timeline"]
