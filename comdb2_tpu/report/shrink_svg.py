"""Re-rendered counterexample SVG for shrink artifacts.

The minimal sub-history is tiny by construction, so the render path
re-checks it on the HOST engine (no device round-trip) and reuses the
existing counterexample renderers: the linear failing-window SVG
(:mod:`.linear_svg`) for the linearizability axis, the cycle ring
(:mod:`.txn_svg`) for the txn axis. Returning the re-check verdict
lets callers (``filetest --shrink``, check.sh) assert the artifact is
still INVALID — a minimal.edn that re-checks clean would mean the
minimizer and the checker disagree.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from ..ops.op import Op


def render_minimal(ops: Sequence[Op], *, checker: str = "linear",
                   model: str = "cas-register",
                   realtime: bool = False):
    """Host re-check ``ops`` and render the counterexample SVG.
    Returns ``(valid?, svg_text | None)`` — the SVG is None when the
    re-check found nothing to draw (which callers should treat as a
    minimizer/checker disagreement worth surfacing)."""
    if checker == "txn":
        from ..txn import check_txn
        from . import txn_svg

        res = check_txn(list(ops), backend="host", realtime=realtime)
        cex = res.get("counterexample")
        svg = txn_svg.render_cycle(cex) if cex else None
        return res["valid?"], svg
    from ..checker import linear
    from ..models.model import MODELS
    from . import linear_svg

    a = linear.analysis(MODELS[model](), list(ops), backend="host")
    svg = (linear_svg.render_analysis(list(ops), a)
           if a.valid is False else None)
    return a.valid, svg


def results_map(result, reverified: Optional[Union[bool, str]] = None
                ) -> dict:
    """A :class:`~comdb2_tpu.shrink.core.ShrinkResult` as the
    ``results.edn`` map ``harness.store.save_shrink`` persists."""
    out = {
        "valid?": result.valid,
        "checker": result.checker,
        "seed-ops": result.seed_ops,
        "minimal-ops": result.n_ops,
        "rounds": result.rounds,
        "candidates": result.candidates,
        "dispatches": result.dispatches,
        "one-minimal?": result.one_minimal,
        "partial?": result.partial,
    }
    out.update({k.replace("_", "-"): v
                for k, v in result.extra.items()})
    if reverified is not None:
        out["reverified-valid?"] = reverified
    return out


__all__ = ["render_minimal", "results_map"]
