"""HTML timeline — per-process gantt of ops colored by completion type
(``jepsen/checker/timeline.clj``). Same CSS classes and layout scheme:
one column per process, one row per history index, invoke/ok/fail/info
colors, tooltip with latency."""

from __future__ import annotations

import os
from html import escape
from typing import Dict, List, Optional, Sequence, Tuple

from ..ops.history import complete, index
from ..ops.op import Op

COL_WIDTH = 100
GUTTER_WIDTH = 106
HEIGHT = 16

STYLESHEET = """\
.ops        { position: absolute; }
.op         { position: absolute; padding: 2px; font: 10px monospace; }
.op.invoke  { background: #C1DEFF; }
.op.ok      { background: #B7FFB7; }
.op.fail    { background: #FFD4D5; }
.op.info    { background: #FEFFC1; }
"""


def pairs(history: Sequence[Op]) -> List[Tuple[Op, Optional[Op]]]:
    """[invoke, completion] pairs plus unmatched [info] singletons
    (``timeline.clj:33-52``)."""
    inflight: Dict = {}
    out: List[Tuple[Op, Optional[Op]]] = []
    for op in history:
        if op.type == "invoke":
            inflight[op.process] = op
        elif op.type == "info" and op.process not in inflight:
            out.append((op, None))
        else:
            inv = inflight.pop(op.process, None)
            if inv is not None:
                out.append((inv, op))
    return out


def process_index(history: Sequence[Op]) -> Dict:
    ps = sorted({op.process for op in history}, key=repr)
    return {p: i for i, p in enumerate(ps)}


def _pair_div(n_hist: int, pindex: Dict, start: Op,
              stop: Optional[Op]) -> str:
    op = stop or start
    left = GUTTER_WIDTH * pindex[start.process]
    top = HEIGHT * (start.index or 0)
    if stop is not None and stop.type == "info":
        height = HEIGHT * (n_hist + 1 - (start.index or 0))
    elif stop is not None:
        height = HEIGHT * max((stop.index or 0) - (start.index or 0), 1)
    else:
        height = HEIGHT
    title = ""
    if stop is not None and stop.time is not None and start.time is not None:
        title = f"{(stop.time - start.time) / 1e6:.0f} ms"
    body = escape(f"{op.process} {op.f} {start.value}")
    if stop is not None and stop.value != start.value:
        body += f"<br />{escape(repr(stop.value))}"
    style = (f"width:{COL_WIDTH}px;left:{left}px;top:{top}px;"
             f"height:{height}px")
    return (f'<div class="op {op.type}" style="{style}" '
            f'title="{escape(title)}">{body}</div>')


def html(test: dict, history: Sequence[Op],
         path: Optional[str] = None) -> str:
    """Render the timeline; optionally write it to ``path``
    (``timeline.clj:92-111``)."""
    h = complete(list(history), index=True)
    pindex = process_index(h)
    divs = "\n".join(_pair_div(len(h), pindex, a, b) for a, b in pairs(h))
    doc = (f"<html><head><style>{STYLESHEET}</style></head><body>"
           f"<h1>{escape(str(test.get('name', 'test')))}</h1>"
           f"<p>{escape(str(test.get('start-time', '')))}</p>"
           f'<div class="ops">{divs}</div></body></html>')
    if path:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            fh.write(doc)
    return doc
