"""Network manipulation (``jepsen/net.clj`` + ``jepsen/control/net.clj``).

A ``Net`` cuts, degrades, and heals links between test nodes by driving
iptables / tc on the nodes through the control session bound to the
executing thread."""

from __future__ import annotations

from typing import Optional

from . import exec_, lit, on_nodes, su
from .remote import RemoteError

TC = "/sbin/tc"


def ip_of(host: str) -> str:
    """Resolve a hostname to an IP on the current session's node
    (``control/net.clj:45-53``); bare IPs pass through."""
    if all(c.isdigit() or c == "." for c in host) and host.count(".") == 3:
        return host
    out = exec_("getent", "hosts", host, check=False)
    if out:
        return out.split()[0]
    return host


class Net:
    """Protocol (``net.clj:9-20``)."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        raise NotImplementedError

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, mean_ms: float = 50,
             variance_ms: float = 10, distribution: str = "normal") -> None:
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        raise NotImplementedError


class NoopNet(Net):
    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, mean_ms=50, variance_ms=10, distribution="normal"):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass


noop = NoopNet()


class IptablesNet(Net):
    """Default impl (``net.clj:34-75``): DROP rules for partitions,
    ``tc netem`` for latency/loss."""

    def __init__(self, interface: str = "eth0"):
        self.interface = interface

    def drop(self, test, src, dest):
        # run on dest: drop packets arriving from src
        def _drop(test_, node):
            su("iptables", "-A", "INPUT", "-s", ip_of(src), "-j", "DROP",
               "-w")
        on_nodes(test, _drop, nodes=[dest])

    def heal(self, test):
        def _heal(test_, node):
            su("iptables", "-F", "-w")
            su("iptables", "-X", "-w")
        on_nodes(test, _heal)

    def slow(self, test, mean_ms=50, variance_ms=10, distribution="normal"):
        def _slow(test_, node):
            su(TC, "qdisc", "add", "dev", self.interface, "root", "netem",
               "delay", f"{mean_ms}ms", f"{variance_ms}ms",
               "distribution", distribution)
        on_nodes(test, _slow)

    def flaky(self, test):
        def _flaky(test_, node):
            su(TC, "qdisc", "add", "dev", self.interface, "root", "netem",
               "loss", "20%", "75%")
        on_nodes(test, _flaky)

    def fast(self, test):
        def _fast(test_, node):
            try:
                su(TC, "qdisc", "del", "dev", self.interface, "root")
            except RemoteError as e:
                if "No such file or directory" not in (e.result.err
                                                       + e.result.out):
                    raise
        on_nodes(test, _fast)


iptables = IptablesNet()
