"""Remote execution transports.

The role of clj-ssh in the reference (``control.clj:233-256``): a
``Remote`` executes shell commands on a host and copies files. Three
implementations:

- :class:`SSHRemote` — OpenSSH subprocess (ssh/scp), with connection
  multiplexing and bounded retries on dropped connections (the
  ``reconnect.clj`` role).
- :class:`LocalRemote` — runs commands on the local machine (single-box
  clusters, CI).
- :class:`RecordingRemote` — captures commands and plays scripted
  responses; the harness's unit-test transport.
"""

from __future__ import annotations

import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple


@dataclass
class ExecResult:
    rc: int
    out: str
    err: str

    @property
    def ok(self) -> bool:
        return self.rc == 0


class RemoteError(RuntimeError):
    def __init__(self, cmd: str, result: ExecResult):
        super().__init__(f"command failed ({result.rc}): {cmd}\n"
                         f"stdout: {result.out}\nstderr: {result.err}")
        self.cmd = cmd
        self.result = result


class Remote:
    """Transport protocol: run a shell command string on a host."""

    def execute(self, host: str, cmd: str,
                timeout: Optional[float] = None) -> ExecResult:
        raise NotImplementedError

    def upload(self, host: str, local: str, remote_path: str) -> None:
        raise NotImplementedError

    def download(self, host: str, remote_path: str, local: str) -> None:
        raise NotImplementedError

    def disconnect(self, host: str) -> None:
        pass


class SSHRemote(Remote):
    """OpenSSH subprocess transport. ``ssh_opts`` mirrors the test map's
    ssh credentials (``core.clj:324-340``): username, port,
    private-key-path, strict-host-key-checking."""

    def __init__(self, ssh_opts: Optional[dict] = None, retries: int = 3,
                 retry_delay: float = 1.0):
        self.opts = ssh_opts or {}
        self.retries = retries
        self.retry_delay = retry_delay

    def _base(self, host: str) -> List[str]:
        o = self.opts
        args = ["ssh", "-o", "BatchMode=yes",
                "-o", "ConnectTimeout=10"]
        if not o.get("strict-host-key-checking", False):
            args += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        if o.get("port"):
            args += ["-p", str(o["port"])]
        if o.get("private-key-path"):
            args += ["-i", o["private-key-path"]]
        user = o.get("username")
        args.append(f"{user}@{host}" if user else host)
        return args

    def execute(self, host, cmd, timeout=None):
        last: Optional[ExecResult] = None
        for attempt in range(self.retries):
            try:
                p = subprocess.run(self._base(host) + [cmd],
                                   capture_output=True, text=True,
                                   timeout=timeout)
            except subprocess.TimeoutExpired:
                # the command may have run on the node — never re-send a
                # possibly-applied, non-idempotent command
                return ExecResult(-1, "", f"timeout after {timeout}s")
            res = ExecResult(p.returncode, p.stdout, p.stderr)
            # 255 is ssh's own "connection failed" code — the command
            # never started, safe to retry; anything else is the remote
            # command's exit status
            if res.rc != 255:
                return res
            last = res
            time.sleep(self.retry_delay * (attempt + 1))
        return last or ExecResult(-1, "", "unreachable")

    def _scp_base(self) -> List[str]:
        o = self.opts
        args = ["scp", "-o", "BatchMode=yes"]
        if not o.get("strict-host-key-checking", False):
            args += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        if o.get("port"):
            args += ["-P", str(o["port"])]
        if o.get("private-key-path"):
            args += ["-i", o["private-key-path"]]
        return args

    def _dest(self, host: str, path: str) -> str:
        # scp's remote side word-splits the path through the remote
        # shell — quote it so dirs with spaces/metacharacters survive
        # (the provisioner quotes its execute() lines the same way)
        import shlex

        user = self.opts.get("username")
        q = shlex.quote(path)
        return (f"{user}@{host}:{q}" if user else f"{host}:{q}")

    def upload(self, host, local, remote_path):
        subprocess.run(self._scp_base() + [local,
                                           self._dest(host, remote_path)],
                       check=True, capture_output=True)

    def download(self, host, remote_path, local):
        subprocess.run(self._scp_base() + [self._dest(host, remote_path),
                                           local],
                       check=True, capture_output=True)


class LocalRemote(Remote):
    """Runs everything on the local machine — for single-box SUTs and
    exercising the control stack without a cluster."""

    def execute(self, host, cmd, timeout=None):
        p = subprocess.run(["/bin/sh", "-c", cmd], capture_output=True,
                           text=True, timeout=timeout)
        return ExecResult(p.returncode, p.stdout, p.stderr)

    def upload(self, host, local, remote_path):
        subprocess.run(["cp", local, remote_path], check=True)

    def download(self, host, remote_path, local):
        subprocess.run(["cp", remote_path, local], check=True)


@dataclass
class RecordingRemote(Remote):
    """Test transport: records (host, cmd) pairs; ``responder`` maps a
    command to an ExecResult (default: success, empty output)."""

    responder: Optional[Callable[[str, str], Optional[ExecResult]]] = None
    commands: List[Tuple[str, str]] = field(default_factory=list)
    uploads: List[Tuple[str, str, str]] = field(default_factory=list)
    downloads: List[Tuple[str, str, str]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def execute(self, host, cmd, timeout=None):
        with self._lock:
            self.commands.append((host, cmd))
        if self.responder:
            r = self.responder(host, cmd)
            if r is not None:
                return r
        return ExecResult(0, "", "")

    def upload(self, host, local, remote_path):
        with self._lock:
            self.uploads.append((host, local, remote_path))

    def download(self, host, remote_path, local):
        with self._lock:
            self.downloads.append((host, remote_path, local))
