"""pmux client — service-name → port discovery.

The harness-side counterpart of ``ct_pmux`` (the reference's
``tools/pmux`` role): every host runs one port multiplexer; services
register their port under a name, clients resolve the name instead of
carrying host:port configuration. The native HA client resolves
port-less discovery entries the same way (``sut_tcp.cpp``
``pmux_get_port``); this module is the Python harness's handle on the
same daemon (register workloads' SUTs, resolve cluster layouts,
inspect assignments in tests).
"""

from __future__ import annotations

import socket
from typing import Dict, List, Optional, Tuple

DEFAULT_PORT = 5105


class PmuxClient:
    """One pmux conversation (line protocol; connection per client,
    reused across requests)."""

    def __init__(self, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT, timeout_s: float = 2.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._file = None

    def _conn(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._file = self._sock.makefile("rw")
        return self._file

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._file = None

    def _request(self, line: str) -> str:
        f = self._conn()
        try:
            f.write(line + "\n")
            f.flush()
            reply = f.readline()
        except OSError:
            self.close()
            raise
        if not reply:
            self.close()
            raise OSError("pmux closed the connection")
        return reply.strip()

    # -- commands ------------------------------------------------------

    def get(self, service: str) -> Optional[int]:
        """Port for ``service``, or None when unregistered."""
        r = self._request(f"get {service}")
        try:
            port = int(r.split()[0])
        except (ValueError, IndexError):
            return None
        return port if port > 0 else None

    def reg(self, service: str) -> int:
        """Allocate (or return the existing) port for ``service``."""
        port = int(self._request(f"reg {service}").split()[0])
        if port < 0:
            raise OSError(f"pmux could not allocate a port: {service}")
        return port

    def use(self, service: str, port: int) -> None:
        """Publish a fixed port for ``service``."""
        r = self._request(f"use {service} {port}")
        if not r.startswith("0"):
            raise OSError(f"pmux use failed: {r}")

    def delete(self, service: str) -> bool:
        return self._request(f"del {service}").startswith("0")

    def used(self) -> Dict[str, int]:
        """All assignments, service -> port."""
        f = self._conn()
        out: Dict[str, int] = {}
        # same error contract as _request: a daemon that died since
        # the last call raises OSError here, and the stale socket must
        # be DROPPED so the next call redials instead of failing on
        # the dead connection forever
        try:
            f.write("used\n")
            f.flush()
            while True:
                line = f.readline()
                if not line:
                    # a dropped connection mid-listing must not read
                    # as "fewer services registered"
                    raise OSError(
                        "pmux closed the connection mid-listing")
                if line.strip() == ".":
                    break
                port_s, svc = line.strip().split(" ", 1)
                out[svc] = int(port_s)
        except OSError:
            self.close()
            raise
        return out

    def hello(self) -> bool:
        try:
            return self._request("hello").startswith("0")
        except OSError:
            return False

    def __enter__(self) -> "PmuxClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def resolve_layout(entries: List[Tuple[str, int]], service: str,
                   timeout_s: float = 2.0) -> List[Tuple[str, int]]:
    """Resolve a cluster layout through per-host pmuxes:
    ``entries`` is [(host, pmux_port), ...]; returns
    [(host, service_port), ...]. Raises when any host's pmux doesn't
    know the service — an undiscoverable node is a provisioning
    failure, not a silent cluster shrink."""
    out = []
    for host, pmux_port in entries:
        with PmuxClient(host, pmux_port, timeout_s) as c:
            port = c.get(service)
        if port is None:
            raise OSError(f"pmux at {host}:{pmux_port} does not know "
                          f"{service!r}")
        out.append((host, port))
    return out
