"""Lock-guarded auto-reopening connection wrapper
(``jepsen/reconnect.clj``): wraps any open/close pair; on an error
during use, the connection is torn down and reopened so the next caller
gets a fresh one."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Generic, Optional, TypeVar

T = TypeVar("T")


class Wrapper(Generic[T]):
    def __init__(self, open_fn: Callable[[], T],
                 close_fn: Optional[Callable[[T], None]] = None,
                 name: str = "conn"):
        self.open_fn = open_fn
        self.close_fn = close_fn
        self.name = name
        self._lock = threading.RLock()
        self._conn: Optional[T] = None

    def open(self) -> "Wrapper[T]":
        with self._lock:
            if self._conn is None:
                self._conn = self.open_fn()
        return self

    def close(self) -> None:
        with self._lock:
            if self._conn is not None and self.close_fn is not None:
                try:
                    self.close_fn(self._conn)
                except Exception:
                    pass
            self._conn = None

    def reopen(self) -> None:
        """(``reconnect.clj:60-72``)"""
        with self._lock:
            self.close()
            self.open()

    def with_conn(self, f: Callable[[T], Any]) -> Any:
        """Run ``f(conn)`` under the lock; on failure, tear the
        connection down before re-raising so the next use reopens
        (``reconnect.clj:92-129``)."""
        with self._lock:
            self.open()
            try:
                return f(self._conn)
            except Exception:
                self.close()
                raise

    def with_retry(self, f: Callable[[T], Any], retries: int = 3,
                   delay: float = 0.5) -> Any:
        """with_conn + bounded retries with reopen between attempts
        (the ``control.clj:124-139`` retry-on-dropped-session shape)."""
        last: Exception = RuntimeError("no attempts")
        for attempt in range(retries):
            try:
                return self.with_conn(f)
            except Exception as e:
                last = e
                if attempt < retries - 1:   # no sleep after the last try
                    time.sleep(delay * (attempt + 1))
        raise last


def wrapper(open_fn, close_fn=None, name="conn") -> Wrapper:
    return Wrapper(open_fn, close_fn, name)
