"""Remote helpers (``jepsen/control/util.clj``): file tests, temp dirs,
daemon start/stop, grepkill."""

from __future__ import annotations

from typing import Optional, Sequence

from . import exec_, lit, su
from .remote import RemoteError


def exists(path: str) -> bool:
    """Does a file exist on the current node? (``control/util.clj:11-14``)"""
    from . import _require_session, build_cmd

    s = _require_session()
    return s.execute(build_cmd("test", "-e", path)).ok


def tmp_dir() -> str:
    """Create a fresh remote temp dir (``control/util.clj:26-36``)."""
    return exec_("mktemp", "-d")


def wget(url: str, dest: Optional[str] = None) -> str:
    """Fetch a URL on the node (``control/util.clj:38-55``)."""
    if dest:
        exec_("wget", "-q", "-O", dest, url)
        return dest
    exec_("wget", "-q", url)
    return url.rsplit("/", 1)[-1]


def install_tarball(url: str, dest_dir: str) -> str:
    """Download + unpack a tarball into dest_dir
    (``control/util.clj:57-100``)."""
    su("mkdir", "-p", dest_dir)
    tmp = exec_("mktemp")
    exec_("wget", "-q", "-O", tmp, url)
    su("tar", "-xf", tmp, "-C", dest_dir)
    exec_("rm", "-f", tmp)
    return dest_dir


def grepkill(pattern: str, signal: str = "KILL") -> None:
    """Kill processes matching a pattern (``control/util.clj:120-130``)."""
    su("pkill", f"-{signal}", "-f", pattern, check=False)


def start_daemon(binary: str, *args: str, logfile: str = "/dev/null",
                 pidfile: Optional[str] = None,
                 chdir: Optional[str] = None) -> None:
    """Start a long-running process detached from the session
    (``control/util.clj:132-164``)."""
    from . import build_cmd

    parts = []
    if chdir:
        parts += ["cd", chdir, lit("&&")]
    parts += [lit("nohup"), binary, *args,
              lit(">>"), logfile, lit("2>&1 & echo $!")]
    pid = su(lit(build_cmd(*parts)))
    if pidfile:
        su(lit(build_cmd(lit("echo"), pid, lit(">"), pidfile)))


def stop_daemon(pidfile: str, signal: str = "TERM") -> None:
    """Kill the pid recorded in pidfile (``control/util.clj:166-183``)."""
    su(lit(f"test -e {pidfile} && kill -{signal} $(cat {pidfile}) "
           f"&& rm -f {pidfile}"), check=False)
