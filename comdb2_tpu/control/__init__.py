"""Control plane — remote command execution on test nodes.

The semantics of ``jepsen/control.clj``: a per-thread *session* (host +
transport + sudo/cd context) against which ``exec`` runs shell-escaped
commands (``control.clj:14-24,154``); ``on_nodes`` runs a function on
every node in parallel, each thread bound to that node's session
(``control.clj:310-319``).
"""

from __future__ import annotations

import shlex
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from .remote import (ExecResult, LocalRemote, RecordingRemote, Remote,
                     RemoteError, SSHRemote)

_tls = threading.local()


class Session:
    """A host + transport binding with sudo/cd context
    (``control.clj:14-24``)."""

    def __init__(self, host: str, remote: Remote,
                 sudo: Optional[str] = None, cwd: Optional[str] = None,
                 root: bool = False):
        self.host = host
        self.remote = remote
        self.sudo = sudo
        self.cwd = cwd
        self.root = root    # session already runs as root: su is a no-op

    def wrap(self, cmd: str) -> str:
        """Apply cd and sudo context (``control.clj:82-111``)."""
        if self.cwd:
            cmd = f"cd {shlex.quote(self.cwd)} && {cmd}"
        if self.sudo and not (self.root and self.sudo == "root"):
            cmd = f"sudo -S -u {self.sudo} sh -c {shlex.quote(cmd)}"
        return cmd

    def execute(self, cmd: str, timeout: Optional[float] = None
                ) -> ExecResult:
        return self.remote.execute(self.host, self.wrap(cmd), timeout)


def escape(arg: Any) -> str:
    """Shell-escape one argument (``control.clj:37-80``): sequences
    join with spaces unescaped (pre-built fragments); everything else is
    quoted when needed."""
    if isinstance(arg, (list, tuple)):
        return " ".join(escape(a) for a in arg)
    s = str(arg)
    return shlex.quote(s) if s else "''"


def lit(s: str) -> "Literal":
    """Mark a string as a raw shell fragment (no quoting) — the
    reference's ``c/lit``."""
    return Literal(s)


class Literal(str):
    pass


def build_cmd(*args: Any) -> str:
    return " ".join(a if isinstance(a, Literal) else escape(a)
                    for a in args)


# --- thread-local session binding (the reference's dynamic vars) -----------

def current_session() -> Optional[Session]:
    return getattr(_tls, "session", None)


class _SessionBinding:
    def __init__(self, session: Session):
        self.session = session

    def __enter__(self):
        self.saved = getattr(_tls, "session", None)
        _tls.session = self.session
        return self.session

    def __exit__(self, *exc):
        _tls.session = self.saved


def with_session(session: Session) -> _SessionBinding:
    return _SessionBinding(session)


def on(host: str, remote: Remote, **kw) -> _SessionBinding:
    return with_session(Session(host, remote, **kw))


def _require_session() -> Session:
    s = current_session()
    if s is None:
        raise RuntimeError("no control session bound on this thread; "
                           "use with_session/on/on_nodes")
    return s


def exec_(*args: Any, timeout: Optional[float] = None,
          check: bool = True) -> str:
    """Run a command on the current session; returns trimmed stdout,
    raises :class:`RemoteError` on nonzero exit (``control.clj:154``)."""
    s = _require_session()
    cmd = build_cmd(*args)
    res = s.execute(cmd, timeout=timeout)
    if check and not res.ok:
        raise RemoteError(cmd, res)
    return res.out.strip()


def su(*args: Any, **kw) -> str:
    """exec as root (``control.clj:96-103``)."""
    s = _require_session()
    root = Session(s.host, s.remote, sudo="root", cwd=s.cwd, root=s.root)
    with with_session(root):
        return exec_(*args, **kw)


def upload(local: str, remote_path: str) -> None:
    s = _require_session()
    s.remote.upload(s.host, local, remote_path)


def download(remote_path: str, local: str) -> None:
    s = _require_session()
    s.remote.download(s.host, remote_path, local)


# --- test-map integration ---------------------------------------------------

def make_remote(test: dict) -> Remote:
    """The transport for a test: ``test["remote"]`` if given, else SSH
    configured from ``test["ssh"]``."""
    r = test.get("remote")
    if r is not None:
        return r
    return SSHRemote(test.get("ssh") or {})


def session_for(test: dict, node: str) -> Session:
    sessions: Dict = test.setdefault("sessions", {})
    if node not in sessions:
        remote = make_remote(test)
        root = (test.get("ssh") or {}).get("username") == "root"
        if isinstance(remote, LocalRemote):
            import os
            root = root or os.geteuid() == 0
        sessions[node] = Session(node, remote, root=root)
    return sessions[node]


def on_nodes(test: dict, f: Callable[[dict, str], Any],
             nodes: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """Run ``f(test, node)`` on every node in parallel, each thread
    bound to that node's session; returns {node: result}
    (``control.clj:310-319``)."""
    nodes = list(nodes if nodes is not None else (test.get("nodes") or []))
    results: Dict[str, Any] = {}
    errors: List[BaseException] = []
    lock = threading.Lock()

    def run1(node):
        try:
            with with_session(session_for(test, node)):
                r = f(test, node)
            with lock:
                results[node] = r
        except BaseException as e:
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=run1, args=(n,), daemon=True)
               for n in nodes]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


def on_many(test: dict, nodes: Sequence[str],
            f: Callable[[dict, str], Any]) -> Dict[str, Any]:
    """on_nodes over an explicit node list (``control.clj:300-308``)."""
    return on_nodes(test, f, nodes=nodes)


__all__ = ["Session", "Remote", "SSHRemote", "LocalRemote",
           "RecordingRemote", "RemoteError", "ExecResult",
           "escape", "lit", "build_cmd", "exec_", "su", "upload",
           "download", "with_session", "on", "current_session",
           "session_for", "on_nodes", "on_many", "make_remote"]
