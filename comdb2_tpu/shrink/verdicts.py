"""Batched candidate verdicts — the device half of the minimizer.

Every shrink round produces B candidate sub-histories; testing them is
exactly the batched-``check_batch`` workload, so this module's job is
to keep the per-candidate device cost at "one lane of one dispatch":

- candidates are grouped into **pow2 kept-op buckets** and each bucket
  chunk rides ONE :func:`~comdb2_tpu.checker.batch.check_batch` call
  (batch axis pow2-padded with copies of the first candidate, table
  sizes pow2-floored to the shared parent memo) — the same
  closed-compiled-program-set discipline as the verifier service;
- candidates with no ok-completion are answered VALID without any
  dispatch (nothing ever constrains the frontier — the service's
  trivial path);
- an engine blowup degrades that chunk to UNKNOWN (a non-survivor:
  the minimizer keeps those ops) instead of killing the whole run.

:func:`check_candidate` is the one-candidate-per-dispatch serial
control — it exists for benchmarks and oracles. Driving it from a
production loop is the exact round-trip-bound bug this subsystem
exists to avoid (~100 ms tunnel round-trip per dispatch), and the
``per-item-dispatch`` analysis rule flags it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..checker import linear_jax as LJ
from ..checker.batch import check_batch, pack_batch_masked
from ..models.memo import MemoizedModel
from ..ops.op import OK
from ..ops.packed import PackedHistory
from ..utils import next_pow2

#: smallest pow2 kept-op bucket — tiny endgame candidates share one
#: shape class instead of compiling per size
MIN_BUCKET = 16

#: candidates per dispatch chunk: big enough to amortize the ~100 ms
#: round-trip over a whole ddmin round, small enough that one chunk's
#: host slicing stays below the device time it overlaps
MAX_BATCH = 64


def bucket_of(n_rows: int) -> int:
    """The pow2 kept-op bucket a candidate lands in (floor
    :data:`MIN_BUCKET`)."""
    return next_pow2(max(int(n_rows), 1), MIN_BUCKET)


def check_candidates(parent: PackedHistory, masks: Sequence[np.ndarray],
                     memo: MemoizedModel, *, F: int = 1024,
                     engine: str = "auto", mesh=None,
                     max_batch: int = MAX_BATCH,
                     counters: Optional[dict] = None) -> np.ndarray:
    """Verdict-test B candidate row masks of one packed parent.

    Returns ``int32[B]`` engine statuses (``LJ.VALID`` / ``INVALID`` /
    ``UNKNOWN``) aligned with ``masks``. ONE ``check_batch`` dispatch
    per pow2 shape-bucket chunk; ``counters`` (optional) accumulates
    ``{"dispatches", "candidates"}``.
    """
    masks = [np.asarray(m, bool) for m in masks]
    out = np.full(len(masks), LJ.VALID, np.int32)
    if counters is not None:
        counters["candidates"] = counters.get("candidates", 0) \
            + len(masks)
    ok_rows = np.asarray(parent.type) == OK
    groups: Dict[int, List[int]] = {}
    for i, m in enumerate(masks):
        if not bool((m & ok_rows).any()):
            continue                    # trivially VALID, no dispatch
        groups.setdefault(bucket_of(int(m.sum())), []).append(i)
    ns = next_pow2(memo.n_states)
    nt = next_pow2(memo.n_transitions)
    for _, idxs in sorted(groups.items()):
        for lo in range(0, len(idxs), max_batch):
            chunk = idxs[lo:lo + max_batch]
            cand = [masks[i] for i in chunk]
            b = next_pow2(len(cand))
            cand = cand + [cand[0]] * (b - len(cand))
            try:
                batch = pack_batch_masked(parent, cand, memo)
                status, _, _ = check_batch(
                    batch, F=F, engine=engine, mesh=mesh,
                    n_states_pad=ns, n_transitions_pad=nt)
                out[chunk] = status[:len(chunk)]
            except Exception:           # noqa: BLE001 — engine blowup
                # a candidate shape the engines can't serve is a
                # non-survivor, never a crashed minimization
                out[chunk] = LJ.UNKNOWN
            if counters is not None:
                counters["dispatches"] = counters.get("dispatches",
                                                      0) + 1
    return out


def check_candidate(parent: PackedHistory, mask: np.ndarray,
                    memo: MemoizedModel, **kw) -> int:
    """ONE candidate, one dispatch — the serial control the batched
    path exists to beat (``scripts/bench_shrink.py`` measures the
    gap). Production code must batch a round's candidates through
    :func:`check_candidates` instead; the ``per-item-dispatch``
    analysis rule flags loops over this entry point."""
    return int(check_candidates(parent, [mask], memo, **kw)[0])


__all__ = ["MAX_BATCH", "MIN_BUCKET", "bucket_of", "check_candidate",
           "check_candidates"]
