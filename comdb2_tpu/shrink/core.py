"""Completion-pair-aware ddmin over columnar op tables.

A Jepsen-style fault-window run hands you a 100k-event history and a
bare INVALID; debugging the SUT means finding a *small* sub-history
that still fails. Classic delta debugging (ddmin, Zeller & Hildebrandt
2002) is serial — test one candidate, look at the verdict, pick the
next — but every shrink step here is "check many candidate
sub-histories against one model", i.e. exactly the batched
``check_batch`` workload the columnar ingest made device-bound. So the
minimizer reshapes ddmin the way TPU-KNN reshapes neighbor search:
each round's whole candidate set is generated as **columnar array
slices of one packed parent** (no Op materialization, no re-packing —
:func:`~comdb2_tpu.checker.batch.pack_batch_masked`) and verdict-
tested in ONE dispatch per pow2 shape bucket
(:mod:`comdb2_tpu.shrink.verdicts`).

The drop unit is the **invoke/complete pair**, never a half-op
(a lone completion would desynchronize the per-process alternation
every segment builder checks); pending invokes are single-row atoms,
and ``:info`` ops stay pinned — an indeterminate op can never be
proven irrelevant, and crash-heavy histories keep their slot
pressure. After the ddmin granularity ladder, a greedy single-pair
elimination endgame runs until a full round removes nothing; that
final round doubles as the **1-minimality certificate**: removing any
remaining pair yields VALID/UNKNOWN.

Seeds that are not INVALID are an error, not a loop
(:class:`SeedVerdictError`): shrinking an UNKNOWN could oscillate
forever between capacity-limited verdicts, and shrinking a VALID
history has nothing to preserve.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Union

import numpy as np

from ..checker import linear_jax as LJ
from ..obs import trace as _obs
from ..models.memo import memoize_model, transitions_of
from ..models.model import MODELS, Model
from ..ops.op import INFO, INVOKE, Op
from ..ops.packed import PackedHistory, pack_history
from .verdicts import MAX_BATCH, check_candidates

#: engine status -> the checker tri-state (protocol.STATUS_VALID twin;
#: kept local so shrink doesn't import the service layer)
_STATUS_NAME = {LJ.VALID: True, LJ.INVALID: False, LJ.UNKNOWN: "unknown"}


class SeedVerdictError(ValueError):
    """The history to minimize is not INVALID. ``verdict`` carries the
    tri-state actually observed (True / "unknown")."""

    def __init__(self, verdict, msg: str):
        super().__init__(msg)
        self.verdict = verdict


@dataclass
class ShrinkResult:
    """What the minimizer hands back. ``ops`` is the minimal
    sub-history (re-indexed, materialized at this API edge only);
    ``one_minimal`` is True iff the final greedy round certified that
    removing any remaining atom flips the verdict; ``partial`` marks a
    deadline/round-cap abort (best-so-far, NOT certified)."""

    checker: str
    valid: Union[bool, str]      # False once the seed is confirmed
    ops: List[Op]
    seed_ops: int
    n_ops: int
    rounds: int
    candidates: int
    dispatches: int
    one_minimal: bool
    partial: bool
    extra: dict = field(default_factory=dict)


def atoms_of(packed: PackedHistory):
    """Droppable atoms + pinned rows of a packed history.

    Returns ``(atoms, pinned)``: ``atoms`` is a list of int row-index
    arrays — one per completed invoke/complete pair (2 rows) or lone
    pending invoke (1 row), in invocation order; ``pinned`` is a
    ``bool[n]`` mask of rows every candidate keeps (``:info`` rows and
    their crashed invokes — plus, by construction, nothing else).
    Vectorized over the packed columns; Op objects are never touched.
    """
    n = len(packed)
    t = np.asarray(packed.type)
    proc = np.asarray(packed.process)
    pair = np.asarray(packed.pair)
    pinned = t == INFO
    inv = np.flatnonzero(t == INVOKE)
    paired = inv[pair[inv] >= 0]
    unpaired = inv[pair[inv] < 0]
    if unpaired.size:
        # next same-process row via one stable argsort: an unpaired
        # invoke whose successor is an :info row is a crashed op —
        # pinned with its completion (indeterminate, may have applied)
        order = np.argsort(proc, kind="stable")
        nxt = np.full(n, -1, np.int64)
        same = proc[order][1:] == proc[order][:-1]
        nxt[order[:-1][same]] = order[1:][same]
        has_nxt = nxt[unpaired] >= 0
        crashed = unpaired[has_nxt & (
            t[np.clip(nxt[unpaired], 0, n - 1)] == INFO)]
        pinned[crashed] = True
        pending = unpaired[~np.isin(unpaired, crashed)]
    else:
        pending = unpaired
    atoms = [np.array([i, pair[i]], np.int64) for i in paired.tolist()]
    atoms += [np.array([i], np.int64) for i in pending.tolist()]
    atoms.sort(key=lambda a: int(a[0]))
    return atoms, pinned


def _chunks(ids: List[int], n: int) -> List[List[int]]:
    """``ids`` split into ``n`` near-equal contiguous chunks."""
    out, start = [], 0
    for k in range(n):
        end = start + (len(ids) - start) // (n - k)
        out.append(ids[start:end])
        start = end
    return [c for c in out if c]


class DdminEngine:
    """The shared step-driven phase machine both axes run.

    One :meth:`step` call runs one shrink **round** — a full candidate
    set generated and verdict-tested in one batched dispatch per shape
    bucket — and returns True when minimization is finished. The
    verifier service drives one step per tick (shrink rounds are just
    more bucket traffic); :func:`minimize` loops it with a deadline.

    Phases: ``seed`` (confirm the parent is INVALID at this engine/F —
    anything else sets :attr:`error` to a :class:`SeedVerdictError`)
    -> ``ddmin`` (granularity ladder) -> ``greedy`` (single-atom
    elimination; the final no-op round is the 1-minimality
    certificate) -> ``done``.

    Subclasses provide ``_seed_round()`` (establish ``self.cur`` or
    set ``self.error``/finish) and ``_test(cand_sets) -> bool array``
    ("still INVALID" per candidate atom-id set), plus ``result()``.

    ``round_cap`` bounds the CANDIDATES one round may test — the
    serving tick loop runs one round synchronously, and an uncapped
    greedy round over a mostly-irreducible 10k-op seed is thousands
    of candidates (dozens of ~100 ms dispatches) wedging every other
    request past its deadline. Capped greedy tests a rotating window
    per round and certifies 1-minimality only after a full
    consecutive clean sweep; the fine ddmin ladder hands over to it
    once its candidate sets would exceed the cap. ``None`` (the API
    default) keeps classic whole-round ddmin.
    """

    def __init__(self, round_cap: Optional[int] = None):
        self.cur: List[int] = []
        self.phase = "seed"
        self.gran = 2
        self.rounds = 0
        self.round_cap = round_cap
        self._greedy_pos = 0
        self._greedy_clean = 0
        self.counters = {"dispatches": 0, "candidates": 0}
        self.one_minimal = False
        self.error: Optional[SeedVerdictError] = None

    @property
    def done(self) -> bool:
        return self.phase == "done"

    def step(self) -> bool:
        """Run one round; True when minimization is finished."""
        with _obs.span("shrink.step", phase=self.phase,
                       rounds=self.counters.get("rounds", 0)):
            if self.phase == "seed":
                self._seed_round()
            elif self.phase == "ddmin":
                self._ddmin_round()
            elif self.phase == "greedy":
                self._greedy_round()
        return self.phase == "done"

    def _ddmin_round(self) -> None:
        n = min(self.gran, len(self.cur))
        if self.round_cap is not None and 2 * n > self.round_cap:
            # bounded-tick mode: the fine ladder's candidate sets no
            # longer fit one round's budget — the capped greedy
            # endgame covers the same single-atom eliminations
            self.phase = "greedy"
            self._greedy_round()
            return
        chunks = _chunks(self.cur, n)
        cands = list(chunks)
        if n > 2:                       # at n == 2 each complement IS
            for k in range(len(chunks)):  # the other chunk
                cands.append([a for j, c in enumerate(chunks)
                              for a in c if j != k])
        surv = self._survivors(cands)
        invalid = np.flatnonzero(surv)
        if invalid.size:
            best = min(invalid.tolist(), key=lambda i: len(cands[i]))
            self.cur = cands[best]
            # reduce-to-subset restarts the ladder; reduce-to-
            # complement keeps (n-1) chunks' worth of granularity
            self.gran = 2 if best < len(chunks) else max(n - 1, 2)
        elif n >= len(self.cur):
            self.phase = "greedy"
        else:
            self.gran = min(n * 2, len(self.cur))
        if len(self.cur) <= 1:
            self.phase = "greedy"

    def _greedy_round(self) -> None:
        if not self.cur:
            # a candidate with zero atoms can only be trivially VALID,
            # so an empty cur means the pinned rows alone never fail —
            # nothing left to certify
            self.one_minimal = True
            self.phase = "done"
            return
        n = len(self.cur)
        take = n if self.round_cap is None else min(self.round_cap, n)
        ks = [(self._greedy_pos + i) % n for i in range(take)]
        cands = [self.cur[:k] + self.cur[k + 1:] for k in ks]
        surv = self._survivors(cands)
        invalid = np.flatnonzero(surv)
        if invalid.size:
            # drop ONE atom per round — single removals interact, so
            # anything beyond the first must be re-certified anyway
            k = ks[int(invalid[0])]
            self.cur = self.cur[:k] + self.cur[k + 1:]
            self._greedy_clean = 0
            self._greedy_pos = k % max(len(self.cur), 1)
            return
        # certificate accounting: 1-minimality needs a FULL
        # consecutive clean sweep (every single-atom removal flipped
        # the verdict with no drop in between)
        self._greedy_clean += take
        self._greedy_pos = (self._greedy_pos + take) % n
        if self._greedy_clean >= n:
            self.one_minimal = True
            self.phase = "done"

    def _survivors(self, cand_sets: List[List[int]]) -> np.ndarray:
        """bool[B]: which candidates are still INVALID."""
        self.rounds += 1
        return self._test(cand_sets)

    def _seed_round(self) -> None:          # pragma: no cover
        raise NotImplementedError

    def _test(self, cand_sets):             # pragma: no cover
        raise NotImplementedError


class Shrinker(DdminEngine):
    """Minimizer for the linearizability axis (see
    :class:`DdminEngine` for the phase machine): drop atoms are
    invoke/complete pairs of the packed parent, candidates are
    columnar row masks, and each round's verdicts ride
    :func:`~comdb2_tpu.shrink.verdicts.check_candidates`."""

    checker = "linear"

    def __init__(self, history: Union[Sequence[Op], PackedHistory],
                 model: Union[Model, str, None] = None, *,
                 F: int = 1024, engine: str = "auto", mesh=None,
                 max_states: int = 1 << 20,
                 max_batch: int = MAX_BATCH,
                 round_cap: Optional[int] = None):
        super().__init__(round_cap)
        if isinstance(model, str) or model is None:
            model = MODELS[model or "cas-register"]()
        self.packed = (history if isinstance(history, PackedHistory)
                       else pack_history(list(history)))
        self.F = F
        self.engine = engine
        self.mesh = mesh
        self.max_batch = max_batch
        self.atoms, self.pinned = atoms_of(self.packed)
        n_inv = int(((np.asarray(self.packed.type) == INVOKE)
                     & ~np.asarray(self.packed.fails)).sum())
        # ONE memo serves every round: candidates are row subsets of
        # the parent, so their transitions and invoke counts are
        # bounded by the parent's
        self.memo = memoize_model(model, transitions_of(self.packed),
                                  max_states=max_states,
                                  max_depth=max(n_inv, 1))
        self.cur = list(range(len(self.atoms)))

    # -- candidate plumbing --------------------------------------------

    def mask_of(self, atom_ids: Sequence[int]) -> np.ndarray:
        m = self.pinned.copy()
        if len(atom_ids):
            m[np.concatenate([self.atoms[a] for a in atom_ids])] = True
        return m

    def _statuses(self, cand_sets: List[List[int]]) -> np.ndarray:
        return check_candidates(
            self.packed, [self.mask_of(s) for s in cand_sets],
            self.memo, F=self.F, engine=self.engine, mesh=self.mesh,
            max_batch=self.max_batch, counters=self.counters)

    def _test(self, cand_sets: List[List[int]]) -> np.ndarray:
        return self._statuses(cand_sets) == LJ.INVALID

    # -- the rounds ----------------------------------------------------

    def _seed_round(self) -> None:
        self.rounds += 1
        st = int(self._statuses([self.cur])[0])
        if st != LJ.INVALID:
            v = _STATUS_NAME[st]
            self.error = SeedVerdictError(
                v, f"seed verdict is {v!r} — only INVALID histories "
                   "shrink (an UNKNOWN seed would loop on capacity-"
                   "limited verdicts, a VALID one has nothing to "
                   "preserve)")
            self.phase = "done"
            return
        self.phase = "ddmin" if len(self.cur) >= 2 else "greedy"

    # -- results -------------------------------------------------------

    def result(self, partial: bool = False) -> ShrinkResult:
        from ..ops.columnar import subset_packed

        mask = self.mask_of(self.cur)
        sub = subset_packed(self.packed, mask)
        return ShrinkResult(
            checker=self.checker,
            valid=(False if self.phase != "seed"
                   and self.error is None else "unknown"),
            ops=sub.ops,                 # API edge: re-indexed Op list
            seed_ops=len(self.packed), n_ops=len(sub),
            rounds=self.rounds,
            candidates=self.counters["candidates"],
            dispatches=self.counters["dispatches"],
            one_minimal=self.one_minimal and not partial,
            partial=partial)


def minimize(history, *, checker: str = "linear",
             model: Union[Model, str, None] = None,
             realtime: bool = False, F: int = 1024,
             engine: str = "auto", mesh=None,
             max_states: int = 1 << 20,
             deadline_s: Optional[float] = None,
             max_rounds: int = 100_000) -> ShrinkResult:
    """Minimize an INVALID history to a 1-minimal sub-history.

    ``checker="linear"`` runs completion-pair ddmin against ``model``
    (name or instance, default cas-register); ``checker="txn"`` runs
    txn-granularity minimal-cycle shrink over the dependency graph
    (:class:`~comdb2_tpu.shrink.txn.TxnShrinker`). Raises
    :class:`SeedVerdictError` when the seed is VALID or UNKNOWN.
    ``deadline_s`` returns best-so-far flagged ``partial`` instead of
    running to the certificate.
    """
    if checker == "txn":
        from .txn import TxnShrinker

        job = TxnShrinker(history, realtime=realtime, mesh=mesh)
    elif checker == "linear":
        job = Shrinker(history, model, F=F, engine=engine, mesh=mesh,
                       max_states=max_states)
    else:
        raise ValueError(f"no shrinker for checker {checker!r}")
    t0 = _obs.monotonic()
    while not job.step():
        if deadline_s is not None \
                and _obs.monotonic() - t0 >= deadline_s:
            return job.result(partial=True)
        if job.rounds >= max_rounds:
            return job.result(partial=True)
    if job.error is not None:
        raise job.error
    return job.result()


__all__ = ["DdminEngine", "SeedVerdictError", "ShrinkResult",
           "Shrinker", "atoms_of", "minimize"]
