"""Txn-granularity shrink — minimal dependency cycles on the MXU.

For serializability violations the natural drop unit is the whole
transaction, and the evidence is the inferred dependency graph: a
cycle among kept txns survives a restriction exactly when every txn on
it is kept, so "is this candidate still invalid" is "is the sliced
sub-adjacency still cyclic" — a batched
:func:`~comdb2_tpu.txn.closure_jax.closure_diag_batch` call, one
dispatch per pow2-N bucket, exactly the service txn kind's shape
discipline. Edges are inferred ONCE from the full history (real
evidence); candidates never re-run the host inference pass.

The decoded counterexample cycle seeds the search (restricting to its
txns provably preserves the cycle), the ddmin ladder + greedy endgame
then strip chords and shortcut sub-cycles, and the final greedy round
certifies 1-minimality: removing any remaining txn leaves the
subgraph acyclic.

Invalid-but-acyclic seeds (direct anomalies only — G1a, duplicates)
have no cycle to minimize: the anomaly records already name the
culprit txns, so the shrinker answers immediately with those, flagged
NOT 1-minimal-certified.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..ops.op import Op
from ..txn.check import verdict_map
from ..txn.counterexample import LAYER_CLASS, decode
from ..txn.edges import READ, TXN_N_FLOOR, TxnGraph, infer_edges
from ..utils import next_pow2
from .core import DdminEngine, SeedVerdictError, ShrinkResult


class TxnShrinker(DdminEngine):
    """Step-driven minimal-cycle shrinker (see module docstring and
    :class:`~comdb2_tpu.shrink.core.DdminEngine`). Atom ids are node
    ids of the inferred :class:`~comdb2_tpu.txn.edges.TxnGraph`."""

    checker = "txn"

    def __init__(self, history: Sequence[Op] = (), *,
                 realtime: bool = False,
                 graph: Optional[TxnGraph] = None,
                 max_batch: int = 64,
                 round_cap: Optional[int] = None,
                 mesh=None):
        super().__init__(round_cap)
        self.ops_list = list(history)
        self.realtime = realtime
        self.graph = graph if graph is not None \
            else infer_edges(self.ops_list, realtime=realtime)
        self.max_batch = max_batch
        self.mesh = mesh
        self.extra: dict = {}

    # -- candidate plumbing --------------------------------------------

    def _sub_adj(self, ids: List[int], n_pad: int) -> np.ndarray:
        idx = np.asarray(ids, np.int64)
        sub = self.graph.adj[:, idx[:, None], idx[None, :]]
        if not self.realtime:
            sub = sub.copy()
            sub[3] = False
        out = np.zeros((sub.shape[0], n_pad, n_pad), bool)
        out[:, :len(ids), :len(ids)] = sub
        return out

    def _test(self, cand_sets: List[List[int]]) -> np.ndarray:
        """bool[B]: candidate txn subsets whose restricted dependency
        subgraph is still cyclic. ONE ``closure_diag_batch`` dispatch
        per pow2-N bucket chunk (batch axis pow2-padded with copies)
        — never a per-candidate ``closure_diag`` loop."""
        from ..txn.closure_jax import closure_diag_batch

        out = np.zeros(len(cand_sets), bool)
        self.counters["candidates"] = (
            self.counters.get("candidates", 0) + len(cand_sets))
        groups: dict = {}
        for i, ids in enumerate(cand_sets):
            if len(ids) < 2:
                continue   # self-edges never enter the graph: acyclic
            groups.setdefault(
                next_pow2(len(ids), TXN_N_FLOOR), []).append(i)
        for n_pad, idxs in sorted(groups.items()):
            for lo in range(0, len(idxs), self.max_batch):
                chunk = idxs[lo:lo + self.max_batch]
                adjs = [self._sub_adj(cand_sets[i], n_pad)
                        for i in chunk]
                b = next_pow2(len(adjs))
                adjs = adjs + [adjs[0]] * (b - len(adjs))
                diag = closure_diag_batch(np.stack(adjs),
                                          mesh=self.mesh)
                out[chunk] = np.asarray(diag)[:len(chunk)].any(
                    axis=(1, 2))
                self.counters["dispatches"] = (
                    self.counters.get("dispatches", 0) + 1)
        return out

    # -- the rounds ----------------------------------------------------

    def _seed_round(self) -> None:
        from ..txn.closure_jax import closure_diag_batch

        self.rounds += 1
        g = self.graph
        cex = None
        if g.n and g.adj.any():
            adj = g.padded()
            if not self.realtime:
                adj = adj.copy()
                adj[3] = False
            diag = closure_diag_batch(adj[None])[0]
            self.counters["dispatches"] += 1
            cex = decode(g, np.asarray(diag)[:, :g.n],
                         realtime=self.realtime)
        verdict = verdict_map(g, cex)["valid?"]
        if verdict is not False:
            self.error = SeedVerdictError(
                verdict, f"seed verdict is {verdict!r} — only INVALID "
                         "histories shrink")
            self.phase = "done"
            return
        if cex is None:
            # invalid via direct anomalies alone (G1a, duplicates,
            # unexpected-value): no cycle to minimize — the anomaly
            # records already name the culprits
            self.cur = sorted(self._anomaly_nodes())
            self.extra["note"] = ("direct-anomaly seed: no dependency "
                                  "cycle to minimize")
            self.extra["anomalies"] = [
                a["name"] for a in g.anomalies if a["name"] != "malformed"]
            self.phase = "done"
            return
        self.extra["seed_class"] = cex["class"]
        self.cur = sorted({s["txn"] for s in cex["cycle"]})
        self.phase = "ddmin" if len(self.cur) > 2 else "greedy"

    def _anomaly_nodes(self) -> set:
        """Best-effort node ids referenced by the direct anomalies
        (their txn fields mix node ids and original history indices;
        resolve through ``Txn.index`` first, raw node id second)."""
        g = self.graph
        by_orig = {t.index: j for j, t in enumerate(g.txns)}
        nodes: set = set()
        for a in g.anomalies:
            if a["name"] == "malformed":
                continue
            refs = []
            if isinstance(a.get("txn"), int):
                refs.append(a["txn"])
            refs += [x for x in a.get("txns", ()) if isinstance(x, int)]
            for x in refs:
                if x in by_orig:
                    nodes.add(by_orig[x])
                elif 0 <= x < g.n:
                    nodes.add(x)
        return nodes or set(range(g.n))

    # -- results -------------------------------------------------------

    def _evidence_txns(self) -> List[int]:
        """Reader txns whose observations SUPPLY the kept cycle's
        edges. The dependency evidence of a list-append graph lives in
        reads — each key's version order is recovered from its longest
        committed read — and that reader need not sit ON the cycle
        (e.g. a final audit read). Without it the emitted sub-history
        would re-check VALID standalone. One txn per cycle-edge key
        (the longest reader), so the addition is bounded by the
        cycle's key count; kept txns that already carry the read add
        nothing."""
        kept = set(self.cur)
        keys = set()
        for a in self.cur:
            for b in self.cur:
                if a != b:
                    for _plane, key in self.graph.labels.get((a, b),
                                                             ()):
                        if key is not None:
                            keys.add(key)
        out = set()
        for k in keys:
            order = tuple(self.graph.orders.get(k, ()))
            if not order:
                continue
            for j, t in enumerate(self.graph.txns):
                if t.status != "ok":
                    continue
                if any(f == READ and mk == k and v is not None
                       and tuple(v) == order
                       for f, mk, v in t.mops):
                    if j not in kept:
                        out.add(j)
                    break
        return sorted(out)

    def _final_class(self) -> Optional[str]:
        """Adya class of the minimal subgraph (smallest cyclic layer,
        host-side — the set is tiny by now)."""
        if len(self.cur) < 2:
            return None
        from ..txn.scc import cyclic_layers_host

        idx = np.asarray(self.cur, np.int64)
        sub = self.graph.adj[:, idx[:, None], idx[None, :]]
        diag = cyclic_layers_host(sub, realtime=self.realtime)
        for i in range(3):
            if diag[i].any():
                return LAYER_CLASS[i]
        return None

    def result(self, partial: bool = False) -> ShrinkResult:
        g = self.graph
        evidence = ([] if self.error is not None
                    else self._evidence_txns())
        rows: List[int] = []
        for j in list(self.cur) + evidence:
            t = g.txns[j]
            for at in (t.invoke_at, t.complete_at):
                if at is not None and 0 <= at < len(self.ops_list):
                    rows.append(at)
        rows = sorted(set(rows))
        ops = [self.ops_list[i].with_(index=k)
               for k, i in enumerate(rows)]
        extra = dict(self.extra)
        # `txns` is the 1-minimal CYCLE set (what the certificate
        # covers); `evidence_txns` are the reader txns included in the
        # emitted ops so minimal.edn re-checks INVALID standalone
        extra["txns"] = list(self.cur)
        if evidence:
            extra["evidence_txns"] = evidence
        cls = self._final_class()
        if cls is not None:
            extra["anomaly_class"] = cls
        return ShrinkResult(
            checker=self.checker,
            valid=(False if self.phase != "seed"
                   and self.error is None else "unknown"),
            ops=ops,
            seed_ops=len(self.ops_list) or g.n,
            n_ops=len(ops) or len(self.cur),
            rounds=self.rounds,
            candidates=self.counters["candidates"],
            dispatches=self.counters["dispatches"],
            one_minimal=self.one_minimal and not partial,
            partial=partial, extra=extra)


__all__ = ["TxnShrinker"]
