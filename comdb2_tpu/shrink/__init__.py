"""TPU-batched counterexample minimization (delta debugging as a
device workload).

``minimize(history, checker=...)`` takes an INVALID history and
returns a **1-minimal** sub-history: removing any remaining
invoke/complete pair (linearizability axis) or transaction (txn axis)
yields VALID/UNKNOWN. Each ddmin round's candidate set is generated as
columnar array slices of one packed parent and verdict-tested in ONE
device dispatch per pow2 shape bucket — see ``docs/shrink.md``.

Surfaces: this API, ``python -m comdb2_tpu.filetest --shrink`` (store
artifacts: ``minimal.edn`` + re-rendered SVG), and the verifier
service's ``kind: "shrink"`` request.
"""

from .core import (DdminEngine, SeedVerdictError, ShrinkResult,
                   Shrinker, atoms_of, minimize)
from .txn import TxnShrinker
from .verdicts import check_candidate, check_candidates

__all__ = ["DdminEngine", "SeedVerdictError", "ShrinkResult",
           "Shrinker", "TxnShrinker", "atoms_of", "check_candidate",
           "check_candidates", "minimize"]
