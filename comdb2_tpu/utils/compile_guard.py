"""Runtime compile-surface guard.

The static inventory (:mod:`comdb2_tpu.analysis.compile_surface`)
declares the CLOSED program set every serving surface may compile;
this module observes what actually compiles so a recompile storm is a
red test (or a failed bench run), not a 38-minute mystery:

- :class:`CompileGuard` captures one :class:`CompileRecord` per XLA
  LOWERING — jax logs ``Compiling <name> with global shapes ...`` per
  distinct (function, shape signature) when ``jax_log_compiles`` is
  on; lowerings are the right unit because a shape-churned workload
  re-lowers even when the persistent program cache absorbs the
  backend compile.
- Module counters mirror the ``DISPATCHES``-style dispatch counters:
  ``XLA_COMPILES`` here, ``pallas_seg.MOSAIC_BUILDS`` (one per fused-
  kernel program built — a Mosaic compile per distinct
  ``(SegKernelSpec, b_pad, stream)``), ``closure_jax.COMPILES`` (one
  per txn closure N-bucket program).
- :func:`CompileGuard.offenders` / :func:`assert_closed` check the
  observed set against the static inventory — tier-1 runs a
  mixed-shape workload under the guard and asserts observed ⊆
  declared; ``bench.py`` and the bench scripts do the same on real
  runs (env ``COMDB2_TPU_COMPILE_GUARD=0`` disables the bench
  assertion, never the capture).

Usage::

    from comdb2_tpu.utils import compile_guard
    with compile_guard.guard() as g:
        ...                       # any checker/service/shrink work
    g.assert_closed()             # raises CompileSurfaceError

Single-threaded by design (this container exposes ONE CPU and the
service core is single-threaded); nested guards each see their own
window of records.
"""

from __future__ import annotations

import logging
import os
import re
from contextlib import contextmanager
from dataclasses import dataclass
from typing import List, Optional, Tuple

#: process-global lowering counter (mirrors txn.closure_jax.DISPATCHES)
XLA_COMPILES = 0

#: active guards, outermost first — only the outermost increments the
#: global counter (with nested guards every attached handler sees
#: every log record; per-guard records stay per-window)
_ACTIVE: list = []

#: jax logger that emits the per-lowering line
_COMPILE_LOGGER = "jax._src.interpreters.pxla"
#: with jax_log_compiles on, this logger also chats per trace at
#: WARNING; attach the (non-parsing) handler there too so logging's
#: last-resort stderr handler stays quiet during the guard window
_NOISY_LOGGERS = ("jax._src.dispatch",)

_COMPILE_RE = re.compile(
    r"Compiling (.+?) with global shapes and types \[(.*)\]",
    re.DOTALL)
_SHAPED_RE = re.compile(r"ShapedArray\((\w+)\[([0-9,\s]*)\]")


@dataclass(frozen=True)
class CompileRecord:
    """One observed XLA lowering: jit name + traced arg shapes."""

    name: str
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[str, ...]

    def format(self) -> str:
        args = ", ".join(
            f"{dt}[{','.join(map(str, sh))}]"
            for dt, sh in zip(self.dtypes, self.shapes))
        return f"{self.name}({args})"


def parse_compile_log(message: str) -> Optional[CompileRecord]:
    """Parse one ``Compiling <name> with global shapes and types
    [...]`` log message (None for other messages)."""
    m = _COMPILE_RE.search(message)
    if not m:
        return None
    name = m.group(1)
    shapes: List[Tuple[int, ...]] = []
    dtypes: List[str] = []
    for dm in _SHAPED_RE.finditer(m.group(2)):
        dtypes.append(dm.group(1))
        dims = dm.group(2).strip()
        shapes.append(tuple(int(d) for d in dims.split(","))
                      if dims else ())
    return CompileRecord(name=name, shapes=tuple(shapes),
                         dtypes=tuple(dtypes))


class CompileSurfaceError(AssertionError):
    """Observed compiles escaped the declared static inventory."""


class CompileGuard(logging.Handler):
    """Captures every XLA lowering in its window as a
    :class:`CompileRecord`. A ``logging.Handler`` attached to jax's
    lowering logger — attaching a handler also keeps the records off
    stderr (logging's last-resort handler only fires when NO handler
    is attached)."""

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.records: List[CompileRecord] = []
        self._counters0: dict = {}

    # -- logging.Handler ------------------------------------------------

    def emit(self, record: logging.LogRecord) -> None:
        global XLA_COMPILES
        try:
            rec = parse_compile_log(record.getMessage())
        except Exception:               # noqa: BLE001 — never raise
            return                      # from a logging handler
        if rec is not None:
            if _ACTIVE and _ACTIVE[0] is self:
                XLA_COMPILES += 1
            self.records.append(rec)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "CompileGuard":
        import jax

        from ..checker import pallas_seg as PS
        from ..txn import closure_jax as CJ

        self._counters0 = {"mosaic": PS.MOSAIC_BUILDS,
                           "closure": CJ.COMPILES}
        self._prev_flag = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        self._prev_propagate = {}
        for name in (_COMPILE_LOGGER,) + _NOISY_LOGGERS:
            lg = logging.getLogger(name)
            lg.addHandler(self)
            # stop propagation to root/absl handlers for the window:
            # attaching a handler only silences logging's last-resort
            # handler, not an installed root handler — without this,
            # every lowering sprays WARNING lines into bench stderr
            self._prev_propagate[name] = lg.propagate
            lg.propagate = False
        _ACTIVE.append(self)
        return self

    def stop(self) -> None:
        import jax

        if self in _ACTIVE:
            _ACTIVE.remove(self)
        for name in (_COMPILE_LOGGER,) + _NOISY_LOGGERS:
            lg = logging.getLogger(name)
            lg.removeHandler(self)
            # nested guards: the logger stays non-propagating until
            # the LAST guard touching it detaches
            if not any(isinstance(h, CompileGuard) for h in
                       lg.handlers):
                lg.propagate = self._prev_propagate.get(name, True)
        jax.config.update("jax_log_compiles", self._prev_flag)

    # -- reporting ------------------------------------------------------

    def counters(self) -> dict:
        """Lowering/build counts inside this guard's window."""
        from ..checker import pallas_seg as PS
        from ..txn import closure_jax as CJ

        return {
            "xla_lowerings": len(self.records),
            "mosaic_builds": PS.MOSAIC_BUILDS
            - self._counters0.get("mosaic", 0),
            "closure_programs": CJ.COMPILES
            - self._counters0.get("closure", 0),
        }

    def offenders(self, inventory=None) -> List[CompileRecord]:
        """Observed records OUTSIDE the declared compile surface."""
        if inventory is None:
            from ..analysis.compile_surface import static_inventory

            inventory = static_inventory()
        return inventory.offenders(self.records)

    def assert_closed(self, inventory=None) -> None:
        off = self.offenders(inventory)
        if off:
            raise CompileSurfaceError(
                "observed compiles escaped the static inventory "
                "(unbucketed shapes reached a jit boundary):\n  "
                + "\n  ".join(r.format() for r in off))

    def summary(self, inventory=None) -> dict:
        """JSON-able guard report (bench artifacts embed this)."""
        off = self.offenders(inventory)
        return {
            **self.counters(),
            "compile_surface_ok": not off,
            "offenders": [r.format() for r in off],
        }


@contextmanager
def guard():
    """``with compile_guard.guard() as g: ...`` — capture every XLA
    lowering in the block."""
    g = CompileGuard().start()
    try:
        yield g
    finally:
        g.stop()


def enabled() -> bool:
    """Whether bench runs should ASSERT surface closure (capture is
    always on there; ``COMDB2_TPU_COMPILE_GUARD=0`` turns the hard
    assert into report-only)."""
    return os.environ.get("COMDB2_TPU_COMPILE_GUARD", "1") != "0"


__all__ = ["CompileGuard", "CompileRecord", "CompileSurfaceError",
           "XLA_COMPILES", "enabled", "guard", "parse_compile_log"]
