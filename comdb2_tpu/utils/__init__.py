"""Shared utilities."""

from .shapes import next_pow2

__all__ = ["next_pow2"]
