"""Result-formatting helpers.

The compact integer-interval rendering of set results and the safe
fraction mirror the reference's ``jepsen/util.clj:483-508`` and
``checker.clj`` ``fraction``.
"""

from __future__ import annotations

from typing import Iterable


def fraction(a: int, b: int) -> float:
    """a/b, but 1 when b is zero (vacuously complete)."""
    return 1.0 if b == 0 else a / b


def integer_interval_set_str(xs: Iterable) -> str:
    """Sorted, compact string for a set of integers:
    ``#{1..3 5 9..10}``. Non-integer or None members fall back to a
    plain sorted rendering (``util.clj:483-508``)."""
    xs = list(xs)
    if any(x is None or not isinstance(x, int) for x in xs):
        return "#{" + " ".join(str(x) for x in sorted(xs, key=repr)) + "}"
    runs = []
    start = end = None
    for cur in sorted(xs):
        if start is None:
            start = end = cur
        elif cur == end + 1:
            end = cur
        else:
            runs.append((start, end))
            start = end = cur
    if start is not None:
        runs.append((start, end))
    body = " ".join(str(a) if a == b else f"{a}..{b}" for a, b in runs)
    return "#{" + body + "}"
