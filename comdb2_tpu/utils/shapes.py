"""Static-shape bucketing helpers.

XLA compiles one program per distinct input shape; rounding capacities
and history lengths up to powers of two keeps the number of compiled
variants logarithmic in problem size.
"""

from __future__ import annotations


def next_pow2(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo)."""
    p = lo
    while p < n:
        p *= 2
    return p
