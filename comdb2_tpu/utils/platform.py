"""JAX platform fallback.

The ambient environment may pin ``JAX_PLATFORMS`` to a plugin backend
(a tunneled TPU) that only registers under specific launch conditions;
offline tools must degrade to CPU instead of crashing with "unknown
backend"."""

from __future__ import annotations


def ensure_backend() -> str:
    """Make sure some JAX backend initializes; falls back to CPU when
    the configured platform can't. Returns the backend name."""
    import jax

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
    return jax.default_backend()
