"""JAX platform fallback.

The ambient environment may pin ``JAX_PLATFORMS`` to a plugin backend
(a tunneled TPU) that only registers under specific launch conditions;
offline tools must degrade to CPU instead of crashing with "unknown
backend"."""

from __future__ import annotations


def ensure_backend() -> str:
    """Make sure some JAX backend initializes; falls back to CPU when
    the configured platform can't. Returns the backend name."""
    import jax

    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
    return jax.default_backend()


def enable_compile_cache(path: str = "/tmp/jax-cache-comdb2tpu",
                         min_compile_secs: float = 0.5) -> None:
    """Turn on the persistent XLA compile cache. Must go through
    jax.config (not env vars): the ambient startup hook may have
    imported jax already, and jax reads the env only at import."""
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
