"""Offline history checker — ``python -m comdb2_tpu.filetest hist.edn``.

The reference's minimal end-to-end slice (``linearizable/filetest/
src/jepsen/filetest.clj:8-21``): read an EDN history file, run the
linearizability analysis against a model, pretty-print the result, exit
0 iff valid (2 on unknown). Histories come from the native drivers
(``ct_register -j``) or any persisted harness run.
"""

from __future__ import annotations

import argparse
import pprint
import sys
from typing import List, Optional

from .checker import analysis
from .checker.checkers import set_checker
from .models.model import MODELS
from .ops.native_loader import parse_history_fast as parse_history


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="check an EDN history file offline")
    p.add_argument("history", help="EDN history file")
    p.add_argument("--model", default="cas-register",
                   choices=sorted(MODELS),
                   help="consistency model (default cas-register)")
    p.add_argument("--checker", default="linear",
                   choices=["linear", "set", "wgl", "txn",
                            "bank", "sets", "dirty"],
                   help="linear (frontier search), wgl (world search), "
                        "set semantics, txn (serializability over "
                        "list-append txn ops), or a workload family "
                        "(bank/sets/dirty — the device column-plane "
                        "checkers, docs/workloads.md; bank needs "
                        "--wl-n/--wl-total)")
    p.add_argument("--txn", action="store_true",
                   help="shorthand for --checker txn")
    p.add_argument("--wl-n", type=int, metavar="N",
                   help="--checker bank: number of accounts")
    p.add_argument("--wl-total", type=int, metavar="T",
                   help="--checker bank: invariant balance total")
    p.add_argument("--realtime", action="store_true",
                   help="with --txn: include realtime edges (strict "
                        "serializability)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "host", "device"])
    p.add_argument("--keyed", action="store_true",
                   help="re-tag [k v] op values as keyed tuples "
                        "(independent-generator histories)")
    p.add_argument("--service", metavar="HOST:PORT",
                   help="submit to a running verifier daemon "
                        "(python -m comdb2_tpu.service) instead of "
                        "checking locally — no local JAX backend is "
                        "touched; exits 3 on a daemon error reply "
                        "(overload/bad-request: nothing was checked)")
    p.add_argument("--shrink", action="store_true",
                   help="on INVALID, minimize to a 1-minimal "
                        "sub-history (completion-pair ddmin, batched "
                        "on device — docs/shrink.md) and write "
                        "minimal.edn + a re-rendered SVG into the "
                        "store (see --store); the exit code stays the "
                        "seed verdict's")
    p.add_argument("--store", default="store", metavar="DIR",
                   help="store root for --shrink artifacts (default "
                        "store/ — the run shows up in the store web "
                        "index like any harness run)")
    p.add_argument("--trace", metavar="PATH",
                   help="write a Chrome/Perfetto trace-event JSON of "
                        "this run (the same span pipeline service "
                        "requests get — parse/pack/device/finalize "
                        "stage breakdown; docs/observability.md)")
    p.add_argument("--follow", action="store_true",
                   help="tail mode (docs/streaming.md): poll the file "
                        "for appended EDN ops (map-per-line) and feed "
                        "them through a local StreamSession, printing "
                        "verdict transitions — the offline twin of "
                        "the service stream kind. Exits when the "
                        "verdict latches or the file goes idle for "
                        "--follow-idle seconds (then the tail settles "
                        "and the final verdict is one-shot-identical)")
    p.add_argument("--follow-poll", type=float, default=0.2,
                   metavar="S", help="tail poll interval (s)")
    p.add_argument("--follow-idle", type=float, default=5.0,
                   metavar="S",
                   help="finalize after this long without new bytes "
                        "(0 = follow forever)")
    args = p.parse_args(argv)
    if args.txn:
        args.checker = "txn"
    if args.checker == "bank" and (args.wl_n is None
                                   or args.wl_total is None):
        p.error("--checker bank needs --wl-n and --wl-total")

    if args.trace:
        from .obs import trace as obs_trace

        obs_trace.enable()
    try:
        return _run(args)
    finally:
        if args.trace:
            from .obs import trace as obs_trace

            obs_trace.export_chrome(args.trace)
            print(f"trace: {len(obs_trace.spans())} span(s) -> "
                  f"{args.trace}", file=sys.stderr)
            # leave the process as found (embedders run main() too)
            obs_trace.disable()
            obs_trace.clear()


#: the workload families (docs/workloads.md) — mirrors
#: checker.wl.batch.FAMILIES without importing jax at parse time
_WL_FAMILIES = ("bank", "sets", "dirty")


def _wl_model(args):
    return ({"n": args.wl_n, "total": args.wl_total}
            if args.checker == "bank" else None)


def _run(args) -> int:
    """The checker run proper (main owns arg parsing + the trace
    export, which must happen on EVERY exit path)."""
    if args.follow:
        if args.checker not in ("linear",):
            print("--follow supports the linear checker only",
                  file=sys.stderr)
            return 3
        from .utils.platform import ensure_backend

        ensure_backend()
        return _run_follow(args)
    if args.service:
        # remote path first: the whole point is NOT to attach this
        # process to a device (the tunnel costs ~100 ms per dispatch;
        # the daemon coalesces many callers into one)
        from .service.client import ServiceClient

        host, _, port = args.service.rpartition(":")
        with open(args.history) as fh:
            text = fh.read()
        try:
            with ServiceClient(host or "127.0.0.1", int(port)) as c:
                if args.shrink:
                    reply = c.shrink(text,
                                     txn=(args.checker == "txn"),
                                     realtime=args.realtime,
                                     model=(None if args.checker ==
                                            "txn" else args.model),
                                     keyed=args.keyed,
                                     raise_on_error=False)
                elif args.checker in _WL_FAMILIES:
                    reply = c.check_wl(text, args.checker,
                                       wl=_wl_model(args),
                                       raise_on_error=False)
                elif args.checker == "txn":
                    reply = c.check(text, txn=True,
                                    realtime=args.realtime,
                                    raise_on_error=False)
                else:
                    reply = c.check(text, model=args.model,
                                    keyed=args.keyed,
                                    raise_on_error=False)
        except (OSError, ValueError) as e:
            # unreachable daemon / bad HOST:PORT: nothing was checked
            # — exiting 1 would record a linearizability violation
            # that never happened
            print(f"verifier service error: {e}", file=sys.stderr)
            return 3
        if args.shrink and reply.get("ok") \
                and reply.get("minimal_history"):
            # persist the daemon's minimal history exactly like the
            # local path (the SVG re-render re-checks on host). The
            # reply's EDN is RAW: keyed [k v] values must re-wrap
            # before the host re-check or they parse as cas pairs
            from .ops.native_loader import parse_history_fast

            mops = parse_history_fast(reply["minimal_history"])
            if (args.keyed or args.model == "cas-register-comdb2") \
                    and args.checker != "txn":
                from .checker.independent import wrap_keyed_history

                mops = wrap_keyed_history(mops)
            _save_shrink_artifacts(mops, reply, args)
        pprint.pprint({k: v for k, v in reply.items()
                       if k != "minimal_history"})
        if not reply.get("ok"):
            # overload/bad-request: the history was NEVER CHECKED —
            # exit 1 would record a linearizability violation that
            # didn't happen, 2 would claim the search gave up. A
            # distinct code keeps the verdict exit contract honest.
            return 3
        valid = reply.get("valid")
        if valid is True:
            return 0
        if valid == "unknown":
            return 2
        return 1

    if (args.checker in ("linear", "txn") + _WL_FAMILIES
            and args.backend != "host") or args.shrink:
        # only the device frontier search needs a JAX backend; the set
        # and wgl checkers (and host linear) are pure host Python, and
        # in the ambient env touching jax attaches the tunneled TPU.
        # --shrink always needs it: candidate verdicts are device
        # dispatches even when the seed check ran --backend host
        from .utils.platform import ensure_backend

        ensure_backend()

    from .obs import trace as obs_trace

    with obs_trace.span("filetest.parse", path=args.history):
        with open(args.history) as fh:
            history = parse_history(fh.read())

    if (args.keyed or args.model == "cas-register-comdb2") \
            and args.checker != "txn" \
            and args.checker not in _WL_FAMILIES:
        # the comdb2 tuple model exists solely for keyed histories;
        # EDN [k v] vectors carry no type tag, so re-tag them here —
        # NEVER for txn histories: their values are micro-op vectors,
        # not [k v] pairs, and wrapping would corrupt them. Workload
        # families never wrap either: a bank read's [b0 b1] balance
        # row would mis-parse as a cas pair
        from .checker.independent import wrap_keyed_history

        history = wrap_keyed_history(history)

    if args.checker in _WL_FAMILIES:
        if args.backend == "host":
            from .checker.wl.batch import _host_fallback

            result = _host_fallback([history], args.checker,
                                    _wl_model(args))[0]
        else:
            from .checker.wl import check_wl_batch

            result = check_wl_batch([history], args.checker,
                                    _wl_model(args))[0]
        pprint.pprint(result)
        valid = result.get("valid?")
    elif args.checker == "txn":
        from .txn import check_txn

        result = check_txn(history, backend=args.backend,
                           realtime=args.realtime)
        cex = result.get("counterexample")
        if cex:
            from .txn.counterexample import render_text

            print(render_text(cex))
        pprint.pprint({k: v for k, v in result.items()
                       if k != "counterexample"})
        valid = result.get("valid?")
    elif args.checker == "set":
        result = set_checker.check({}, None, history)
        pprint.pprint(result)
        valid = result.get("valid?")
    elif args.checker == "wgl":
        from .checker import wgl

        result = wgl.analysis(MODELS[args.model](), history)
        pprint.pprint(result)
        valid = result.get("valid?")
    else:
        a = analysis(MODELS[args.model](), history, backend=args.backend)
        result = a.to_map()
        result.pop("configs", None)
        pprint.pprint(result)
        valid = a.valid

    if args.shrink:
        if args.checker not in ("linear", "txn"):
            print("--shrink supports the linear and txn checkers "
                  "only", file=sys.stderr)
        elif valid is not False:
            # the seed-rejection contract: shrinking a VALID history
            # has nothing to preserve, shrinking an UNKNOWN would
            # loop on capacity-limited verdicts
            print(f"--shrink: seed verdict is {valid!r} — only "
                  "INVALID histories shrink", file=sys.stderr)
        else:
            from .shrink import SeedVerdictError, minimize

            try:
                r = minimize(history,
                             checker=("txn" if args.checker == "txn"
                                      else "linear"),
                             model=args.model, realtime=args.realtime)
            except SeedVerdictError as e:
                # the main analysis escalates frontier capacity (or
                # ran on host); the shrinker's fixed-F seed re-check
                # can still come back UNKNOWN — degrade gracefully,
                # exactly like the not-INVALID branch above
                print(f"--shrink: {e}", file=sys.stderr)
            else:
                _save_shrink_artifacts(r.ops, r, args)

    if valid is True:
        return 0
    if valid == "unknown":
        return 2
    return 1


def _run_follow(args) -> int:
    """Tail mode: incremental byte-offset reads of a map-per-line EDN
    history, each batch of complete new lines fed as one delta to a
    local :class:`~comdb2_tpu.stream.StreamSession` (keyed histories
    re-wrapped PER DELTA — the values carry no type tag; nemesis
    completions stay type ``info`` and ride through the ingest like
    any op). Prints a line per verdict TRANSITION plus a progress
    line per append; the idle timeout settles the tail and exits with
    the standard verdict code."""
    import time

    from .obs.trace import monotonic as mono
    from .stream import StreamSession

    keyed = args.keyed or args.model == "cas-register-comdb2"
    s = StreamSession(args.model)
    pos = 0
    buf = ""
    last_valid = True
    last_bytes = mono()

    def transition(out) -> None:
        nonlocal last_valid
        if out["valid"] != last_valid:
            print(f"verdict: {last_valid!r} -> {out['valid']!r} at "
                  f"op {out['op_index']} "
                  f"(checked_through={out['checked_through']})",
                  flush=True)
            last_valid = out["valid"]

    while True:
        try:
            with open(args.history) as fh:
                fh.seek(pos)
                chunk = fh.read()
                pos = fh.tell()
        except FileNotFoundError:
            chunk = ""
        if chunk:
            buf += chunk
            lines, _, buf = buf.rpartition("\n")
            if lines.strip():
                ops = parse_history(lines)
                if keyed:
                    from .checker.independent import \
                        wrap_keyed_history

                    ops = wrap_keyed_history(ops)
                out = s.append(ops)
                print(f"append: +{len(ops)} ops -> valid="
                      f"{out['valid']!r} checked_through="
                      f"{out['checked_through']}/{out['op_count']} "
                      f"engine={out['engine']} "
                      f"dispatches={out['dispatches']}", flush=True)
                transition(out)
                if out["valid"] is not True:
                    break
            last_bytes = mono()
        elif args.follow_idle > 0 and \
                mono() - last_bytes >= args.follow_idle:
            break
        else:
            time.sleep(max(args.follow_poll, 0.01))
    if buf.strip() and s.valid is True:
        # a final line without a trailing newline (writer crashed or
        # never terminated the file) is still part of the history —
        # the idle timeout decided the stream ended, so feed it
        # before the final settle or the one-shot-identical claim
        # breaks on exactly the histories whose writer died
        ops = parse_history(buf)
        if keyed:
            from .checker.independent import wrap_keyed_history

            ops = wrap_keyed_history(ops)
        transition(s.append(ops))
    out = s.finalize_input()
    transition(out)
    pprint.pprint({k: out[k] for k in
                   ("valid", "op_index", "op_count",
                    "checked_through", "segments", "engine",
                    "dispatches", "appends", "replays")
                   if k in out}
                  | ({"cause": out["cause"]} if "cause" in out
                     else {}))
    if out["valid"] is True:
        return 0
    if out["valid"] == "unknown":
        return 2
    return 1


def _save_shrink_artifacts(ops, result, args) -> None:
    """Persist minimal.edn + results.edn + the re-rendered SVG into
    the store (one run dir, linked from the store web index).
    ``result`` is a ShrinkResult (local path) or the daemon's reply
    dict (service path); the SVG re-render re-checks the minimal
    history on host and the verdict lands in results.edn."""
    from .harness.store import save_shrink
    from .ops.history import history_to_edn
    from .report import shrink_svg

    checker = "txn" if args.checker == "txn" else "linear"
    rv, svg = shrink_svg.render_minimal(
        list(ops), checker=checker, model=args.model,
        realtime=args.realtime)
    if isinstance(result, dict):
        rm = {"valid?": result.get("valid"), "checker": checker,
              "seed-ops": result.get("seed_ops"),
              "minimal-ops": result.get("minimal_ops"),
              "rounds": result.get("rounds"),
              "candidates": result.get("candidates"),
              "dispatches": result.get("dispatches"),
              "one-minimal?": result.get("one_minimal"),
              "partial?": result.get("partial"),
              "reverified-valid?": rv}
        # the reply flattens ShrinkResult.extra (txn diagnosis etc.)
        # — persist it like the local path's results_map does
        for k in ("txns", "evidence_txns", "anomaly_class",
                  "seed_class", "anomalies", "note", "cause"):
            if k in result:
                rm[k.replace("_", "-")] = result[k]
    else:
        rm = shrink_svg.results_map(result, reverified=rv)
    d = save_shrink(history_to_edn(list(ops)), rm, svg=svg,
                    store_root=args.store)
    print(f"shrink: {len(ops)} ops -> {d}/minimal.edn",
          file=sys.stderr)
    if rv is not False:
        # a clean re-check means the minimizer and the offline
        # checker disagree — surface it, never hide it
        print(f"shrink: WARNING minimal history re-checked {rv!r}",
              file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
