"""Deprecated shim — this grew into :mod:`comdb2_tpu.service`.

The mesh/sharding helpers that lived here moved verbatim to
:mod:`comdb2_tpu.service.sharding` when the serving subsystem was
built around them; import from there. This module re-exports them so
existing callers keep working one release longer.
"""

from __future__ import annotations

import warnings

from ..service.sharding import (check_histories_sharded,  # noqa: F401
                                make_mesh)

warnings.warn(
    "comdb2_tpu.parallel moved to comdb2_tpu.service.sharding; "
    "import from there", DeprecationWarning, stacklevel=2)

__all__ = ["make_mesh", "check_histories_sharded"]
