"""The comdb2 test suite — every workload from ``comdb2/core.clj``,
re-built over the table-level connection interface
(:mod:`comdb2_tpu.workloads.sqlish`) so they run against the in-memory
serializable backend today and any real SUT adapter tomorrow.

Workloads: cas-register (``core.clj:358-479``), bank (``:71-177``),
sets (``:223-271``), dirty-reads (``:320-355``), plus the Adya G2
anti-dependency workload (``jepsen/adya.clj``). Test builders mirror
``register-tester[-nemesis]``, ``bank-test``, ``sets-test``,
``dirty-reads-tester`` (``core.clj:567-613,274-316,252-271,550-564``).
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Optional

from ..checker import checkers as C
from ..checker import independent as I
from ..checker.workloads import bank_checker, dirty_reads_checker, g2_checker
from ..harness import client as client_ns
from ..harness import db as db_ns
from ..harness import fake
from ..harness import generator as gen
from ..models import model as M
from ..report import perf_checker, Timeline
from .sqlish import (Conn, Indeterminate, MemDB, Rollback,
                     with_txn_retries)


def _invoke_guard(fn):
    """Map backend outcomes to op completions: Rollback → fail,
    Indeterminate → info (the worker then retires the process)."""
    def wrapped(self, test, op):
        try:
            return fn(self, test, op)
        except Rollback:
            return {**op, "type": "fail"}
        except Indeterminate as e:
            return {**op, "type": "info", "error": str(e)}
    return wrapped


# --- cas register (core.clj:358-479) ---------------------------------------

class CasRegisterClient(client_ns.Client):
    """Read/write/cas on a one-row ``register(id,val,uid)`` table.
    Values are ``(key, v)`` tuples from the independent generator; reads
    return ``(1, current)``; a write that updates zero rows inserts; cas
    updates ``where id=k and val=expected`` and fails on zero rows."""

    def __init__(self, connect: Callable[[], Conn]):
        self.connect = connect
        self.conn: Optional[Conn] = None

    def setup(self, test, node):
        c = CasRegisterClient(self.connect)
        c.conn = self.connect()
        # fresh table per run (core.clj:362-366 deletes register rows)
        with_txn_retries(lambda: c.conn.delete("register"))
        return c

    @_invoke_guard
    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if op["value"] is not None else (1, None)
        uid = random.randrange(100000) * 1000
        with self.conn.transaction() as t:
            rows = t.select("register", lambda r: r["id"] == k)
            cur = rows[0]["val"] if rows else None
            if f == "read":
                return {**op, "type": "ok", "value": I.tuple_(k, cur)}
            if f == "write":
                if rows:
                    n = t.update("register", {"val": v, "uid": uid},
                                 lambda r: r["id"] == k)
                else:
                    t.insert("register", {"id": k, "val": v, "uid": uid})
                    n = 1
                if n == 0:
                    return {**op, "type": "fail"}
                return {**op, "type": "ok"}
            if f == "cas":
                expected, new = v
                n = t.update("register", {"val": new, "uid": uid},
                             lambda r: r["id"] == k and r["val"] == expected)
                return {**op, "type": "ok" if n == 1 else "fail"}
        raise ValueError(f"unknown f {f!r}")


def r(test=None, process=None):
    return {"type": "invoke", "f": "read", "value": I.tuple_(1, None)}


def w(test=None, process=None):
    return {"type": "invoke", "f": "write",
            "value": I.tuple_(1, random.randrange(5))}


def cas(test=None, process=None):
    return {"type": "invoke", "f": "cas",
            "value": I.tuple_(1, (random.randrange(5),
                                  random.randrange(5)))}


# --- bank (core.clj:71-177) -------------------------------------------------

class BankClient(client_ns.Client):
    """Transfers between n accounts; total balance is invariant."""

    def __init__(self, connect: Callable[[], Conn], n: int,
                 starting_balance: int = 10):
        self.connect = connect
        self.n = n
        self.starting_balance = starting_balance
        self.conn: Optional[Conn] = None

    def setup(self, test, node):
        c = BankClient(self.connect, self.n, self.starting_balance)
        c.conn = self.connect()

        def create_accounts():
            with c.conn.transaction() as t:
                existing = {row["id"] for row in t.select("accounts")}
                for i in range(self.n):
                    if i not in existing:
                        t.insert("accounts",
                                 {"id": i,
                                  "balance": self.starting_balance})
        with_txn_retries(create_accounts)
        return c

    @_invoke_guard
    def invoke(self, test, op):
        with self.conn.transaction() as t:
            if op["f"] == "read":
                rows = t.select("accounts")
                rows.sort(key=lambda r: r["id"])
                return {**op, "type": "ok",
                        "value": tuple(r["balance"] for r in rows)}
            if op["f"] == "transfer":
                v = op["value"]
                frm, to, amount = v["from"], v["to"], v["amount"]
                b1 = t.select("accounts",
                              lambda r: r["id"] == frm)[0]["balance"] - amount
                b2 = t.select("accounts",
                              lambda r: r["id"] == to)[0]["balance"] + amount
                if b1 < 0:
                    return {**op, "type": "fail",
                            "value": ("negative", frm, b1)}
                if b2 < 0:
                    return {**op, "type": "fail",
                            "value": ("negative", to, b2)}
                t.update("accounts", {"balance": b1},
                         lambda rr: rr["id"] == frm)
                t.update("accounts", {"balance": b2},
                         lambda rr: rr["id"] == to)
                return {**op, "type": "ok"}
        raise ValueError(f"unknown f {op['f']!r}")


def bank_read(test=None, process=None):
    return {"type": "invoke", "f": "read", "value": None}


def bank_transfer(test, process):
    n = test["_bank_n"]
    return {"type": "invoke", "f": "transfer",
            "value": {"from": random.randrange(n),
                      "to": random.randrange(n),
                      "amount": random.randrange(5)}}


def bank_diff_transfer(test, process):
    """Transfers between *different* accounts (core.clj:146-150)."""
    while True:
        op = bank_transfer(test, process)
        if op["value"]["from"] != op["value"]["to"]:
            return op


# --- sets (core.clj:223-271) ------------------------------------------------

class SetClient(client_ns.Client):
    """add: insert a unique row into ``jepsen(id,value)``; read: the
    sorted set of values."""

    def __init__(self, connect: Callable[[], Conn]):
        self.connect = connect
        self.conn: Optional[Conn] = None

    def setup(self, test, node):
        c = SetClient(self.connect)
        c.conn = self.connect()
        return c

    @_invoke_guard
    def invoke(self, test, op):
        with self.conn.transaction() as t:
            if op["f"] == "add":
                key = getattr(self.conn, "gen_key", lambda: random.getrandbits(62))()
                t.insert("jepsen", {"id": key, "value": op["value"]})
                return {**op, "type": "ok"}
            if op["f"] == "read":
                vals = frozenset(row["value"] for row in t.select("jepsen"))
                return {**op, "type": "ok", "value": vals}
        raise ValueError(f"unknown f {op['f']!r}")


# --- dirty reads (core.clj:320-355) -----------------------------------------

class DirtyReadsClient(client_ns.Client):
    """write x: update every row of ``dirty`` to x (in random order);
    read: all x values (skipping the -1 initializer rows). A failed
    write whose x becomes visible is a dirty read."""

    def __init__(self, connect: Callable[[], Conn], n: int):
        self.connect = connect
        self.n = n
        self.conn: Optional[Conn] = None

    def setup(self, test, node):
        c = DirtyReadsClient(self.connect, self.n)
        c.conn = self.connect()

        def create_rows():
            with c.conn.transaction() as t:
                existing = {row["id"] for row in t.select("dirty")}
                for i in range(self.n):
                    if i not in existing:
                        t.insert("dirty", {"id": i, "x": -1})
        with_txn_retries(create_rows)
        return c

    @_invoke_guard
    def invoke(self, test, op):
        with self.conn.transaction() as t:
            if op["f"] == "read":
                rows = t.select("dirty", lambda r: r["x"] != -1)
                return {**op, "type": "ok",
                        "value": tuple(r["x"] for r in rows)}
            if op["f"] == "write":
                x = op["value"]
                order = list(range(self.n))
                random.shuffle(order)
                for i in order:
                    t.select("dirty", lambda r, i=i: r["id"] == i)
                for i in order:
                    t.update("dirty", {"x": x},
                             lambda r, i=i: r["id"] == i)
                return {**op, "type": "ok"}
        raise ValueError(f"unknown f {op['f']!r}")


def dirty_reads_read(test=None, process=None):
    return {"type": "invoke", "f": "read", "value": None}


class _DirtyWrites(gen.Generator):
    """Writes of consecutive integers (core.clj:527-534)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            self._i += 1
            v = self._i
        return {"type": "invoke", "f": "write", "value": v}


# --- adya G2 (jepsen/adya.clj) ----------------------------------------------

class G2Client(client_ns.Client):
    """Anti-dependency-cycle workload: in one txn, predicate-read tables
    a and b for the key; if both empty, insert the present id into its
    table. At most one insert may commit per key (``adya.clj:12-55``)."""

    def __init__(self, connect: Callable[[], Conn]):
        self.connect = connect
        self.conn: Optional[Conn] = None

    def setup(self, test, node):
        c = G2Client(self.connect)
        c.conn = self.connect()
        return c

    @_invoke_guard
    def invoke(self, test, op):
        k, ids = op["value"]
        a_id, b_id = ids
        with self.conn.transaction() as t:
            a = t.select("a", lambda row: row["key"] == k
                         and row["value"] % 3 == 0)
            b = t.select("b", lambda row: row["key"] == k
                         and row["value"] % 3 == 0)
            if a or b:
                return {**op, "type": "fail"}
            if a_id is not None:
                t.insert("a", {"id": a_id, "key": k, "value": 30})
            else:
                t.insert("b", {"id": b_id, "key": k, "value": 30})
        return {**op, "type": "ok"}


class ListAppendClient(client_ns.Client):
    """Elle's list-append workload over the table interface: a txn op's
    value is a sequence of micro-ops ``("append", k, v)`` /
    ``("r", k, None)``; appends insert ``{key, v}`` rows into the
    ``la`` table, reads select the key's rows in insertion order (the
    whole list — version order is recoverable). Reads within one txn
    see the txn's OWN earlier appends (buffered-write fixup) so the
    history honors the standard list-append semantics."""

    def __init__(self, connect: Callable[[], Conn]):
        self.connect = connect
        self.conn: Optional[Conn] = None

    def setup(self, test, node):
        c = ListAppendClient(self.connect)
        c.conn = self.connect()
        return c

    @_invoke_guard
    def invoke(self, test, op):
        done = []
        own: dict = {}
        with self.conn.transaction() as t:
            for f, k, v in op["value"]:
                if f == "append":
                    t.insert("la", {"key": k, "v": v})
                    own.setdefault(k, []).append(v)
                    done.append(("append", k, v))
                else:
                    rows = t.select("la", lambda r, k=k: r["key"] == k)
                    vals = [r["v"] for r in rows] + own.get(k, [])
                    done.append(("r", k, tuple(vals)))
        return {**op, "type": "ok", "value": tuple(done)}


def list_append_gen(n_keys: int = 3, max_micro: int = 3):
    """Txn invocations with unique per-key append values (the Elle
    precondition) — thread-safe counters shared by all workers."""
    counters = [0] * n_keys
    lock = threading.Lock()

    def next_val(k):
        with lock:
            counters[k] += 1
            return counters[k]

    def gen_op(test=None, process=None):
        mops = []
        for _ in range(random.randint(1, max_micro)):
            k = random.randrange(n_keys)
            if random.random() < 0.5:
                mops.append(("append", k, next_val(k)))
            else:
                mops.append(("r", k, None))
        return {"type": "invoke", "f": "txn", "value": tuple(mops)}

    return gen_op


def g2_gen():
    """Concurrent unique keys, two inserts per key with globally unique
    ids, 2 threads per key — the reference's shape exactly
    (``adya.clj:14-55``: ``independent/concurrent-generator 2 (range)``
    over a two-op seq)."""
    import itertools

    from ..harness import independent_gen as IG

    ids = itertools.count(1)
    lock = threading.Lock()

    def next_id():
        with lock:
            return next(ids)

    def fgen(k):
        return gen.seq([
            lambda t, p: {"type": "invoke", "f": "insert",
                          "value": (None, next_id())},
            lambda t, p: {"type": "invoke", "f": "insert",
                          "value": (next_id(), None)},
        ])

    return IG.concurrent_generator(2, itertools.count(1), fgen)


# --- test builders (core.clj:195-208,567-613) -------------------------------

def with_nemesis(client_gen):
    """10 s on / 10 s off nemesis cycle around a client generator
    (``core.clj:179-193``)."""
    import itertools

    return gen.phases(
        gen.phases(
            gen.nemesis(
                gen.seq(itertools.cycle(
                    [gen.sleep(0), {"type": "info", "f": "start"},
                     gen.sleep(10), {"type": "info", "f": "stop"}])),
                client_gen),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(5)))


def basic_test(opts: dict) -> dict:
    """noop-test + 5 nodes + overrides (``core.clj:195-208``)."""
    t = fake.noop_test()
    t.update({"nodes": ["m1", "m2", "m3", "m4", "m5"],
              "name": "comdb2"})
    t.update(opts)
    return t


def _default_connect() -> Callable[[], Conn]:
    db = MemDB()
    return db.connect


def register_tester(opts: Optional[dict] = None,
                    connect: Optional[Callable[[], Conn]] = None,
                    time_limit: float = 10.0,
                    quiesce: float = 0.0) -> dict:
    """The register test (``core.clj:567-589``): concurrency 10, mix
    [w cas cas r] staggered 1/10 s, independent-keyed linearizable +
    perf + timeline checkers."""
    connect = connect or _default_connect()
    t = basic_test({
        "name": "register",
        "client": CasRegisterClient(connect),
        "concurrency": 10,
        # the independent checker unwraps (k, v) tuples per key, so the
        # per-key model is a plain cas-register (the comdb2 tuple
        # variant is for un-partitioned keyed histories)
        "model": M.cas_register(),
        "generator": gen.phases(
            gen.time_limit(time_limit,
                           gen.stagger(0.1, gen.clients(
                               gen.mix([w, cas, cas, r])))),
            gen.log("waiting for quiescence"),
            gen.sleep(quiesce)),
        "checker": C.compose({
            "perf": perf_checker(),
            "timeline": Timeline(),
            "linearizable": I.checker(C.Linearizable()),
        }),
    })
    t.update(opts or {})
    return t


def register_tester_nemesis(opts: Optional[dict] = None,
                            connect: Optional[Callable[[], Conn]] = None,
                            time_limit: float = 300.0) -> dict:
    """register + partition nemesis (``core.clj:591-613``)."""
    from ..harness import nemesis as N

    t = register_tester(opts={}, connect=connect, time_limit=time_limit)
    t["name"] = "register-nemesis"
    t["nemesis"] = N.partition_random_halves()
    t["generator"] = gen.phases(
        with_nemesis(gen.stagger(0.1, gen.clients(
            gen.mix([w, cas, cas, r])))),
        gen.log("waiting for quiescence"),
        gen.sleep(10))
    t.update(opts or {})
    return t


def bank_test(opts: Optional[dict] = None,
              connect: Optional[Callable[[], Conn]] = None,
              n: int = 5, starting_balance: int = 10,
              time_limit: float = 100.0) -> dict:
    """(``core.clj:274-316``)"""
    connect = connect or _default_connect()
    t = basic_test({
        "name": "bank",
        "client": BankClient(connect, n, starting_balance),
        "concurrency": 10,
        "_bank_n": n,
        "model": {"n": n, "total": n * starting_balance},
        "generator": gen.clients(
            gen.time_limit(time_limit,
                           gen.stagger(0.05,
                                       gen.mix([bank_read,
                                                bank_diff_transfer])))),
        "checker": C.compose({"perf": perf_checker(),
                              "bank": bank_checker}),
    })
    t.update(opts or {})
    return t


def sets_test(opts: Optional[dict] = None,
              connect: Optional[Callable[[], Conn]] = None,
              adds: int = 100) -> dict:
    """Unique adds then a final read (``core.clj:252-271``)."""
    connect = connect or _default_connect()
    counter = iter(range(1 << 60))

    def add(test=None, process=None):
        return {"type": "invoke", "f": "add", "value": next(counter)}

    t = basic_test({
        "name": "set",
        "client": SetClient(connect),
        "concurrency": 10,
        "generator": gen.clients(gen.phases(
            gen.limit(adds, add),
            gen.once({"type": "invoke", "f": "read", "value": None}))),
        "checker": C.set_checker,
    })
    t.update(opts or {})
    return t


def dirty_reads_tester(opts: Optional[dict] = None,
                       connect: Optional[Callable[[], Conn]] = None,
                       n: int = 4, time_limit: float = 10.0) -> dict:
    """(``core.clj:550-564``)"""
    connect = connect or _default_connect()
    t = basic_test({
        "name": "dirty-reads",
        "client": DirtyReadsClient(connect, n),
        "concurrency": 4,
        "generator": gen.clients(
            gen.time_limit(time_limit,
                           gen.mix([dirty_reads_read, _DirtyWrites()]))),
        "checker": C.compose({"dirty-reads": dirty_reads_checker,
                              "perf": perf_checker()}),
    })
    t.update(opts or {})
    return t


def g2_test(opts: Optional[dict] = None,
            connect: Optional[Callable[[], Conn]] = None,
            ops: int = 100) -> dict:
    """Adya G2 (``adya.clj``)."""
    connect = connect or _default_connect()
    t = basic_test({
        "name": "g2",
        "client": G2Client(connect),
        "concurrency": 10,
        "generator": gen.clients(gen.limit(ops, g2_gen())),
        "checker": g2_checker,
    })
    t.update(opts or {})
    return t
