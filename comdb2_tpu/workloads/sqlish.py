"""Table-level SUT connection interface + in-memory implementation.

The reference's workloads speak JDBC/SQL to comdb2 (``comdb2/core.clj``,
via ``java.jdbc``). This framework's workloads speak a small
*operation-level* interface instead — insert/select/update/delete inside
serializable transactions — which a real backend adapts to its wire
protocol, and which :class:`MemDB` implements in-memory with strictly
serializable transactions (one global lock) for harness self-tests.
The optional chaos knobs inject failed and indeterminate outcomes to
exercise the harness's fail/info paths.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable, Dict, List, Optional


class Rollback(Exception):
    """Raised inside a transaction to abort it (maps to the reference's
    retriable serialization aborts, ``comdb2/core.clj:37-50``)."""


class Indeterminate(Exception):
    """The operation may or may not have applied (timeout/crash) — the
    worker records :info and retires the process."""


def with_txn_retries(fn: Callable[[], Any], attempts: int = 1000) -> Any:
    """Re-run fn until it commits — the reference's ``with-txn-retries``
    loop on retriable aborts (``comdb2/core.clj:52-61``); indeterminate
    outcomes during *setup* are also retried (setup is idempotent)."""
    last: Exception = RuntimeError("no attempts")
    for _ in range(attempts):
        try:
            return fn()
        except (Rollback, Indeterminate) as e:
            last = e
    raise last


class Conn:
    """One client connection. Rows are dicts. ``transaction()`` yields a
    transactional view with serializable isolation."""

    def transaction(self):
        raise NotImplementedError

    # autocommit single-op forms
    def insert(self, table: str, row: dict) -> None:
        with self.transaction() as t:
            t.insert(table, row)

    def select(self, table: str,
               pred: Optional[Callable[[dict], bool]] = None) -> List[dict]:
        with self.transaction() as t:
            return t.select(table, pred)

    def update(self, table: str, assign: dict,
               pred: Optional[Callable[[dict], bool]] = None) -> int:
        with self.transaction() as t:
            return t.update(table, assign, pred)

    def delete(self, table: str,
               pred: Optional[Callable[[dict], bool]] = None) -> int:
        with self.transaction() as t:
            return t.delete(table, pred)

    def close(self) -> None:
        pass


class MemDB:
    """Shared in-memory database: ``{table: [row-dict, ...]}`` guarded
    by one lock — transactions are strictly serializable, like the
    reference's serializable isolation config (``linearizable.lrl``).

    chaos_fail / chaos_unknown: probabilities of raising Rollback /
    Indeterminate at commit time."""

    def __init__(self, chaos_fail: float = 0.0, chaos_unknown: float = 0.0,
                 seed: int = 0):
        self.tables: Dict[str, List[dict]] = {}
        self.lock = threading.RLock()
        self.chaos_fail = chaos_fail
        self.chaos_unknown = chaos_unknown
        self.rng = random.Random(seed)
        self.next_id = 0

    def connect(self) -> "MemConn":
        return MemConn(self)

    def gen_key(self) -> int:
        with self.lock:
            k = self.next_id
            self.next_id += 1
            return k


class _Txn:
    """A serializable transaction over MemDB: holds the global lock,
    buffers writes, applies at commit (so chaos-aborted txns leave no
    trace, and chaos-indeterminate txns may or may not apply)."""

    def __init__(self, db: MemDB):
        self.db = db
        self.writes: List[Callable[[], None]] = []

    # --- ops ---------------------------------------------------------------

    def select(self, table, pred=None):
        rows = self.db.tables.get(table, [])
        return [dict(r) for r in rows if pred is None or pred(r)]

    def insert(self, table, row):
        row = dict(row)
        def apply():
            self.db.tables.setdefault(table, []).append(row)
        self.writes.append(apply)

    def update(self, table, assign, pred=None) -> int:
        matched = [r for r in self.db.tables.get(table, [])
                   if pred is None or pred(r)]
        def apply():
            for r in matched:
                r.update(assign)
        self.writes.append(apply)
        return len(matched)

    def delete(self, table, pred=None) -> int:
        rows = self.db.tables.get(table, [])
        matched = [r for r in rows if pred is None or pred(r)]
        def apply():
            t = self.db.tables.get(table, [])
            for r in matched:
                try:
                    t.remove(r)
                except ValueError:
                    pass
        self.writes.append(apply)
        return len(matched)

    # --- commit protocol ---------------------------------------------------

    def _commit(self):
        db = self.db
        if db.chaos_fail and db.rng.random() < db.chaos_fail:
            raise Rollback("chaos: serialization failure")
        if db.chaos_unknown and db.rng.random() < db.chaos_unknown:
            # apply-or-not with 50/50, then report indeterminate
            if db.rng.random() < 0.5:
                for w in self.writes:
                    w()
            raise Indeterminate("chaos: connection lost at commit")
        for w in self.writes:
            w()


class _TxnCtx:
    def __init__(self, db: MemDB):
        self.db = db

    def __enter__(self) -> _Txn:
        self.db.lock.acquire()
        self.txn = _Txn(self.db)
        return self.txn

    def __exit__(self, exc_type, exc, tb):
        try:
            if exc_type is None:
                self.txn._commit()
        finally:
            self.db.lock.release()
        return False


class MemConn(Conn):
    def __init__(self, db: MemDB):
        self.db = db

    def transaction(self):
        return _TxnCtx(self.db)

    def gen_key(self) -> int:
        return self.db.gen_key()
