"""Workloads: the comdb2 test suite over a table-level SUT interface.

- :mod:`comdb2_tpu.workloads.sqlish` — serializable connection protocol
  + in-memory backend with chaos injection
- :mod:`comdb2_tpu.workloads.comdb2` — cas-register, bank, sets,
  dirty-reads, G2 workloads and their test builders
"""

from . import sqlish
from . import comdb2

__all__ = ["sqlish", "comdb2"]
