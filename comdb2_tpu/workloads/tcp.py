"""TCP SUT client — drive the native ``sut_server`` over its line
protocol.

This closes the distributed loop end to end: the harness's workers talk
to a real out-of-process SUT over sockets, socket timeouts surface as
indeterminate (``info``) completions exactly like the reference's
JDBC timeouts, and process faults (SIGSTOP on the server) produce the
hung-op behavior the checker must reason about.
"""

from __future__ import annotations

import socket
import time
from typing import Optional, Sequence, Tuple

from ..harness import client as client_ns
from ..ops.kv import tuple_


class SutConnection:
    """One line-protocol connection with a hard timeout."""

    def __init__(self, host: str, port: int, timeout_s: float = 1.0):
        self.addr = (host, port)
        self.timeout_s = timeout_s
        self.sock: Optional[socket.socket] = None
        self.rfile = None

    def connect(self) -> None:
        self.close()
        s = socket.create_connection(self.addr, timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = s
        self.rfile = s.makefile("r")

    def close(self) -> None:
        try:
            if self.rfile is not None:
                self.rfile.close()
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None
        self.rfile = None

    def request(self, line: str) -> str:
        """Send one request line; returns the reply line. Raises
        ``TimeoutError`` when the server doesn't answer in time (the
        op's outcome is then unknown — it may have applied)."""
        if self.sock is None:
            self.connect()
        try:
            self.sock.sendall((line + "\n").encode())
            reply = self.rfile.readline()
        except socket.timeout as e:
            self.close()
            raise TimeoutError(f"SUT timeout on {line!r}") from e
        except OSError as e:
            self.close()
            raise TimeoutError(f"SUT connection lost on {line!r}") from e
        if not reply.endswith("\n"):
            # empty = connection closed; partial = the server died or
            # stalled MID-REPLY — accepting "V 12" for "V 123" would
            # fabricate a wrong read under exactly the faults the
            # harness injects (same contract as ct_tcp_request's -2)
            self.close()
            raise TimeoutError(f"SUT truncated reply on {line!r}")
        return reply.strip()


class TcpRegisterClient(client_ns.Client):
    """read/write/cas against ``sut_server``; values are keyed
    ``(k, v)`` tuples like the comdb2 register client's. A timeout
    yields an ``info`` completion (indeterminate — the worker retires
    the process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7777,
                 timeout_s: float = 1.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.conn: Optional[SutConnection] = None

    def setup(self, test, node):
        c = TcpRegisterClient(self.host, self.port, self.timeout_s)
        c.conn = SutConnection(self.host, self.port, self.timeout_s)
        c.conn.connect()
        return c

    def teardown(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if op["value"] is not None else (1, None)
        try:
            if f == "read":
                reply = self.conn.request("R")
                if reply == "NIL":
                    return {**op, "type": "ok", "value": tuple_(k, None)}
                if reply.startswith("V "):
                    return {**op, "type": "ok",
                            "value": tuple_(k, int(reply[2:]))}
                return {**op, "type": "fail"}
            if f == "write":
                reply = self.conn.request(f"W {v}")
            elif f == "cas":
                a, b = v
                reply = self.conn.request(f"C {a} {b}")
            else:
                raise ValueError(f"unknown f {f!r}")
            if reply == "OK" or reply.startswith("OK "):
                return {**op, "type": "ok"}
            if reply == "FAIL":
                return {**op, "type": "fail"}
            return {**op, "type": "info", "error": reply}
        except TimeoutError as e:
            return {**op, "type": "info", "error": str(e)}


class TcpClusterRegisterClient(TcpRegisterClient):
    """Register client against a replicated ``sut_node`` cluster: each
    worker talks to one node (cycled), so reads land on replicas and a
    partition between nodes is visible to the checker — the client-side
    shape of the reference's 5-node register test
    (``comdb2/core.clj:567-613``).

    Mutations ride replay nonces (``M <nonce> <cmd>``): an attempt whose
    outcome was lost is retried on the next node, and a node that
    already applied it replays the recorded outcome — the cdb2api HA
    retry backed by blkseq dedup. Only an exhausted retry budget
    surfaces as an indeterminate ``info`` op."""

    def __init__(self, ports, timeout_s: float = 1.0,
                 mutate_retries: int = 3):
        super().__init__("127.0.0.1", ports[0], timeout_s)
        self.ports = list(ports)
        self._next = 0
        self.mutate_retries = mutate_retries
        self._session = None
        self._seq = 0
        self._port_ix = 0

    def _clone(self):
        return TcpClusterRegisterClient(self.ports, self.timeout_s,
                                        self.mutate_retries)

    def _post_connect(self) -> None:
        """Per-connection preamble hook (the SQL client sends its
        session SETs here)."""

    def setup(self, test, node):
        import random as _random

        port_ix = self._next % len(self.ports)
        self._next += 1
        c = self._clone()
        c._port_ix = port_ix
        c._session = _random.SystemRandom().getrandbits(32)
        c.conn = SutConnection(self.host, self.ports[port_ix],
                               self.timeout_s)
        c.conn.connect()
        c._post_connect()
        return c

    def _rotate(self) -> None:
        """Reconnect to the next node (retry-elsewhere)."""
        self._port_ix = (self._port_ix + 1) % len(self.ports)
        self.conn.close()
        self.conn = SutConnection(self.host, self.ports[self._port_ix],
                                  self.timeout_s)

    def _mutate(self, cmd: str) -> str:
        """Send one nonce-wrapped mutation with retry-elsewhere;
        returns the final reply ("UNKNOWN" when the budget exhausts)."""
        self._seq += 1
        nonce = (self._session << 24) | self._seq
        line = f"M {nonce} {cmd}"
        maybe_delivered = False
        for _ in range(self.mutate_retries):
            try:
                reply = self.conn.request(line)
            except TimeoutError:
                maybe_delivered = True      # sent, no complete reply
                self._rotate()
                continue
            except OSError:
                self._rotate()              # never connected: safe
                continue
            if reply.startswith("OK") or reply == "FAIL":
                return reply
            maybe_delivered = True      # delivered, outcome unresolved
            self._rotate()
        # FAIL is only safe when no attempt can have been delivered
        return "UNKNOWN" if maybe_delivered else "FAIL"

    def invoke(self, test, op):
        """Keyed commands (``R k`` / ``W k v`` / ``C k a b``): the
        cluster stores one register per key like the reference's
        register table, and the independent checker verifies per key."""
        f = op["f"]
        k, v = op["value"] if op["value"] is not None else (1, None)
        if f == "read":
            # reads have no side effects, so any failure is safely
            # :fail (never pends) — an info read would stay pending
            # forever and pending ops are what blow up the checker
            try:
                reply = self.conn.request(f"R {k}")
            except (TimeoutError, OSError):
                return {**op, "type": "fail"}
            if reply == "NIL":
                return {**op, "type": "ok", "value": tuple_(k, None)}
            if reply.startswith("V "):
                return {**op, "type": "ok",
                        "value": tuple_(k, int(reply[2:]))}
            return {**op, "type": "fail"}
        if f == "write":
            reply = self._mutate(f"W {k} {v}")
        elif f == "cas":
            a, b = v
            reply = self._mutate(f"C {k} {a} {b}")
        else:
            raise ValueError(f"unknown f {f!r}")
        if reply.startswith("OK"):
            return {**op, "type": "ok"}
        if reply == "FAIL":
            return {**op, "type": "fail"}
        return {**op, "type": "info", "error": reply}


class ClusterControl:
    """Admin-plane driver for a ``sut_node`` cluster: cluster/primary
    discovery (the ``cdb2_cluster_info`` / ``sys.cmd.send('bdb
    cluster')`` role, ``ctest/nemesis.c:15-47``) and symmetric
    partitions over the B/U control verbs."""

    def __init__(self, ports, timeout_s: float = 2.0):
        self.ports = list(ports)
        self.timeout_s = timeout_s

    def _req(self, port: int, line: str) -> str:
        conn = SutConnection("127.0.0.1", port, self.timeout_s)
        try:
            conn.connect()
            return conn.request(line)
        finally:
            conn.close()

    def info(self):
        """[{node, role, applied, durable, term, leader}] for reachable
        nodes; ``durable`` is meaningful on the current primary only."""
        out = []
        for i, port in enumerate(self.ports):
            try:
                r = self._req(port, "I").split()
                d = {"node": int(r[1]), "role": r[2],
                     "applied": int(r[3]), "durable": int(r[4]),
                     "port": port}
                if len(r) >= 7:
                    d["term"] = int(r[5])
                    d["leader"] = int(r[6])
                out.append(d)
            except (TimeoutError, OSError, IndexError, ValueError):
                out.append({"node": i, "role": "down", "port": port})
        return out

    def primary(self):
        """Discovered primary node id, or None."""
        for n in self.info():
            if n["role"] == "primary":
                return n["node"]
        return None

    def partition(self, side_a, side_b) -> None:
        """Symmetric partition: every node in side_a drops traffic with
        every node in side_b and vice versa (the grudge map shape of
        ``nemesis.clj:21-27``). Best-effort like the iptables nemesis:
        an unreachable node's verbs are skipped rather than aborting
        half-installed."""
        for a in side_a:
            for b in side_b:
                for port, peer in ((self.ports[a], b),
                                   (self.ports[b], a)):
                    try:
                        self._req(port, f"B {peer}")
                    except (TimeoutError, OSError):
                        pass

    def heal(self) -> None:
        for port in self.ports:
            try:
                self._req(port, "U")
            except (TimeoutError, OSError):
                pass

    def clock(self, node: int, offset_ms: int) -> bool:
        """Set node's wall-clock offset (the in-tree ``date -s`` — the
        K verb). Reset with 0. Returns whether the command landed —
        best-effort callers (nemeses) ignore it, but deterministic
        tests must assert it (a silently-dropped clock jump would turn
        a control-plane failure into a misleading verdict)."""
        try:
            return self._req(self.ports[node],
                             f"K {offset_ms}") == "OK"
        except (TimeoutError, OSError):
            return False

    def clocks_reset(self) -> None:
        for i in range(len(self.ports)):
            self.clock(i, 0)

    def await_replicated(self, timeout_s: float = 10.0) -> bool:
        """Coherency gate: wait until every node's applied LSN matches
        the primary's (the ``blockcoherent.sh:15-37`` role)."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            info = self.info()
            applied = [n.get("applied") for n in info]
            if all(a is not None for a in applied) and \
                    len(set(applied)) == 1:
                return True
            _time.sleep(0.1)
        return False


class ClusterPartitioner:
    """Nemesis client: on ``start`` discovers the primary and cuts
    {primary, one random other node} off from the rest — the
    highest-yield fault of the reference suite (``nemesis.c:90-144``
    breaknet targets master+1); on ``stop`` heals."""

    def __init__(self, control: ClusterControl, rng=None,
                 isolate_primary: bool = False):
        """``isolate_primary`` cuts the primary ALONE from everyone —
        in an N=3 cluster the breaknet shape {master, +1} keeps a
        majority on the master's side, so isolating the primary is the
        variant that actually denies it quorum."""
        import random as _random

        self.control = control
        self.rng = rng or _random.Random(0)
        self.isolate_primary = isolate_primary

    def setup(self, test, node):
        return self

    def teardown(self, test):
        self.control.heal()

    def invoke(self, test, op):
        n = len(self.control.ports)
        if op["f"] == "start":
            primary = self.control.primary()
            if primary is None:
                primary = 0
            others = [i for i in range(n) if i != primary]
            extra = ([] if self.isolate_primary or len(others) <= 1
                     else [self.rng.choice(others)])
            side_a = [primary] + extra
            side_b = [i for i in range(n) if i not in side_a]
            self.control.partition(side_a, side_b)
            return {**op, "value": f"cut {side_a} from {side_b}"}
        self.control.heal()
        return dict(op)


class ClusterProcs(list):
    """The ``sut_node`` processes of one cluster, with enough spawn
    context to KILL -9 and RESTART members mid-run — the killcluster
    disruptor's handle (``killclustertest.sh:36-84`` kill-9s real DB
    processes and relies on txn-log recovery). Subclasses list so
    existing ``for p in procs: p.kill()`` teardowns keep working."""

    def __init__(self, procs, argv_per_node, ports, wait_s=5.0):
        super().__init__(procs)
        self.argv_per_node = argv_per_node
        self.ports = list(ports)
        self.wait_s = wait_s

    def kill9(self, i: int) -> None:
        """SIGKILL node ``i`` (no shutdown path runs — buffered,
        un-fsynced state dies with the process)."""
        self[i].kill()
        self[i].wait()

    def restart(self, i: int, wait_ready: bool = True) -> None:
        """Restart node ``i`` with its original argv (same state dir:
        recovery replays the log)."""
        import subprocess
        import time

        self[i] = subprocess.Popen(self.argv_per_node[i],
                                   stdout=subprocess.DEVNULL,
                                   stderr=subprocess.DEVNULL)
        if wait_ready:
            _wait_ready(self[i], self.ports[i],
                        time.monotonic() + self.wait_s, "sut_node")

    def kill9_all(self) -> None:
        for i in range(len(self)):
            self[i].kill()
        for i in range(len(self)):
            self[i].wait()

    def restart_all(self) -> None:
        for i in range(len(self)):
            self.restart(i, wait_ready=False)
        import time

        deadline = time.monotonic() + self.wait_s
        for i, port in enumerate(self.ports):
            _wait_ready(self[i], port, deadline, "sut_node")


class ClusterClockScrambler:
    """Nemesis client: on ``start`` scrambles every node's wall clock
    by a random offset within ±max_skew_ms (the ``clock-scrambler``
    role, ``nemesis.clj:172-187``, over the SUT's K verb instead of
    ``date -s``); on ``stop`` resets all clocks. Harmless against the
    monotonic-lease implementation; the --bad-lease control is what
    gives it teeth."""

    def __init__(self, control: ClusterControl, rng=None,
                 max_skew_ms: int = 60_000):
        import random as _random

        self.control = control
        self.rng = rng or _random.Random(0)
        self.max_skew_ms = max_skew_ms

    def setup(self, test, node):
        return self

    def teardown(self, test):
        self.control.clocks_reset()

    def invoke(self, test, op):
        if op["f"] == "start":
            offs = []
            for i in range(len(self.control.ports)):
                off = self.rng.randint(-self.max_skew_ms,
                                       self.max_skew_ms)
                self.control.clock(i, off)
                offs.append(off)
            return {**op, "value": f"clock offsets {offs}"}
        self.control.clocks_reset()
        return dict(op)


def spawn_cluster(binary: str, ports, durable: bool = True,
                  timeout_ms: int = 2000, wait_s: float = 5.0,
                  elect_ms: Optional[int] = None,
                  lease_ms: Optional[int] = None,
                  dirs: Optional[Sequence[str]] = None,
                  flags: Sequence[str] = ()) -> "ClusterProcs":
    """Start one ``sut_node`` per port on localhost; returns a
    :class:`ClusterProcs` once every node answers PING.
    ``elect_ms``/``lease_ms`` tune the failover timings; ``dirs`` gives
    each node a persistent state directory (crash-restart recovery);
    ``flags`` passes extra per-node options (e.g. ``["-B"]`` for the
    split-brain control, ``["-x"]`` for no-fsync)."""
    import subprocess
    import time

    plist = ",".join(str(p) for p in ports)
    argv_per_node = []
    procs = []
    for i in range(len(ports)):
        args = [binary, "-i", str(i), "-n", plist,
                "-t", str(timeout_ms)]
        if elect_ms is not None:
            args += ["-e", str(elect_ms)]
        if lease_ms is not None:
            args += ["-l", str(lease_ms)]
        if dirs is not None:
            args += ["-d", str(dirs[i])]
        if not durable:
            args.append("-N")
        args += list(flags)
        argv_per_node.append(args)
        procs.append(subprocess.Popen(args,
                                      stdout=subprocess.DEVNULL,
                                      stderr=subprocess.DEVNULL))
    cluster = ClusterProcs(procs, argv_per_node, ports, wait_s=wait_s)
    deadline = time.monotonic() + wait_s
    try:
        for i, port in enumerate(ports):
            _wait_ready(procs[i], port, deadline, "sut_node")
    except RuntimeError:
        cluster.kill9_all()
        raise
    return cluster


def _wait_ready(proc, port: int, deadline: float, name: str) -> None:
    """Poll until the server answers PING, it dies, or the deadline
    passes (shared by spawn_server/spawn_cluster)."""
    import time

    conn = SutConnection("127.0.0.1", port, timeout_s=0.3)
    while True:
        rc = proc.poll()
        if rc is not None:          # died at startup (port taken, …)
            raise RuntimeError(
                f"{name} on port {port} exited rc={rc} at startup")
        if time.monotonic() > deadline:
            raise RuntimeError(f"{name} on port {port} never became ready")
        try:
            conn.connect()
            if conn.request("P") == "PONG":
                conn.close()
                return
        except (OSError, TimeoutError):
            time.sleep(0.05)


def spawn_server(binary: str, port: int, *flags: str,
                 wait_s: float = 5.0) -> "subprocess.Popen":
    """Start a local sut_server and wait until it answers PING."""
    import subprocess
    import time

    proc = subprocess.Popen([binary, "-p", str(port), *flags],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        _wait_ready(proc, port, time.monotonic() + wait_s, "sut_server")
    except RuntimeError:
        proc.kill()
        proc.wait()
        raise
    return proc


class ClusterTxn:
    """One wire transaction against a ``sut_node`` cluster — the
    client side of the TB/TR/TP/TW/TI/TC verbs (the cdb2 begin/.../
    commit surface; server-side OCC validation at commit). All verbs
    forward to the leader, so the txn can be driven through any node."""

    def __init__(self, conn: SutConnection):
        self.conn = conn
        self.txid: Optional[int] = None

    # request-line builders + success token: the SQL-surface txn
    # (:mod:`.sql`) overrides ONLY these; reply parsing is shared so
    # the two surfaces cannot silently disagree on shapes
    _dml_ok = "OK"

    def _q_read(self, key: int) -> str:
        return f"TR {self.txid} {key}"

    def _q_predicate(self, table: str, key: int) -> str:
        return f"TP {self.txid} {table} {key}"

    def _q_write(self, key: int, val: int) -> str:
        return f"TW {self.txid} {key} {val}"

    def _q_insert(self, table: str, key: int, rid: int,
                  val: int) -> str:
        return f"TI {self.txid} {table} {key} {rid} {val}"

    def begin(self) -> None:
        reply = self.conn.request("TB")
        if not reply.startswith("T "):
            raise TxnAborted(f"begin failed: {reply}")
        self.txid = int(reply[2:])

    def read(self, key: int) -> Optional[int]:
        reply = self.conn.request(self._q_read(key))
        if reply == "NIL":
            return None
        if reply.startswith("V "):
            return int(reply[2:])
        raise TxnAborted(f"read failed: {reply}")

    def predicate(self, table: str, key: int):
        """All committed rows of (table, key) as [(id, value)]."""
        reply = self.conn.request(self._q_predicate(table, key))
        if not reply.startswith("V"):
            raise TxnAborted(f"predicate failed: {reply}")
        rows = []
        for tok in reply[1:].split():
            rid, val = tok.split(":")
            rows.append((int(rid), int(val)))
        return rows

    def write(self, key: int, val: int) -> None:
        reply = self.conn.request(self._q_write(key, val))
        if reply != self._dml_ok:
            raise TxnAborted(f"write failed: {reply}")

    def insert(self, table: str, key: int, rid: int, val: int) -> None:
        reply = self.conn.request(self._q_insert(table, key, rid, val))
        if reply != self._dml_ok:
            raise TxnAborted(f"insert failed: {reply}")

    def commit(self, nonce: int = 0) -> str:
        """Returns "ok" | "fail" | "unknown"."""
        line = (f"TC {self.txid} {nonce}" if nonce
                else f"TC {self.txid}")
        reply = self.conn.request(line)
        if reply.startswith("OK"):
            return "ok"
        if reply == "FAIL":
            return "fail"
        return "unknown"

    def abort(self) -> None:
        try:
            self.conn.request(f"TA {self.txid}")
        except (TimeoutError, OSError):
            pass


class TxnAborted(Exception):
    """A txn verb failed server-side (conflict / failover): the txn is
    dead and nothing was applied — a clean :fail for mutations."""


class _ClusterTxnClientBase(client_ns.Client):
    """Shared plumbing for txn workload clients: per-worker node
    assignment (cycled), a txn runner that maps conflicts to ``fail``
    and lost outcomes to ``info``."""

    def __init__(self, ports, timeout_s: float = 1.0):
        self.ports = list(ports)
        self.timeout_s = timeout_s
        self._next = 0
        self.conn: Optional[SutConnection] = None
        self._session = 0
        self._seq = 0

    def _clone(self):
        raise NotImplementedError

    def setup(self, test, node):
        import random as _random

        c = self._clone()
        port = self.ports[self._next % len(self.ports)]
        self._next += 1
        c.conn = SutConnection("127.0.0.1", port, self.timeout_s)
        c.conn.connect()
        c._session = _random.SystemRandom().getrandbits(32)
        return c

    def teardown(self, test):
        if self.conn is not None:
            self.conn.close()

    def _nonce(self) -> int:
        self._seq += 1
        return (self._session << 24) | self._seq

    def _make_txn(self):
        """Txn factory — the SQL-surface clients (:mod:`.sql`) swap in
        a text-statement txn here; everything else is shared."""
        return ClusterTxn(self.conn)

    def _run_txn(self, op, body, read_only=False):
        """Run ``body(txn)`` in one wire txn; body returns the ``ok``
        completion (or a full completion dict to use verbatim)."""
        txn = self._make_txn()
        try:
            txn.begin()
            out = body(txn)
            if isinstance(out, dict) and out.get("type") != "ok":
                txn.abort()
                return out
            verdict = txn.commit(0 if read_only else self._nonce())
            if verdict == "ok":
                if isinstance(out, dict):
                    return out
                if out is None:
                    # keep the INVOKED value (e.g. G2's (key, ids) —
                    # the checker keys on it); body returns a value
                    # only when the completion carries new data
                    return {**op, "type": "ok"}
                return {**op, "type": "ok", "value": out}
            if verdict == "fail":
                return {**op, "type": "fail"}
            return {**op, "type": ("fail" if read_only else "info"),
                    "error": "commit unknown"}
        except TxnAborted as e:
            return {**op, "type": "fail", "error": str(e)}
        except (TimeoutError, OSError) as e:
            # a lost reply mid-txn: reads are side-effect-free (fail);
            # a lost COMMIT reply is indeterminate (info)
            return {**op, "type": ("fail" if read_only else "info"),
                    "error": str(e)}


class BankTcpClient(_ClusterTxnClientBase):
    """The bank workload over the wire (``comdb2/core.clj:71-129``):
    accounts are registers keyed 0..n-1; transfers read both balances
    and write both back in one OCC txn — serializability of the commit
    validation is what keeps the total balance invariant."""

    def __init__(self, ports, n: int, starting_balance: int = 10,
                 timeout_s: float = 1.0):
        super().__init__(ports, timeout_s)
        self.n = n
        self.starting_balance = starting_balance

    def _clone(self):
        return BankTcpClient(self.ports, self.n, self.starting_balance,
                             self.timeout_s)

    def setup(self, test, node):
        c = super().setup(test, node)
        deadline = __import__("time").monotonic() + 15.0
        while __import__("time").monotonic() < deadline:
            txn = ClusterTxn(c.conn)
            try:
                txn.begin()
                missing = [i for i in range(c.n)
                           if txn.read(i) is None]
                for i in missing:
                    txn.write(i, c.starting_balance)
                if txn.commit(c._nonce()) == "ok" or not missing:
                    return c
            except (TxnAborted, TimeoutError, OSError):
                pass
            __import__("time").sleep(0.1)
        raise RuntimeError("could not initialize bank accounts")

    def invoke(self, test, op):
        if op["f"] == "read":
            def body(txn):
                balances = []
                for i in range(self.n):
                    v = txn.read(i)
                    if v is None:
                        raise TxnAborted("uninitialized account")
                    balances.append(v)
                return tuple(balances)
            return self._run_txn(op, body, read_only=True)
        if op["f"] == "transfer":
            v = op["value"]
            frm, to, amount = v["from"], v["to"], v["amount"]

            def body(txn):
                b1 = txn.read(frm)
                b2 = txn.read(to)
                if b1 is None or b2 is None:
                    raise TxnAborted("uninitialized account")
                if b1 - amount < 0:
                    return {**op, "type": "fail",
                            "value": ("negative", frm, b1 - amount)}
                txn.write(frm, b1 - amount)
                txn.write(to, b2 + amount)
                return None
            return self._run_txn(op, body)
        raise ValueError(f"unknown f {op['f']!r}")


class G2TcpClient(_ClusterTxnClientBase):
    """Adya G2 over the wire (``jepsen/adya.clj:12-55``): predicate-
    read tables a and b for the key; if neither holds a matching row,
    insert this op's id into its table. Phantom safety comes from the
    server's per-(table, key) version validation at commit: at most
    one insert can commit per key."""

    def _clone(self):
        return G2TcpClient(self.ports, self.timeout_s)

    def invoke(self, test, op):
        k, ids = op["value"]
        a_id, b_id = ids

        def body(txn):
            a = [r for r in txn.predicate("a", k) if r[1] % 3 == 0]
            b = [r for r in txn.predicate("b", k) if r[1] % 3 == 0]
            if a or b:
                return {**op, "type": "fail"}
            if a_id is not None:
                txn.insert("a", k, a_id, 30)
            else:
                txn.insert("b", k, b_id, 30)
            return None
        return self._run_txn(op, body)


class DirtyReadsTcpClient(_ClusterTxnClientBase):
    """The dirty-reads workload over the wire
    (``comdb2/core.clj:320-355``): ``write x`` updates every row of the
    dirty table to x in ONE txn (reading each row first, so the commit
    carries a read set); ``read`` returns all rows' values from one
    read-only txn. A row value from a write that reported :fail is the
    anomaly (``core.clj:492-523``); a non-uniform read is an
    inconsistent (torn) read. The ``-R`` dirty-commit control applies
    conflicted txns while reporting FAIL — the classic
    effects-misclassification bug this workload exists to catch.

    Rows live at register keys ``base .. base+n-1`` (base offsets the
    dirty table away from other workloads' keys)."""

    def __init__(self, ports, n: int, base: int = 10_000,
                 timeout_s: float = 1.0):
        super().__init__(ports, timeout_s)
        self.n = n
        self.base = base

    def _clone(self):
        return DirtyReadsTcpClient(self.ports, self.n, self.base,
                                   self.timeout_s)

    def setup(self, test, node):
        c = super().setup(test, node)
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            txn = ClusterTxn(c.conn)
            try:
                txn.begin()
                missing = [i for i in range(c.n)
                           if txn.read(c.base + i) is None]
                for i in missing:
                    txn.write(c.base + i, -1)
                if txn.commit(c._nonce()) == "ok" or not missing:
                    return c
            except (TxnAborted, TimeoutError, OSError):
                pass
            time.sleep(0.1)
        raise RuntimeError("could not initialize dirty rows")

    def invoke(self, test, op):
        if op["f"] == "read":
            def body(txn):
                vals = []
                for i in range(self.n):
                    v = txn.read(self.base + i)
                    if v is None:
                        raise TxnAborted("uninitialized row")
                    vals.append(v)
                # skip initializer rows, like the reference's
                # ``where x != -1`` (core.clj:341)
                return tuple(v for v in vals if v != -1)
            return self._run_txn(op, body, read_only=True)
        if op["f"] == "write":
            import random as _random

            x = op["value"]
            order = list(range(self.n))
            _random.shuffle(order)

            def body(txn):
                for i in order:
                    txn.read(self.base + i)
                for i in order:
                    txn.write(self.base + i, x)
                return None
            return self._run_txn(op, body)
        raise ValueError(f"unknown f {op['f']!r}")


class ListAppendTcpClient(_ClusterTxnClientBase):
    """Elle's list-append workload over the wire txn surface: appends
    are inserts into table ``a`` at ``BASE + k`` (insert-only, so the
    server's per-(table, key) row-count validation gives appends the
    same conflict rules as the G2 workload), reads are predicate
    reads returning the key's rows in log order — the WHOLE list,
    so committed reads recover the version order Elle-style. Reads
    see the txn's own buffered appends (client-side fixup: the wire
    predicate read serves the committed prefix only)."""

    BASE = 30_000

    def _clone(self):
        return ListAppendTcpClient(self.ports, self.timeout_s)

    def invoke(self, test, op):
        def body(txn):
            done = []
            own: dict = {}
            for f, k, v in op["value"]:
                if f == "append":
                    txn.insert("a", self.BASE + k, v, v)
                    own.setdefault(k, []).append(v)
                    done.append(("append", k, v))
                else:
                    rows = txn.predicate("a", self.BASE + k)
                    vals = [val for _rid, val in rows] + own.get(k, [])
                    done.append(("r", k, tuple(vals)))
            return {**op, "type": "ok", "value": tuple(done)}
        return self._run_txn(op, body)


class CounterTcpClient(_ClusterTxnClientBase):
    """The counter workload over the wire (``checker.clj:220-272``):
    ``add v`` reads the counter register and writes back the sum in one
    OCC txn (a conflicted add cleanly fails and is retried by the
    generator's next op); ``read`` returns the register from a
    read-only txn. ``-T`` (no validation) loses concurrent updates, so
    a later read falls below the sum of acknowledged adds — the
    counter checker's lower bound."""

    KEY = 20_000

    def _clone(self):
        return CounterTcpClient(self.ports, self.timeout_s)

    def invoke(self, test, op):
        if op["f"] == "read":
            def body(txn):
                v = txn.read(self.KEY)
                return 0 if v is None else v
            return self._run_txn(op, body, read_only=True)
        if op["f"] == "add":
            v = op["value"]

            def body(txn):
                cur = txn.read(self.KEY)
                txn.write(self.KEY, (0 if cur is None else cur) + v)
                return None
            return self._run_txn(op, body)
        raise ValueError(f"unknown f {op['f']!r}")
