"""TCP SUT client — drive the native ``sut_server`` over its line
protocol.

This closes the distributed loop end to end: the harness's workers talk
to a real out-of-process SUT over sockets, socket timeouts surface as
indeterminate (``info``) completions exactly like the reference's
JDBC timeouts, and process faults (SIGSTOP on the server) produce the
hung-op behavior the checker must reason about.
"""

from __future__ import annotations

import socket
from typing import Optional, Tuple

from ..harness import client as client_ns
from ..ops.kv import tuple_


class SutConnection:
    """One line-protocol connection with a hard timeout."""

    def __init__(self, host: str, port: int, timeout_s: float = 1.0):
        self.addr = (host, port)
        self.timeout_s = timeout_s
        self.sock: Optional[socket.socket] = None
        self.rfile = None

    def connect(self) -> None:
        self.close()
        s = socket.create_connection(self.addr, timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = s
        self.rfile = s.makefile("r")

    def close(self) -> None:
        try:
            if self.rfile is not None:
                self.rfile.close()
            if self.sock is not None:
                self.sock.close()
        except OSError:
            pass
        self.sock = None
        self.rfile = None

    def request(self, line: str) -> str:
        """Send one request line; returns the reply line. Raises
        ``TimeoutError`` when the server doesn't answer in time (the
        op's outcome is then unknown — it may have applied)."""
        if self.sock is None:
            self.connect()
        try:
            self.sock.sendall((line + "\n").encode())
            reply = self.rfile.readline()
        except socket.timeout as e:
            self.close()
            raise TimeoutError(f"SUT timeout on {line!r}") from e
        except OSError as e:
            self.close()
            raise TimeoutError(f"SUT connection lost on {line!r}") from e
        if not reply:
            self.close()
            raise TimeoutError(f"SUT closed connection on {line!r}")
        return reply.strip()


class TcpRegisterClient(client_ns.Client):
    """read/write/cas against ``sut_server``; values are keyed
    ``(k, v)`` tuples like the comdb2 register client's. A timeout
    yields an ``info`` completion (indeterminate — the worker retires
    the process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 7777,
                 timeout_s: float = 1.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.conn: Optional[SutConnection] = None

    def setup(self, test, node):
        c = TcpRegisterClient(self.host, self.port, self.timeout_s)
        c.conn = SutConnection(self.host, self.port, self.timeout_s)
        c.conn.connect()
        return c

    def teardown(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if op["value"] is not None else (1, None)
        try:
            if f == "read":
                reply = self.conn.request("R")
                if reply == "NIL":
                    return {**op, "type": "ok", "value": tuple_(k, None)}
                if reply.startswith("V "):
                    return {**op, "type": "ok",
                            "value": tuple_(k, int(reply[2:]))}
                return {**op, "type": "fail"}
            if f == "write":
                reply = self.conn.request(f"W {v}")
            elif f == "cas":
                a, b = v
                reply = self.conn.request(f"C {a} {b}")
            else:
                raise ValueError(f"unknown f {f!r}")
            if reply == "OK":
                return {**op, "type": "ok"}
            if reply == "FAIL":
                return {**op, "type": "fail"}
            return {**op, "type": "info", "error": reply}
        except TimeoutError as e:
            return {**op, "type": "info", "error": str(e)}


def spawn_server(binary: str, port: int, *flags: str,
                 wait_s: float = 5.0) -> "subprocess.Popen":
    """Start a local sut_server and wait until it answers PING."""
    import subprocess
    import time

    proc = subprocess.Popen([binary, "-p", str(port), *flags],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + wait_s
    conn = SutConnection("127.0.0.1", port, timeout_s=0.3)
    while time.monotonic() < deadline:
        rc = proc.poll()
        if rc is not None:      # died at startup (bad port/flags)
            raise RuntimeError(
                f"sut_server on port {port} exited rc={rc} at startup")
        try:
            conn.connect()
            if conn.request("P") == "PONG":
                conn.close()
                return proc
        except (OSError, TimeoutError):
            time.sleep(0.05)
    proc.kill()
    raise RuntimeError(f"sut_server on port {port} never became ready")
