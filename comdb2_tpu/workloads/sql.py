"""SQL-text workload clients — drive ``sut_node`` through its SQL
front end instead of the typed verbs.

The reference harness speaks ONLY SQL text: per-connection session
controls then text statements (``set hasql on``, ``set transaction
serializable``, ``set max_retries 100000`` — ``comdb2/core.clj:371-375``),
reads as SELECTs, writes as INSERT-or-UPDATE, cas as ``update ... where
id = ? and val = expected`` classified by affected-row count
(``comdb2/core.clj:432-474``, ``ctest/register.c:157-171``). These
clients issue the same statement shapes over the wire; the server
parses them into the typed verbs (``native/src/sql_front.cpp``, the
``db/sqlinterfaces.c:5970`` role). Replay safety rides ``SET cnonce``
(the cdb2api cnonce role) instead of the ``M`` wrapper.

The point is parity, not convenience: the same workloads and negative
controls must hold when driven through the query-language surface.
"""

from __future__ import annotations

from ..ops.kv import tuple_
from .tcp import (ClusterTxn, G2TcpClient, SutConnection,
                  TcpClusterRegisterClient, TxnAborted)

SESSION_SETS = ("set hasql on", "set transaction serializable",
                "set max_retries 100000")


def _session_setup(conn: SutConnection) -> None:
    """The reference's per-connection session preamble."""
    for stmt in SESSION_SETS:
        if conn.request(stmt) != "OK":
            raise OSError(f"session setup failed: {stmt!r}")


class SqlTxn(ClusterTxn):
    """One SQL-text transaction (BEGIN .. COMMIT) with the ClusterTxn
    API, so the txn workload clients run unchanged over SQL. Only the
    statement text and control verbs differ; reply parsing is the
    shared ClusterTxn code."""

    _dml_ok = "ROWS 1"

    def _q_read(self, key: int) -> str:
        return f"select val from register where id = {key}"

    def _q_predicate(self, table: str, key: int) -> str:
        return f"select id, v from {table} where k = {key}"

    def _q_write(self, key: int, val: int) -> str:
        return f"update register set val = {val} where id = {key}"

    def _q_insert(self, table: str, key: int, rid: int,
                  val: int) -> str:
        return (f"insert into {table} (id, k, v) values "
                f"({rid}, {key}, {val})")

    def begin(self) -> None:
        reply = self.conn.request("begin")
        if reply.startswith("ERR transaction already open"):
            # a prior txn died server-side (conflict / failover) with
            # the session id still set — roll it back and retry once
            self.conn.request("rollback")
            reply = self.conn.request("begin")
        if reply != "OK":
            raise TxnAborted(f"begin failed: {reply}")
        self.txid = 0          # session-scoped; id lives server-side

    def commit(self, nonce: int = 0) -> str:
        if nonce:
            if self.conn.request(f"set cnonce {nonce}") != "OK":
                return "unknown"
        reply = self.conn.request("commit")
        if reply.startswith("OK"):
            return "ok"
        if reply == "FAIL":
            return "fail"
        return "unknown"

    def abort(self) -> None:
        try:
            self.conn.request("rollback")
        except (TimeoutError, OSError):
            pass


class SqlClusterRegisterClient(TcpClusterRegisterClient):
    """The register workload as SQL text with HA retry: reads are
    SELECTs, writes INSERT-or-UPDATE, cas the guarded UPDATE — each
    classified by rowcount like the reference client. Mutations carry
    ``SET cnonce`` so a retried statement that already applied replays
    its recorded outcome (blkseq dedup) on whichever node serves it."""

    def _clone(self):
        return SqlClusterRegisterClient(self.ports, self.timeout_s,
                                        self.mutate_retries)

    def _post_connect(self) -> None:
        _session_setup(self.conn)

    def _rotate(self) -> None:
        super()._rotate()
        # a fresh connection is a fresh SQL session
        try:
            self._post_connect()
        except (TimeoutError, OSError):
            pass               # next request surfaces the failure

    def _mutate_sql(self, stmt: str) -> str:
        """One nonce-carrying SQL mutation with retry-elsewhere;
        returns "OK" | "FAIL" | "UNKNOWN" (the _mutate contract)."""
        self._seq += 1
        nonce = (self._session << 24) | self._seq
        maybe_delivered = False
        for _ in range(self.mutate_retries):
            try:
                # side-effect-free session statement: a timeout here
                # means the mutation was never sent — rotate without
                # marking the attempt as possibly delivered
                if self.conn.request(f"set cnonce {nonce}") != "OK":
                    self._rotate()
                    continue
            except (TimeoutError, OSError):
                self._rotate()
                continue
            try:
                reply = self.conn.request(stmt)
            except TimeoutError:
                maybe_delivered = True      # sent, no complete reply
                self._rotate()
                continue
            except OSError:
                self._rotate()              # never connected: safe
                continue
            if reply == "ROWS 1":
                return "OK"
            if reply == "ROWS 0":
                return "FAIL"
            maybe_delivered = True
            self._rotate()
        return "UNKNOWN" if maybe_delivered else "FAIL"

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if op["value"] is not None else (1, None)
        if f == "read":
            try:
                reply = self.conn.request(
                    f"select val from register where id = {k}")
            except (TimeoutError, OSError):
                return {**op, "type": "fail"}
            if reply == "NIL":
                return {**op, "type": "ok", "value": tuple_(k, None)}
            if reply.startswith("V "):
                return {**op, "type": "ok",
                        "value": tuple_(k, int(reply[2:]))}
            return {**op, "type": "fail"}
        if f == "write":
            reply = self._mutate_sql(
                f"insert into register (id, val) values ({k}, {v})")
        elif f == "cas":
            a, b = v
            reply = self._mutate_sql(
                f"update register set val = {b} "
                f"where id = {k} and val = {a}")
        else:
            raise ValueError(f"unknown f {f!r}")
        if reply == "OK":
            return {**op, "type": "ok"}
        if reply == "FAIL":
            return {**op, "type": "fail"}
        return {**op, "type": "info", "error": reply}


class SqlG2Client(G2TcpClient):
    """Adya G2 driven as SQL text: predicate SELECTs over tables a/b
    and a guarded INSERT, in one BEGIN..COMMIT (``jepsen/adya.clj:
    12-55``). Server-side OCC validation at commit is what must keep
    at most one insert per key — including under ``-T`` (buggy-txn),
    where the anomaly must surface through this surface too."""

    def _clone(self):
        return SqlG2Client(self.ports, self.timeout_s)

    def setup(self, test, node):
        c = super().setup(test, node)
        _session_setup(c.conn)
        return c

    def _make_txn(self):
        return SqlTxn(self.conn)
