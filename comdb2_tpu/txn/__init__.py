"""Transactional serializability checking — the Elle axis.

The linearizability engines verify single-op histories; this package
verifies *transactional* ones. A txn op's value is a sequence of
micro-ops over list-append registers::

    (("append", k, v), ("r", k, (v1, v2, ...)))   # completion
    (("append", k, v), ("r", k, None))            # invocation

Reads return the whole list, so every committed read recovers a
prefix of the key's version order — the property Elle's list-append
workload is built on (elle/list_append.clj). The pipeline:

- :mod:`.edges` — host pass: version orders from reads, then ww/wr/rw
  dependency edges (realtime optional) as padded adjacency tensors.
- :mod:`.closure_jax` — device cycle engine: transitive closure by
  repeated squaring of N x N tiles inside ONE jit (O(log N) matmuls
  on the MXU; never a per-edge dispatch).
- :mod:`.scc` — host Tarjan SCC engine (oracle + small-N fast path).
- :mod:`.counterexample` — shortest-cycle decode back to actual ops.
- :mod:`.adapters` — second-opinion views of the legacy G2 and
  dirty-reads workload histories.

``check_txn`` runs the whole pipeline; ``checker.checkers.
Serializable`` wraps it in the standard checker protocol.
"""

from __future__ import annotations

from .edges import (TXN_N_FLOOR, TxnGraph, infer_edges, txns_of_history)
from .check import check_txn

__all__ = ["TXN_N_FLOOR", "TxnGraph", "infer_edges",
           "txns_of_history", "check_txn"]
