"""Dependency-edge inference over list-append txn histories.

This is the host half of the serializability checker (the Elle move,
elle/list_append.clj): because every read returns the key's WHOLE
list, the longest committed read of a key IS its version order, and
every other read must be a prefix of it. From the recovered orders:

- ``ww``  w1 -> w2 when w1's append immediately precedes w2's in a
  key's version order (write dependency).
- ``wr``  w -> t when txn t read a list whose last element was
  appended by w (read dependency).
- ``rw``  t -> w when w appended the element immediately after the
  last one t observed — including the first element after an empty
  read (anti-dependency).
- ``rt``  (optional) t1 -> t2 when t1 completed before t2 was
  invoked (realtime order, for strict serializability).

Direct (non-cycle) anomalies are flagged here too, Adya names:

- ``G1a`` — a committed read observed a value appended by a txn that
  reported :fail (aborted read; the ``-R`` dirty-commit control's
  signature). The dirty txn's effects are real — it joins the graph
  as a node so cycles through it are found.
- ``duplicate`` — one value appears twice in a read, or two txns
  appended the same (key, value) (the ``-D`` no-dedup control).
- ``incompatible-order`` — two committed reads of one key disagree
  on the prefix order (torn version order; e.g. split-brain).

The adjacency output is a ``(4, N, N)`` bool tensor (ww, wr, rw, rt
planes) padded to a pow2 txn count — the same closed-program-set
convention as :mod:`comdb2_tpu.service.bucketing` — so the device
closure engine compiles one program per bucket, forever.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.op import Op
from ..utils import next_pow2

#: pow2 floor of the padded txn-count axis (bucketing convention)
TXN_N_FLOOR = 16

#: adjacency planes, in order
PLANES = ("ww", "wr", "rw", "rt")

APPEND = "append"
READ = "r"


def micro_ops(value: Any) -> Tuple[Tuple[Any, ...], ...]:
    """Normalize a txn op value to a tuple of ``(f, k, v)`` micro-ops.
    EDN round-trips deliver nested tuples already; lists are accepted
    for hand-built histories. Raises ``ValueError`` on malformed
    micro-ops (the service answers those ``bad-request``)."""
    if value is None:
        return ()
    out = []
    for m in value:
        m = tuple(m)
        if len(m) != 3 or m[0] not in (APPEND, READ):
            raise ValueError(f"malformed micro-op {m!r}")
        f, k, v = m
        if f == READ and v is not None:
            v = tuple(v)
        out.append((f, k, v))
    return tuple(out)


@dataclass
class Txn:
    """One transaction instance recovered from the history."""

    index: int                 # node id in the graph
    op: Op                     # the completion (or lone invoke) op
    invoke_at: int             # history position of the invocation
    complete_at: int           # history position of the completion
    status: str                # "ok" | "fail" | "info"
    mops: Tuple[Tuple[Any, ...], ...] = ()
    dirty: bool = False        # failed txn whose writes were observed


@dataclass
class TxnGraph:
    """The inferred dependency graph plus everything the counterexample
    decoder needs to speak in terms of actual ops."""

    txns: List[Txn]
    adj: np.ndarray                      # (4, n, n) bool — PLANES order
    labels: Dict[Tuple[int, int], List[Tuple[str, Any]]]
    anomalies: List[dict] = field(default_factory=list)
    orders: Dict[Any, Tuple[Any, ...]] = field(default_factory=dict)

    @property
    def n(self) -> int:
        return len(self.txns)

    def padded(self, n_pad: Optional[int] = None) -> np.ndarray:
        """The adjacency tensor padded to a pow2 txn count (floor
        ``TXN_N_FLOOR``) — pad rows/cols carry no edges, so they are
        inert under closure."""
        n = self.n
        np2 = n_pad if n_pad is not None else next_pow2(max(n, 1),
                                                        TXN_N_FLOOR)
        if np2 < n:
            raise ValueError(f"n_pad {np2} < {n} txns")
        out = np.zeros((len(PLANES), np2, np2), dtype=bool)
        out[:, :n, :n] = self.adj
        return out


def txns_of_history(history: Sequence[Op]) -> Tuple[List[Txn], List[dict]]:
    """Pair txn invocations with their completions. A process runs one
    txn at a time (the harness worker contract); an unpaired invoke or
    an :info completion is indeterminate — its writes may be visible.
    Non-txn ops (nemesis, other workloads) are skipped."""
    txns: List[Txn] = []
    anomalies: List[dict] = []
    open_at: Dict[Any, Tuple[int, Op]] = {}
    for i, op in enumerate(history):
        if op.f != "txn":
            continue
        if op.type == "invoke":
            if op.process in open_at:
                raise ValueError(
                    f"process {op.process!r} double-pending at {i}")
            open_at[op.process] = (i, op)
            continue
        # an orphan completion (truncated history) has an UNKNOWN
        # invoke time: -1 keeps it unconstrained in the realtime
        # plane instead of fabricating rt edges from its completion
        # position (lost invokes may have overlapped anything)
        inv_i, inv_op = open_at.pop(op.process, (-1, op))
        try:
            mops = micro_ops(op.value if op.value is not None
                             else inv_op.value)
        except ValueError as e:
            anomalies.append({"name": "malformed", "op": op,
                              "error": str(e)})
            continue
        txns.append(Txn(index=len(txns), op=op, invoke_at=inv_i,
                        complete_at=i, status=op.type, mops=mops))
    for inv_i, inv_op in open_at.values():
        try:
            mops = micro_ops(inv_op.value)
        except ValueError as e:
            anomalies.append({"name": "malformed", "op": inv_op,
                              "error": str(e)})
            continue
        txns.append(Txn(index=len(txns), op=inv_op, invoke_at=inv_i,
                        complete_at=len(history), status="info",
                        mops=mops))
    return txns, anomalies


def _version_orders(txns: List[Txn], anomalies: List[dict]):
    """Longest-read version order per key + the value->writer map.
    Reads must agree prefix-wise; disagreement is flagged once per
    key. Duplicate values (in one read, or appended twice) are the
    ``-D`` shape."""
    writer: Dict[Tuple[Any, Any], int] = {}
    longest: Dict[Any, Tuple[Any, ...]] = {}
    for t in txns:
        for f, k, v in t.mops:
            if f != APPEND or v is None:
                # a value-less append (an invocation that never
                # learned its value, e.g. an aborted generator txn)
                # can't be tracked
                continue
            if (k, v) in writer:
                anomalies.append({
                    "name": "duplicate",
                    "key": k, "value": v,
                    "txns": [writer[(k, v)], t.index],
                    "note": "value appended by two txns (no-dedup)"})
            else:
                writer[(k, v)] = t.index
    for t in txns:
        if t.status != "ok":
            continue
        for f, k, v in t.mops:
            if f != READ or v is None:
                continue
            if len(set(v)) != len(v):
                anomalies.append({
                    "name": "duplicate", "key": k, "txn": t.index,
                    "read": v,
                    "note": "value read twice in one list"})
            phantom = [x for x in v if (k, x) not in writer]
            if phantom:
                # a value NOBODY appended is fabricated/corrupted
                # data — exactly the dirty-data class this checker
                # hunts; silently accepting it would also suppress
                # the wr/ww edges of the legitimate neighbors
                anomalies.append({
                    "name": "unexpected-value", "key": k,
                    "txn": t.index, "values": phantom,
                    "note": "read observed value(s) no txn appended"})
            cur = longest.get(k, ())
            short, long_ = sorted((cur, tuple(v)), key=len)
            if long_[:len(short)] != short:
                anomalies.append({
                    "name": "incompatible-order", "key": k,
                    "txn": t.index, "read": v, "longest": cur})
                continue
            longest[k] = long_
    return longest, writer


def infer_edges(history: Sequence[Op],
                realtime: bool = False) -> TxnGraph:
    """Run the whole host pass: txn recovery, version orders, direct
    anomalies, and the (4, n, n) dependency adjacency."""
    txns, anomalies = txns_of_history(history)
    orders, writer = _version_orders(txns, anomalies)

    # failed/indeterminate txns join the graph only when their writes
    # are OBSERVED (their effects provably happened). A failed txn
    # observed is the G1a aborted read; an :info txn observed is a
    # normal maybe-committed outcome.
    observed: set = set()
    for k, order in orders.items():
        for v in order:
            w = writer.get((k, v))
            if w is not None:
                observed.add(w)
    node_of: Dict[int, int] = {}
    nodes: List[Txn] = []
    for t in txns:
        if t.status == "ok" or t.index in observed:
            if t.status != "ok":
                t.dirty = True
            node_of[t.index] = len(nodes)
            nodes.append(t)
    for t in nodes:
        if t.dirty and t.status == "fail":
            anomalies.append({
                "name": "G1a", "txn": node_of[t.index],
                "note": "a :fail txn's append was observed by a "
                        "committed read (aborted read / dirty "
                        "commit)"})

    n = len(nodes)
    adj = np.zeros((len(PLANES), n, n), dtype=bool)
    labels: Dict[Tuple[int, int], List[Tuple[str, Any]]] = {}

    def edge(plane: str, a: int, b: int, key: Any) -> None:
        if a == b:
            return
        p = PLANES.index(plane)
        if not adj[p, a, b]:
            adj[p, a, b] = True
        labels.setdefault((a, b), []).append((plane, key))

    pos: Dict[Tuple[Any, Any], int] = {}
    for k, order in orders.items():
        for i, v in enumerate(order):
            pos[(k, v)] = i
        # ww: consecutive observed appends
        for a, b in zip(order, order[1:]):
            wa, wb = writer.get((k, a)), writer.get((k, b))
            if wa in node_of and wb in node_of:
                edge("ww", node_of[wa], node_of[wb], k)

    for t in nodes:
        ti = node_of[t.index]
        for f, k, v in t.mops:
            if f != READ or v is None:
                continue
            # strip this txn's OWN trailing appends (a read after an
            # append inside one txn sees it; it is not a dependency)
            seen = list(v)
            while seen and writer.get((k, seen[-1])) == t.index:
                seen.pop()
            order = orders.get(k, ())
            if seen:
                last = seen[-1]
                w = writer.get((k, last))
                if w is not None and w in node_of:
                    edge("wr", node_of[w], ti, k)
                nxt = pos.get((k, last))
                nxt = None if nxt is None else nxt + 1
            else:
                nxt = 0
            if nxt is not None and nxt < len(order):
                w = writer.get((k, order[nxt]))
                if w is not None and w in node_of:
                    edge("rw", ti, node_of[w], k)

    if realtime and n:
        # one broadcast, not an O(n^2) Python loop: the service runs
        # this at admission on a single CPU, where a 4096-txn double
        # loop would stall the whole daemon for over a minute. rt
        # edges carry no per-edge labels either (~n^2/2 of them at
        # realtime) — the counterexample decoder synthesizes the
        # constant ("rt", None) label on demand.
        ok = np.array([t.status == "ok" for t in nodes])
        comp = np.array([t.complete_at for t in nodes])
        inv = np.array([t.invoke_at for t in nodes])
        rt = (comp[:, None] < inv[None, :]) & ok[:, None] & ok[None, :]
        np.fill_diagonal(rt, False)
        adj[PLANES.index("rt")] = rt

    return TxnGraph(txns=nodes, adj=adj, labels=labels,
                    anomalies=anomalies, orders=orders)


__all__ = ["TXN_N_FLOOR", "PLANES", "Txn", "TxnGraph", "micro_ops",
           "txns_of_history", "infer_edges"]
