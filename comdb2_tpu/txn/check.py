"""The serializability pipeline: history -> edges -> cycles -> verdict.

``check_txn`` is the single entry point every surface shares (the
``Serializable`` checker class, ``filetest --txn``, the service's
``txn`` request kind, and the cluster anomaly tests). Verdict map::

    {"valid?": True | False | "unknown",
     "txn-count": n, "edge-count": e,
     "anomalies": [...direct anomalies...],     # G1a / duplicate / ...
     "counterexample": {"class": "G2-item", "cycle": [...]} | None}

Backends: ``host`` (Tarjan SCC), ``device`` (matrix closure, one jit
dispatch), ``auto`` (host below ``DEVICE_THRESHOLD`` txns — tiny
graphs are cheaper than one tunnel round-trip; device above).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..obs import trace as _obs
from ..ops.op import Op
from .edges import TxnGraph, infer_edges

#: auto-backend crossover: below this many txns the ~100 ms dispatch
#: round-trip dwarfs a host SCC over a sparse graph
DEVICE_THRESHOLD = 1024


@_obs.traced("txn.check")
def check_txn(history: Sequence[Op],
              backend: str = "auto",
              realtime: bool = False,
              graph: Optional[TxnGraph] = None) -> dict:
    """Check a txn history for serializability. Malformed histories
    raise ``ValueError`` (callers map that to unknown/bad-request —
    same contract as the linear pipeline's packing)."""
    g = graph if graph is not None else infer_edges(history,
                                                   realtime=realtime)
    cex = None
    if g.n and g.adj.any():
        if backend == "host" or (backend == "auto"
                                 and g.n < DEVICE_THRESHOLD):
            from .scc import cyclic_layers_host

            diag = cyclic_layers_host(g.adj, realtime=realtime)
        else:
            from .closure_jax import cyclic_layers_device

            diag = cyclic_layers_device(g.adj, realtime=realtime)
        from .counterexample import decode

        cex = decode(g, np.asarray(diag), realtime=realtime)
    return verdict_map(g, cex)


def verdict_map(graph: TxnGraph, cex: Optional[dict]) -> dict:
    """The verdict for an inferred graph + decoded counterexample —
    the ONE place the tri-state is computed, shared by every surface
    (check_txn here, the service's coalesced dispatch) so a partially
    unparseable history answers ``unknown`` identically everywhere."""
    anomalies = [a for a in graph.anomalies if a["name"] != "malformed"]
    malformed = len(graph.anomalies) - len(anomalies)
    valid = not anomalies and cex is None
    if valid and malformed:
        valid = "unknown"                # something was unparseable
    out = {
        "valid?": valid,
        "txn-count": graph.n,
        "edge-count": int(graph.adj.sum()),
        "anomalies": anomalies,
        "counterexample": cex,
    }
    if malformed:
        out["malformed-ops"] = malformed
    return out


__all__ = ["DEVICE_THRESHOLD", "check_txn", "verdict_map"]
