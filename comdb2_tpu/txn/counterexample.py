"""Counterexample decode — a shortest dependency cycle in actual ops.

The engines return only WHICH vertices sit on a cycle, per layer (the
readback must stay small). Reconstruction runs on the host over the
labeled adjacency the inference pass already holds: pick the smallest
Adya layer with a cycle, BFS the shortest closed walk through one
cyclic vertex, and render every hop with its edge type, key, and the
real txn ops — the shape of the reference's ``:anomalies`` output
(elle's explain-cycle), so a human can replay the violation.
"""

from __future__ import annotations

from collections import deque
from typing import List, Optional

import numpy as np

from .edges import TxnGraph
from .scc import layers_of

#: Adya class per smallest cyclic layer
LAYER_CLASS = ("G0", "G1c", "G2-item")


#: BFS start vertices tried before settling for the best cycle found
#: so far — decode runs inside the single-threaded service tick, and
#: an all-cyclic 4096-node realtime graph would otherwise pay one
#: full-graph BFS per cyclic vertex (the same stall class the
#: vectorized rt inference fixed). Any cycle is a valid
#: counterexample; minimality is best-effort.
MAX_BFS_STARTS = 8


def shortest_cycle(layer: np.ndarray, mask: np.ndarray) -> List[int]:
    """A short cycle through a masked vertex of one layer's
    adjacency: BFS from up to ``MAX_BFS_STARTS`` cyclic vertices,
    keeping the shortest closed walk seen."""
    best: List[int] = []
    for v in np.flatnonzero(mask)[:MAX_BFS_STARTS]:
        prev = {int(v): -1}
        q = deque([int(v)])
        found = None
        while q and found is None:
            u = q.popleft()
            for w in np.flatnonzero(layer[u]):
                w = int(w)
                if w == v:
                    found = u
                    break
                if w not in prev:
                    prev[w] = u
                    q.append(w)
        if found is None:
            continue                      # v reaches no cycle back
        path = [found]
        while path[-1] != v:
            path.append(prev[path[-1]])
        path.reverse()                    # v ... found, edge found->v
        if not best or len(path) < len(best):
            best = path
        if len(best) == 2:
            break                         # can't beat a 2-cycle
    return best


def explain_edge(graph: TxnGraph, a: int, b: int,
                 allowed_planes) -> dict:
    """The label of edge a->b restricted to the layer's planes. rt
    edges are label-free (edge inference skips ~n^2/2 label appends);
    their constant label is synthesized here."""
    for plane, key in graph.labels.get((a, b), ()):
        if plane in allowed_planes:
            return {"type": plane, "key": key}
    if "rt" in allowed_planes and graph.adj[3, a, b]:
        return {"type": "rt", "key": None}
    return {"type": "?", "key": None}


def decode(graph: TxnGraph, diag: np.ndarray,
           realtime: bool = False) -> Optional[dict]:
    """Engine output -> counterexample map, or None when acyclic.
    ``diag`` is the (3, n)-sliced cyclic-vertex mask (any padding
    already trimmed); ``realtime`` must match what the engine saw."""
    layer_ix = None
    for i in range(3):
        if diag[i].any():
            layer_ix = i
            break
    if layer_ix is None:
        return None
    rt = ("rt",) if realtime else ()
    allowed = (("ww",) + rt, ("ww", "wr") + rt,
               ("ww", "wr", "rw") + rt)[layer_ix]
    layers = layers_of(graph.adj, realtime=realtime)
    cycle = shortest_cycle(layers[layer_ix], diag[layer_ix])
    steps = []
    for i, a in enumerate(cycle):
        b = cycle[(i + 1) % len(cycle)]
        t = graph.txns[a]
        steps.append({
            "txn": a,
            "process": t.op.process,
            "status": t.status + (" (dirty)" if t.dirty else ""),
            "value": t.mops,
            "edge": explain_edge(graph, a, b, allowed),
        })
    return {"class": LAYER_CLASS[layer_ix], "cycle": steps}


def render_text(cex: dict) -> str:
    """One line per hop: ``T3 ok (p 1) [...] --rw(k=2)--> T5``."""
    lines = [f"{cex['class']} cycle, {len(cex['cycle'])} txns:"]
    steps = cex["cycle"]
    for i, s in enumerate(steps):
        nxt = steps[(i + 1) % len(steps)]["txn"]
        e = s["edge"]
        key = "" if e["key"] is None else f"(k={e['key']})"
        lines.append(
            f"  T{s['txn']} {s['status']} (p {s['process']}) "
            f"{list(s['value'])!r} --{e['type']}{key}--> T{nxt}")
    return "\n".join(lines)


__all__ = ["LAYER_CLASS", "shortest_cycle", "decode", "render_text"]
