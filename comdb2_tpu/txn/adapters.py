"""Second-opinion views: legacy workload histories as txn histories.

The bespoke :class:`~comdb2_tpu.checker.workloads.G2Checker` and
:class:`~comdb2_tpu.checker.workloads.DirtyReadsChecker` each encode
ONE anomaly shape. Re-expressing their histories as txn micro-ops
lets the dependency-graph checker pass judgement on the same runs —
two independent verdicts that must agree on the seeded negative
controls (the cross-wiring satellite). The adapters are lossy only
where the source history is:

- G2 ops never record what their predicate reads observed, but a
  committed insert PROVES its predicate saw empty (that is the only
  path to the insert), and insert-only tables mean the final table
  contents are exactly the committed inserts — so a synthesized
  final audit read anchors the version order the rw edges need.
- Dirty-reads registers are overwriting (no recoverable version
  order), so each write becomes its own single-append key: a read
  observing value x is a read of x's key, which makes "a :fail
  write's value was read" exactly the graph checker's G1a.
"""

from __future__ import annotations

from typing import List, Sequence

from ..checker.independent import is_tuple
from ..ops.op import Op


def g2_as_txns(history: Sequence[Op]) -> List[Op]:
    """Adya-G2 insert ops -> txn ops. Each insert is one txn that
    predicate-read BOTH tables for its key (observed empty) and
    appended its row id to its own table's list; a final audit txn
    reads every touched table list (committed inserts, history
    order). Two committed inserts per key then form the rw/rw cycle
    whose count shortcut is G2Checker."""
    out: List[Op] = []
    committed: dict = {}                  # (k, tbl) -> [rid...]
    keys: List = []
    for op in history:
        if op.f != "insert" or op.value is None:
            continue
        v = op.value
        k, ids = (v.key, v.value) if is_tuple(v) else (v[0], v[1])
        a_id, b_id = ids
        tbl, rid = ("a", a_id) if a_id is not None else ("b", b_id)
        empty = None if op.type == "invoke" else ()
        mops = (("r", (k, "a"), empty), ("r", (k, "b"), empty),
                ("append", (k, tbl), rid))
        out.append(op.with_(f="txn", value=mops))
        if (k, "a") not in committed:
            keys.append(k)
            committed[(k, "a")] = []
            committed[(k, "b")] = []
        if op.type == "ok":
            committed[(k, tbl)].append(rid)
    if out:
        audit = tuple(("r", kt, tuple(rids))
                      for kt, rids in committed.items())
        out.append(Op("g2-audit", "invoke", "txn",
                      tuple((f, kt, None) for f, kt, _ in audit)))
        out.append(Op("g2-audit", "ok", "txn", audit))
    return out


def dirty_reads_as_txns(history: Sequence[Op]) -> List[Op]:
    """Dirty-reads ops -> txn ops, one single-append key per written
    value: ``write x`` appends x to key ``("dirty", x)``; a read
    observing x reads that key as ``(x,)``. A value written more than
    once is skipped (attribution ambiguous — the adapter declines
    rather than fabricate evidence); the seeded control tests write
    distinct values. A read of a :fail write's value then surfaces as
    the graph checker's G1a."""
    writes: dict = {}                     # x -> write count
    for op in history:
        if op.f == "write" and op.type != "invoke" \
                and op.value is not None:
            writes[op.value] = writes.get(op.value, 0) + 1
    out: List[Op] = []
    for op in history:
        if op.f == "write" and op.value is not None:
            if writes.get(op.value, 0) != 1:
                continue
            out.append(op.with_(
                f="txn", value=(("append", ("dirty", op.value),
                                 op.value),)))
        elif op.f == "read" and op.value is not None:
            observed = tuple(x for x in set(op.value)
                             if writes.get(x, 0) == 1)
            mops = tuple(("r", ("dirty", x),
                          None if op.type == "invoke" else (x,))
                         for x in observed)
            if mops:
                out.append(op.with_(f="txn", value=mops))
    return out


__all__ = ["g2_as_txns", "dirty_reads_as_txns"]
