"""Host cycle engine — iterative Tarjan SCC over the layered graph.

The device engine (:mod:`.closure_jax`) and this module answer the
same question — "which vertices sit on a cycle, per Adya layer?" —
so either can back the checker and each is the other's oracle in
tests. Layers nest cumulatively (the Adya hierarchy):

- layer 0: ww                      (a cycle here is G0)
- layer 1: ww | wr                 (first cycle here is G1c)
- layer 2: ww | wr | rw            (first cycle here is G2-item)

With realtime edges enabled the rt plane is OR-ed into every layer
(strict serializability: cycles against realtime order count too).

Self-edges never exist (edge inference skips them), so a vertex is
cyclic iff its SCC has size >= 2.
"""

from __future__ import annotations

from typing import List

import numpy as np


def cyclic_vertices(adj: np.ndarray) -> np.ndarray:
    """Bool mask of vertices on some cycle of one adjacency matrix.
    Iterative Tarjan — this runs on 4096-node service-bucket graphs
    on a single CPU, so no recursion and adjacency lists built once
    via numpy."""
    n = adj.shape[0]
    heads: List[np.ndarray] = [np.flatnonzero(adj[i]) for i in range(n)]
    index = np.full(n, -1, dtype=np.int64)
    low = np.zeros(n, dtype=np.int64)
    on_stack = np.zeros(n, dtype=bool)
    cyclic = np.zeros(n, dtype=bool)
    stack: List[int] = []
    counter = 0
    for root in range(n):
        if index[root] != -1:
            continue
        # explicit DFS frames: (vertex, next-successor-ordinal)
        frames = [(root, 0)]
        while frames:
            v, si = frames[-1]
            if si == 0:
                index[v] = low[v] = counter
                counter += 1
                stack.append(v)
                on_stack[v] = True
            succ = heads[v]
            advanced = False
            while si < len(succ):
                w = int(succ[si])
                si += 1
                if index[w] == -1:
                    frames[-1] = (v, si)
                    frames.append((w, 0))
                    advanced = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            frames.pop()
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    cyclic[comp] = True
            if frames:
                pv = frames[-1][0]
                low[pv] = min(low[pv], low[v])
    return cyclic


def layers_of(adj: np.ndarray, realtime: bool = False) -> np.ndarray:
    """(3, n, n) cumulative Adya layers from the (4, n, n) planes."""
    ww, wr, rw, rt = (adj[i] for i in range(4))
    l0 = ww.copy()
    if realtime:
        l0 |= rt
    l1 = l0 | wr
    l2 = l1 | rw
    return np.stack([l0, l1, l2])


def cyclic_layers_host(adj: np.ndarray,
                       realtime: bool = False) -> np.ndarray:
    """(3, n) bool — per-layer cyclic-vertex masks, host engine."""
    layers = layers_of(adj, realtime)
    return np.stack([cyclic_vertices(layers[i]) for i in range(3)])


__all__ = ["cyclic_vertices", "layers_of", "cyclic_layers_host"]
