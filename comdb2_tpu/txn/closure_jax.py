"""Device cycle engine — boolean transitive closure on the MXU.

Cycle detection over a txn dependency graph is matmul-shaped (the
tensor-core BFS observation, PAPERS.md): with A the adjacency matrix,
``A+ = A | A^2 | A^4 | ...`` converges in ceil(log2 N) squarings, and
a vertex is on a cycle iff ``A+[v, v]``. All three Adya layers ride
one ``(3, N, N)`` stacked operand so a single jit dispatch classifies
G0 / G1c / G2-item — never a per-edge or per-layer device call (the
~100 ms tunnel round-trip rule; the ``per-item-dispatch`` analysis
rule names this module's entry points).

Transfer economics on the tunneled link (~25 MB/s): adjacency bits
ship PACKED (``np.packbits``, 8x smaller — 6 MB instead of 48 MB at
the 4096 bucket) and unpack on device; the readback is only the
``(3, N)`` diagonal mask. N is pow2-bucketed (floor
``edges.TXN_N_FLOOR``) so the compiled-program set stays closed, and
the batch axis is pow2 too (service convention).
"""

from __future__ import annotations

import numpy as np

#: dispatch counter — bench_txn asserts the single-dispatch rule on it
DISPATCHES = 0

#: closure programs built this process (one per N bucket) — the
#: compile-surface guard diffs it (utils/compile_guard.py)
COMPILES = 0

# NOTE on carry donation (continuous-batching round): the closure
# kernels deliberately do NOT donate their packed upload. jit donation
# aliases inputs to OUTPUTS only, and the (B, 4, N, N/8) uint8 operand
# can never alias the (B, 3, N) bool diagonal readback — donating it
# would be a guaranteed no-op that logs a "donated buffers were not
# usable" warning per program class. The stream kernel's carries
# (checker/pallas_seg) DO donate: there the scan carry shapes equal
# the output shapes exactly.


def _jnp():
    import jax.numpy as jnp

    return jnp


def _unpack_bits(packed, n: int):
    """(..., N/8) uint8 -> (..., N) bool (device side)."""
    jnp = _jnp()
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)   # packbits is MSB-first
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*packed.shape[:-1], n).astype(bool)


def _closure_step(g):
    """One squaring: g | g.g — g is (..., 3, N, N) bool. The matmul
    rides the MXU as bf16 with f32 accumulation, which is EXACT here:
    operands are 0/1 (bf16-representable) and every partial sum is
    non-negative, so a true reachability count can never cancel or
    round to zero — only the > 0 bit survives anyway."""
    jnp = _jnp()
    gb = g.astype(jnp.bfloat16)
    sq = jnp.einsum("...ij,...jk->...ik", gb, gb,
                    preferred_element_type=jnp.float32)
    return g | (sq > 0)


def _build_layers(planes, n: int):
    """(..., 4, N, N/8) packed planes -> (..., 3, N, N) cumulative
    Adya layers (ww; ww|wr; ww|wr|rw), with the rt plane OR-ed into
    every layer (it is shipped all-zero when realtime is off — one
    program serves both modes)."""
    jnp = _jnp()
    a = _unpack_bits(planes, n)                       # (..., 4, N, N)
    ww, wr, rw, rt = (a[..., i, :, :] for i in range(4))
    l0 = ww | rt
    l1 = l0 | wr
    l2 = l1 | rw
    return jnp.stack([l0, l1, l2], axis=-3)


def _diag_kernel(planes, n: int):
    g = _build_layers(planes, n)
    # ceil(log2 n) squarings reach the full closure; the trip count is
    # static per bucket so the loop unrolls into one fused program
    for _ in range(max(1, (n - 1).bit_length())):
        g = _closure_step(g)
    jnp = _jnp()
    eye = jnp.eye(n, dtype=bool)
    return jnp.any(g & eye, axis=-1)                  # (..., 3, N)


_JITTED = {}


def _jitted(n: int):
    """One jit wrapper per N bucket (jax.jit itself specializes per
    input shape, so the single and batched entries share it). Named
    wrapper, not ``partial``: the compile log (and so the compile-
    surface guard) keys programs by the jit name, and a partial
    lowers as ``<unnamed wrapped function>``."""
    global COMPILES
    import jax

    fn = _JITTED.get(n)
    if fn is None:
        def closure_diag_kernel(planes):
            return _diag_kernel(planes, n=n)

        fn = jax.jit(closure_diag_kernel)
        _JITTED[n] = fn
        COMPILES += 1
    return fn


def _jitted_sharded(n: int, mesh, batch_axis: str = "batch"):
    """The batched closure with its batch axis shard_mapped over
    ``mesh`` — per-shard body = the SAME ``_diag_kernel`` at B/D, so
    every batched txn surface scales by dispatch width without a new
    engine. Named wrapper (``closure_diag_kernel_sharded``) for the
    compile-surface guard; one program per (N bucket, mesh) counted in
    ``COMPILES`` like the single-device entries."""
    global COMPILES
    import jax
    from jax.sharding import PartitionSpec as PS

    key = (n, mesh, batch_axis)
    fn = _JITTED.get(key)
    if fn is None:
        if hasattr(jax, "shard_map"):                # jax >= 0.6
            shard_map, check_kw = jax.shard_map, {"check_vma": False}
        else:                                        # 0.4.x spelling
            from jax.experimental.shard_map import shard_map
            check_kw = {"check_rep": False}
        sm = shard_map(
            lambda planes: _diag_kernel(planes, n=n),
            mesh=mesh, in_specs=(PS(batch_axis),),
            out_specs=PS(batch_axis),
            # no collectives: each shard's closure is a closed
            # computation over its own adjacency stack
            **check_kw)

        def closure_diag_kernel_sharded(planes):
            return sm(planes)

        fn = jax.jit(closure_diag_kernel_sharded)
        _JITTED[key] = fn
        COMPILES += 1
    return fn


def _pack(adj: np.ndarray) -> np.ndarray:
    return np.packbits(adj.astype(np.uint8), axis=-1)


def closure_diag(adj: np.ndarray) -> np.ndarray:
    """(4, N, N) bool planes -> (3, N) bool per-layer cyclic-vertex
    mask. ONE device dispatch; N must be pow2 (use
    ``TxnGraph.padded``)."""
    global DISPATCHES
    n = adj.shape[-1]
    out = _jitted(n)(_pack(adj))
    DISPATCHES += 1
    return np.asarray(out)


def closure_diag_batch(adjs: np.ndarray, mesh=None,
                       batch_axis: str = "batch") -> np.ndarray:
    """(B, 4, N, N) bool -> (B, 3, N) bool. ONE dispatch for the whole
    batch — the service's coalesced path (B pow2-padded by the
    caller). With a >1-device ``mesh`` the batch axis shard_maps over
    it (pure data parallelism; still ONE dispatch): B pads to a pow2
    multiple of D with all-zero adjacencies — acyclic by construction,
    their diagonals read all-False and are sliced off before return,
    so a pad graph can never surface as a verdict."""
    return closure_diag_batch_async(adjs, mesh=mesh,
                                    batch_axis=batch_axis)()


def closure_diag_batch_async(adjs: np.ndarray, mesh=None,
                             batch_axis: str = "batch"):
    """Stage the batched closure and return a zero-argument
    ``finalize()`` producing the (B, 3, N) diagonal mask — the
    stage/finalize seam the service's in-flight ring rides: between
    stage and finalize the squaring loop runs asynchronously on
    device, so the tick can pack the NEXT bucket's operands (or stage
    a check-kind dispatch) while this one squares."""
    global DISPATCHES
    n = adjs.shape[-1]
    B = adjs.shape[0]
    D = int(mesh.shape[batch_axis]) if mesh is not None else 1
    if D > 1:
        from ..utils import next_pow2

        if D & (D - 1):
            raise ValueError(
                f"mesh axis {batch_axis!r} must be a power of two "
                f"(got {D}) — per-shard shapes must stay pow2")
        b_pad = max(next_pow2(B), D)
        if b_pad != B:
            pad = np.zeros((b_pad - B,) + adjs.shape[1:], adjs.dtype)
            adjs = np.concatenate([adjs, pad])
        out = _jitted_sharded(n, mesh, batch_axis)(_pack(adjs))
        DISPATCHES += 1
        return lambda: np.asarray(out)[:B]
    out = _jitted(n)(_pack(adjs))
    DISPATCHES += 1
    return lambda: np.asarray(out)


def cyclic_layers_device(adj: np.ndarray,
                         realtime: bool = False) -> np.ndarray:
    """Device twin of :func:`scc.cyclic_layers_host` over UNPADDED
    (4, n, n) planes: pads to the bucket, masks rt when realtime is
    off, and trims the answer back to n."""
    from .edges import TXN_N_FLOOR
    from ..utils import next_pow2

    n = adj.shape[-1]
    np2 = next_pow2(max(n, 1), TXN_N_FLOOR)
    padded = np.zeros((4, np2, np2), dtype=bool)
    padded[:, :n, :n] = adj
    if not realtime:
        padded[3] = False
    return closure_diag(padded)[:, :n]


__all__ = ["COMPILES", "DISPATCHES", "closure_diag",
           "closure_diag_batch", "closure_diag_batch_async",
           "cyclic_layers_device"]
