"""Metrics registry — counters, gauges, fixed-bucket histograms.

The histogram is the load-bearing type: fixed exponential bucket
edges mean p50/p95/p99 are derivable from ~16 integers per series
(cumulative walk + linear interpolation inside the landing bucket) —
no sample storage, so a daemon serving millions of requests carries
O(metrics) memory, not O(requests). The quantile error is bounded by
the landing bucket's width; the golden test
(``tests/test_obs.py``) pins the math against exact samples.

Two render forms, both served by the verifier daemon's
``kind:"metrics"`` request (docs/service.md) and snapshotted into the
store web status:

- :meth:`Registry.snapshot` — nested JSON (``{name: {type, series:
  [{labels, ...values}]}}``), the programmatic form benches and tests
  consume;
- :meth:`Registry.render_prometheus` — the Prometheus text exposition
  format (``name_bucket{le="..."} N`` cumulative histograms,
  ``_sum``/``_count``, ``# TYPE`` headers) for scrapers.

Stdlib only; single-threaded by design (one CPU, one tick loop — no
locks). Metric names are documented in docs/observability.md.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

#: default latency edges (milliseconds): exponential-ish 1 ms – 60 s,
#: sized for the serving path (a ~100 ms tunnel round-trip lands
#: mid-table; a 5.5 s overloaded p99 is still resolved, not clamped)
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)


class Counter:
    """Monotonic count. ``value`` is assignable so process-global
    module counters (compile counters, ``VerifierCore.m``) can be
    mirrored into the registry at scrape time."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n=1) -> None:
        self.value += n

    def snapshot(self) -> dict:
        return {"value": self.value}


class Gauge:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v) -> None:
        self.value = v

    def snapshot(self) -> dict:
        return {"value": self.value}


class Histogram:
    """Fixed-bucket histogram; quantiles by cumulative walk + linear
    interpolation within the landing bucket (error <= bucket width).
    ``counts[i]`` holds observations <= ``edges[i]``; the final slot
    is the +Inf overflow bucket."""

    __slots__ = ("edges", "counts", "sum", "count")

    def __init__(self, buckets=DEFAULT_MS_BUCKETS):
        self.edges: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts: List[int] = [0] * (len(self.edges) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.edges, v)] += 1
        self.sum += v
        self.count += 1

    def quantile(self, q: float) -> float:
        if not self.count:
            return 0.0
        target = max(q, 0.0) * self.count
        cum, lo = 0, 0.0
        for i, edge in enumerate(self.edges):
            c = self.counts[i]
            if c and cum + c >= target:
                frac = min(max((target - cum) / c, 0.0), 1.0)
                return lo + (edge - lo) * frac
            cum += c
            lo = edge
        # landed in the +Inf overflow bucket: clamp to the last finite
        # edge — an honest "at least this much", never a fabrication
        return self.edges[-1]

    def snapshot(self) -> dict:
        cum, buckets = 0, []
        for edge, c in zip(self.edges, self.counts):
            cum += c
            buckets.append([edge, cum])
        buckets.append(["+Inf", cum + self.counts[-1]])
        return {"count": self.count, "sum": round(self.sum, 3),
                "p50": round(self.quantile(0.50), 3),
                "p95": round(self.quantile(0.95), 3),
                "p99": round(self.quantile(0.99), 3),
                "buckets": buckets}


class _Family:
    __slots__ = ("typ", "help", "series")

    def __init__(self, typ: str, help_: str):
        self.typ = typ
        self.help = help_
        self.series: Dict[tuple, object] = {}


class Registry:
    """Name -> metric family -> labeled series. Get-or-create API so
    instrumented call sites never pre-register."""

    def __init__(self) -> None:
        self._families: Dict[str, _Family] = {}

    def _series(self, name: str, typ: str, help_: str, labels: dict,
                make):
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = _Family(typ, help_)
        elif fam.typ != typ:
            raise ValueError(
                f"metric {name!r} is a {fam.typ}, not a {typ}")
        key = tuple(sorted(labels.items()))
        obj = fam.series.get(key)
        if obj is None:
            obj = fam.series[key] = make()
        return obj

    def counter(self, name: str, help: str = "",
                **labels) -> Counter:
        return self._series(name, "counter", help, labels, Counter)

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        return self._series(name, "gauge", help, labels, Gauge)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_MS_BUCKETS, **labels) -> Histogram:
        return self._series(name, "histogram", help, labels,
                            lambda: Histogram(buckets))

    # -- render --------------------------------------------------------

    def snapshot(self) -> dict:
        out = {}
        for name in sorted(self._families):
            fam = self._families[name]
            out[name] = {
                "type": fam.typ,
                "series": [{"labels": dict(k), **obj.snapshot()}
                           for k, obj in sorted(fam.series.items())],
            }
        return out

    def render_prometheus(self) -> str:
        lines: List[str] = []
        for name in sorted(self._families):
            fam = self._families[name]
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            lines.append(f"# TYPE {name} {fam.typ}")
            for key, obj in sorted(fam.series.items()):
                base = _labels(dict(key))
                if fam.typ == "histogram":
                    cum = 0
                    for edge, c in zip(obj.edges, obj.counts):
                        cum += c
                        lines.append(
                            f"{name}_bucket"
                            f"{_labels(dict(key), le=_le(edge))} "
                            f"{cum}")
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels(dict(key), le='+Inf')} "
                        f"{cum + obj.counts[-1]}")
                    lines.append(f"{name}_sum{base} "
                                 f"{_num(obj.sum)}")
                    lines.append(f"{name}_count{base} {obj.count}")
                else:
                    lines.append(f"{name}{base} {_num(obj.value)}")
        return "\n".join(lines) + "\n"


def _le(edge: float) -> str:
    return str(int(edge)) if float(edge).is_integer() else str(edge)


def _num(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _labels(labels: dict, **extra) -> str:
    labels = {**labels, **extra}
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


__all__ = ["Counter", "DEFAULT_MS_BUCKETS", "Gauge", "Histogram",
           "Registry"]
