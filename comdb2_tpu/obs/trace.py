"""Span tracing — monotonic clock, context-var nesting, Perfetto export.

The span model (docs/observability.md): a span is one named interval
on the process-wide monotonic clock, carrying an optional request id
(``rid``) and a flat ``args`` dict (bucket key, byte counts, batch
width ...). Nesting is implicit: entering a span makes it the parent
of every span opened inside its ``with`` block (context-var, so the
single-threaded tick loop and nested engine calls correlate without
explicit plumbing); the request id propagates the same way via
:func:`request`.

Off the hot path by construction: when tracing is disabled —
the default — :func:`span` returns a shared no-op context manager
after ONE module-flag check, :func:`record` returns immediately, and
the :func:`traced` decorator calls straight through. Enabled spans
cost two clock reads and a deque append; the instrumented call sites
are per-dispatch/per-request, never per-op.

:func:`monotonic` is the one sanctioned clock for the dispatch
pipeline (the ``raw-clock-in-pipeline`` analysis rule): every stage
duration and the device-time attribution must come off the same
monotonic timebase or the per-request stage sums stop tiling the
measured wall time.

Export (:func:`export_chrome`) is the Chrome trace-event JSON format
(``{"traceEvents": [{"ph": "X", "ts": µs, "dur": µs, ...}]}``) —
loadable in Perfetto / ``chrome://tracing`` unmodified.
"""

from __future__ import annotations

import functools
import json
import os
import time as _time
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Optional

#: THE pipeline clock. Dispatch modules import this instead of
#: ``time.monotonic`` (rule ``raw-clock-in-pipeline``) so every stage
#: timestamp — queue wait, host pack, device, finalize — and every
#: span share one timebase.
monotonic = _time.monotonic

#: retained-span cap: a long-running daemon must not grow without
#: bound; the deque drops oldest, ``dropped_spans()`` counts.
DEFAULT_MAX_SPANS = 200_000

_ENABLED = False
_spans: deque = deque(maxlen=DEFAULT_MAX_SPANS)
_dropped = 0

_rid_var: ContextVar = ContextVar("comdb2_tpu_obs_rid", default=None)
_parent_var: ContextVar = ContextVar("comdb2_tpu_obs_span",
                                     default=None)


class Span:
    """One named monotonic-clock interval (see module docstring).
    Context manager; finished spans land in the module buffer."""

    __slots__ = ("name", "t0", "t1", "rid", "args", "parent", "_token")

    def __init__(self, name: str, args: Optional[dict] = None,
                 rid=None):
        self.name = name
        self.args = args if args is not None else {}
        self.rid = rid if rid is not None else _rid_var.get()
        self.parent = _parent_var.get()
        self.t0 = monotonic()
        self.t1: Optional[float] = None
        self._token = None

    def set(self, **attrs) -> "Span":
        """Attach attributes after the fact (byte counts etc.)."""
        self.args.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._token = _parent_var.set(self)
        return self

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            _parent_var.reset(self._token)
            self._token = None
        self.t1 = monotonic()
        _append(self)
        return False


class _NoopSpan:
    """The disabled-mode singleton: every method is a no-op."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


def _append(s: Span) -> None:
    global _dropped
    if len(_spans) == _spans.maxlen:
        _dropped += 1
    _spans.append(s)


# -- the API call sites use -------------------------------------------


def span(name: str, *, rid=None, **attrs):
    """Open one span. Disabled mode returns the shared no-op after a
    single flag check — safe at dispatch-level call sites."""
    if not _ENABLED:
        return _NOOP
    return Span(name, attrs, rid=rid)


def record(name: str, t0: float, t1: float, *, rid=None,
           **attrs) -> None:
    """Emit an already-measured interval as a finished span — the
    retroactive form for intervals whose endpoints were captured
    before the span could be opened (async device windows, whole
    per-request rows at reply time)."""
    if not _ENABLED:
        return
    s = Span(name, attrs, rid=rid)
    s.t0 = t0
    s.t1 = t1
    _append(s)


def traced(name: str):
    """Decorator form of :func:`span` for whole functions (the
    checker/txn/shrink pipeline stages)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _ENABLED:
                return fn(*a, **kw)
            with Span(name):
                return fn(*a, **kw)
        return wrapper
    return deco


@contextmanager
def request(rid):
    """Set the request-id correlation for every span opened inside."""
    token = _rid_var.set(rid)
    try:
        yield
    finally:
        _rid_var.reset(token)


# -- lifecycle ---------------------------------------------------------


def enable(max_spans: int = DEFAULT_MAX_SPANS) -> None:
    global _ENABLED, _spans, _dropped
    if _spans.maxlen != max_spans:
        _spans = deque(_spans, maxlen=max_spans)
    _dropped = 0
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def clear() -> None:
    global _dropped
    _spans.clear()
    _dropped = 0


def spans() -> list:
    """Finished spans, oldest first (tests and exporters)."""
    return list(_spans)


def dropped_spans() -> int:
    return _dropped


# -- export ------------------------------------------------------------


def export_chrome(path: Optional[str] = None) -> dict:
    """The buffered spans as a Chrome/Perfetto trace-event document;
    with ``path``, also written atomically (tmp + rename — artifact
    passes run while the daemon keeps serving)."""
    events = []
    for s in list(_spans):
        args = dict(s.args)
        if s.rid is not None:
            args["rid"] = s.rid
        if s.parent is not None:
            args["parent"] = s.parent.name
        events.append({
            "name": s.name, "cat": "comdb2_tpu", "ph": "X",
            "ts": round(s.t0 * 1e6, 3),
            "dur": round(((s.t1 if s.t1 is not None else s.t0)
                          - s.t0) * 1e6, 3),
            "pid": os.getpid(), "tid": 1, "args": args,
        })
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"dropped_spans": _dropped}}
    if path is not None:
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh)
        os.replace(tmp, path)
    return doc


__all__ = ["DEFAULT_MAX_SPANS", "Span", "clear", "disable",
           "dropped_spans", "enable", "enabled", "export_chrome",
           "monotonic", "record", "request", "span", "spans",
           "traced"]
