"""Observability plane — zero-dependency tracing + metrics.

Two stdlib-only modules the whole pipeline threads through:

- :mod:`.trace` — monotonic-clock span API (context-var propagated,
  request-id correlated) exporting Chrome/Perfetto trace-event JSON.
  Disabled mode is a single flag check per call site; the serving hot
  path carries per-dispatch spans only (never per-op — the
  ``per-op-host-loop`` discipline applies to instrumentation too).
- :mod:`.metrics` — counters/gauges/fixed-bucket histograms whose
  p50/p95/p99 are derivable without storing samples, rendered as
  Prometheus text and a JSON snapshot (the service ``kind:"metrics"``
  scrape).

This package must stay import-light (stdlib only, no jax/numpy): the
dispatch modules it instruments import it at module top, and the
analysis rule ``raw-clock-in-pipeline`` makes :func:`trace.monotonic`
the one sanctioned clock there. See ``docs/observability.md``.
"""

from . import metrics, trace

__all__ = ["metrics", "trace"]
