"""comdb2_tpu — a TPU-native distributed-systems test harness and
linearizability checker.

This package rebuilds the capabilities of the jepsen-io/comdb2 stack
(the Jepsen harness + the Knossos linearizability checker, vendored in the
reference under ``linearizable/jepsen/src/``) as a TPU-first framework:

- ``comdb2_tpu.ops``      — operation & history core (knossos/op.clj,
  knossos/history.clj semantics) plus packed tensor forms and EDN I/O.
- ``comdb2_tpu.models``   — single-threaded datatype models and the
  state-space memoization that lowers ``model.step`` to integer gathers
  (knossos/model.clj, knossos/model/memo.clj).
- ``comdb2_tpu.checker``  — the checker layer: the TPU batched-frontier
  linearizability search (knossos/linear.clj as vmapped tensor ops),
  a host reference implementation, and the non-linearizability checkers
  (set / counter / queue / bank / dirty-reads / G2).
- ``comdb2_tpu.service``  — the verification serving layer: the
  batching checker-as-a-service daemon (shape-bucketed request
  coalescing over TCP), its client, and device-mesh sharding (the
  former ``comdb2_tpu.parallel``, kept as a shim).
- ``comdb2_tpu.harness``  — the test runtime: generators, clients,
  workers, nemesis scheduling, the results store, web UI, killcluster
  oracle, and the CLI.
- ``comdb2_tpu.control``  — the control plane: remote execution, network
  partitions, clock and process faults.
- ``comdb2_tpu.workloads`` — the comdb2 test suite over a table-level
  serializable connection interface (+ in-memory chaos backend).
- ``comdb2_tpu.report``   — latency/rate SVG graphs, HTML timelines,
  counterexample rendering.
- ``comdb2_tpu.filetest`` — offline history checker CLI.
"""

__version__ = "0.1.0"
