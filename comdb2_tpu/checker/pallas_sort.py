"""Pallas TPU kernel: per-lane bitonic sort of (hi, lo) int32 key
pairs — the hot op of the flat-batch dedup.

The dedup sorts each batch lane's packed config keys. XLA lowers
``jnp.lexsort`` to a generic variadic sort in HBM; this kernel instead
runs the full bitonic network — all ``log2(N)·(log2(N)+1)/2``
compare-exchange passes — on one lane block resident in VMEM, with the
two words compared lexicographically ((hi, lo) ascending).

Shapes: ``hi``/``lo`` are ``(B, N)`` int32 with N a power of two; each
of the B rows sorts independently (rows map to dedup *blocks* — one
batch lane's frontier + candidates, padded). Use
:func:`sort_pairs_available` to gate on environments without Mosaic
support, and fall back to ``jnp.lexsort``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _compare_exchange(h, l, j, k):
    """One bitonic pass at distance j within sorted-run size k,
    formulated with circular shifts (Mosaic has no multi-dim vector
    reshape): every element fetches its partner by rolling ±j along
    the lane axis and keeps the min or max of the pair."""
    from jax.experimental.pallas import tpu as pltpu

    B, N = h.shape
    idx = jax.lax.broadcasted_iota(jnp.int32, (B, N), 1)
    is_low = (idx & j) == 0           # partner at idx + j, else idx - j
    asc = (idx & k) == 0              # sorted-run direction

    # partner values: roll N-j brings idx+j here; roll +j brings idx-j
    # (pltpu.roll requires non-negative shifts)
    ph = jnp.where(is_low, pltpu.roll(h, N - j, 1), pltpu.roll(h, j, 1))
    pl_ = jnp.where(is_low, pltpu.roll(l, N - j, 1), pltpu.roll(l, j, 1))

    mine_less = (h < ph) | ((h == ph) & (l < pl_))
    min_h = jnp.where(mine_less, h, ph)
    min_l = jnp.where(mine_less, l, pl_)
    max_h = jnp.where(mine_less, ph, h)
    max_l = jnp.where(mine_less, pl_, l)

    take_min = is_low == asc          # low end of an ascending pair
    return (jnp.where(take_min, min_h, max_h),
            jnp.where(take_min, min_l, max_l))


def _bitonic_kernel(hi_ref, lo_ref, out_hi_ref, out_lo_ref, *, N):
    h = hi_ref[:]
    l = lo_ref[:]
    k = 2
    while k <= N:                     # static python loops: the whole
        j = k // 2                    # network unrolls into the kernel
        while j >= 1:
            h, l = _compare_exchange(h, l, j, k)
            j //= 2
        k *= 2
    out_hi_ref[:] = h
    out_lo_ref[:] = l


@functools.partial(jax.jit, static_argnames=("lanes_per_block",))
def sort_pairs(hi, lo, lanes_per_block: int = 8):
    """Sort each row of (hi, lo) ascending lexicographically. Returns
    (hi_sorted, lo_sorted). N must be a power of two; B must divide by
    ``lanes_per_block``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, N = hi.shape
    assert N & (N - 1) == 0, "N must be a power of two"
    L = min(lanes_per_block, B)
    while B % L:
        L -= 1
    grid = (B // L,)
    spec = pl.BlockSpec((L, N), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    kernel = functools.partial(_bitonic_kernel, N=N)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec, spec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((B, N), jnp.int32),
                   jax.ShapeDtypeStruct((B, N), jnp.int32)],
    )(hi, lo)


@functools.lru_cache(maxsize=1)
def sort_pairs_available() -> bool:
    """Probe once whether the kernel compiles+runs on this backend."""
    try:
        hi = jnp.asarray(np.array([[3, 1, 2, 0]], np.int32))
        lo = jnp.asarray(np.array([[0, 1, 0, 1]], np.int32))
        h, l = sort_pairs(hi, lo, lanes_per_block=1)
        return (np.asarray(h) == [[0, 1, 2, 3]]).all()
    except Exception:
        return False
