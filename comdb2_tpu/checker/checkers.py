"""The checker layer.

A checker validates a complete history against a model and returns a map
with at least ``"valid?"`` — ``True``, ``False``, or ``"unknown"``.
Mirrors the reference's ``jepsen/checker.clj``:

- :func:`check_safe` wraps exceptions as ``:unknown`` (``checker.clj:54-64``)
- :func:`compose` runs named sub-checkers in parallel and merges their
  verdicts by priority false > unknown > true (``checker.clj:20-35,274-286``)
- :class:`Linearizable` drives the TPU frontier search
  (``checker.clj:71-85``)
- :class:`SetChecker` — ok/lost/unexpected/recovered (``checker.clj:108-154``)
- :class:`Queue` / :class:`TotalQueue` — (``checker.clj:87-218``)
- :class:`Counter` — bounds-interval analysis (``checker.clj:220-272``)
"""

from __future__ import annotations

import traceback
from collections import Counter as Multiset
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence

from ..models import model as M
from ..ops.op import Op
from ..utils.intervals import fraction, integer_interval_set_str
from . import linear

UNKNOWN = "unknown"

# :valid? priorities — larger dominates under composition
# (checker.clj:20-25)
_VALID_PRIORITY = {True: 0, UNKNOWN: 0.5, False: 1}


def merge_valid(valids: Sequence[Any]):
    """The highest-priority verdict wins (``checker.clj:27-35``).
    A verdict value outside the tri-state (a buggy sub-checker
    returning ``"crashed"``, a None) coerces to ``unknown`` — it must
    neither silently win as a pseudo-False nor leak a non-tri-state
    value to callers switching on the result."""
    out = True
    for v in valids:
        if v not in _VALID_PRIORITY:
            v = UNKNOWN
        if _VALID_PRIORITY[v] > _VALID_PRIORITY[out]:
            out = v
    return out


class Checker:
    """Protocol: ``check(test, model, history, opts) -> dict`` with a
    ``"valid?"`` key (``checker.clj:37-52``)."""

    def check(self, test: dict, model, history: List[Op],
              opts: Optional[dict] = None) -> dict:
        raise NotImplementedError


def check_safe(checker: Checker, test: dict, model, history: List[Op],
               opts: Optional[dict] = None) -> dict:
    """Run a checker, converting exceptions to an ``unknown`` verdict
    with the traceback attached (``checker.clj:54-64``)."""
    try:
        return checker.check(test, model, history, opts)
    except Exception:
        return {"valid?": UNKNOWN, "error": traceback.format_exc()}


class UnbridledOptimism(Checker):
    """Everything is awesome (``checker.clj:66-69``)."""

    def check(self, test, model, history, opts=None):
        return {"valid?": True}


unbridled_optimism = UnbridledOptimism()


class Compose(Checker):
    """Run a map of named checkers concurrently; result maps nest under
    their names, ``"valid?"`` merges by priority (``checker.clj:274-286``).
    """

    def __init__(self, checker_map: Dict[str, Checker]):
        self.checker_map = dict(checker_map)

    def check(self, test, model, history, opts=None):
        names = list(self.checker_map)
        with ThreadPoolExecutor(max_workers=max(1, len(names))) as pool:
            futs = {name: pool.submit(check_safe, self.checker_map[name],
                                      test, model, history, opts)
                    for name in names}
            results = {name: f.result() for name, f in futs.items()}
        out: dict = dict(results)
        out["valid?"] = merge_valid([r.get("valid?") for r in results.values()])
        return out


def compose(checker_map: Dict[str, Checker]) -> Compose:
    return Compose(checker_map)


class Linearizable(Checker):
    """Validates linearizability with the memoized frontier search
    (``checker.clj:71-85`` → ``knossos.linear/analysis``). Frontier
    samples in the result are truncated to 10, as the reference truncates
    configs/final-paths."""

    def __init__(self, backend: str = "auto", **analysis_kw):
        self.backend = backend
        self.analysis_kw = analysis_kw

    def check(self, test, model, history, opts=None):
        a = linear.analysis(model, history, backend=self.backend,
                            **self.analysis_kw)
        out = a.to_map()
        if "configs" in out:
            out["configs"] = out["configs"][:10]
        if out.get("paths"):
            out["paths"] = out["paths"][:10]
        if a.valid is False:
            self._render_svg(test, history, a, opts)
        return out

    @staticmethod
    def _render_svg(test, history, a, opts) -> None:
        """Drop ``linear.svg`` (failing window + final paths) into the
        test's store dir on failure, like the reference's linearizable
        checker (``checker.clj:71-85`` → ``render-analysis!``).
        Best-effort: rendering must never destroy a verdict."""
        import os

        from ..harness.store import artifact_dir

        base = artifact_dir(test, opts)
        if base is None:
            return
        try:
            from ..report import linear_svg
            linear_svg.render_analysis(list(history), a,
                                       os.path.join(base, "linear.svg"))
        except Exception:
            pass


linearizable = Linearizable()


class Serializable(Checker):
    """Transactional serializability via the dependency-graph checker
    (:mod:`comdb2_tpu.txn`): Elle-style edge inference over
    list-append txn ops, then cycle detection — host Tarjan or the
    TPU matrix-closure engine (one jit dispatch per history).

    ``adapter`` optionally re-expresses a legacy workload history as
    txn ops first (see :mod:`comdb2_tpu.txn.adapters`) so the graph
    checker can second-opinion the bespoke checkers. An adapter
    returning an empty list yields ``unknown`` (nothing to check is
    not a clean bill)."""

    def __init__(self, backend: str = "auto", realtime: bool = False,
                 adapter=None):
        self.backend = backend
        self.realtime = realtime
        self.adapter = adapter

    def check(self, test, model, history, opts=None):
        from ..txn import check_txn

        ops = list(history)
        if self.adapter is not None:
            ops = self.adapter(ops)
            if not ops:
                return {"valid?": UNKNOWN,
                        "error": "adapter produced no txn ops"}
        out = check_txn(ops, backend=self.backend,
                        realtime=self.realtime)
        if out["valid?"] is False:
            self._render(test, out, opts)
        return out

    @staticmethod
    def _render(test, result, opts) -> None:
        """Drop ``serializable.txt`` + ``serializable.svg`` (the
        decoded cycle) into the store dir on failure — best-effort,
        like the linearizable checker's SVG."""
        import os

        from ..harness.store import artifact_dir

        base = artifact_dir(test, opts)
        if base is None:
            return
        try:
            from ..report import txn_svg
            from ..txn.counterexample import render_text

            os.makedirs(base, exist_ok=True)
            cex = result.get("counterexample")
            with open(os.path.join(base, "serializable.txt"),
                      "w") as fh:
                if cex:
                    fh.write(render_text(cex) + "\n")
                for a in result.get("anomalies", ()):
                    fh.write(f"{a}\n")
            if cex:
                txn_svg.render_cycle(
                    cex, os.path.join(base, "serializable.svg"))
        except Exception:
            pass


serializable = Serializable()


class Queue(Checker):
    """Every dequeue must come from somewhere: assume every non-failing
    enqueue succeeded and only ok dequeues happened, then fold the model
    over that subsequence. O(n) — use with an unordered-queue model
    (``checker.clj:87-105``)."""

    def check(self, test, model, history, opts=None):
        cur = model
        for op in history:
            take = (op.type == "invoke" if op.f == "enqueue"
                    else op.type == "ok" if op.f == "dequeue" else False)
            if not take:
                continue
            cur = M.step(cur, op.f, op.value)
            if cur is None:
                return {"valid?": False,
                        "error": f"inconsistent at {op}"}
        return {"valid?": True, "final-queue": cur}


queue = Queue()


class SetChecker(Checker):
    """Adds followed by a final read: every successful add must be read
    back; nothing never-attempted may appear (``checker.clj:108-154``).
    """

    def check(self, test, model, history, opts=None):
        attempts = {op.value for op in history
                    if op.type == "invoke" and op.f == "add"}
        adds = {op.value for op in history
                if op.type == "ok" and op.f == "add"}
        final_read = None
        for op in history:
            if op.type == "ok" and op.f == "read":
                final_read = op.value
        if final_read is None:
            return {"valid?": UNKNOWN, "error": "Set was never read"}
        final_read = set(final_read)
        ok = final_read & attempts
        unexpected = final_read - attempts
        lost = adds - final_read
        recovered = ok - adds
        return {
            "valid?": not lost and not unexpected,
            "ok": integer_interval_set_str(ok),
            "lost": integer_interval_set_str(lost),
            "unexpected": integer_interval_set_str(unexpected),
            "recovered": integer_interval_set_str(recovered),
            "ok-frac": fraction(len(ok), len(attempts)),
            "unexpected-frac": fraction(len(unexpected), len(attempts)),
            "lost-frac": fraction(len(lost), len(attempts)),
            "recovered-frac": fraction(len(recovered), len(attempts)),
        }


set_checker = SetChecker()


class TotalQueue(Checker):
    """What goes in must come out — multiset analysis over
    enqueues/dequeues; requires the history to drain the queue
    (``checker.clj:163-218``)."""

    def check(self, test, model, history, opts=None):
        attempts = Multiset(op.value for op in history
                            if op.type == "invoke" and op.f == "enqueue")
        enqueues = Multiset(op.value for op in history
                            if op.type == "ok" and op.f == "enqueue")
        dequeues = Multiset(op.value for op in history
                            if op.type == "ok" and op.f == "dequeue")
        ok = dequeues & attempts
        unexpected = Multiset({v: n for v, n in dequeues.items()
                               if v not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        n_att = sum(attempts.values())
        return {
            "valid?": not lost and not unexpected,
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
            "ok-frac": fraction(sum(ok.values()), n_att),
            "unexpected-frac": fraction(sum(unexpected.values()), n_att),
            "duplicated-frac": fraction(sum(duplicated.values()), n_att),
            "lost-frac": fraction(sum(lost.values()), n_att),
            "recovered-frac": fraction(sum(recovered.values()), n_att),
        }


total_queue = TotalQueue()


class CounterChecker(Checker):
    """A monotonically-growing counter: each read must fall between the
    sum of ok adds at invoke time (lower) and the sum of attempted adds
    at completion time (upper) (``checker.clj:220-272``)."""

    def check(self, test, model, history, opts=None):
        lower = upper = 0
        pending: Dict[Any, list] = {}   # process -> [lower, read-value]
        reads: List[tuple] = []
        for op in history:
            key = (op.type, op.f)
            if key == ("invoke", "read"):
                pending[op.process] = [lower, op.value]
            elif key == ("ok", "read"):
                lo, _ = pending.pop(op.process)
                reads.append((lo, op.value, upper))
            elif key == ("invoke", "add"):
                upper += op.value
            elif key == ("ok", "add"):
                lower += op.value
        errors = [r for r in reads
                  if r[1] is None or not (r[0] <= r[1] <= r[2])]
        return {"valid?": not errors, "reads": reads, "errors": errors}


counter = CounterChecker()
