"""WGL-style world search — the second checker engine.

The semantics of ``knossos/core.clj``: a *world* is a model state, a set
of pending invocations, and an index into the history (``core.clj:32-40``).
At each invocation the world forks into every permutation of every
subset of its pending ops (``possible-worlds``, ``core.clj:82-145``);
completions prune worlds that haven't linearized the op yet; a world
reaching the end of history short-circuits the search as valid
(``short-circuit!``, ``core.clj:334-340``).

Engineering mirrors the reference where it matters:

- degenerate-world dedup on (state, pending, index) (``core.clj:44-56``)
  with a bounded lossy seen-cache (the 24-bit cache, ``core.clj:261-279``)
- best-first scheduling by depth (priority −index, ``core.clj:342-345``)
- explorer threads over a shared queue (ncpu+2, ``core.clj:368-390``)
- the permutations-of-subsets expansion is computed as the closure of
  single-op linearizations with dedup — same reachable set, no factorial
  blowup on duplicate states

States come from the memoized model, so stepping is an array gather.
This engine is host-side by design (the frontier of *worlds* at
different indices doesn't batch the way the linear engine's per-op
configs do); the device engine (:mod:`.linear_jax`) is the primary.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
from dataclasses import dataclass, field
from typing import FrozenSet, List, Optional, Sequence, Tuple, Union

from ..models.memo import MemoizedModel, memo as make_memo
from ..models.model import Model
from ..ops.op import INVOKE, OK, FAIL, INFO, Op
from ..ops.packed import PackedHistory, pack_history

VALID = True
UNKNOWN = "unknown"

# event kinds in the compiled schedule
E_SKIP = 0
E_INVOKE = 1
E_OK = 2


@dataclass
class WGLResult:
    valid: Union[bool, str]
    deepest_index: int = 0
    worlds_explored: int = 0
    cause: Optional[str] = None


def _compile_events(packed: PackedHistory) -> List[Tuple[int, int, int]]:
    """Per-op (kind, invocation-index, transition-id)."""
    events = []
    for i in range(len(packed)):
        t = int(packed.type[i])
        if t == INVOKE and not packed.fails[i]:
            events.append((E_INVOKE, i, int(packed.trans[i])))
        elif t == OK:
            inv = int(packed.pair[i])
            events.append((E_OK, inv, -1))
        else:
            events.append((E_SKIP, -1, -1))
    return events


def _linearization_closure(succ, state: int,
                           pending: FrozenSet[Tuple[int, int]]):
    """All (state', remaining-pending') reachable by linearizing any
    sequence of pending ops — the deduplicated form of
    permutations-of-subsets (``core.clj:82-145``). Pending entries are
    (invocation-index, transition-id) pairs."""
    seen = {(state, pending)}
    stack = [(state, pending)]
    while stack:
        s, p = stack.pop()
        for entry in p:
            _, tr = entry
            s2 = int(succ[s, tr])
            if s2 < 0:
                continue
            nxt = (s2, p - {entry})
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


def check(mm: MemoizedModel, packed: PackedHistory,
          n_threads: Optional[int] = None,
          max_worlds: int = 1 << 22,
          seen_bits: int = 24) -> WGLResult:
    """Run the world search; returns a :class:`WGLResult`."""
    events = _compile_events(packed)
    n = len(events)
    succ = mm.succ
    if n == 0:
        return WGLResult(valid=True)

    n_threads = n_threads or min(32, (os.cpu_count() or 2) + 2)
    # lossy seen-cache, overwrite on collision (core.clj:261-279)
    seen_mask = (1 << seen_bits) - 1
    seen: List[Optional[Tuple]] = [None] * (seen_mask + 1)

    heap: List[Tuple[int, int, int, FrozenSet]] = []
    # entries: (-index, tiebreak, state, pending)
    counter = itertools.count()
    heapq.heappush(heap, (0, next(counter), 0, frozenset()))
    lock = threading.Lock()
    cond = threading.Condition(lock)
    stats = {"explored": 0, "deepest": 0, "active": 0,
             "result": None, "overflow": False}

    def offer(index: int, state: int, pending: FrozenSet) -> None:
        key = (index, state, pending)
        slot = hash(key) & seen_mask
        with cond:
            if seen[slot] == key:
                return
            seen[slot] = key
            heapq.heappush(heap,
                           (-index, next(counter), state, pending))
            cond.notify()

    def explore_one(index: int, state: int, pending: FrozenSet) -> None:
        """Advance a world until it forks, dies, or finishes."""
        while True:
            if index >= n:
                stats["result"] = True
                return
            kind, inv, tr = events[index]
            if kind == E_SKIP:
                index += 1
                continue
            if kind == E_OK:
                # completion: the op must already be linearized
                if any(e[0] == inv for e in pending):
                    return                      # world dies
                index += 1
                continue
            # invoke: fork into the linearization closure
            pending2 = pending | {(inv, tr)}
            outcomes = _linearization_closure(succ, state, pending2)
            if len(outcomes) == 1:
                (state, pending) = next(iter(outcomes))
                index += 1
                continue
            first = True
            for (s2, p2) in outcomes:
                if first:
                    nxt = (s2, p2)
                    first = False
                else:
                    offer(index + 1, s2, p2)
            (state, pending) = nxt
            index += 1

    def explorer():
        while True:
            with cond:
                while not heap and stats["active"] > 0 \
                        and stats["result"] is None \
                        and not stats["overflow"]:
                    cond.wait(0.05)
                if stats["result"] is not None or stats["overflow"]:
                    cond.notify_all()
                    return
                if not heap:
                    if stats["active"] == 0:
                        cond.notify_all()
                        return
                    continue
                negi, _, state, pending = heapq.heappop(heap)
                stats["active"] += 1
                stats["explored"] += 1
                stats["deepest"] = max(stats["deepest"], -negi)
                if stats["explored"] > max_worlds:
                    stats["overflow"] = True
                    stats["active"] -= 1
                    cond.notify_all()
                    return
            try:
                explore_one(-negi, state, pending)
            finally:
                with cond:
                    stats["active"] -= 1
                    cond.notify_all()

    threads = [threading.Thread(target=explorer, daemon=True,
                                name=f"wgl-{i}")
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    if stats["result"] is True:
        return WGLResult(valid=True, deepest_index=n,
                         worlds_explored=stats["explored"])
    if stats["overflow"]:
        return WGLResult(valid=UNKNOWN, deepest_index=stats["deepest"],
                         worlds_explored=stats["explored"],
                         cause="world budget exhausted")
    return WGLResult(valid=False, deepest_index=stats["deepest"],
                     worlds_explored=stats["explored"])


def analysis(model: Model, history: Sequence[Op],
             **kw) -> dict:
    """``knossos.core/analysis`` equivalent (``core.clj:484-512``):
    returns {"valid?", "deepest-index", "worlds-explored"}."""
    packed = (history if isinstance(history, PackedHistory)
              else pack_history(list(history)))
    if len(packed) == 0:
        return {"valid?": True, "deepest-index": 0, "worlds-explored": 0}
    mm = make_memo(model, packed)
    r = check(mm, packed, **kw)
    out = {"valid?": r.valid, "deepest-index": r.deepest_index,
           "worlds-explored": r.worlds_explored}
    if r.cause:
        out["cause"] = r.cause
    return out
