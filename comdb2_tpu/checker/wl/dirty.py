"""Dirty-reads workload as a failed-write visibility join.

The comdb2 dirty-reads test writes values (some of which FAIL) and
reads the row back from every node at once; a failed write's value
must never become visible to any read, and the per-node views of one
read should agree (``comdb2/core.clj:492-523``,
:class:`~..workloads.DirtyReadsChecker`).

On device the join is a gather: failed-write values intern into a
per-lane id table, the ``failed`` visibility plane is bool[B, V], and
each read row is its per-node value ids int32[B, R, N]; a read is
dirty when any valid node id gathers True from the failed plane. The
per-node-DISAGREEMENT check (masked min != max over node ids) rides
the same program — like the oracle's ``inconsistent-reads`` it is
diagnostic only, so ``valid?`` stays bit-identical to the (fixed)
host oracle.

Malformed read values — a scalar or a ``str`` where a per-node
sequence belongs — are rejected at encode time with the same
``malformed-reads`` cause the hardened oracle reports; the lane
answers UNKNOWN, never a silently per-character-iterated verdict.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np


class DirtyColumns(NamedTuple):
    failed: np.ndarray      # bool[B, V]
    reads: np.ndarray       # int32[B, R, N] value ids (0 where masked)
    node_mask: np.ndarray   # bool[B, R, N]
    read_mask: np.ndarray   # bool[B, R]
    read_index: np.ndarray  # int32[B, R] — op index of each read row
    tables: tuple           # per-lane id -> value
    malformed: tuple        # per-lane list of offending op indices


def is_malformed_read(v) -> bool:
    """A read value must be a per-node sequence: a ``str`` silently
    iterates per character and a scalar raises — both are driver bugs
    the checker must name, not absorb."""
    return isinstance(v, (str, bytes)) or not isinstance(v, (list,
                                                             tuple))


def encode_dirty(histories: Sequence[Sequence], *, r_pad: int,
                 n_pad: int, v_pad: int) -> DirtyColumns:
    """Host encode: intern failed-write values and read elements into
    one per-lane table (first-occurrence order); malformed reads mark
    the lane instead of joining the planes."""
    B = len(histories)
    failed = np.zeros((B, v_pad), bool)
    reads = np.zeros((B, r_pad, n_pad), np.int32)
    node_mask = np.zeros((B, r_pad, n_pad), bool)
    read_mask = np.zeros((B, r_pad), bool)
    read_index = np.full((B, r_pad), -1, np.int32)
    tables = []
    malformed = []
    for b, hist in enumerate(histories):
        ids: dict = {}

        def eid(v):
            from ..workloads import freeze_value

            v = freeze_value(v)
            i = ids.get(v)
            if i is None:
                i = ids[v] = len(ids)
                if i >= v_pad:
                    raise ValueError(
                        f"history {b}: > {v_pad} distinct values")
            return i

        bad_ops = []
        r = 0
        for i, op in enumerate(hist):
            if op.f == "write" and op.type == "fail" \
                    and op.value is not None:
                failed[b, eid(op.value)] = True
            elif (op.f == "read" and op.type == "ok"
                    and op.value is not None):
                if is_malformed_read(op.value):
                    bad_ops.append(i if op.index is None else op.index)
                    continue
                if len(op.value) > n_pad:
                    raise ValueError(
                        f"history {b}: read of > {n_pad} node views")
                if r >= r_pad:
                    raise ValueError(f"history {b}: > {r_pad} reads")
                read_mask[b, r] = True
                read_index[b, r] = i if op.index is None else op.index
                for j, x in enumerate(op.value):
                    reads[b, r, j] = eid(x)
                    node_mask[b, r, j] = True
                r += 1
        tables.append(tuple(ids))
        malformed.append(tuple(bad_ops))
    return DirtyColumns(failed, reads, node_mask, read_mask,
                        read_index, tuple(tables), tuple(malformed))


@functools.partial(jax.jit, static_argnames=("n_reads", "n_nodes",
                                             "n_values"))
def wl_dirty_check(failed, reads, node_mask, read_mask, *,
                   n_reads: int, n_nodes: int, n_values: int):
    """One batched dirty-reads verdict (``wl-dirty`` ladder,
    PROGRAMS.md): visibility join + per-node disagreement in one
    program."""
    B = reads.shape[0]
    assert reads.shape == (B, n_reads, n_nodes)
    assert failed.shape == (B, n_values)
    flat = reads.reshape(B, n_reads * n_nodes)
    hit = jnp.take_along_axis(failed, flat, axis=1) \
        .reshape(B, n_reads, n_nodes) & node_mask
    dirty = jnp.any(hit, axis=2) & read_mask                 # (B,R)
    big = jnp.where(node_mask, reads, -(1 << 30))
    small = jnp.where(node_mask, reads, 1 << 30)
    disagree = (jnp.max(big, axis=2) != jnp.min(small, axis=2)) \
        & read_mask
    any_dirty = jnp.any(dirty, axis=1)
    first_bad = jnp.where(any_dirty, jnp.argmax(dirty, axis=1), -1)
    return (~any_dirty, dirty, disagree, first_bad)


def dirty_verdicts(cols: DirtyColumns, out) -> List[dict]:
    """Decode to the oracle's shape: ``dirty-reads`` /
    ``inconsistent-reads`` carry the offending READ VALUES (decoded
    through the lane's table), malformed lanes answer UNKNOWN with
    the op indices."""
    from ..checkers import UNKNOWN

    valid, dirty, disagree, first_bad = (np.asarray(x) for x in out)
    verdicts = []
    for b, table in enumerate(cols.tables):
        def row(r):
            return tuple(table[cols.reads[b, r, j]]
                         for j in np.flatnonzero(cols.node_mask[b, r]))

        filthy = [row(r) for r in np.flatnonzero(dirty[b])]
        inconsistent = [row(r) for r in np.flatnonzero(disagree[b])]
        v = {"valid?": bool(valid[b]),
             "inconsistent-reads": inconsistent,
             "dirty-reads": filthy,
             "first-bad-read": int(first_bad[b])}
        if cols.malformed[b]:
            v["valid?"] = UNKNOWN
            v["malformed-reads"] = list(cols.malformed[b])
        verdicts.append(v)
    return verdicts


__all__ = ["DirtyColumns", "dirty_verdicts", "encode_dirty",
           "is_malformed_read", "wl_dirty_check"]
