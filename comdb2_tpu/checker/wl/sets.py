"""Sets workload as per-element bitmap membership algebra.

The Jepsen set test adds elements and reads the whole set back once at
the end; the verdict is pure set algebra over three populations
(``checker.clj:108-154``, :class:`~..checkers.SetChecker`):

- lost       = acked adds the final read never returned
- unexpected = read-back elements nobody ever attempted (phantoms)
- recovered  = attempted-not-acked adds that surfaced anyway (legal)

On device each history lane is three element bitmaps over a
host-interned id space (first-occurrence order, exactly like the
packer's value tables): ``attempts`` / ``adds`` / ``final_read``
bool[B, E]. The whole batch verdict is a handful of fused masked
reductions — no frontier, no sort. ``E`` comes from the ``WL_ELEMS``
ladder; histories agreeing on the rung share one program.

A history with no ok read answers UNKNOWN ("Set was never read") on
the host side, mirroring the oracle — its lane still rides the
dispatch (masked out) so the batch stays one program.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np


class SetsColumns(NamedTuple):
    attempts: np.ndarray    # bool[B, E]
    adds: np.ndarray        # bool[B, E]
    final_read: np.ndarray  # bool[B, E]
    has_read: np.ndarray    # bool[B]
    tables: tuple           # per-lane id -> element value


def encode_sets(histories: Sequence[Sequence], *,
                e_pad: int) -> SetsColumns:
    """Host encode: intern each lane's element values (adds AND read
    contents — a phantom element appears only in the read) in
    first-occurrence order, then set bitmap bits."""
    B = len(histories)
    attempts = np.zeros((B, e_pad), bool)
    adds = np.zeros((B, e_pad), bool)
    final_read = np.zeros((B, e_pad), bool)
    has_read = np.zeros(B, bool)
    tables = []
    for b, hist in enumerate(histories):
        ids: dict = {}

        def eid(v):
            i = ids.get(v)
            if i is None:
                i = ids[v] = len(ids)
                if i >= e_pad:
                    raise ValueError(
                        f"history {b}: > {e_pad} distinct elements")
            return i

        last_read = None
        for op in hist:
            if op.f == "add" and op.value is not None:
                i = eid(op.value)
                if op.type == "invoke":
                    attempts[b, i] = True
                elif op.type == "ok":
                    # an acked add is by definition attempted, even in
                    # completion-only histories with no invoke events
                    attempts[b, i] = True
                    adds[b, i] = True
            elif (op.f == "read" and op.type == "ok"
                    and op.value is not None):
                last_read = op.value
        if last_read is not None:
            has_read[b] = True
            for v in last_read:
                final_read[b, eid(v)] = True
        tables.append(tuple(ids))
    return SetsColumns(attempts, adds, final_read, has_read,
                       tuple(tables))


@functools.partial(jax.jit, static_argnames=("n_elems",))
def wl_sets_check(attempts, adds, final_read, has_read, *,
                  n_elems: int):
    """One batched sets verdict over bool[B, E] membership planes
    (``wl-sets`` ladder, PROGRAMS.md)."""
    assert attempts.shape[1] == n_elems
    ok = final_read & attempts
    unexpected = final_read & ~attempts
    lost = adds & ~final_read
    recovered = ok & ~adds
    valid = has_read & ~jnp.any(lost | unexpected, axis=1)
    return (valid, ok, lost, unexpected, recovered)


def _sets_delta_body(attempts, adds, final_read, attempts_d, adds_d,
                     read_d, has_read_d, has_read):
    """One LANE's sets delta against its bitmap-plane carry. Shared
    verbatim between the solo jit and the vmapped megabatch form.
    ``has_read_d`` (this delta read) and ``has_read`` (union INCLUDING
    this delta) are host-computed scalars — an empty-set read is still
    a read, so presence can't be inferred from ``read_d``. A read
    REPLACES ``final_read`` (last-read-wins, matching the one-shot
    encoder), which is why the sets verdict is only provisional until
    close."""
    att = attempts | attempts_d
    add = adds | adds_d
    fr = jnp.where(has_read_d, read_d, final_read)
    lost = add & ~fr
    unexpected = fr & ~att
    valid_now = has_read & ~jnp.any(lost | unexpected)
    return (att, add, fr, valid_now, jnp.sum(lost),
            jnp.sum(unexpected))


@functools.partial(jax.jit, static_argnames=("n_elems",))
def wl_sets_delta(attempts, adds, final_read, attempts_d, adds_d,
                  read_d, has_read_d, has_read, *, n_elems: int):
    """Stream-rung solo advance: O(delta) dispatches — the carry is
    the three (E,) membership planes at the session's ``WL_ELEMS``
    rung (the ``wl-sets-delta`` ladder, PROGRAMS.md)."""
    assert attempts.shape == (n_elems,)
    return _sets_delta_body(attempts, adds, final_read, attempts_d,
                            adds_d, read_d, has_read_d, has_read)


@functools.partial(jax.jit, static_argnames=("n_elems",))
def wl_sets_delta_mb(carries, attempts_d, adds_d, read_d, has_read_d,
                     has_read, *, n_elems: int):
    """Megabatched advance: ``carries`` is a TUPLE of per-lane
    ``(attempts, adds, final_read)`` device triples (stacked INSIDE
    the jit); delta planes arrive host-stacked with a lane axis.
    Returns one output tuple per lane — same body as solo,
    bit-identical per lane."""
    att = jnp.stack([c[0] for c in carries])
    add = jnp.stack([c[1] for c in carries])
    fr = jnp.stack([c[2] for c in carries])
    assert att.shape == (len(carries), n_elems)
    outs = jax.vmap(_sets_delta_body)(att, add, fr, attempts_d,
                                      adds_d, read_d, has_read_d,
                                      has_read)
    return tuple(tuple(o[i] for o in outs)
                 for i in range(len(carries)))


def sets_verdicts(cols: SetsColumns, out) -> List[dict]:
    """Decode to the oracle's result shape — same interval-set strings
    and fractions as :class:`~..checkers.SetChecker`, bit-identical on
    every lane."""
    from ...utils.intervals import fraction, integer_interval_set_str
    from ..checkers import UNKNOWN

    valid, ok, lost, unexpected, recovered = \
        (np.asarray(x) for x in out)
    verdicts = []
    for b, table in enumerate(cols.tables):
        if not cols.has_read[b]:
            verdicts.append({"valid?": UNKNOWN,
                             "error": "Set was never read"})
            continue
        dec = lambda plane: {table[i] for i in np.flatnonzero(plane[b])}
        n_att = int(np.count_nonzero(cols.attempts[b]))
        sets = {k: dec(p) for k, p in
                (("ok", ok), ("lost", lost),
                 ("unexpected", unexpected), ("recovered", recovered))}
        v = {"valid?": bool(valid[b])}
        for k, s in sets.items():
            v[k] = integer_interval_set_str(s)
            v[f"{k}-frac"] = fraction(len(s), n_att)
        # match the oracle's key order/shape exactly
        verdicts.append({"valid?": v["valid?"],
                         "ok": v["ok"], "lost": v["lost"],
                         "unexpected": v["unexpected"],
                         "recovered": v["recovered"],
                         "ok-frac": v["ok-frac"],
                         "unexpected-frac": v["unexpected-frac"],
                         "lost-frac": v["lost-frac"],
                         "recovered-frac": v["recovered-frac"]})
    return verdicts


__all__ = ["SetsColumns", "encode_sets", "sets_verdicts",
           "wl_sets_check", "wl_sets_delta", "wl_sets_delta_mb"]
