"""Device workload-checker families — bank / sets / dirty-reads.

The full Jepsen checker suite beside the register tester
(PAPER.md §1.1), lowered from the per-op host loops in
``checker/workloads.py`` to batched tensor reductions: none of these
needs a frontier search, so a whole batch of histories is ONE jit per
pow2 bucket (``check_wl_batch``), the service serves them as
``kind:"wl"``, and bank/sets run live as stream-session rungs
(``comdb2_tpu.stream.wl``). The host checkers remain as parity
oracles — golden tests assert bit-agreement on every seeded
valid/violation twin. docs/workloads.md has the family semantics,
tensor layouts, and violation taxonomy.
"""

from .bank import (BankColumns, bank_verdicts, default_init,
                   encode_bank, wl_bank_check, wl_bank_delta,
                   wl_bank_delta_mb)
from .batch import (FAMILIES, WL_ACCOUNTS, WL_BATCH, WL_DELTA_PADS,
                    WL_ELEMS, WL_NODES, WL_READS, WL_SNAPS,
                    WL_VALUES, bucket_of, check_wl_batch,
                    stage_wl_batch, wl_dims)
from .dirty import (DirtyColumns, dirty_verdicts, encode_dirty,
                    is_malformed_read, wl_dirty_check)
from .sets import (SetsColumns, encode_sets, sets_verdicts,
                   wl_sets_check, wl_sets_delta, wl_sets_delta_mb)
from .synth import bank_batch, dirty_batch, sets_batch

__all__ = ["BankColumns", "DirtyColumns", "FAMILIES", "SetsColumns",
           "WL_ACCOUNTS", "WL_BATCH", "WL_DELTA_PADS", "WL_ELEMS",
           "WL_NODES", "WL_READS", "WL_SNAPS", "WL_VALUES",
           "bank_batch", "bank_verdicts", "bucket_of",
           "check_wl_batch", "default_init", "dirty_batch",
           "dirty_verdicts", "encode_bank", "encode_dirty",
           "encode_sets", "is_malformed_read", "sets_batch",
           "sets_verdicts", "stage_wl_batch", "wl_bank_check",
           "wl_bank_delta", "wl_bank_delta_mb", "wl_dims",
           "wl_dirty_check", "wl_sets_check", "wl_sets_delta",
           "wl_sets_delta_mb"]
