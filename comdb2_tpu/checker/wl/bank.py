"""Bank workload as a batched tensor family.

The Jepsen bank test moves money between ``n`` accounts with
``transfer`` ops and reads all balances at once; the invariant is that
every read sees exactly ``n`` balances summing to the model total
(``comdb2/core.clj:152-177``, :class:`~..workloads.BankChecker`). No
frontier search is needed — the whole check is a masked row-sum
reduction, so a batch of histories is one jit.

Tensor layout (axis 0 = history lane, all dims pow2-padded from the
``checker.wl.batch`` ladders):

- ``reads``      int32[B, R, A]  — ok-read balance rows (0-padded)
- ``read_mask``  bool[B, R]      — real read rows
- ``wrong_n``    bool[B, R]      — host-flagged ragged rows (a read
  with the wrong account count cannot be laid out in (A,) faithfully;
  the flag rides into the device reduction so the verdict is still a
  single device readback)
- ``init``       int32[B, A]     — starting balances
- ``transfers``  int32[B, T, A]  — per-ok-transfer account deltas
  (0-padded rows are no-ops)
- ``total``      int32[B]

All-int32 on device: this env runs without x64, and bank balances are
bounded by the model total (the encoder range-checks).

Beyond the oracle's wrong-n / wrong-total, the device also proves a
DIAGNOSTIC snapshot-inconsistency plane: prefix snapshots
``S_t = init + cumsum(transfers)[:t]`` (t = 0..T) are the only states
a serializable bank can ever expose, so a read matching NO ``S_t``
observed a mid-transfer (fractured) state even when its total happens
to balance. Like the dirty-reads oracle's ``inconsistent-reads``, it
does not affect ``valid?`` — the device verdict stays bit-identical to
:class:`~..workloads.BankChecker`.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class BankColumns(NamedTuple):
    """Encoded bank histories (see module docstring). ``read_index``
    maps read rows back to op indices for counterexample reporting."""
    reads: np.ndarray       # int32[B, R, A]
    read_mask: np.ndarray   # bool[B, R]
    wrong_n: np.ndarray     # bool[B, R]
    wrong_len: np.ndarray   # int32[B, R] — found length of wrong-n rows
    init: np.ndarray        # int32[B, A]
    transfers: np.ndarray   # int32[B, T, A]
    total: np.ndarray       # int32[B]
    read_index: np.ndarray  # int32[B, R] — op index of each read row
    n: int                  # the model's account count (un-padded)


def default_init(model: dict) -> List[int]:
    """Starting balances: the model's ``init`` when present, else the
    Jepsen default of an even split (remainder on account 0)."""
    n, total = int(model["n"]), int(model["total"])
    if "init" in model:
        init = [int(x) for x in model["init"]]
        if len(init) != n or sum(init) != total:
            raise ValueError("model init must hold n balances summing "
                             "to total")
        return init
    per = total // n
    return [total - per * (n - 1)] + [per] * (n - 1)


def encode_bank(histories: Sequence[Sequence], model: dict, *,
                r_pad: int, a_pad: int, t_pad: int) -> BankColumns:
    """Host encode: one pass per history over its ops into the padded
    column planes. ``transfer`` op values are ``(frm, to, amount)``."""
    B = len(histories)
    n = int(model["n"])
    if a_pad < n:
        raise ValueError(f"a_pad {a_pad} < model n {n}")
    if abs(int(model["total"])) >= 1 << 30:
        raise ValueError("bank totals must fit int32 (no x64 here)")
    init_row = default_init(model)
    reads = np.zeros((B, r_pad, a_pad), np.int32)
    read_mask = np.zeros((B, r_pad), bool)
    wrong_n = np.zeros((B, r_pad), bool)
    wrong_len = np.zeros((B, r_pad), np.int32)
    read_index = np.full((B, r_pad), -1, np.int32)
    transfers = np.zeros((B, t_pad, a_pad), np.int32)
    init = np.zeros((B, a_pad), np.int32)
    init[:, :n] = init_row
    total = np.full(B, int(model["total"]), np.int32)
    for b, hist in enumerate(histories):
        r = t = 0
        for i, op in enumerate(hist):
            if op.type != "ok" or op.value is None:
                continue
            if op.f == "read":
                row = list(op.value)
                if r >= r_pad:
                    raise ValueError(f"history {b}: > {r_pad} reads")
                read_mask[b, r] = True
                read_index[b, r] = i if op.index is None else op.index
                if len(row) != n:
                    wrong_n[b, r] = True
                    wrong_len[b, r] = len(row)
                else:
                    reads[b, r, :n] = row
                r += 1
            elif op.f == "transfer":
                frm, to, amt = op.value
                if t >= t_pad:
                    raise ValueError(
                        f"history {b}: > {t_pad} transfers")
                transfers[b, t, int(frm)] -= int(amt)
                transfers[b, t, int(to)] += int(amt)
                t += 1
    return BankColumns(reads, read_mask, wrong_n, wrong_len, init,
                       transfers, total, read_index, n)


@functools.partial(jax.jit, static_argnames=("n_reads", "n_accounts",
                                             "n_snaps"))
def wl_bank_check(reads, read_mask, wrong_n, init, transfers, total,
                  *, n_reads: int, n_accounts: int, n_snaps: int):
    """One batched bank verdict. Shapes are drawn from the closed
    ``wl-bank`` ladder (PROGRAMS.md); the static kwargs restate the
    padded dims so call sites are auditable by the
    ``unbucketed-dispatch-site`` rule."""
    assert reads.shape == (reads.shape[0], n_reads, n_accounts)
    assert transfers.shape[1] == n_snaps
    sums = jnp.sum(reads, axis=2)                              # (B,R)
    wrong_total = read_mask & ~wrong_n & (sums != total[:, None])
    bad = read_mask & (wrong_n | wrong_total)
    # snapshot plane: S_0 = init, S_t = init + cumsum(transfers)[t-1]
    snaps = jnp.concatenate(
        [jnp.zeros_like(transfers[:, :1]),
         jnp.cumsum(transfers, axis=1)],
        axis=1) + init[:, None, :]                          # (B,T+1,A)

    def any_match(seen, snap_t):                            # (B,A)
        m = jnp.all(reads == snap_t[:, None, :], axis=2)    # (B,R)
        return seen | m, None

    seen, _ = lax.scan(any_match,
                       jnp.zeros(read_mask.shape, bool),
                       jnp.moveaxis(snaps, 1, 0))
    snap_bad = read_mask & ~wrong_n & ~seen
    any_bad = jnp.any(bad, axis=1)
    first_bad = jnp.where(any_bad, jnp.argmax(bad, axis=1), -1)
    return (~any_bad, wrong_total, snap_bad, first_bad, sums)


def _bank_delta_body(balance, reads, read_mask, wrong_n, transfers,
                     total):
    """One LANE's bank delta against its running-balance carry. Shared
    verbatim between the solo jit and the vmapped megabatch form so a
    fused advance is bit-identical to the solo one. Snapshot depth
    counts from the carry: ``S_0 = balance`` (the pre-delta state is a
    legal read), ``S_t = balance + cumsum(transfers)[t-1]``."""
    snaps = jnp.concatenate(
        [jnp.zeros_like(transfers[:1]),
         jnp.cumsum(transfers, axis=0)], axis=0) + balance[None, :]
    new_balance = snaps[-1]
    sums = jnp.sum(reads, axis=1)                               # (R,)
    wrong_total = read_mask & ~wrong_n & (sums != total)
    bad = read_mask & (wrong_n | wrong_total)

    def any_match(seen, snap_t):
        return seen | jnp.all(reads == snap_t[None, :], axis=1), None

    seen, _ = lax.scan(any_match, jnp.zeros(read_mask.shape, bool),
                       snaps)
    snap_bad = read_mask & ~wrong_n & ~seen
    any_bad = jnp.any(bad)
    first_bad = jnp.where(any_bad, jnp.argmax(bad), -1)
    return (new_balance, any_bad, first_bad, jnp.sum(bad),
            jnp.sum(snap_bad))


@functools.partial(jax.jit, static_argnames=("n_reads", "n_accounts",
                                             "n_snaps"))
def wl_bank_delta(balance, reads, read_mask, wrong_n, transfers,
                  total, *, n_reads: int, n_accounts: int,
                  n_snaps: int):
    """Stream-rung solo advance: O(delta) — the carry is the (A,)
    running balance, the delta planes are this append's reads and
    transfer rows padded up ``WL_DELTA_PADS`` (the ``wl-bank-delta``
    ladder, PROGRAMS.md)."""
    assert reads.shape == (n_reads, n_accounts)
    assert transfers.shape == (n_snaps, n_accounts)
    return _bank_delta_body(balance, reads, read_mask, wrong_n,
                            transfers, total)


@functools.partial(jax.jit, static_argnames=("n_reads", "n_accounts",
                                             "n_snaps"))
def wl_bank_delta_mb(balances, reads, read_mask, wrong_n, transfers,
                     totals, *, n_reads: int, n_accounts: int,
                     n_snaps: int):
    """Megabatched advance: ``balances`` is a TUPLE of per-lane
    device carries (stacked INSIDE the jit — eager host stacking of
    device arrays would compile an off-inventory infra program); the
    delta planes arrive host-stacked with a lane axis. Returns one
    output tuple per lane, vmapping the SAME body as the solo form —
    bit-identical per lane."""
    bal = jnp.stack(balances)
    assert reads.shape == (bal.shape[0], n_reads, n_accounts)
    assert transfers.shape[1] == n_snaps
    outs = jax.vmap(_bank_delta_body)(bal, reads, read_mask, wrong_n,
                                      transfers, totals)
    return tuple(tuple(o[i] for o in outs)
                 for i in range(len(balances)))


def bank_verdicts(cols: BankColumns, out) -> List[dict]:
    """Decode one device readback into per-history oracle-shaped
    verdict dicts (the ``bad-reads`` taxonomy of
    :class:`~..workloads.BankChecker`, plus the snapshot plane)."""
    valid, wrong_total, snap_bad, first_bad, sums = \
        (np.asarray(x) for x in out)
    verdicts = []
    for b in range(cols.read_mask.shape[0]):
        bad_reads = []
        for r in np.flatnonzero(cols.read_mask[b]):
            if cols.wrong_n[b, r]:
                bad_reads.append({"type": "wrong-n",
                                  "expected": cols.n,
                                  "found": int(cols.wrong_len[b, r]),
                                  "index": int(cols.read_index[b, r])})
            elif wrong_total[b, r]:
                bad_reads.append({"type": "wrong-total",
                                  "expected": int(cols.total[b]),
                                  "found": int(sums[b, r]),
                                  "index": int(cols.read_index[b, r])})
        snaps = [int(cols.read_index[b, r])
                 for r in np.flatnonzero(snap_bad[b])]
        verdicts.append({"valid?": bool(valid[b]),
                         "bad-reads": bad_reads,
                         "snapshot-inconsistent": snaps,
                         "first-bad-read": int(first_bad[b])})
    return verdicts


__all__ = ["BankColumns", "bank_verdicts", "default_init",
           "encode_bank", "wl_bank_check", "wl_bank_delta",
           "wl_bank_delta_mb"]
