"""Batched entry for the workload-checker families.

``check_wl_batch`` is the one dispatch surface: encode a batch of
histories into the family's column planes, pad every jit-visible dim
up its declared ladder, and launch ONE program per pow2 bucket
(``DISPATCHES`` counts launches; tests assert one per bucket). The
ladders below are the ``wl-<family>`` rows of PROGRAMS.md — the
compile guard closes over them, so every rung pair is a program the
daemon may prime and nothing else ever compiles.

Histories that exceed the top rung of a per-history axis fall back to
the HOST ORACLE (the demoted ``workloads.py`` checkers) — same
verdict, ``engine: "host"`` attribution, no open-ended program.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .bank import bank_verdicts, encode_bank, wl_bank_check
from .dirty import dirty_verdicts, encode_dirty, wl_dirty_check
from .sets import encode_sets, sets_verdicts, wl_sets_check

#: the checker families this subsystem serves
FAMILIES = ("bank", "sets", "dirty")

#: batch-lane rungs (histories per dispatch; bigger batches chunk)
WL_BATCH = (1, 8, 64, 512)
#: ok-read rows per history (bank + dirty)
WL_READS = (8, 64, 512)
#: bank account columns
WL_ACCOUNTS = (8, 32, 128)
#: bank transfer rows (snapshot plane depth is T + 1)
WL_SNAPS = (8, 64, 512)
#: sets element-universe width
WL_ELEMS = (128, 1024, 8192)
#: dirty per-read node views
WL_NODES = (4, 16)
#: dirty distinct-value universe width
WL_VALUES = (128, 1024, 8192)
#: stream-rung per-APPEND row pads (bank delta reads / transfers) —
#: an append past the top rung dispatches in sequential solo chunks
WL_DELTA_PADS = (8, 64)

#: launched wl programs (one per pow2 bucket — the amortization claim
#: tests assert against this, exactly like stream.engine.DISPATCHES)
DISPATCHES = 0


def bucket_of(n: int, ladder: Tuple[int, ...]) -> int:
    """The smallest rung >= n (None past the top — the caller routes
    host). Shares its name with the sanctioned bucketing helpers the
    ``unbucketed-dispatch-site`` rule recognizes."""
    for p in ladder:
        if p >= n:
            return p
    return None


def _dims(histories, family: str, model: Optional[dict]):
    """Per-batch padded dims (max over lanes, bucketed), or None when
    any per-history axis exceeds its top rung."""
    n_reads = n_elems = n_nodes = n_vals = n_snaps = 1
    for hist in histories:
        r = t = 0
        elems = set()
        vals = set()
        for op in hist:
            if op.value is None:
                continue
            if family == "bank":
                if op.type == "ok" and op.f == "read":
                    r += 1
                elif op.type == "ok" and op.f == "transfer":
                    t += 1
            elif family == "sets":
                if op.f == "add":
                    elems.add(_key(op.value))
                elif op.type == "ok" and op.f == "read":
                    elems |= {_key(v) for v in op.value}
            elif family == "dirty":
                if op.f == "write":
                    vals.add(_key(op.value))
                elif op.type == "ok" and op.f == "read":
                    r += 1
                    if not isinstance(op.value, (str, bytes)) \
                            and isinstance(op.value, (list, tuple)):
                        n_nodes = max(n_nodes, len(op.value))
                        vals |= {_key(v) for v in op.value}
        n_reads = max(n_reads, r)
        n_snaps = max(n_snaps, t)
        n_elems = max(n_elems, len(elems))
        n_vals = max(n_vals, len(vals))
    if family == "bank":
        a = int(model["n"]) if model else 1
        dims = {"r_pad": bucket_of(n_reads, WL_READS),
                "a_pad": bucket_of(a, WL_ACCOUNTS),
                "t_pad": bucket_of(n_snaps, WL_SNAPS)}
    elif family == "sets":
        dims = {"e_pad": bucket_of(n_elems, WL_ELEMS)}
    else:
        dims = {"r_pad": bucket_of(n_reads, WL_READS),
                "n_pad": bucket_of(n_nodes, WL_NODES),
                "v_pad": bucket_of(n_vals, WL_VALUES)}
    if any(v is None for v in dims.values()):
        return None
    return dims


def _key(v):
    from ..workloads import freeze_value

    return freeze_value(v)


def _host_fallback(histories, family: str,
                   model: Optional[dict]) -> List[dict]:
    from ..checkers import check_safe, set_checker
    from ..workloads import bank_checker, dirty_reads_checker

    chk = {"bank": bank_checker, "sets": set_checker,
           "dirty": dirty_reads_checker}[family]
    out = []
    for hist in histories:
        v = check_safe(chk, {}, model, list(hist))
        v["engine"] = "host"
        out.append(v)
    return out


def stage_wl_batch(histories: Sequence[Sequence], family: str,
                   model: Optional[dict] = None, *,
                   b_pad: Optional[int] = None,
                   dims: Optional[dict] = None):
    """Encode one bucket's batch and LAUNCH its device program;
    returns a zero-arg finalize whose call is the readback point
    (the verdict list, padded lanes sliced off). This is the
    stage/finish seam the service ring overlaps host packing against
    — same contract as ``checker.batch.check_batch_async``. ``dims``
    pins the padded per-history axes (the service passes its
    WlBucket's, so every chunk of a bucket reuses one program);
    without it the batch max is measured and bucketed here. Raises
    ``ValueError`` on unknown family / missing bank model; a batch
    past the rungs (or an encode-time overflow) finalizes through the
    host oracle instead."""
    global DISPATCHES
    if family not in FAMILIES:
        raise ValueError(f"unknown wl family {family!r}")
    if family == "bank" and (model is None or "n" not in model
                             or "total" not in model):
        raise ValueError("bank needs a model {'n':..,'total':..}")
    histories = [list(h) for h in histories]
    if not histories:
        return lambda: []
    if len(histories) > WL_BATCH[-1]:
        raise ValueError(
            f"batch of {len(histories)} exceeds the top WL_BATCH "
            f"rung ({WL_BATCH[-1]}) — chunk first (check_wl_batch "
            "does)")
    if dims is None:
        dims = _dims(histories, family, model)
    if dims is None or any(v is None for v in dims.values()):
        return lambda: _host_fallback(histories, family, model)
    B = len(histories)
    bp = b_pad if b_pad is not None else bucket_of(B, WL_BATCH)
    # pad lanes by duplicating lane 0 (same trick as the megabatch
    # collector) — padded verdicts are sliced off before return
    padded = histories + [histories[0]] * (bp - B)
    try:
        if family == "bank":
            cols = encode_bank(padded, model, **dims)
            out = wl_bank_check(
                cols.reads, cols.read_mask, cols.wrong_n, cols.init,
                cols.transfers, cols.total,
                n_reads=dims["r_pad"], n_accounts=dims["a_pad"],
                n_snaps=dims["t_pad"])
            DISPATCHES += 1
            return lambda: bank_verdicts(cols, out)[:B]
        if family == "sets":
            cols = encode_sets(padded, **dims)
            out = wl_sets_check(cols.attempts, cols.adds,
                                cols.final_read, cols.has_read,
                                n_elems=dims["e_pad"])
            DISPATCHES += 1
            return lambda: sets_verdicts(cols, out)[:B]
        cols = encode_dirty(padded, **dims)
        out = wl_dirty_check(cols.failed, cols.reads, cols.node_mask,
                             cols.read_mask,
                             n_reads=dims["r_pad"],
                             n_nodes=dims["n_pad"],
                             n_values=dims["v_pad"])
        DISPATCHES += 1
        return lambda: dirty_verdicts(cols, out)[:B]
    except ValueError:
        # encode-time overflow (a lane past a per-history cap the
        # pre-scan could not see, e.g. interning growth) — host route
        return lambda: _host_fallback(histories, family, model)


def check_wl_batch(histories: Sequence[Sequence], family: str,
                   model: Optional[dict] = None, *,
                   b_pad: Optional[int] = None) -> List[dict]:
    """Check a batch of one family's histories on device — one
    program per pow2 bucket (:func:`stage_wl_batch` staged and
    finalized in one step). ``model`` is the bank model dict
    (``{"n": .., "total": ..}``); other families take None. ``b_pad``
    forces the batch rung; by default lanes bucket up ``WL_BATCH``
    and over-top batches chunk."""
    histories = [list(h) for h in histories]
    top = WL_BATCH[-1]
    if len(histories) > top:
        out = []
        for i in range(0, len(histories), top):
            out.extend(check_wl_batch(histories[i:i + top], family,
                                      model, b_pad=top))
        return out
    return stage_wl_batch(histories, family, model, b_pad=b_pad)()


def wl_dims(histories, family: str,
            model: Optional[dict] = None) -> Optional[dict]:
    """Padded per-history axes for a batch (max over lanes, bucketed
    up the family's ladders), or None when any axis exceeds its top
    rung — the service's bucket derivation (``wl_bucket_for``)."""
    return _dims([list(h) for h in histories], family, model)


__all__ = ["DISPATCHES", "FAMILIES", "WL_ACCOUNTS", "WL_BATCH",
           "WL_DELTA_PADS", "WL_ELEMS", "WL_NODES", "WL_READS",
           "WL_SNAPS", "WL_VALUES", "bucket_of", "check_wl_batch",
           "stage_wl_batch", "wl_dims"]
